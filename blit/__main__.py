"""Command-line interface: ``python -m blit <command>``.

The reference is a library driven from the Julia REPL; the tool it
replaces on the recording nodes — rawspec — is a CLI.  blit ships both:
the library (:mod:`blit.gbt` et al.) and this thin command layer over it.

Commands:
  reduce     GUPPI RAW (file, .NNNN.raw sequence stem, or member list)
             → filterbank product (.fil streams to disk; .h5 = FBH5).
  search     GUPPI RAW → .hits drift-rate search product: the on-device
             Taylor-tree dedoppler over windowed spectra (ISSUE 6) —
             only hit records ever cross the readback link.
  stream     LIVE reduction (ISSUE 7): follow a RAW file the recorder is
             still appending to (or replay a completed one at wall-clock
             / accelerated rate) and produce the .fil/.h5 — or, with
             --search, .hits — product *during* the session, with
             watermark lateness masking and p50/p99 chunk→product
             latency in the report.  Byte-identical to the batch path
             for a completed stream.
  scan       Whole (session, scan) across the device mesh: crawl the
             tree, map every player's RAW sequence onto the (band, bank)
             mesh, stream each stitched band to a per-band product —
             the reference's ``loadscan`` (src/gbt.jl:99) as a command.
  inventory  Crawl a data tree (reference getinventory semantics) and
             print records as JSON lines or a table.
  info       Print the normalized header of a .fil / .h5 / .raw file.
  serve-bench
             Replay a zipfian request mix against a ProductService
             (blit/serve) over synthetic RAW inputs and report hit-rate,
             coalesce counts, and p50/p99 queue wait.  ``--fleet``
             replays through a REAL multi-process fleet front door
             (ISSUE 14: consistent-hash routing, hedged reads, deadline
             propagation) and reports per-tier hit-rate, SLO attainment
             and the hedge counters.
  fleet-peer Run ONE serving peer of the fleet (ISSUE 14): a
             ProductService over stdlib HTTP (/product /warm /stats
             /healthz /metrics /drain) beating a heartbeat lease;
             SIGTERM drains gracefully — refuse new, finish in-flight,
             release live capacity holds.
  ingest-bench
             File→product throughput probe of the asynchronous output
             plane (blit/outplane): per-stage table with the readback/
             write stages and the overlap-efficiency gauge, optionally
             A/B'd against the synchronous path (and against spans
             disabled, for the tracing-overhead bound).
  tune       Offline ingest autotune (ISSUE 8): sweep the ingest knobs
             (chunk_frames / prefetch_depth / out_depth) with real timed
             reductions on THIS rig and persist the winner as a
             content-addressed per-rig tuning profile that reduce /
             scan / serve / stream load automatically.
  telemetry  Fleet telemetry (ISSUE 5): harvest per-worker Timelines,
             fault counters and spans into one per-host report (text /
             Prometheus exposition / JSON), render a saved report, or
             run a multi-worker demo reduction that also exports a
             Perfetto-loadable trace.
  trace-view Render a flight-recorder dump (written automatically when a
             stall watchdog trips, a breaker opens, or an agent dies)
             into a readable incident summary.
  chaos      Crash-recovery drill (ISSUE 12): run a seeded kill/hang
             schedule against a real supervised sharded scan or live
             stream and assert detection, degrade-and-resume (reshaped
             mesh or pool fallback / session rejoin) and product
             byte-identity against an uninterrupted oracle.  The
             ``--fault corrupt`` leg (ISSUE 13) instead corrupts a
             delivered RAW frame under a digest sidecar and asserts
             masked-not-garbage: the product must be byte-identical to
             a zero-filled oracle with ``integrity.bad_block`` >= 1.
  fsck       Archive integrity check (ISSUE 13): walk a tree of
             products / disk-cache entries verifying every manifest
             and content digest; mismatches are QUARANTINED
             (``.quarantine/`` sibling) and exit != 0.  ``--repair``
             re-derives quarantined cache entries from their recorded
             recipes and retires corpses superseded by a verified
             replacement.
  top        Live terminal dashboard (ISSUE 11): tail a monitor spool
             dir or poll a publisher endpoint during an in-progress
             reduce/scan/stream/serve — per-stage throughput, stage-tail
             p50/p99, SLO burn, host health.  ``--once`` renders one
             frame (tests/scripts).
  bench-diff Compare a fresh bench.py / ingest-bench JSON against the
             checked-in BENCH_*.json trajectory with noise bands and
             exit 0 (pass) / 2 (regress) — the CI perf-regression gate.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _cmd_reduce(args: argparse.Namespace) -> int:
    from blit.pipeline import RawReducer, reducer_for_product

    kw = dict(stokes=args.stokes, fqav_by=args.fqav, dtype=args.dtype)
    if args.product is not None:
        red = reducer_for_product(args.product, **kw)
    else:
        red = RawReducer(nfft=args.nfft, nint=args.nint, **kw)
    src: object = args.raw[0] if len(args.raw) == 1 else args.raw
    if args.resume:
        hdr = red.reduce_resumable(src, args.output,
                                   compression=args.compression)
    else:
        hdr = red.reduce_to_file(src, args.output,
                                 compression=args.compression)
    stats = red.stats
    print(
        json.dumps(
            {
                "output": args.output,
                "nsamps": hdr.get("nsamps"),
                "nchans": hdr.get("nchans"),
                "nifs": hdr.get("nifs"),
                "input_bytes": stats.input_bytes,
                "gbps": round(stats.gbps, 3),
            }
        )
    )
    return 0


def _cmd_search(args: argparse.Namespace) -> int:
    from blit.pipeline import PRODUCT_PRESETS
    from blit.search import DedopplerReducer

    nfft, nint = ((args.nfft, args.nint) if args.product is None
                  else PRODUCT_PRESETS[args.product])
    red = DedopplerReducer(
        nfft=nfft, nint=nint, dtype=args.dtype,
        window_spectra=args.window_spectra, top_k=args.top_k,
        snr_threshold=args.snr, max_drift_bins=args.max_drift_bins,
        kernel=args.kernel, interpret=args.interpret,
    )
    src: object = args.raw[0] if len(args.raw) == 1 else args.raw
    if args.resume:
        hdr = red.search_resumable(src, args.output)
    else:
        hdr = red.search_to_file(src, args.output)
    tl = red.timeline.report()
    print(
        json.dumps(
            {
                "output": args.output,
                "windows": hdr.get("search_windows"),
                "hits": hdr.get("search_nhits"),
                "nchans": hdr.get("nchans"),
                "window_spectra": hdr.get("search_window_spectra"),
                "snr_threshold": hdr.get("search_snr_threshold"),
                "top_k": hdr.get("search_top_k"),
                # The per-window tree latency / hits-per-window
                # distributions (sync path populates tree_s; the async
                # plane's equivalent is out.chunk_latency_s).
                "hists": tl.get("hists", {}),
            }
        )
    )
    return 0


def _cmd_stream(args: argparse.Namespace) -> int:
    """Live reduction front door (ISSUE 7).  Default mode FOLLOWS the
    file as the recorder appends (ending at the ``.done`` marker or the
    idle timeout); ``--replay-rate`` replays a completed recording
    through the same plane — the latency rig and the byte-identity
    drill."""
    from blit.observability import Timeline
    from blit.pipeline import PRODUCT_PRESETS
    from blit.stream import FileTailSource, ReplaySource

    # Live monitoring (ISSUE 11): a session that never pauses is what
    # the monitor plane exists for — the flags start the publisher, the
    # reducer's publishing hook streams the watermark/latency telemetry.
    pub = _monitor_from_flags(args)

    if args.replay_rate is not None:
        src = ReplaySource(args.raw, rate=args.replay_rate)
    else:
        src = FileTailSource(args.raw, poll_s=args.poll,
                             idle_timeout_s=args.idle_timeout,
                             done_path=args.done_file)
    nfft, nint = ((args.nfft, args.nint) if args.product is None
                  else PRODUCT_PRESETS[args.product])
    tl = Timeline()
    if args.search:
        from blit.stream import stream_search

        hdr = stream_search(
            src, args.output, lateness_s=args.lateness, nfft=nfft,
            nint=nint, dtype=args.dtype, timeline=tl,
            window_spectra=args.window_spectra, snr_threshold=args.snr,
            top_k=args.top_k, resume=args.resume,
        )
        body = {"hits": hdr.get("search_nhits"),
                "windows": hdr.get("search_windows")}
    else:
        from blit.stream import stream_reduce

        hdr = stream_reduce(
            src, args.output, lateness_s=args.lateness, nfft=nfft,
            nint=nint, stokes=args.stokes, fqav_by=args.fqav,
            dtype=args.dtype, compression=args.compression, timeline=tl,
            resume=args.resume,
        )
        body = {"nsamps": hdr.get("nsamps"), "nchans": hdr.get("nchans")}
    lat = tl.report().get("hists", {}).get("stream.chunk_to_product_s", {})
    out = {
        "output": args.output,
        **body,
        "stream_chunks": hdr.get("stream_chunks"),
        "late_chunks": hdr.get("stream_late_chunks"),
        "dup_chunks": hdr.get("stream_dup_chunks"),
        "masked_chunks": hdr.get("stream_masked_chunks"),
        "degraded_spectra": hdr.get("stream_degraded_spectra",
                                    hdr.get("stream_degraded_windows")),
        "chunk_to_product_p50_s": lat.get("p50"),
        "chunk_to_product_p99_s": lat.get("p99"),
    }
    if hdr.get("_masked_chunks"):
        out["masked_chunk_seqs"] = hdr["_masked_chunks"]
    if hdr.get("stream_flight_dump"):
        out["flight_dump"] = hdr["stream_flight_dump"]
    if pub is not None:
        pub.tick()
        out["monitor"] = {"port": pub.port, "spool": pub.spool_path,
                          "samples": pub.seq}
        from blit import monitor

        monitor.shutdown_publisher()
    print(json.dumps(out))
    return 0


def _cmd_scan(args: argparse.Namespace) -> int:
    from blit.config import default_window_frames, mesh_defaults
    from blit.inventory import get_inventory
    from blit.observability import Timeline
    from blit.parallel.scan import (
        reduce_scan_mesh_to_files,
        reduce_scan_pool_to_files,
    )

    mdef = mesh_defaults()
    # Parallelism selection (ISSUE 9): --sharded = the fully-threaded
    # sharded reduction plane; --pool = the per-player pool fallback /
    # byte-identity oracle; neither = SiteConfig/BLIT_MESH_SHARDED picks
    # between the sharded plane and the serial mesh window loop.
    sharded = args.sharded or (mdef["sharded"] and not args.pool)

    invs = [get_inventory(args.file_re or r"\.raw$", root=args.root)]
    # The EFFECTIVE window (library default + nint rounding), so the
    # stats line reports what actually executed.  An unset --window-frames
    # consults this rig's tuning profile first (blit/tune.py): the scan's
    # frames-per-dispatch is the same quantity `blit tune` converged as
    # chunk_frames, so the profile transfers.
    tuning = {"source": "explicit"}
    depths = {"prefetch_depth": mdef["prefetch_depth"],
              "out_depth": mdef["out_depth"]}
    if args.window_frames is None:
        # Resolve through a throwaway probe reducer so the profile key
        # comes out of EXACTLY the code path reduce/serve/stream use —
        # a scan flag can never silently diverge from the fingerprint
        # (the probe supplies RawReducer's own defaults for every knob
        # scan doesn't expose).
        from blit.pipeline import RawReducer

        probe = RawReducer(nfft=args.nfft, nint=args.nint,
                           stokes=args.stokes, fqav_by=args.fqav,
                           dtype=args.dtype)
        probe_prov = probe.tuning_provenance()
        # The sharded plane's rotation depths resolve from the SAME
        # profile (unless BLIT_MESH_PREFETCH/BLIT_MESH_OUT_DEPTH pinned
        # them) — the "tuning profiles resolved per-rig as today" rule.
        for knob in ("prefetch_depth", "out_depth"):
            if (depths[knob] is None
                    and probe_prov["sources"][knob] == "profile"):
                depths[knob] = getattr(probe, knob)
        if probe_prov["sources"]["chunk_frames"] == "profile":
            wf = probe.chunk_frames
            prov = probe_prov["profile"]
            prov["profile_source"] = prov.pop("source")
            tuning = {"source": "profile", **prov}
            # The profile's chunk_frames was converged on the REDUCE
            # path, whose per-dispatch overhead is lighter than scan's
            # per-window mesh stitch + readback sync — a profile far
            # below scan's own default shrinks windows enough to let
            # that overhead dominate.  Keep the profile (the operator
            # tuned this rig) but say so, loudly and in the stats line.
            default_wf = default_window_frames(args.nfft)
            if wf * 16 < default_wf:
                import logging

                tuning["window_vs_default"] = {"window_frames": wf,
                                               "default": default_wf}
                logging.getLogger("blit.scan").warning(
                    "tuning profile sets window_frames=%d, far below the "
                    "scan default of %d for nfft=%d; if per-window "
                    "overhead dominates, pass --window-frames explicitly "
                    "or re-run `blit tune` at scan-scale chunk_frames",
                    wf, default_wf, args.nfft)
        else:
            wf = default_window_frames(args.nfft)
            tuning = {"source": "default"}
    else:
        wf = args.window_frames
    wf = max((wf // args.nint) * args.nint, args.nint)
    tl = Timeline()
    parallel = "sharded" if sharded else ("pool" if args.pool else "mesh")
    if args.search:
        if args.resume:
            # Whole-scan search has no resume machinery (the per-file
            # `blit search --resume` path does) — refuse loudly rather
            # than silently re-running a crashed pod search from frame 0.
            raise SystemExit(
                "--resume is not supported with scan --search; re-run "
                "fresh, or use `blit search --resume` per player"
            )
        # Filterbank-product knobs the search planes cannot honor
        # (DedopplerReducer searches Stokes-I unaveraged spectra; .hits
        # are JSON lines): refuse loudly, like --resume above, instead
        # of writing a product the flags pretend to have shaped.
        if args.stokes != "I":
            raise SystemExit("--stokes is not supported with --search "
                             "(drift search runs on Stokes I)")
        if args.fqav != 1:
            raise SystemExit("--fqav is not supported with --search "
                             "(the drift transform needs full-resolution "
                             "fine channels)")
        if args.compression is not None:
            raise SystemExit("--compression applies to .h5 filterbank "
                             "products, not .hits")
        # Effective window: whole search windows (window_spectra * nint
        # frames each), resolved through the SAME reducer knob path both
        # search planes use — so the stats line reports what actually
        # executed and the two paths dispatch at identical shapes.
        from blit.search import DedopplerReducer

        probe = DedopplerReducer(
            nfft=args.nfft, nint=args.nint, dtype=args.dtype,
            window_spectra=args.window_spectra,
        )
        unit = probe.window_spectra * args.nint
        wf = max((wf // unit) * unit, unit)
        if args.pool:
            if args.max_frames is not None:
                # DedopplerReducer searches whole recordings; silently
                # dropping the cap would also break the sharded-vs-pool
                # byte-identity diff this path exists to provide.
                raise SystemExit(
                    "--max-frames is not supported with --pool --search "
                    "(the per-player reducers search whole recordings)"
                )
            from blit.observability import profile_trace

            with profile_trace(args.trace_logdir):
                written = _pool_scan_search(args, invs, wf, tl)
        else:
            # The sharded search plane: every chip searches its own
            # frequency slice; per-player .hits products (ISSUE 9).
            from blit.parallel.sharded import search_scan_sharded_to_files

            parallel = "sharded"
            written = search_scan_sharded_to_files(
                args.session, args.scan, inventories=invs,
                out_dir=args.output_dir, nfft=args.nfft, nint=args.nint,
                dtype=args.dtype, window_spectra=args.window_spectra,
                top_k=args.top_k, snr_threshold=args.snr,
                max_drift_bins=args.max_drift_bins, kernel=args.kernel,
                interpret=args.interpret, window_frames=wf,
                max_frames=args.max_frames, timeline=tl,
                trace_logdir=args.trace_logdir, **depths,
            )
        for player, (path, hdr) in sorted(written.items()):
            print(json.dumps({
                "player": list(player), "output": path,
                "windows": hdr.get("search_windows"),
                "nchans": hdr.get("nchans"),
            }))
        print(json.dumps({"window_frames": wf, "parallel": parallel,
                          "tuning": tuning, "stages": tl.report()}))
        return 0
    kw = dict(
        inventories=invs,
        out_dir=args.output_dir,
        nfft=args.nfft,
        nint=args.nint,
        stokes=args.stokes,
        fqav_by=args.fqav,
        despike=not args.no_despike,
        window_frames=wf,
        max_frames=args.max_frames,
        compression=args.compression,
        dtype=args.dtype,
        timeline=tl,
    )
    if args.pool:
        if args.resume:
            raise SystemExit(
                "--resume applies to the mesh/sharded paths; the pool "
                "fallback re-runs whole per-bank reductions"
            )
        # The pool oracle honors --trace-logdir like every other scan
        # path — wrapped here because the library call itself takes no
        # trace knob (it is plain host-looped reducers).
        from blit.observability import profile_trace

        with profile_trace(args.trace_logdir):
            written = reduce_scan_pool_to_files(args.session, args.scan,
                                                **kw)
    elif sharded:
        from blit.parallel.sharded import reduce_scan_sharded_to_files

        written = reduce_scan_sharded_to_files(
            args.session, args.scan, resume=args.resume,
            trace_logdir=args.trace_logdir, **depths, **kw,
        )
    else:
        written = reduce_scan_mesh_to_files(
            args.session, args.scan, resume=args.resume,
            trace_logdir=args.trace_logdir, **kw,
        )
    for band, (path, hdr) in sorted(written.items()):
        print(
            json.dumps(
                {
                    "band": band,
                    "output": path,
                    "nsamps": hdr.get("nsamps"),
                    "nchans": hdr.get("nchans"),
                    "fch1": hdr.get("fch1"),
                    "foff": hdr.get("foff"),
                }
            )
        )
    # Per-stage throughput (read/device/readback/write), like blit reduce.
    print(json.dumps({"window_frames": wf, "parallel": parallel,
                      "tuning": tuning, "stages": tl.report()}))
    return 0


def _pool_scan_search(args: argparse.Namespace, invs, wf: int, tl) -> dict:
    """The pool-path whole-scan search fallback/oracle: one
    :class:`blit.search.DedopplerReducer` per (band, bank) player, each
    writing its own ``.hits`` — the per-player twin of
    ``search_scan_sharded_to_files`` (same dispatch shapes via
    ``chunk_frames=window_frames``, so the products are byte-identical;
    tests/test_sharded.py).

    Oracle scope: each reducer searches its player's WHOLE recording,
    so byte-identity to the sharded path holds when the players share a
    common whole-window span (the recorded case).  Ragged recordings
    diverge by design — the sharded path truncates every player to the
    pod-agreed minimum span; ``--max-frames`` is rejected here for the
    same reason (the caller raises before dispatch)."""
    import os

    from blit.inventory import scan_grid
    from blit.search import DedopplerReducer

    band_ids, _, grid = scan_grid(invs, args.session, args.scan)
    # ``wf`` arrives already rounded to whole search windows by
    # _cmd_scan (the sharded path's own rounding), so chunk_frames
    # dispatches at the identical shapes byte-identity assumes.
    written = {}
    for b, row in enumerate(grid):
        for k, rp in enumerate(row):
            red = DedopplerReducer(
                nfft=args.nfft, nint=args.nint, dtype=args.dtype,
                window_spectra=args.window_spectra, top_k=args.top_k,
                snr_threshold=args.snr,
                max_drift_bins=args.max_drift_bins, kernel=args.kernel,
                interpret=args.interpret, chunk_frames=wf, timeline=tl,
            )
            out = os.path.join(
                args.output_dir, f"band{band_ids[b]}bank{k}.hits"
            )
            hdr = red.search_to_file(rp, out)
            written[(band_ids[b], k)] = (out, hdr)
    return written


def _cmd_inventory(args: argparse.Namespace) -> int:
    from blit.inventory import get_inventory, raw_sequences

    records = get_inventory(
        args.file_re,
        root=args.root,
        session_re=args.session_re,
        extra=args.extra,
    )
    if args.sequences:
        for rec, paths in raw_sequences(records):
            print(json.dumps({"stem_of": rec._asdict(), "files": paths}))
        return 0
    for rec in records:
        print(json.dumps(rec._asdict()))
    return 0


def _cmd_fleet_peer(args: argparse.Namespace) -> int:
    """``blit fleet-peer`` (ISSUE 14): one serving peer of the fleet —
    a ProductService behind the HTTP wire (``/product``, ``/warm``,
    ``/stats``, ``/healthz``, ``/metrics``, ``/drain``), beating a
    heartbeat lease the front door watches.  SIGTERM/SIGINT drain
    gracefully: refuse new work, finish in-flight (releasing live
    capacity holds), then exit.  ``--port 0`` binds an ephemeral port,
    published via ``--port-file`` (atomic write) for the spawner."""
    import os
    import threading

    from blit.config import DEFAULT
    from blit.observability import Timeline
    from blit.serve import ProductCache, ProductService, Scheduler
    from blit.serve.http import PeerServer, install_drain_handler

    tl = Timeline()
    # Archive plane (ISSUE 19): --catalog-root arms the peer's catalog
    # (kind="catalog" asks + local session=/scan= resolution);
    # --cold-dir/--disk-bytes arm the tiered store behind the hot disk
    # cache.  Flags override the env/config defaults.
    config = DEFAULT
    if args.catalog_root:
        config = config.with_(catalog_root=args.catalog_root)
    if args.cold_dir:
        config = config.with_(cache_cold_dir=args.cold_dir)
    from blit.config import archive_defaults

    service = ProductService(
        cache=ProductCache(args.cache_dir, ram_bytes=args.ram_bytes,
                           disk_bytes=args.disk_bytes,
                           cold_dir=archive_defaults(config)["cold_dir"],
                           timeline=tl),
        scheduler=Scheduler(max_concurrency=args.concurrency,
                            queue_depth=args.queue_depth, timeline=tl,
                            retry_seed=args.retry_seed),
        timeline=tl,
        config=config,
    )
    server = PeerServer(service, name=args.name, port=args.port,
                        host=args.host,
                        lease_dir=args.lease_dir, proc=args.proc,
                        beat_interval_s=args.beat_interval).start()
    stop = threading.Event()

    def _drain():
        server.drain(timeout=args.drain_timeout)
        stop.set()

    uninstall = install_drain_handler(_drain, exit_after=False)
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(server.port))
        os.replace(tmp, args.port_file)
    # --standby (ISSUE 17): the peer itself serves identically — it is
    # the FRONT DOOR that keeps a standby out of the ring until the
    # elastic controller admits it.  The flag rides the bring-up line
    # so spawners and operators see the role the process was given.
    print(json.dumps({"name": args.name, "url": server.url,
                      "pid": os.getpid(), "lease_dir": args.lease_dir,
                      "proc": args.proc,
                      "standby": bool(args.standby)}), flush=True)
    try:
        stop.wait()
    except KeyboardInterrupt:
        service.drain(timeout=args.drain_timeout)
    uninstall()
    server.close()
    service.close()
    return 0


def _spawn_fleet_peers(td: str, npeers: int, *, concurrency: int,
                       queue_depth: int, ram_bytes: int,
                       beat_interval_s: float = 0.2,
                       bringup_timeout_s: float = 120.0,
                       standbys: int = 0,
                       extra_env: Optional[dict] = None,
                       catalog_root: Optional[str] = None,
                       cold_dirs: bool = False,
                       disk_bytes: Optional[int] = None):
    """Bring up ``npeers`` REAL ``blit fleet-peer`` subprocesses (the
    bench/chaos rig): per-peer cache dirs + one shared lease dir under
    ``td``, ephemeral ports published through port files.  Returns
    ``(procs, peers, lease_dir)`` with ``procs`` a list of
    ``(Popen, logfile)`` pairs and ``peers`` the name→url map the
    front door takes.

    ``standbys`` additionally spawns that many ``--standby`` peers
    (ISSUE 17): named ``standby{j}``, lease proc ``npeers + j``,
    appended to both ``procs`` and ``peers`` — the caller registers
    them via ``door.add_standby`` instead of the ring-seeding map.

    Archive plane (ISSUE 19): ``catalog_root`` arms every peer's
    catalog, ``cold_dirs`` gives each peer a per-peer cold tier under
    ``td``, ``disk_bytes`` caps the hot disk tier (what forces
    demotion)."""
    import os
    import subprocess
    import time as _time

    from blit.serve.http import wait_http_ready

    lease_dir = os.path.join(td, "leases")
    names = [f"peer{i}" for i in range(npeers)]
    names += [f"standby{j}" for j in range(max(0, standbys))]
    procs, peers = [], {}
    for i, name in enumerate(names):
        port_file = os.path.join(td, f"{name}.port")
        cmd = [sys.executable, "-m", "blit", "fleet-peer",
               "--name", name,
               "--cache-dir", os.path.join(td, f"cache-{name}"),
               "--lease-dir", lease_dir, "--proc", str(i),
               "--port", "0", "--port-file", port_file,
               "--concurrency", str(concurrency),
               "--queue-depth", str(queue_depth),
               "--ram-bytes", str(ram_bytes),
               "--beat-interval", str(beat_interval_s),
               "--retry-seed", str(i)]
        if catalog_root:
            cmd += ["--catalog-root", catalog_root]
        if cold_dirs:
            cmd += ["--cold-dir", os.path.join(td, f"cold-{name}")]
        if disk_bytes is not None:
            cmd += ["--disk-bytes", str(disk_bytes)]
        if i >= npeers:
            cmd.append("--standby")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(extra_env or {})
        logf = open(os.path.join(td, f"{name}.log"), "w")
        procs.append((subprocess.Popen(cmd, stdout=logf, stderr=logf,
                                       env=env), logf))
    try:
        for i, name in enumerate(names):
            port_file = os.path.join(td, f"{name}.port")
            deadline = _time.monotonic() + bringup_timeout_s
            while not os.path.exists(port_file):
                if procs[i][0].poll() is not None:
                    raise RuntimeError(
                        f"{name} died at bring-up "
                        f"(rc={procs[i][0].returncode}; see {name}.log)")
                if _time.monotonic() > deadline:
                    raise TimeoutError(f"{name} port file never appeared")
                _time.sleep(0.05)
            with open(port_file) as f:
                url = f"http://127.0.0.1:{int(f.read().strip())}"
            wait_http_ready(url, timeout_s=bringup_timeout_s)
            peers[name] = url
    except BaseException:
        _reap_fleet_peers(procs)
        raise
    return procs, peers, lease_dir


def _reap_fleet_peers(procs) -> None:
    """Terminate (then kill) peer subprocesses and close their logs —
    every exit path of the bench/chaos rigs."""
    for p, _ in procs:
        if p.poll() is None:
            p.terminate()
    for p, logf in procs:
        try:
            p.wait(timeout=10)
        except Exception:  # noqa: BLE001 — escalate to SIGKILL
            p.kill()
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001 — nothing left to do
                pass
        try:
            logf.close()
        except OSError:
            pass


def _cmd_serve_bench(args: argparse.Namespace) -> int:
    """Drive a ProductService with a zipfian request replay — the serving
    layer's dispatch-overhead probe (ISSUE 3): most traffic re-asks for a
    few hot products, so the report's hit-rate/coalesce/queue-wait numbers
    are what a multi-tenant deployment would see.  ``--fleet`` replays
    the same mix through a REAL multi-process fleet front door instead
    (ISSUE 14): N ``fleet-peer`` subprocesses behind consistent-hash
    routing, reporting per-tier hit-rate, SLO attainment and the hedge
    counters."""
    import math
    import os
    import random
    import tempfile
    import threading
    import time as _time

    from blit.observability import Timeline
    from blit.serve import (
        Overloaded,
        ProductCache,
        ProductRequest,
        ProductService,
        Scheduler,
    )
    from blit.serve.http import install_drain_handler
    from blit.testing import synth_raw

    if args.archive_day:
        return _serve_bench_archive_day(args)
    if args.diurnal:
        return _serve_bench_diurnal(args)
    if args.fleet:
        return _serve_bench_fleet(args)
    from blit.config import DEFAULT

    with tempfile.TemporaryDirectory(prefix="blit-serve-bench-") as td:
        # Distinct products = distinct synthetic recordings (tiny: the
        # bench measures the serving layer, not the channelizer).
        ntime = (8 + 3) * args.nfft  # 8 PFB frames at ntap=4
        reqs = []
        for i in range(args.distinct):
            path = os.path.join(td, f"bench{i:03d}.raw")
            synth_raw(path, nblocks=1, obsnchan=2, ntime_per_block=ntime,
                      seed=i)
            reqs.append(ProductRequest(raw=path, nfft=args.nfft, nint=1))
        # Zipfian popularity over the distinct products: p(k) ∝ 1/(k+1)^s
        # — one pick sequence, replayed identically by every pass so the
        # request-log A/B compares the same workload.
        rng = random.Random(args.seed)
        weights = [1.0 / math.pow(k + 1, args.zipf_s)
                   for k in range(args.distinct)]
        picks = rng.choices(range(args.distinct), weights=weights,
                            k=args.requests)

        def one_pass(request_log_dir, pass_id: int = 0) -> dict:
            tl = Timeline()
            cache_dir = (os.path.join(td, f"cache{pass_id}")
                         if args.disk_cache else None)
            # Pin the env for this pass's service construction: an
            # ambient BLIT_REQUEST_LOG would override the config and
            # silently invalidate the off/on A/B ("" = disabled, the
            # request_log_defaults encoding).
            prev = os.environ.get("BLIT_REQUEST_LOG")
            os.environ["BLIT_REQUEST_LOG"] = request_log_dir or ""
            try:
                service = ProductService(
                    cache=ProductCache(cache_dir,
                                       ram_bytes=args.ram_bytes,
                                       timeline=tl),
                    scheduler=Scheduler(max_concurrency=args.concurrency,
                                        queue_depth=args.queue_depth,
                                        timeline=tl,
                                        retry_seed=args.seed),
                    timeline=tl,
                    config=DEFAULT.with_(
                        request_log_dir=request_log_dir),
                )
            finally:
                if prev is None:
                    os.environ.pop("BLIT_REQUEST_LOG", None)
                else:
                    os.environ["BLIT_REQUEST_LOG"] = prev
            # Graceful-shutdown satellite (ISSUE 14): SIGTERM/SIGINT
            # drains the scheduler — in-flight jobs finish, queued ones
            # deliver Cancelled, and kind="stream" capacity holds
            # release instead of leaking on interpreter exit.
            uninstall_signals = install_drain_handler(
                lambda: service.drain(timeout=30.0))
            errors: list = []
            rejected = [0]
            lock = threading.Lock()
            it = iter(picks)

            def client_loop(cid: int) -> None:
                while True:
                    with lock:
                        k = next(it, None)
                    if k is None:
                        return
                    try:
                        service.get(reqs[k], timeout=120,
                                    client=f"client{cid}")
                    except Overloaded:
                        with lock:
                            rejected[0] += 1
                    except Exception as e:  # noqa: BLE001 — reported
                        with lock:
                            errors.append(repr(e))

            t0 = _time.perf_counter()
            threads = [threading.Thread(target=client_loop, args=(c,))
                       for c in range(args.clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = _time.perf_counter() - t0
            uninstall_signals()
            service.close()
            stats = service.stats()
            qw = stats["queue_wait"]
            rep = {
                "requests": args.requests,
                "distinct": args.distinct,
                "clients": args.clients,
                "zipf_s": args.zipf_s,
                "wall_s": round(wall, 3),
                "hit_rate": stats["hit_rate"],
                "coalesced": stats["coalesced"],
                "scheduled": stats["scheduled"],
                "rejected_overloaded": rejected[0],
                "queue_wait_p50_s": round(qw["p50"], 6),
                "queue_wait_p99_s": round(qw["p99"], 6),
                "cache": stats["cache"],
                # Latency distributions (ISSUE 5): the bounded
                # histograms the serving timeline accumulated — tails,
                # not averages.
                "hists": tl.report().get("hists", {}),
                "errors": errors[:5],
            }
            if request_log_dir:
                from blit import monitor

                recs = monitor.read_requests(request_log_dir)
                rep["request_log"] = monitor.aggregate_requests(recs)
            return rep

        if args.request_log_compare:
            # The ISSUE 15 A/B (the --spans-compare discipline): the
            # identical replay with request logging DISABLED then
            # ENABLED — the report pins the disabled pass's record
            # count at zero and prices the enabled pass.  An untimed
            # warmup pass absorbs the XLA compiles first, so off/on
            # compare warm against warm instead of cold against warm.
            one_pass(None, 9)
            off = one_pass(None, 0)
            log_dir = args.request_log or os.path.join(td, "reqlog")
            # Measured, not assumed: the disabled pass must have
            # written NOTHING anywhere under the bench root.
            import glob as _glob

            off_records = len(_glob.glob(
                os.path.join(td, "**", "requests-*.jsonl*"),
                recursive=True))
            on = one_pass(log_dir, 1)
            overhead = (on["wall_s"] / off["wall_s"] - 1.0
                        if off["wall_s"] else 0.0)
            print(json.dumps({
                "request_log_compare": True,
                "off_wall_s": off["wall_s"],
                "on_wall_s": on["wall_s"],
                "overhead_pct": round(overhead * 100.0, 2),
                "off_records": off_records,
                "on_records": (on.get("request_log") or {}).get(
                    "records", 0),
                "off": off,
                "on": on,
            }))
            return 1 if off["errors"] or on["errors"] else 0
        rep = one_pass(args.request_log, 0)
        print(json.dumps(rep))
        return 1 if rep["errors"] else 0


def _serve_bench_fleet(args: argparse.Namespace) -> int:
    """``serve-bench --fleet`` (ISSUE 14): replay the zipfian mix at
    accelerated clock through a REAL fleet — N ``fleet-peer``
    subprocesses behind an in-process :class:`FleetFrontDoor` (the HTTP
    hop is at the peer boundary, where the bytes actually move).  The
    report is what a deployment watches: per-tier hit-rate across the
    fleet, SLO attainment against ``--slo-ms``, request p50/p99, and
    the hedge/failover counters with the duplicate-compute bound."""
    import math
    import os
    import random
    import tempfile
    import threading
    import time as _time

    from blit import monitor, observability
    from blit.config import DEFAULT
    from blit.observability import HistogramStats, Timeline
    from blit.serve import Overloaded, ProductRequest
    from blit.serve.fleet import FleetFrontDoor
    from blit.serve.http import (
        WIRE_CTYPE,
        WIRE_HEADER,
        decode_product,
        decode_product_wire,
        http_json,
        http_request,
        install_drain_handler,
        wire_request,
    )
    from blit.serve.scheduler import DeadlineExpired
    from blit.testing import synth_raw

    rng = random.Random(args.seed)
    tl = Timeline()
    with tempfile.TemporaryDirectory(prefix="blit-fleet-bench-") as td:
        ntime = (8 + 3) * args.nfft  # 8 PFB frames at ntap=4
        reqs = []
        for i in range(args.distinct):
            path = os.path.join(td, f"bench{i:03d}.raw")
            synth_raw(path, nblocks=1, obsnchan=2, ntime_per_block=ntime,
                      seed=i)
            reqs.append(ProductRequest(raw=path, nfft=args.nfft, nint=1))
        # Request observability is ON for the fleet replay (ISSUE 15):
        # the report's p50/p99 come from the access records, and the
        # peers inherit the spool dir through their environment.  The
        # door's env is pinned too — an ambient BLIT_REQUEST_LOG would
        # override the config and send its records elsewhere.
        reqlog_dir = args.request_log or os.path.join(td, "reqlog")
        os.environ["BLIT_REQUEST_LOG"] = reqlog_dir
        procs, peers, lease_dir = _spawn_fleet_peers(
            td, args.peers, concurrency=args.concurrency,
            queue_depth=args.queue_depth, ram_bytes=args.ram_bytes,
            extra_env={"BLIT_REQUEST_LOG": reqlog_dir})
        door = FleetFrontDoor(
            peers, lease_dir=lease_dir, timeline=tl,
            replicas=args.replicas, peer_ttl_s=args.peer_ttl,
            poll_s=min(0.1, args.peer_ttl / 4),
            hedge_floor_s=args.hedge_floor_ms / 1e3,
            request_timeout_s=60.0,
            config=DEFAULT.with_(request_log_dir=reqlog_dir)).start()
        uninstall = install_drain_handler(lambda: door.drain())
        weights = [1.0 / math.pow(k + 1, args.zipf_s)
                   for k in range(args.distinct)]
        picks = rng.choices(range(args.distinct), weights=weights,
                            k=args.requests)
        lat = HistogramStats()
        slo_s = args.slo_ms / 1e3
        lock = threading.Lock()
        attained = [0]
        rejected = [0]
        expired = [0]
        errors: list = []
        it = iter(picks)

        def client_loop(cid: int) -> None:
            while True:
                with lock:
                    k = next(it, None)
                if k is None:
                    return
                t = _time.perf_counter()
                ok = False
                try:
                    door.get(reqs[k], client=f"client{cid}",
                             deadline_s=args.deadline)
                    ok = True
                except DeadlineExpired:
                    with lock:
                        expired[0] += 1
                except Overloaded as e:
                    with lock:
                        rejected[0] += 1
                    _time.sleep(min(0.25, e.retry_after_s))
                except Exception as e:  # noqa: BLE001 — reported below
                    with lock:
                        errors.append(repr(e))
                dt = _time.perf_counter() - t
                lat.observe(dt)
                # SLO attainment counts SERVED requests only: a fleet
                # that 503s everything in a millisecond must read as
                # 0% attained, not 100%.
                if ok and dt <= slo_s:
                    with lock:
                        attained[0] += 1

        try:
            t0 = _time.perf_counter()
            threads = [threading.Thread(target=client_loop, args=(c,))
                       for c in range(args.clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = _time.perf_counter() - t0
            tiers = {"hit.ram": 0, "hit.disk": 0, "miss": 0}
            per_peer = {}
            for name, url in sorted(peers.items()):
                try:
                    _, _, s = http_json("GET", url, "/stats", timeout=5.0)
                except OSError:
                    continue
                c = (s.get("cache") or {})
                for k in tiers:
                    tiers[k] += int(c.get(k, 0))
                per_peer[name] = {
                    "hit_rate": s.get("hit_rate"),
                    "scheduled": s.get("scheduled"),
                    "coalesced": s.get("coalesced"),
                }
            served_tier = tiers["hit.ram"] + tiers["hit.disk"]
            total_tier = served_tier + tiers["miss"]
            # Wire back-compat probe (ISSUE 16): ONE explicit
            # legacy-JSON request and ONE binary request against the
            # same peer for the same product — the CI smoke pins that
            # the binary frame was actually negotiated somewhere AND
            # that a client which never sends the binary Accept still
            # gets the identical bytes.
            try:
                probe_doc = json.dumps(wire_request(
                    reqs[0], client="compat")).encode()
                purl = sorted(peers.values())[0]
                st_j, hdr_j, pay_j = http_request(
                    "POST", purl, "/product", body=probe_doc,
                    headers={"Content-Type": "application/json"},
                    timeout=60.0)
                st_b, hdr_b, pay_b = http_request(
                    "POST", purl, "/product", body=probe_doc,
                    headers={"Content-Type": "application/json",
                             "Accept": f"{WIRE_CTYPE}, application/json"},
                    timeout=60.0)
                _, dj = decode_product(json.loads(pay_j))
                _, db = decode_product_wire(
                    pay_b, encoding=hdr_b.get("content-encoding"))
                compat = {
                    "legacy_wire": hdr_j.get(WIRE_HEADER.lower()),
                    "binary_wire": hdr_b.get(WIRE_HEADER.lower()),
                    "byte_identical": bool(
                        st_j == 200 and st_b == 200
                        and dj.dtype == db.dtype
                        and dj.shape == db.shape
                        and dj.tobytes() == db.tobytes()),
                }
            except Exception as e:  # noqa: BLE001 — probe is advisory
                compat = {"error": repr(e)}
            # Fleet trace harvest (ISSUE 15 tentpole #4): stitch the
            # peers' span batches (their live /snapshot endpoints, with
            # histogram exemplars) and the door's own spans/hists into
            # ONE reviewable artifact — the Perfetto export plus a raw
            # .snapshot.json that `blit trace-view --fleet` reads after
            # the peers are gone.
            trace_block = None
            if args.trace_out:
                spans, hists = monitor.gather_trace_sources(
                    list(peers.values()))
                seen_ids = {s.get("span") for s in spans}
                spans.extend(s for s in observability.tracer().span_dicts()
                             if s.get("span") not in seen_ids)
                for k, h in list(tl.hists.items()):
                    if k in hists:
                        hists[k].merge(h)
                    else:
                        hists[k] = HistogramStats.from_state(h.state())
                stitcher = observability.Tracer(
                    max_spans=max(1, len(spans)), enabled=True)
                stitcher.ingest(spans)
                stitcher.export_chrome(args.trace_out)
                snap_path = args.trace_out + ".snapshot.json"
                with open(snap_path, "w") as f:
                    json.dump({"spans": spans,
                               "hists": {k: h.state()
                                         for k, h in hists.items()}}, f)
                trace_block = dict(observability.trace_summary(spans),
                                   out=args.trace_out,
                                   snapshot=snap_path)
            # The report's latency quantiles come from the ACCESS
            # RECORDS (ISSUE 15 satellite): what the door actually
            # logged per request, not a separate in-bench stopwatch.
            all_recs = monitor.read_requests(reqlog_dir)
            door_agg = monitor.aggregate_requests(
                monitor.filter_requests(all_recs, role="door"))
            fstats = door.stats()
            c = fstats["counters"]
            hedges = c.get("fleet.hedge", 0)
            report = {
                "fleet": True,
                "requests": args.requests,
                "distinct": args.distinct,
                "clients": args.clients,
                "peers": args.peers,
                "replicas": args.replicas,
                "zipf_s": args.zipf_s,
                "wall_s": round(wall, 3),
                "rps": round(args.requests / wall, 1) if wall else None,
                "tiers": tiers,
                "hit_rate": (round(served_tier / total_tier, 4)
                             if total_tier else 0.0),
                "hit_rate_ram": (round(tiers["hit.ram"] / total_tier, 4)
                                 if total_tier else 0.0),
                "hit_rate_disk": (round(tiers["hit.disk"] / total_tier, 4)
                                  if total_tier else 0.0),
                "slo": {"target_s": slo_s,
                        "attained": round(attained[0] / args.requests, 4)
                        if args.requests else None},
                "request_p50_s": round(lat.percentile(0.50), 6),
                "request_p99_s": round(lat.percentile(0.99), 6),
                "hedge": {
                    "hedges": hedges,
                    "wins": c.get("fleet.hedge.win", 0),
                    "dup_done": c.get("fleet.hedge.dup_done", 0),
                    "rate": (round(hedges / args.requests, 4)
                             if args.requests else 0.0),
                    # The acceptance bound: each hedge adds at most ONE
                    # duplicate dispatch, so compute on the hedged slice
                    # is <= 2x by construction; dup_ratio reports how
                    # much actually ran to completion.
                    "dup_ratio": (round(
                        c.get("fleet.hedge.dup_done", 0) / hedges, 4)
                        if hedges else 0.0),
                },
                "failovers": c.get("fleet.failover", 0),
                # The hot-path data plane (ISSUE 16): which wire each
                # peer answer rode, the keep-alive pool's reuse ratio,
                # and the negotiation/back-compat probe CI asserts on.
                "wire": {
                    "mode": fstats.get("wire"),
                    "binary_responses": c.get("fleet.wire.binary", 0),
                    "json_responses": c.get("fleet.wire.json", 0),
                    "wire_gb": round(
                        tl.hists["fleet.wire_bytes"].total / 1e9, 6)
                    if "fleet.wire_bytes" in tl.hists else 0.0,
                    "pool": {
                        "open": c.get("fleet.pool.open", 0),
                        "reuse": c.get("fleet.pool.reuse", 0),
                        "evict": c.get("fleet.pool.evict", 0),
                        "idle": fstats.get("pool"),
                    },
                    "compat": compat,
                },
                "rejected_overloaded": rejected[0],
                "deadline_expired": expired[0],
                "per_peer": per_peer,
                "request_log": {
                    "dir": reqlog_dir,
                    "records": len(all_recs),
                    "door_records": door_agg["records"],
                    "p50_s": door_agg["p50_s"],
                    "p99_s": door_agg["p99_s"],
                    "by_status": door_agg["by_status"],
                    "by_tier": door_agg["by_tier"],
                },
                "errors": errors[:5],
            }
            if trace_block is not None:
                report["trace"] = trace_block
            print(json.dumps(report))
        finally:
            uninstall()
            door.close()
            _reap_fleet_peers(procs)
    return 1 if errors else 0


def _serve_bench_diurnal(args: argparse.Namespace) -> int:
    """``serve-bench --diurnal`` (ISSUE 17 tentpole #4): day-shaped
    load at accelerated clock over a REAL fleet with the ELASTIC
    controller in the loop.  Each cycle is one diurnal swing: a peak
    burst that should page the burn-rate evaluator into a scale-out
    (warm handoff → membership flip; forced through the manual lever
    when the rig serves the peak inside the SLO, and the report says
    which lever moved), a post-resize probe that pins the hit-rate
    within 10% of the pre-resize probe, then a trough of idle
    controller ticks that drains the coldest peer back out.  The
    report asserts what the acceptance gates on: SLO attainment
    through all the resizes, the hit-rate bound per cycle, and ZERO
    requests routed to a departed peer."""
    import math
    import os
    import random
    import tempfile
    import threading
    import time as _time

    from blit.monitor import BurnRateEvaluator, SLObjective
    from blit.observability import HistogramStats, Timeline
    from blit.serve import Overloaded, ProductRequest
    from blit.serve.elastic import FleetController
    from blit.serve.fleet import FleetError, FleetFrontDoor
    from blit.serve.http import http_json, install_drain_handler
    from blit.serve.scheduler import DeadlineExpired
    from blit.testing import synth_raw

    rng = random.Random(args.seed)
    tl = Timeline()
    cycles = max(1, args.cycles)
    standbys = args.standbys if args.standbys is not None else cycles
    report: dict = {"diurnal": True, "cycles": cycles,
                    "peers": args.peers, "standbys": standbys,
                    "replicas": args.replicas, "distinct": args.distinct,
                    "clients": args.clients, "zipf_s": args.zipf_s}
    ok = False
    with tempfile.TemporaryDirectory(prefix="blit-diurnal-") as td:
        ntime = (8 + 3) * args.nfft  # 8 PFB frames at ntap=4
        reqs = []
        for i in range(args.distinct):
            path = os.path.join(td, f"bench{i:03d}.raw")
            synth_raw(path, nblocks=1, obsnchan=2, ntime_per_block=ntime,
                      seed=i)
            reqs.append(ProductRequest(raw=path, nfft=args.nfft, nint=1))
        procs, peers, lease_dir = _spawn_fleet_peers(
            td, args.peers, concurrency=args.concurrency,
            queue_depth=args.queue_depth, ram_bytes=args.ram_bytes,
            standbys=standbys)
        names = [f"peer{i}" for i in range(args.peers)]
        standby_names = [f"standby{j}" for j in range(standbys)]
        proc_of = {nm: procs[i][0]
                   for i, nm in enumerate(names + standby_names)}
        door = FleetFrontDoor(
            {nm: peers[nm] for nm in names}, lease_dir=lease_dir,
            timeline=tl, replicas=args.replicas,
            peer_ttl_s=args.peer_ttl, poll_s=min(0.1, args.peer_ttl / 4),
            hedge_floor_s=args.hedge_floor_ms / 1e3,
            request_timeout_s=60.0).start()
        for j, nm in enumerate(standby_names):
            door.add_standby(nm, peers[nm], proc=args.peers + j)

        def terminate(nm: str) -> None:
            """The scale-in epilogue: SIGTERM the retired child — the
            peer's drain handler finishes in-flight work and exits."""
            p = proc_of.get(nm)
            if p is not None and p.poll() is None:
                p.terminate()

        uninstall = install_drain_handler(lambda: door.drain())
        weights = [1.0 / math.pow(k + 1, args.zipf_s)
                   for k in range(args.distinct)]
        slo_s = args.slo_ms / 1e3
        lat = HistogramStats()
        lock = threading.Lock()
        counts = {"issued": 0, "served": 0, "attained": 0,
                  "rejected": 0, "expired": 0}
        errors: list = []

        def run_burst(n: int, record: bool = True) -> None:
            picks = rng.choices(range(args.distinct), weights=weights,
                                k=n)
            it = iter(picks)

            def worker(cid: int) -> None:
                while True:
                    with lock:
                        k = next(it, None)
                    if k is None:
                        return
                    t = _time.perf_counter()
                    got, err = False, None
                    for _attempt in range(4):
                        try:
                            door.get(reqs[k], client=f"diurnal{cid}")
                            got = True
                            break
                        except DeadlineExpired:
                            with lock:
                                counts["expired"] += 1
                            break
                        except Overloaded as e:
                            with lock:
                                counts["rejected"] += 1
                            _time.sleep(min(0.25, e.retry_after_s))
                        except (FleetError, OSError) as e:
                            # Transient while a flip/eject settles:
                            # back off a beat and retry, like a real
                            # client's loop.
                            err = repr(e)
                            _time.sleep(0.2)
                        except Exception as e:  # noqa: BLE001
                            err = repr(e)
                            break
                    if not got and err is not None:
                        with lock:
                            errors.append(err)
                    if not record:
                        continue
                    dt = _time.perf_counter() - t
                    lat.observe(dt)
                    with lock:
                        counts["issued"] += 1
                        if got:
                            counts["served"] += 1
                            if dt <= slo_s:
                                counts["attained"] += 1

            threads = [threading.Thread(target=worker, args=(c,))
                       for c in range(args.clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        def cache_totals() -> dict:
            out = {}
            for nm, p in sorted(door._peers.items()):
                try:
                    _, _, s = http_json("GET", p.url, "/stats",
                                        timeout=2.0, pool=door.pool)
                except OSError:
                    continue
                c = s.get("cache") or {}
                out[nm] = (c.get("hit.ram", 0) + c.get("hit.disk", 0),
                           c.get("miss", 0))
            return out

        def window_hit_rate(before: dict, after: dict):
            dh = dm = 0
            for nm, (h1, m1) in after.items():
                if nm not in before:
                    continue
                h0, m0 = before[nm]
                dh += max(0, h1 - h0)
                dm += max(0, m1 - m0)
            return (dh / (dh + dm)) if dh + dm else None

        peak_n = max(16, args.requests // 2)
        probe_n = max(12, args.requests // 4)
        tick_s = 30.0  # the accelerated clock: one tick "is" 30s of day
        forced = {"out": 0, "in": 0}
        departed: dict = {}
        cyc_reports: list = []
        ctl = None
        try:
            # Untimed warm-up: first-touch XLA compiles and cache fills
            # land OUTSIDE the SLO ledger, like a deployment bring-up.
            run_burst(args.requests, record=False)
            ev = BurnRateEvaluator(
                [SLObjective("fleet-latency", "fleet.request_s",
                             args.burn_threshold_ms / 1e3, budget=0.05)],
                fast_window=2, slow_window=4, fast_burn=4.0,
                slow_burn=2.0)
            ctl = FleetController(
                door, ev, feed=tl, terminate=terminate,
                idle_windows=args.idle_windows,
                hysteresis_s=args.hysteresis,
                warm_timeout_s=args.warm_timeout,
                min_peers=args.peers, poll_s=0.5)
            # Prime the feed baseline so the warm-up's latencies are
            # not the first tick's delta — the day starts NOW.
            ctl._feed_state = tl.state()
            t0 = _time.perf_counter()
            for c in range(cycles):
                ring_pre = sorted(door.ring.peers())
                # Pre-resize probe: the hit-rate the flip must not
                # crater (caches are warm from the previous swing).
                a0 = cache_totals()
                run_burst(probe_n)
                a1 = cache_totals()
                hit_pre = window_hit_rate(a0, a1)
                # -- PEAK: the day's load pages the evaluator.
                out_rec = None
                for _ in range(4):
                    run_burst(peak_n)
                    act = ctl.observe(interval_s=tick_s)
                    if act is not None and act["action"] == "scale-out":
                        out_rec = act
                        break
                organic_out = out_rec is not None
                if out_rec is None:
                    # A fast rig can serve the whole peak inside the
                    # SLO; force the flip so the resize contract is
                    # still exercised — the report says which lever.
                    out_rec = ctl.scale_out()
                    if out_rec is not None:
                        forced["out"] += 1
                # Post-resize probe: the warm-handoff dividend.
                b0 = cache_totals()
                run_burst(probe_n)
                b1 = cache_totals()
                hit_post = window_hit_rate(b0, b1)
                hit_ok = (hit_pre is not None and hit_post is not None
                          and hit_post >= hit_pre - 0.10)
                # -- TROUGH: sustained idle drains the coldest peer.
                _time.sleep(args.hysteresis)  # let the flap guard lapse
                in_rec = None
                for _ in range(args.idle_windows + 6):
                    act = ctl.observe(interval_s=tick_s)
                    if act is not None and act["action"] == "scale-in":
                        in_rec = act
                        break
                organic_in = in_rec is not None
                if in_rec is None:
                    in_rec = ctl.scale_in()
                    if in_rec is not None:
                        forced["in"] += 1
                if in_rec is not None:
                    victim = in_rec["peer"]
                    departed[victim] = door._peers[victim].requests
                _time.sleep(args.hysteresis)  # disarm before next peak
                cyc_reports.append({
                    "cycle": c,
                    "ring_pre": ring_pre,
                    "ring_post": sorted(door.ring.peers()),
                    "scale_out": out_rec,
                    "organic_out": organic_out,
                    "scale_in": in_rec,
                    "organic_in": organic_in,
                    "hit_rate_pre_resize": (round(hit_pre, 4)
                                            if hit_pre is not None
                                            else None),
                    "hit_rate_post_resize": (round(hit_post, 4)
                                             if hit_post is not None
                                             else None),
                    "hit_bound_ok": hit_ok,
                })
            wall = _time.perf_counter() - t0
            # ZERO requests to a departed peer: the per-peer request
            # counter of every retired peer must not have moved since
            # its retirement.
            requests_to_departed = sum(
                max(0, door._peers[nm].requests - snap)
                for nm, snap in departed.items())
            attain = (counts["attained"] / counts["issued"]
                      if counts["issued"] else None)
            slo_ok = attain is not None and attain >= args.slo_floor
            resizes_out = sum(1 for r in cyc_reports if r["scale_out"])
            resizes_in = sum(1 for r in cyc_reports if r["scale_in"])
            hit_ok_all = all(r["hit_bound_ok"] for r in cyc_reports)
            fstats = door.stats()
            cnt = fstats["counters"]
            rh = tl.hists.get("elastic.resize_s")
            wb = tl.hists.get("elastic.warm_bytes")
            ok = (resizes_out >= cycles and resizes_in >= cycles
                  and slo_ok and hit_ok_all
                  and requests_to_departed == 0 and not errors)
            report.update(
                requests=counts["issued"],
                served=counts["served"],
                wall_s=round(wall, 3),
                slo={"target_s": slo_s,
                     "attained": (round(attain, 4)
                                  if attain is not None else None),
                     "floor": args.slo_floor, "ok": slo_ok},
                request_p50_s=round(lat.percentile(0.50), 6),
                request_p99_s=round(lat.percentile(0.99), 6),
                scale_outs=resizes_out,
                scale_ins=resizes_in,
                forced_resizes=forced,
                requests_to_departed=requests_to_departed,
                hit_bound_ok=hit_ok_all,
                cycles_detail=cyc_reports,
                elastic={
                    "scale_out": cnt.get("elastic.scale_out", 0),
                    "scale_in": cnt.get("elastic.scale_in", 0),
                    "warm_timeout": cnt.get("elastic.warm_timeout", 0),
                    "flap_suppressed": cnt.get(
                        "elastic.flap_suppressed", 0),
                    "resize_p50_s": (round(rh.percentile(0.50), 6)
                                     if rh is not None else None),
                    "resize_p99_s": (round(rh.percentile(0.99), 6)
                                     if rh is not None else None),
                    "warm_bytes": int(wb.total) if wb is not None else 0,
                },
                controller=ctl.stats(),
                rejected_overloaded=counts["rejected"],
                deadline_expired=counts["expired"],
                errors=errors[:5],
            )
            # The flat scalar block bench-diff extracts and gates,
            # exactly like the ingest/archive-day records.
            report["metrics"] = {
                "diurnal.cycles": float(len(cyc_reports)),
                "diurnal.slo_attained": float(attain or 0.0),
                "diurnal.request_p50_s": report["request_p50_s"],
                "diurnal.request_p99_s": report["request_p99_s"],
                "diurnal.scale_out": float(cnt.get(
                    "elastic.scale_out", 0)),
                "diurnal.scale_in": float(cnt.get("elastic.scale_in", 0)),
                "diurnal.warm_timeouts": float(cnt.get(
                    "elastic.warm_timeout", 0)),
                "diurnal.requests_to_departed": float(
                    requests_to_departed),
                "diurnal.post_resize_min_hit_rate": float(min(
                    (r["hit_rate_post_resize"] for r in cyc_reports
                     if r["hit_rate_post_resize"] is not None),
                    default=0.0)),
            }
            report["ok"] = ok
            body = json.dumps(report)
            print(body)
            if args.out:
                with open(args.out, "w") as f:
                    f.write(body)
        finally:
            uninstall()
            if ctl is not None:
                ctl.close()
            door.close()
            _reap_fleet_peers(procs)
    return 0 if ok else 1


def _serve_bench_archive_day(args: argparse.Namespace) -> int:
    """``serve-bench --archive-day`` (ISSUE 16 tentpole #4, extended
    into the ISSUE 19 archive-plane proof): replay a zipfian
    MULTI-SESSION observing day at accelerated clock over REAL
    ``fleet-peer`` subprocesses serving a REAL on-disk archive tree.
    Every product ask is by-(session, scan, player) and resolves
    through the door's catalog, peers run hot(+cold) tiered caches
    with a bounded hot disk (what forces demotion), and
    ``kind="catalog"`` asks ride the same wire.  Two passes per run —
    binary then legacy JSON, identical seeds, fresh peer caches — and
    the report carries catalog-lookup p50/p99, per-tier
    (ram/wire/disk/cold/derive) rates, SLO attainment against
    ``--slo-ms``, the wire A/B with a byte-identity pin AND the
    addressed-vs-explicit-member byte-identity pin.  The record
    carries ``config.backend`` (the rig) and a flat ``metrics`` dict
    so ``blit bench-diff`` extracts and gates it exactly like the
    ingest records."""
    import math
    import os
    import random
    import tempfile
    import threading
    import time as _time

    from blit import monitor
    from blit.config import DEFAULT
    from blit.observability import HistogramStats, Timeline
    from blit.serve import Overloaded, ProductRequest
    from blit.serve.fleet import FleetFrontDoor
    from blit.serve.http import http_json, install_drain_handler
    from blit.serve.scheduler import DeadlineExpired
    from blit.testing import build_observation_tree

    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — rig label only
        backend = (os.environ.get("JAX_PLATFORMS") or "cpu").split(
            ",")[0] or "cpu"

    def q(h, p: float) -> float:
        return round(h.percentile(p), 6) if h is not None and h.n else 0.0

    players = ((0, 0), (0, 1))
    slo_s = args.slo_ms / 1e3
    with tempfile.TemporaryDirectory(prefix="blit-archive-day-") as td:
        # The day's archive is a REAL on-disk BL tree (ISSUE 19):
        # --sessions observing sessions x --distinct scans x the
        # player pair, crawled by every peer's catalog AND the
        # door's.  Popularity is zipfian along BOTH axes — a few hot
        # sessions dominate the day and within a session a few hot
        # scans dominate — which is what makes the warm tiers earn
        # their bytes.
        arc = os.path.join(td, "archive")
        raw_ntime = 6 * args.nfft  # x2 blocks/file = 12 frames' worth
        scan_names = [f"{i + 1:04d}" for i in range(args.distinct)]
        sess_names = [f"AGBT25A_999_{s:02d}"
                      for s in range(args.sessions)]
        for sess in sess_names:
            build_observation_tree(
                arc, sess, scans=tuple(scan_names), players=players,
                kind="raw", nchans=2, raw_ntime=raw_ntime, nfiles=1)
        reqs = []   # (addressed request, session, scan)
        weights = []
        for s, sess in enumerate(sess_names):
            for i, scan in enumerate(scan_names):
                w = 1.0 / (math.pow(s + 1, args.zipf_s)
                           * math.pow(i + 1, args.zipf_s))
                for band, bank in players:
                    reqs.append((ProductRequest(
                        raw="", session=sess, scan=scan, band=band,
                        bank=bank, nfft=args.nfft, nint=1),
                        sess, scan))
                    weights.append(w)
        picks = random.Random(args.seed).choices(
            range(len(reqs)), weights=weights, k=args.requests)

        def one_pass(wire_mode: str, tag: str):
            """One full day replay on a fresh fleet speaking
            ``wire_mode``; returns ``(pass_report, probe)`` where
            ``probe`` is the decoded hottest product for the cross-wire
            byte-identity pin."""
            pd = os.path.join(td, tag)
            os.makedirs(pd, exist_ok=True)
            tl = Timeline()
            # Pin the pass's wire on the environment: fleet_defaults
            # lets ambient BLIT_FLEET_WIRE* override the config, which
            # would silently turn the A/B into two identical passes.
            pinned = {"BLIT_FLEET_WIRE": wire_mode,
                      "BLIT_FLEET_WIRE_DEFLATE": "1" if args.deflate
                      else "0",
                      "BLIT_REQUEST_LOG": args.request_log or ""}
            prev = {k: os.environ.get(k) for k in pinned}
            os.environ.update(pinned)
            procs, peers, lease_dir = _spawn_fleet_peers(
                pd, args.peers, concurrency=args.concurrency,
                queue_depth=args.queue_depth, ram_bytes=args.ram_bytes,
                extra_env=pinned, catalog_root=arc, cold_dirs=True,
                disk_bytes=args.disk_bytes)
            try:
                door = FleetFrontDoor(
                    peers, lease_dir=lease_dir, timeline=tl,
                    replicas=args.replicas, peer_ttl_s=args.peer_ttl,
                    poll_s=min(0.1, args.peer_ttl / 4),
                    hedge_floor_s=args.hedge_floor_ms / 1e3,
                    request_timeout_s=60.0,
                    config=DEFAULT.with_(
                        fleet_wire=wire_mode, catalog_root=arc,
                        request_log_dir=args.request_log)).start()
            finally:
                for k, v in prev.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            uninstall = install_drain_handler(lambda: door.drain())
            lat = HistogramStats()
            lock = threading.Lock()
            rejected = [0]
            delivered = [0]  # decoded product bytes handed to clients
            slo_ok = [0]
            nprod = [0]
            catalog_asks = [0]
            errors: list = []
            it = iter(enumerate(picks))

            def client_loop(cid: int) -> None:
                while True:
                    with lock:
                        nk = next(it, None)
                    if nk is None:
                        return
                    n, k = nk
                    req, sess, scan = reqs[k]
                    if n % 16 == 0:
                        # Every 16th slot also asks the CATALOG about
                        # the scan it is about to fetch — the
                        # archive-plane control queries ride the same
                        # wire and feed the same catalog.lookup_s
                        # histogram as door-side resolution.
                        try:
                            door.get(ProductRequest(
                                kind="catalog",
                                raw=f"{sess}/{scan}"),
                                client=f"client{cid}")
                            with lock:
                                catalog_asks[0] += 1
                        except Exception as e:  # noqa: BLE001
                            with lock:
                                errors.append(f"catalog: {e!r}")
                    t = _time.perf_counter()
                    ok = False
                    try:
                        _, d = door.get(req, client=f"client{cid}")
                        ok = True
                        with lock:
                            delivered[0] += d.nbytes
                    except (Overloaded, DeadlineExpired) as e:
                        with lock:
                            rejected[0] += 1
                        if isinstance(e, Overloaded):
                            _time.sleep(min(0.25, e.retry_after_s))
                    except Exception as e:  # noqa: BLE001 — reported
                        with lock:
                            errors.append(repr(e))
                    dur = _time.perf_counter() - t
                    lat.observe(dur)
                    with lock:
                        nprod[0] += 1
                        if ok and dur <= slo_s:
                            slo_ok[0] += 1

            try:
                t0 = _time.perf_counter()
                threads = [threading.Thread(target=client_loop,
                                            args=(c,))
                           for c in range(args.clients)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = _time.perf_counter() - t0
                # Byte-identity probes on the day's hottest product:
                # (1) decoded through THIS pass's wire for the
                # cross-wire pin, (2) addressed-by-(session, scan,
                # player) vs explicit member paths — the ISSUE 19
                # catalog-resolution acceptance.
                probe = None
                addr_identical = None
                try:
                    req0, sess0, scan0 = reqs[0]
                    ph, pdata = door.get(req0, client="probe")
                    probe = (dict(ph), pdata.dtype.str,
                             tuple(pdata.shape), pdata.tobytes())
                    members = door.catalog.resolve(
                        sess0, scan0, band=req0.band, bank=req0.bank)
                    _, edata = door.get(
                        ProductRequest(raw=tuple(members),
                                       nfft=args.nfft, nint=1),
                        client="probe-explicit")
                    addr_identical = (
                        pdata.dtype == edata.dtype
                        and pdata.shape == edata.shape
                        and pdata.tobytes() == edata.tobytes())
                except Exception as e:  # noqa: BLE001 — reported
                    errors.append(f"probe: {e!r}")
                tiers = {"hit.ram": 0, "hit.disk": 0, "hit.wire": 0,
                         "hit.cold": 0, "derive": 0, "miss": 0}
                for _name, url in sorted(peers.items()):
                    try:
                        _, _, s = http_json("GET", url, "/stats",
                                            timeout=5.0)
                    except OSError:
                        continue
                    cst = (s.get("cache") or {})
                    for k in tiers:
                        tiers[k] += int(cst.get(k, 0))
                # The peers' serialize histogram rides their /snapshot
                # endpoints (merged across the fleet); deserialize and
                # wire bytes live on the door's own timeline.
                _, peer_hists = monitor.gather_trace_sources(
                    list(peers.values()))
                ser = peer_hists.get("fleet.serialize_s")
                de = tl.hists.get("fleet.deserialize_s")
                wire_h = tl.hists.get("fleet.wire_bytes")
                wire_bytes = float(wire_h.total) if wire_h else 0.0
                served = (tiers["hit.ram"] + tiers["hit.disk"]
                          + tiers["hit.cold"])
                total = served + tiers["miss"]
                ch = tl.hists.get("catalog.lookup_s")
                c = door.stats()["counters"]
                rep = {
                    "wire": wire_mode,
                    "wall_s": round(wall, 3),
                    "rps": (round(args.requests / wall, 1)
                            if wall else None),
                    "tiers": tiers,
                    "hit_rate": (round(served / total, 4)
                                 if total else 0.0),
                    # wire_bytes is what moved on the socket (base64
                    # inflates the JSON pass ~4/3); wire_gbps is the
                    # USEFUL throughput — decoded product bytes
                    # delivered to clients per wall second, the number
                    # the two wires compete on.
                    "wire_bytes": int(wire_bytes),
                    "delivered_bytes": delivered[0],
                    "wire_gbps": (round(delivered[0] / wall / 1e9, 6)
                                  if wall else 0.0),
                    "request_p50_s": q(lat, 0.50),
                    "request_p99_s": q(lat, 0.99),
                    "serialize_p50_s": q(ser, 0.50),
                    "serialize_p99_s": q(ser, 0.99),
                    "deserialize_p50_s": q(de, 0.50),
                    "deserialize_p99_s": q(de, 0.99),
                    "catalog_lookup_p50_s": q(ch, 0.50),
                    "catalog_lookup_p99_s": q(ch, 0.99),
                    "catalog_asks": catalog_asks[0],
                    "slo_attained": (round(slo_ok[0] / nprod[0], 4)
                                     if nprod[0] else 0.0),
                    "addressing_byte_identical": addr_identical,
                    "door": {
                        "binary_responses": c.get("fleet.wire.binary",
                                                  0),
                        "json_responses": c.get("fleet.wire.json", 0),
                        "pool_open": c.get("fleet.pool.open", 0),
                        "pool_reuse": c.get("fleet.pool.reuse", 0),
                        "pool_evict": c.get("fleet.pool.evict", 0),
                    },
                    "rejected": rejected[0],
                    "errors": errors[:5],
                }
                return rep, probe
            finally:
                uninstall()
                door.close()
                _reap_fleet_peers(procs)

        bin_rep, bin_probe = one_pass("binary", "binary")
        json_rep, json_probe = one_pass("json", "legacy")
        byte_identical = (bin_probe is not None
                          and bin_probe == json_probe)
        addressing_ok = (bin_rep["addressing_byte_identical"] is True
                         and json_rep["addressing_byte_identical"]
                         is True)
        speedup = (json_rep["wall_s"] / bin_rep["wall_s"]
                   if bin_rep["wall_s"] else 0.0)
        # The accelerated-clock framing: the replay IS the day's
        # zipfian ask stream compressed into wall_s, so the modeled
        # archive-day request count is requests x (86400 / wall_s) —
        # the number the catalog/tier quantiles were measured under.
        accel = (86400.0 / bin_rep["wall_s"] if bin_rep["wall_s"]
                 else 0.0)
        bt = bin_rep["tiers"]
        t_total = (bt["hit.ram"] + bt["hit.disk"] + bt["hit.wire"]
                   + bt["hit.cold"] + bt["miss"])

        def tier_rate(k: str) -> float:
            return round(bt[k] / t_total, 4) if t_total else 0.0

        report = {
            "serve_bench": "archive-day",
            "requests": args.requests,
            "sessions": args.sessions,
            "scans_per_session": args.distinct,
            "distinct": args.sessions * args.distinct * len(players),
            "clients": args.clients,
            "peers": args.peers,
            "replicas": args.replicas,
            "zipf_s": args.zipf_s,
            "seed": args.seed,
            "slo_ms": args.slo_ms,
            "clock_accel": round(accel, 1),
            "modeled_day_requests": int(args.requests * accel),
            "config": {"backend": backend, "nfft": args.nfft,
                       "peers": args.peers,
                       "deflate": bool(args.deflate),
                       "disk_bytes": args.disk_bytes},
            "binary": bin_rep,
            "legacy_json": json_rep,
            "ab": {
                "byte_identical": byte_identical,
                "addressing_byte_identical": addressing_ok,
                "wire_speedup": round(speedup, 4),
                "binary_wall_s": bin_rep["wall_s"],
                "json_wall_s": json_rep["wall_s"],
                "binary_wire_gbps": bin_rep["wire_gbps"],
                "json_wire_gbps": json_rep["wire_gbps"],
            },
            # The flat gate surface: bench-diff reads exactly these
            # (throughput/hit-rate/attainment band up,
            # latency-quantile band inverted).  tier_derive_rate is
            # report-only — a RISING derive rate is a regression, so
            # it must not ride the higher-is-better extractor.
            "metrics": {
                "fleet_hit_rate": bin_rep["hit_rate"],
                "fleet_wire_gbps": bin_rep["wire_gbps"],
                "wire_speedup": round(speedup, 4),
                "fleet_request_p50_s": bin_rep["request_p50_s"],
                "fleet_request_p99_s": bin_rep["request_p99_s"],
                "fleet_serialize_p99_s": bin_rep["serialize_p99_s"],
                "fleet_deserialize_p99_s":
                    bin_rep["deserialize_p99_s"],
                "catalog_lookup_p50_s":
                    bin_rep["catalog_lookup_p50_s"],
                "catalog_lookup_p99_s":
                    bin_rep["catalog_lookup_p99_s"],
                "tier_ram_hit_rate": tier_rate("hit.ram"),
                "tier_disk_hit_rate": tier_rate("hit.disk"),
                "tier_wire_hit_rate": tier_rate("hit.wire"),
                "tier_cold_hit_rate": tier_rate("hit.cold"),
                "tier_derive_rate": (round(bt["derive"] / t_total, 4)
                                     if t_total else 0.0),
                "slo_attained": bin_rep["slo_attained"],
            },
            "errors": (bin_rep["errors"] + json_rep["errors"])[:5],
        }
        if args.request_log:
            # The archive access log: door records carry the LOGICAL
            # (session, scan) address, so `blit requests --aggregate`
            # groups a day's traffic per scan (ISSUE 19 satellite).
            recs = monitor.read_requests(args.request_log)
            report["request_log"] = monitor.aggregate_requests(recs)
        out = json.dumps(report)
        print(out)
        if args.out:
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                f.write(out + "\n")
            os.replace(tmp, args.out)
    if report["errors"]:
        return 1
    return 0 if (byte_identical and addressing_ok) else 1


def _monitor_from_flags(args: argparse.Namespace):
    """Start the process-wide metrics publisher from ``--monitor-*``
    CLI flags (ISSUE 11) and install it as the singleton every
    ``publishing`` hook resolves (:func:`blit.monitor
    .install_publisher`) — so the reductions this command runs
    auto-publish exactly as an env-enabled deployment would, without
    mutating the environment.  Returns the publisher (caller shuts it
    down) or None when no flag was given."""
    if (getattr(args, "monitor_spool", None) is None
            and getattr(args, "monitor_port", None) is None):
        return None
    from blit import monitor

    pub = monitor.install_publisher(monitor.MetricsPublisher(
        interval_s=args.monitor_interval,
        spool_dir=args.monitor_spool,
        port=args.monitor_port).start())
    if pub.port is not None:
        print(f"# monitor: {pub.url}/metrics", file=sys.stderr)
    return pub


def _cmd_ingest_bench(args: argparse.Namespace) -> int:
    """File→product throughput probe for the asynchronous output plane
    (ISSUE 4): reduce a synthetic RAW recording to a real on-disk product
    and print the per-stage table — including the new ``readback`` and
    ``write`` stages — plus the overlap-efficiency gauge, optionally
    A/B'ing against the fully synchronous path (``--sync-compare``).
    This is the table an operator reads when a deployment's end-to-end
    rate collapses below the kernel rate (docs/WORKFLOWS.md "Diagnosing
    a slow link")."""
    import os
    import tempfile
    import time as _time

    from blit.outplane import INGEST_HISTS

    from blit.pipeline import RawReducer
    from blit.testing import synth_raw

    def run(async_output: bool) -> dict:
        red = RawReducer(nfft=args.nfft, nint=args.nint,
                         chunk_frames=args.chunk_frames,
                         fqav_by=args.fqav, dtype=args.dtype,
                         nbits=args.nbits, quant_scale=args.quant_scale,
                         async_output=async_output, tune_online=False)
        out = os.path.join(td, "bench_async.fil" if async_output
                           else "bench_sync.fil")
        t0 = _time.perf_counter()
        red.reduce_to_file(raw_path, out)
        wall = _time.perf_counter() - t0
        tl = red.timeline
        return {
            "async_output": async_output,
            "wall_s": round(wall, 3),
            "ingest_gbps": round(file_bytes / wall / 1e9, 3),
            "overlap_efficiency": round(tl.overlap_efficiency(), 3),
            "stages": {
                k: {"calls": v.calls, "s": round(v.seconds, 4),
                    "bytes": v.bytes}
                for k, v in sorted(list(tl.stages.items()))
            },
            # Stage TAILS from the telemetry hists (ISSUE 8 satellite):
            # readback lag / per-append write / per-chunk service
            # latency p50/p99 — the burst an average hides.
            "stage_quantiles": tl.hist_quantiles(INGEST_HISTS),
            # Per-chunk latency distributions (out.chunk_latency_s /
            # out.readback_lag_s — ISSUE 5): the tails behind the stage
            # sums above.
            "hists": tl.report().get("hists", {}),
            "product_bytes": os.path.getsize(out),
        }

    def run_dedoppler() -> dict:
        """The science leg (ISSUE 6): the same recording through the
        search plane — RAW → windowed spectra → on-device Taylor tree →
        ``.hits`` — reporting drift-rate trials/s alongside the ingest
        rate (a drift trial = one (drift row, channel) cell scored)."""
        from blit.search import DedopplerReducer

        red = DedopplerReducer(
            nfft=args.nfft, nint=args.nint,
            chunk_frames=args.chunk_frames, dtype=args.dtype,
            window_spectra=args.dedoppler_window, snr_threshold=5.0,
        )
        out = os.path.join(td, "bench.hits")
        t0 = _time.perf_counter()
        hdr = red.search_to_file(raw_path, out)
        wall = _time.perf_counter() - t0
        T = hdr["search_window_spectra"]
        windows = hdr.get("search_windows", 0)
        trials = (2 * T - 1) * hdr["nchans"] * windows
        tl = red.timeline
        return {
            "windows": windows,
            "window_spectra": T,
            "hits": hdr.get("search_nhits"),
            "wall_s": round(wall, 3),
            "ingest_gbps": round(file_bytes / wall / 1e9, 4),
            "drift_rates_per_s": round(trials / wall, 1),
            "hists": tl.report().get("hists", {}),
            "product_bytes": os.path.getsize(out),
        }

    def run_live(drill: bool) -> dict:
        """The live leg (ISSUE 7): replay the recording through the
        streaming ingest plane at ``--live-rate`` × wall-clock recording
        rate and report p50/p99 chunk→product latency.  The recording is
        re-synthesized with TBIN stretched so it SPANS ``--live-seconds``
        of wall time — replay pacing is meaningless on a microsecond
        recording.  ``drill=True`` is the seeded late-chunk drill: one
        chunk held past a tightened lateness budget, proving the product
        masks (and flight-records) instead of wedging.

        With ``--packets`` (ISSUE 18) the replay goes through the
        PACKET front end — the recording framed as datagrams, with the
        ``--packet-drop``/``--packet-reorder``/``--packet-dup``
        schedules applied — so the leg measures the sustained-capture
        contract: 1× for the whole session, back-pressure shedding as
        masked gaps (counted in the report), never a stall.  The stall
        watchdog is ARMED, so a completed leg IS the zero-stall proof
        (``stalls`` would have been a raised incident, not a number)."""
        from blit.observability import Timeline
        from blit.stream import (
            PacketReplaySource,
            ReplaySource,
            stream_reduce,
        )

        packets = bool(getattr(args, "packets", False))
        nblocks = max(4, args.blocks)
        ntime = (args.chunks * args.chunk_frames + 3) * args.nfft
        per_block = -(-ntime // nblocks)
        live_raw = os.path.join(td, "live.raw")
        synth_raw(live_raw, nblocks=nblocks, obsnchan=args.nchan,
                  ntime_per_block=per_block,
                  tbin=args.live_seconds / (nblocks * per_block))
        tl = Timeline()
        red = RawReducer(nfft=args.nfft, nint=args.nint,
                         chunk_frames=args.chunk_frames, fqav_by=args.fqav,
                         dtype=args.dtype, timeline=tl, tune_online=False)
        lateness = None
        late = {}
        if drill:
            # Chunk 1 arrives well past a tightened budget: it must be
            # masked (zero weight) while the stream keeps flowing.
            lateness = 0.02 * args.live_seconds
            late = {1: 0.8 * args.live_seconds}
        if packets:
            src = PacketReplaySource(
                live_raw, rate=args.live_rate,
                packet_ntime=args.packet_ntime,
                drop=(args.packet_drop or None),
                reorder=args.packet_reorder, dup=args.packet_dup,
                seed=0, timeline=tl)
            # The sustained-capture leg must complete masked, not
            # wedged: a whole-stream lateness stall would hide behind
            # the default budget, so bound it by the recording span.
            lateness = lateness or 0.25 * args.live_seconds
        else:
            src = ReplaySource(live_raw, rate=args.live_rate, late=late)
        out = os.path.join(td, "live_drill.fil" if drill else "live.fil")
        t0 = _time.perf_counter()
        hdr = stream_reduce(src, out, reducer=red, lateness_s=lateness,
                            stall_timeout_s=max(5.0,
                                                2 * args.live_seconds))
        wall = _time.perf_counter() - t0
        lat = tl.report().get("hists", {}).get(
            "stream.chunk_to_product_s", {})
        leg = {
            "rate": args.live_rate,
            "recording_s": round(args.live_seconds, 3),
            "wall_s": round(wall, 3),
            "chunks": hdr["stream_chunks"],
            "chunk_to_product_p50_s": lat.get("p50"),
            "chunk_to_product_p99_s": lat.get("p99"),
            "late_chunks": hdr["stream_late_chunks"],
            "dup_chunks": hdr["stream_dup_chunks"],
            "masked_chunks": hdr["stream_masked_chunks"],
            # Output spectra whose PFB windows touched a zero-filled
            # sample — the clean path must report 0 here.
            "degraded_spectra": hdr["stream_degraded_spectra"],
            "product_bytes": os.path.getsize(out),
            # The armed watchdog raised on any stall, so reaching this
            # line proves zero.
            "stalls": 0,
        }
        if packets:
            leg["packet"] = src.packet_report()
        if hdr.get("stream_flight_dump"):
            leg["flight_dump"] = hdr["stream_flight_dump"]
        return leg

    def run_chaos() -> dict:
        """The recovery leg (ISSUE 12): a live consumer is SIGKILLed
        mid-session by a seeded ``stream.chunk:kill`` fault, the
        :class:`blit.recover.StreamSupervisor` detects the death and
        restarts it with ``resume=True`` (StreamCursor rejoin), and the
        leg reports detection latency (``recover.detect_s``), recovery
        time (``recover.resume_s``), the frames the rejoin recomputed,
        and product byte-identity against the batch oracle."""
        from blit.observability import Timeline
        from blit.recover import StreamSupervisor
        from blit.stream import StreamCursor

        nblocks = max(4, args.blocks)
        ntime = (args.chunks * args.chunk_frames + 3) * args.nfft
        chaos_raw = os.path.join(td, "chaos.raw")
        synth_raw(chaos_raw, nblocks=nblocks, obsnchan=args.nchan,
                  ntime_per_block=-(-ntime // nblocks))
        oracle = os.path.join(td, "chaos_oracle.fil")
        RawReducer(nfft=args.nfft, nint=args.nint,
                   chunk_frames=args.chunk_frames, fqav_by=args.fqav,
                   dtype=args.dtype,
                   tune_online=False).reduce_to_file(chaos_raw, oracle)
        out = os.path.join(td, "chaos.fil")
        tl = Timeline()
        sup = StreamSupervisor(
            chaos_raw, out, kind="reduce",
            knobs=dict(nfft=args.nfft, nint=args.nint,
                       chunk_frames=args.chunk_frames,
                       fqav_by=args.fqav, dtype=args.dtype,
                       tune_online=False),
            replay_rate=args.chaos_rate,
            faults=f"stream.chunk:kill:after={args.chaos_after}",
            lease_ttl_s=3.0, poll_s=0.05, timeline=tl,
        )
        import filecmp

        t0 = _time.perf_counter()
        rep = _chaos_run(sup)  # a failed drill becomes a failed LEG
        wall = _time.perf_counter() - t0
        try:
            identical = filecmp.cmp(out, oracle, shallow=False)
        except OSError:
            identical = False
        hists = tl.report().get("hists", {})
        cur = StreamCursor.load(out)  # removed on clean completion
        frames_claimed_at_crash = None
        for a in rep.get("attempts", []):
            if not a.get("ok", True):
                frames_claimed_at_crash = a.get("failure", {})
        return {
            "wall_s": round(wall, 3),
            "recovered": rep.get("recovered"),
            "attempts": len(rep.get("attempts", [])),
            "products_identical": identical,
            "cursor_removed": cur is None,
            "detect": hists.get("recover.detect_s", {}),
            "resume": hists.get("recover.resume_s", {}),
            "failure": frames_claimed_at_crash,
        }

    # --chunk-frames 0 (or negative) = auto: resolve from this rig's
    # tuning profile (blit/tune.py) exactly as `blit reduce` would; the
    # probe's provenance is embedded in the report's ingest_config.
    if args.chunk_frames is not None and args.chunk_frames <= 0:
        args.chunk_frames = None
    # tune_online=False throughout the bench: a converged OnlineTuner
    # persisting mid-run (warmup is exactly its warmup window) would
    # reshape later legs' knobs AFTER this probe resolved the published
    # provenance — the A/B legs and ingest_config must describe ONE
    # knob set, like _cmd_tune's measured sweeps.
    probe = RawReducer(nfft=args.nfft, nint=args.nint,
                       chunk_frames=args.chunk_frames, fqav_by=args.fqav,
                       dtype=args.dtype, nbits=args.nbits,
                       tune_online=False)
    args.chunk_frames = probe.chunk_frames

    # Live monitoring (ISSUE 11): --monitor-spool / --monitor-port start
    # the process publisher, so `blit top` (or a curl at /metrics) can
    # watch this bench while it runs — the CI monitor smoke rides this.
    pub = _monitor_from_flags(args)

    with tempfile.TemporaryDirectory(prefix="blit-ingest-bench-") as td:
        raw_path = os.path.join(td, "bench.raw")
        # File length leaves exactly the (ntap-1)*nfft PFB tail after the
        # last chunk so no flush-shape recompile triggers (bench.py rule).
        ntime = (args.chunks * args.chunk_frames + 3) * args.nfft
        _, blocks = synth_raw(raw_path, nblocks=args.blocks,
                              obsnchan=args.nchan,
                              ntime_per_block=-(-ntime // args.blocks))
        file_bytes = sum(b.nbytes for b in blocks)
        if args.digests:
            # The integrity A/B (ISSUE 13 acceptance): every leg then
            # ingests through per-block digest verification — the
            # reported rates must sit inside the bench-diff noise band
            # of an unarmed run.
            from blit import integrity

            integrity.write_raw_digests(raw_path)
        # Untimed warmup: compile the channelizer (and fault the product
        # path's buffers) so the timed legs measure steady-state
        # streaming, not the one-off jit compile.
        RawReducer(nfft=args.nfft, nint=args.nint,
                   chunk_frames=args.chunk_frames, fqav_by=args.fqav,
                   dtype=args.dtype, nbits=args.nbits,
                   quant_scale=args.quant_scale,
                   tune_online=False).reduce_to_file(
            raw_path, os.path.join(td, "warmup.fil"))
        legs = [run(True)]
        if args.sync_compare:
            legs.append(run(False))
        report = {
            "file_bytes": file_bytes,
            # The knob set every leg ran, with tuning provenance (ISSUE 8
            # satellite: the BENCH table names the profile behind it).
            "ingest_config": {
                "nfft": args.nfft, "nint": args.nint, "nchan": args.nchan,
                "chunk_frames": args.chunk_frames,
                "prefetch_depth": probe.prefetch_depth,
                "out_depth": probe.out_depth, "dtype": args.dtype,
                "nbits": args.nbits, "digests": bool(args.digests),
                "tuning": probe.tuning_provenance(),
            },
            "legs": legs,
        }
        if args.dedoppler:
            report["dedoppler"] = run_dedoppler()
        if args.live:
            report["live"] = run_live(False)
        if args.live_drill:
            report["live_drill"] = run_live(True)
        if args.chaos:
            report["chaos"] = run_chaos()
        if len(legs) == 2 and legs[1]["wall_s"] > 0:
            from blit.testing import sync_compare_verdict

            report.update(sync_compare_verdict(
                os.path.join(td, "bench_async.fil"),
                os.path.join(td, "bench_sync.fil"),
                async_wall_s=legs[0]["wall_s"],
                sync_wall_s=legs[1]["wall_s"]))
        if args.spans_compare:
            # Tracing-overhead A/B (ISSUE 5 acceptance: always-on spans
            # must cost <= 1%): interleave spans-on/spans-off legs so slow
            # drift doesn't masquerade as overhead, and compare the best
            # wall of each arm (min is the standard noise-floor estimator
            # for identical repeated work).
            from blit import observability

            tr = observability.tracer()
            prev, walls = tr.enabled, {True: [], False: []}
            try:
                for _ in range(args.spans_reps):
                    for enabled in (True, False):
                        tr.enabled = enabled
                        walls[enabled].append(run(True)["wall_s"])
            finally:
                tr.enabled = prev
            on, off = min(walls[True]), min(walls[False])
            report["spans_on_s"] = on
            report["spans_off_s"] = off
            report["span_overhead"] = round(on / max(off, 1e-9) - 1.0, 4)
        if args.history_compare:
            # History+anomaly overhead A/B (ISSUE 20 acceptance: the
            # durable store + baselines must cost <= 1% on an ingest
            # leg) — the --spans-compare discipline: interleaved arms
            # so slow drift doesn't masquerade as overhead, best wall
            # per arm.  Each arm runs under a fast-ticking publisher;
            # the ON arm's publisher also feeds tiered rings and
            # scores anomaly baselines every tick.
            import shutil as _shutil

            from blit import monitor as _mon
            from blit.config import SiteConfig as _SC

            hist_td = os.path.join(td, "hist-ab")
            hwalls = {True: [], False: []}
            for _ in range(args.spans_reps):
                for enabled in (True, False):
                    if enabled:
                        _shutil.rmtree(hist_td, ignore_errors=True)
                        cfg = _SC(history_dir=hist_td,
                                  history_raw_s=0.5)
                    else:
                        cfg = _SC(history_anomaly=False)
                    p2 = _mon.MetricsPublisher(
                        interval_s=0.05, spool_dir="", port=-1,
                        config=cfg).start()
                    try:
                        hwalls[enabled].append(run(True)["wall_s"])
                    finally:
                        p2.close()
            hon, hoff = min(hwalls[True]), min(hwalls[False])
            report["history_on_s"] = hon
            report["history_off_s"] = hoff
            report["history_overhead"] = round(
                hon / max(hoff, 1e-9) - 1.0, 4)
        if pub is not None:
            pub.tick()  # a final sample so short benches always spool one
            report["monitor"] = {"port": pub.port,
                                 "spool": pub.spool_path,
                                 "samples": pub.seq}
            from blit import monitor

            monitor.shutdown_publisher()
        print(json.dumps(report))
    return 0


def _cmd_tune(args: argparse.Namespace) -> int:
    """Offline ingest autotune (ISSUE 8 tentpole): coordinate descent
    over ``chunk_frames`` / ``prefetch_depth`` / ``out_depth`` with real
    timed file→product reductions on THIS rig, persisting the winner as
    a content-addressed per-rig tuning profile
    (:mod:`blit.tune`) that every subsequent ``reduce`` / ``scan`` /
    ``serve`` / ``stream`` with unset knobs loads automatically.  Note
    each new ``chunk_frames`` candidate costs one XLA compile — tuning
    is an offline, once-per-rig operation by design."""
    import os
    import tempfile

    from blit.outplane import INGEST_HISTS
    import time as _time

    from blit import tune as T
    from blit.pipeline import RawReducer
    from blit.testing import synth_raw

    def build(knobs: dict, **kw) -> "RawReducer":
        return RawReducer(
            nfft=args.nfft, nint=args.nint, fqav_by=args.fqav,
            dtype=args.dtype, nbits=args.nbits,
            chunk_frames=knobs["chunk_frames"],
            prefetch_depth=knobs["prefetch_depth"],
            out_depth=knobs["out_depth"], tune_online=False, **kw,
        )

    with tempfile.TemporaryDirectory(prefix="blit-tune-") as td:
        if args.raw:
            raw_path = args.raw
            file_bytes = os.path.getsize(raw_path)
            from blit.io.guppi import open_raw

            rdr = open_raw(raw_path)
            tuned_nchan = int(rdr.header(0)["OBSNCHAN"])
            total_samps = sum(rdr.block_ntime_kept(i)
                              for i in range(rdr.nblocks))
        else:
            raw_path = os.path.join(td, "tune.raw")
            ntime = (args.chunks * args.chunk_frames + 3) * args.nfft
            _, blocks = synth_raw(raw_path, nblocks=args.blocks,
                                  obsnchan=args.nchan,
                                  ntime_per_block=-(-ntime // args.blocks))
            file_bytes = sum(b.nbytes for b in blocks)
            tuned_nchan = args.nchan
            total_samps = sum(b.shape[1] for b in blocks)
        # Candidates must keep >=2 full chunks inside the recording:
        # a chunk spanning most of the file measures a degenerate
        # near-zero-overhead run that always wins and then missizes
        # every real reduction on the rig.
        max_cf = max(args.nint, total_samps // args.nfft // 2)
        # Normalize FIRST so the untimed warmup (jit compile + page
        # faults) runs at the exact knob set tune() measures first — a
        # recording-clamped base must not pay its compile inside the
        # first timed trial (that would understate baseline_gbps).
        base = T.normalize_base({"chunk_frames": args.chunk_frames},
                                nint=args.nint, max_chunk_frames=max_cf)
        build(base).reduce_to_file(raw_path, os.path.join(td, "warm.fil"))
        seq = [0]

        def measure(knobs: dict) -> float:
            best = 0.0
            for _ in range(max(1, args.reps)):
                red = build(knobs)
                out = os.path.join(td, f"t{seq[0]}.fil")
                seq[0] += 1
                t0 = _time.perf_counter()
                red.reduce_to_file(raw_path, out)
                best = max(best,
                           file_bytes / (_time.perf_counter() - t0) / 1e9)
                os.unlink(out)
            return best

        best, trials = T.tune(measure, base=base, nint=args.nint,
                              max_trials=args.trials,
                              max_chunk_frames=max_cf)
        # One confirmation pass at the winner captures the stage tails
        # that travel with the profile as provenance.
        winner = build(best)
        t0 = _time.perf_counter()
        winner.reduce_to_file(raw_path, os.path.join(td, "winner.fil"))
        score = file_bytes / (_time.perf_counter() - t0) / 1e9
        key, ident = T.rig_fingerprint(**winner._tune_fingerprint_kw())
        prof = T.TuningProfile(
            key=key, rig=ident, source="offline",
            tuned_nchan=tuned_nchan,
            score_gbps=round(score, 4), trials=len(trials),
            stages=winner.timeline.hist_quantiles(INGEST_HISTS),
            **{k: int(best[k]) for k in T.KNOBS},
        )
        path = T.save_profile(prof)
        # trials[0] IS the base measurement (tune() scores its — possibly
        # recording-size-clamped — starting point first), so the baseline
        # survives even when the requested chunk_frames was capped.
        base_score = trials[0]["score"] if trials else None
        print(json.dumps({
            "profile": path,
            "key": key,
            "winner": prof.knobs(),
            "score_gbps": prof.score_gbps,
            "baseline_gbps": (round(base_score, 4)
                              if base_score is not None else None),
            "trials": trials,
        }))
    return 0


def _chaos_run(sup) -> dict:
    """Run a supervisor for the chaos drill, converting an exhausted
    recovery budget into a failed REPORT instead of a traceback — the
    --json-out artifact must exist exactly when the drill fails (that
    is the run CI needs to triage)."""
    try:
        rep = sup.run()
    except RuntimeError as e:
        rep = {"recovered": False, "error": str(e), "attempts": [],
               "attempts_tried": sup.state().get("attempt", 0) + 1}
    return rep


def _cmd_fsck(args: argparse.Namespace) -> int:
    """``blit fsck`` (ISSUE 13): verify an archive tree's manifests and
    cache-entry content digests, quarantine what fails, optionally
    repair.  Exit 0 = clean tree; 1 = corruption found (the report
    names every artifact, and everything bad is already quarantined
    unless ``--no-quarantine``)."""
    from blit import integrity

    rep = integrity.fsck(args.root, repair=args.repair,
                         quarantine=not args.no_quarantine)
    cold = getattr(args, "cold_dir", None)
    if cold:
        # The cold tier (ISSUE 19) shares the hot tier's sidecar
        # convention, so the SAME walk verifies/quarantines/repairs it
        # — one merged report, one exit verdict.
        crep = integrity.fsck(cold, repair=args.repair,
                              quarantine=not args.no_quarantine)
        rep = {
            "root": rep["root"], "cold_root": crep["root"],
            "checked": rep["checked"] + crep["checked"],
            "ok": rep["ok"] + crep["ok"],
            "unmanifested": (rep["unmanifested"]
                             + crep["unmanifested"]),
            "in_progress": rep["in_progress"] + crep["in_progress"],
            "bad": rep["bad"] + crep["bad"],
            "quarantined": rep["quarantined"] + crep["quarantined"],
            "repaired": rep["repaired"] + crep["repaired"],
            "repair_failed": (rep["repair_failed"]
                              + crep["repair_failed"]),
            "clean": rep["clean"] and crep["clean"],
        }
    body = json.dumps(rep)
    print(body)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(body)
    return 0 if rep["clean"] else 1


def _cmd_backfill(args: argparse.Namespace) -> int:
    """``blit backfill`` (ISSUE 19 tentpole #3): walk an archive root
    through the catalog crawl, derive + publish EVERY (session, scan,
    player) product into a hot(+cold) cache — the fleet then serves the
    archive day from warm tiers instead of recompute storms.

    Resumable by construction: a product's completion is recorded in an
    append-only fsync-per-line LEDGER only AFTER its cache publish
    lands, so a kill mid-derive leaves no entry and the product simply
    re-derives on resume, while completed products are never re-derived
    (the acceptance kill-drill).  Products are content-addressed, so an
    interrupted+resumed backfill finishes byte-identical to an
    uninterrupted one.

    Paced like the PR-12 Scrubber: after each product the walker sleeps
    off the debt ``max(0, input_bytes / bytes_per_s - elapsed)`` so a
    backfill sharing a host with foreground serving never starves it."""
    import os
    import time as _time

    from blit.config import DEFAULT, archive_defaults
    from blit.observability import Timeline
    from blit.serve.cache import ProductCache, fingerprint_for
    from blit.serve.catalog import CatalogIndex
    from blit.serve.service import ProductRequest

    config = DEFAULT
    if args.cold_dir:
        config = config.with_(cache_cold_dir=args.cold_dir)
    if args.bytes_per_s is not None:
        config = config.with_(backfill_bytes_per_s=args.bytes_per_s
                              if args.bytes_per_s > 0 else None)
    bps = archive_defaults(config)["backfill_bytes_per_s"]
    tl = Timeline()
    cache = ProductCache(args.cache_dir, ram_bytes=args.ram_bytes,
                         disk_bytes=args.disk_bytes,
                         cold_dir=archive_defaults(config)["cold_dir"],
                         timeline=tl)
    catalog = CatalogIndex(args.root, config=config, rescan_s=0.0,
                           timeline=tl)
    catalog.refresh(force=True)
    ledger_path = args.ledger or os.path.join(args.cache_dir,
                                              "backfill.ledger.jsonl")
    done: set = set()
    if os.path.exists(ledger_path):
        with open(ledger_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    done.add(json.loads(line)["fp"])
                except (ValueError, KeyError):
                    # A torn tail line (the crash wrote half a record):
                    # treat as not-completed — the product re-derives.
                    continue
    os.makedirs(os.path.dirname(os.path.abspath(ledger_path)),
                exist_ok=True)
    ledger = open(ledger_path, "a")
    if ledger.tell() > 0:
        with open(ledger_path, "rb") as f:
            f.seek(-1, os.SEEK_END)
            torn_tail = f.read(1) != b"\n"
        if torn_tail:
            # Terminate the crash's half-record so the claims appended
            # below never concatenate onto it (both would be lost on
            # the NEXT resume).
            ledger.write("\n")
            ledger.flush()

    def _claim(fp: str, session: str, scan: str, player: str) -> None:
        """fsync-before-claim: the ledger line is durable BEFORE the
        product counts as completed — a crash can lose work, never
        fake it."""
        ledger.write(json.dumps({"fp": fp, "session": session,
                                 "scan": scan, "player": player,
                                 "t": round(_time.time(), 3)}) + "\n")
        ledger.flush()
        os.fsync(ledger.fileno())
        done.add(fp)

    report = {"backfill": True, "root": os.path.abspath(args.root),
              "cache_dir": args.cache_dir,
              "cold_dir": archive_defaults(config)["cold_dir"],
              "ledger": ledger_path, "bytes_per_s": bps,
              "products_total": 0, "derived": 0, "skipped_ledger": 0,
              "skipped_cached": 0, "errors": []}
    t_start = _time.perf_counter()
    bytes_read = 0
    debt_s = 0.0
    stop = False
    try:
        with catalog._lock:
            sessions = {s: dict(e["scans"])
                        for s, e in catalog._sessions.items()}
        for session in sorted(sessions):
            if stop:
                break
            for scan in sorted(sessions[session]):
                if stop:
                    break
                seqs = sessions[session][scan]["sequences"]
                for (band, bank), members in sorted(seqs.items()):
                    if args.limit and report["products_total"] >= args.limit:
                        stop = True
                        break
                    report["products_total"] += 1
                    player = f"BLP{band}{bank}"
                    req = (ProductRequest(raw=tuple(members),
                                          product=args.product)
                           if args.product else
                           ProductRequest(raw=tuple(members),
                                          nfft=args.nfft,
                                          nint=args.nint))
                    reducer = req.reducer()
                    fp = fingerprint_for(reducer, req.raw_source)
                    if fp in done:
                        report["skipped_ledger"] += 1
                        continue
                    if cache.contains(fp):
                        # Published but the claim never landed (killed
                        # in the publish→claim window) — or a foreground
                        # serve beat us to it.  Completed either way.
                        _claim(fp, session, scan, player)
                        report["skipped_cached"] += 1
                        continue
                    t0 = _time.perf_counter()
                    nbytes = sum(os.path.getsize(m) for m in members)
                    try:
                        header, data = reducer.reduce(req.raw_source)
                        cache.put(fp, header, data, recipe=req.recipe())
                        cache.note_derive()
                    except Exception as e:  # noqa: BLE001 — reported
                        report["errors"].append(
                            f"{session}/{scan}/{player}: {e!r}")
                        continue
                    _claim(fp, session, scan, player)
                    report["derived"] += 1
                    bytes_read += nbytes
                    # The Scrubber debt discipline: pay for the bytes
                    # just read before touching the next product.
                    if bps:
                        dt = _time.perf_counter() - t0
                        debt_s = max(0.0, nbytes / bps - dt)
                        if debt_s > 0:
                            _time.sleep(debt_s)
    finally:
        ledger.close()
    report["wall_s"] = round(_time.perf_counter() - t_start, 3)
    report["bytes_read"] = bytes_read
    report["cache"] = cache.stats()
    body = json.dumps(report)
    print(body)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(body)
    return 1 if report["errors"] else 0


def _chaos_corrupt(args: argparse.Namespace, work: str,
                   report: dict) -> int:
    """The ``blit chaos --fault corrupt`` leg (ISSUE 13 satellite):
    seeded in-flight corruption of one delivered RAW block under a
    digest sidecar.  The contract asserted end to end: the mismatch is
    DETECTED (``integrity.bad_block`` >= 1), the block is MASKED to
    zero weight (never garbage), and the product is byte-identical to
    an oracle reduction of the same recording with that block zeroed.

    Geometry note: blocks are sized so the whole drill fits one device
    chunk — every block then arrives as ONE delivery, so "delivery k"
    is "block k" and the zero-filled oracle is exact."""
    import filecmp
    import os

    import numpy as np

    from blit import faults, integrity
    from blit.io.guppi import GuppiRaw, write_raw
    from blit.pipeline import RawReducer
    from blit.testing import synth_raw

    nblocks = max(2, args.chunks)
    per_block = max(4, args.window_frames) * args.nfft
    victim = min(max(0, args.after), nblocks - 1)
    in_dir = os.path.join(work, "input")
    oracle_dir = os.path.join(work, "oracle_input")
    os.makedirs(in_dir, exist_ok=True)
    os.makedirs(oracle_dir, exist_ok=True)
    raw = os.path.join(in_dir, "chaos.raw")
    synth_raw(raw, nblocks=nblocks, obsnchan=args.nchan,
              ntime_per_block=per_block, seed=args.seed)
    # The zero-filled oracle: the SAME recording with the victim block
    # zeroed (same basename so derived headers cannot differ).
    rdr0 = GuppiRaw(raw, native=False)
    blocks = [np.array(rdr0.read_block(i)) for i in range(nblocks)]
    blocks[victim][:] = 0
    write_raw(os.path.join(oracle_dir, "chaos.raw"),
              dict(rdr0.header(0)), blocks)
    integrity.write_raw_digests(raw)
    # One chunk spans the whole recording: leave the (ntap-1)-frame PFB
    # tail after chunk_frames so every block lands as one delivery.
    cf = max(args.nint, (nblocks * per_block) // args.nfft - 3)
    kw = dict(nfft=args.nfft, nint=args.nint, chunk_frames=cf,
              tune_online=False)
    oracle = os.path.join(work, "oracle.fil")
    RawReducer(**kw).reduce_to_file(
        os.path.join(oracle_dir, "chaos.raw"), oracle)
    out = os.path.join(work, "chaos.fil")
    faults.reset_counters()
    faults.install(faults.FaultRule(point="guppi.read", mode="corrupt",
                                    after=victim, times=1))
    try:
        rdr = GuppiRaw(raw)  # arms the digest sidecar
        hdr = RawReducer(**kw).reduce_to_file(rdr, out)
    finally:
        faults.clear()
    counters = faults.counters()
    try:
        identical = filecmp.cmp(out, oracle, shallow=False)
    except OSError:
        identical = False
    bad_blocks = int(counters.get("integrity.bad_block", 0))
    report.update(
        recovered=bad_blocks >= 1,
        byte_identical=identical,
        victim_block=victim,
        masked_blocks=hdr.get("_masked_blocks", []),
        integrity={k: v for k, v in sorted(counters.items())
                   if k.startswith(("integrity.", "mask."))},
        work_dir=work,
    )
    body = json.dumps(report)
    print(body)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(body)
    return 0 if (identical and bad_blocks >= 1) else 1


def _chaos_fleet(args: argparse.Namespace, work: str, report: dict) -> int:
    """``blit chaos --fleet`` (ISSUE 14 tentpole): break a REAL
    multi-process serving fleet mid-replay and assert the front door's
    recovery contract end to end:

    - the failed peer (SIGKILL / SIGSTOP-wedge / SIGSTOP+SIGCONT
      partition) is DETECTED within the lease TTL and ejected,
    - its key range re-routes: every request completes,
    - every served product is BYTE-IDENTICAL to a single-process
      oracle reduction,
    - ``/healthz`` degrades honestly and (partition) recovers,
    - post-recovery hit-rate returns to within 10% of pre-kill.

    The victim is the OWNER of the hottest product — the worst case for
    the cache-warm replication story."""
    import math
    import os
    import random
    import signal
    import time as _time

    import numpy as np

    from blit.observability import Timeline
    from blit.serve import Overloaded, ProductRequest
    from blit.serve.cache import fingerprint_for
    from blit.serve.fleet import FleetError, FleetFrontDoor
    from blit.serve.http import http_json
    from blit.serve.scheduler import DeadlineExpired
    from blit.testing import synth_raw

    rng = random.Random(args.seed)
    nfft = args.nfft
    distinct = max(2, args.fleet_distinct)
    total = max(30, args.fleet_requests)
    ntime = (8 + 3) * nfft
    reqs, oracle = [], {}
    for i in range(distinct):
        path = os.path.join(work, f"prod{i:02d}.raw")
        synth_raw(path, nblocks=1, obsnchan=2, ntime_per_block=ntime,
                  seed=args.seed + i)
        req = ProductRequest(raw=path, nfft=nfft, nint=1)
        reqs.append(req)
        # The single-process oracle: the same reduction, no fleet.
        _, data = req.reducer().reduce(path)
        oracle[i] = np.asarray(data)
    procs, peers, lease_dir = _spawn_fleet_peers(
        work, args.peers, concurrency=2, queue_depth=32,
        ram_bytes=64 << 20, beat_interval_s=min(0.2, args.lease_ttl / 5))
    tl = Timeline()
    door = FleetFrontDoor(
        peers, lease_dir=lease_dir, timeline=tl, replicas=args.replicas,
        peer_ttl_s=args.lease_ttl, poll_s=args.poll,
        health_poll_s=max(args.poll, 0.5),
        hedge_floor_s=0.05, request_timeout_s=10.0).start()

    fp0 = fingerprint_for(reqs[0].reducer(), reqs[0].raw_source)
    victim = door.ring.owners(fp0)[0]
    victim_proc = procs[int(victim.removeprefix("peer"))][0]
    weights = [1.0 / math.pow(k + 1, 1.2) for k in range(distinct)]
    picks = rng.choices(range(distinct), weights=weights, k=total)
    third = total // 3

    def cache_totals() -> dict:
        out = {}
        for name, url in peers.items():
            try:
                _, _, s = http_json("GET", url, "/stats", timeout=2.0)
            except OSError:
                continue
            c = s.get("cache") or {}
            out[name] = (c.get("hit.ram", 0) + c.get("hit.disk", 0),
                         c.get("miss", 0))
        return out

    def window_hit_rate(before: dict, after: dict):
        """Hit rate of the interval, over peers alive in BOTH samples
        (a SIGKILLed peer's counters vanish mid-drill)."""
        dh = dm = 0
        for name, (h1, m1) in after.items():
            if name not in before:
                continue
            h0, m0 = before[name]
            dh += max(0, h1 - h0)
            dm += max(0, m1 - m0)
        return (dh / (dh + dm)) if dh + dm else None

    failed: list = []
    diffs: list = []

    def run_slice(idxs) -> None:
        for k in idxs:
            for _attempt in range(8):
                try:
                    _, d = door.get(reqs[k], client="chaos")
                except Overloaded as e:
                    _time.sleep(min(0.25, e.retry_after_s))
                    continue
                except (FleetError, DeadlineExpired, OSError):
                    # Transient while the failure is being detected:
                    # back off a beat and retry — a real client's loop.
                    _time.sleep(0.2)
                    continue
                if not np.array_equal(np.asarray(d), oracle[k]):
                    diffs.append(k)
                failed_here = False
                break
            else:
                failed_here = True
            if failed_here:
                failed.append(k)

    try:
        run_slice(picks[:third])                     # warm the fleet
        marks = {"warm": cache_totals()}
        health_pre = door.health()
        run_slice(picks[third:2 * third])            # pre-kill window
        marks["pre_kill"] = cache_totals()
        hit_pre = window_hit_rate(marks["warm"], marks["pre_kill"])

        sig = (signal.SIGKILL if args.fault == "kill" else signal.SIGSTOP)
        t_kill = _time.monotonic()
        victim_proc.send_signal(sig)
        # Detection: the lease goes stale, the door ejects within the
        # TTL (+ the watch cadence), traffic re-routes to the replicas.
        detect_budget = args.lease_ttl * 3 + 5.0
        while victim in door.ring and \
                _time.monotonic() - t_kill < detect_budget:
            _time.sleep(args.poll / 2)
        detect_s = _time.monotonic() - t_kill
        detected = victim not in door.ring
        health_after = door.health()

        tail = picks[2 * third:]
        run_slice(tail[:len(tail) // 2])             # recovery window
        marks["recovering"] = cache_totals()
        run_slice(tail[len(tail) // 2:])             # recovered window
        marks["recovered"] = cache_totals()
        hit_post = window_hit_rate(marks["recovering"],
                                   marks["recovered"])

        rejoined = None
        if args.fault == "partition":
            victim_proc.send_signal(signal.SIGCONT)
            budget = _time.monotonic() + args.lease_ttl * 4 + 5.0
            while victim not in door.ring and _time.monotonic() < budget:
                _time.sleep(args.poll / 2)
            rejoined = victim in door.ring
        health_final = door.health()

        fstats = door.stats()
        hit_recovered = (hit_pre is not None and hit_post is not None
                         and hit_post >= hit_pre - 0.10)
        report.update(
            peers=args.peers,
            replicas=args.replicas,
            requests=total,
            distinct=distinct,
            victim=victim,
            detected=detected,
            detect_s=round(detect_s, 3),
            lease_ttl_s=args.lease_ttl,
            recovered=detected and not failed,
            byte_identical=not diffs,
            differing_products=diffs[:8],
            failed_requests=len(failed),
            hit_rate_pre_kill=(round(hit_pre, 4)
                               if hit_pre is not None else None),
            hit_rate_post_recovery=(round(hit_post, 4)
                                    if hit_post is not None else None),
            hit_rate_recovered=hit_recovered,
            rejoined=rejoined,
            healthz={
                "pre": health_pre["status"],
                "after_detect": health_after["status"],
                "final": health_final["status"],
                "final_reasons": health_final["reasons"],
            },
            counters=fstats["counters"],
            work_dir=work,
        )
    finally:
        door.close()
        # A SIGSTOPped victim cannot be reaped until it runs again.
        if args.fault in ("hang", "partition") and \
                victim_proc.poll() is None:
            try:
                victim_proc.send_signal(signal.SIGCONT)
            except OSError:
                pass
        _reap_fleet_peers(procs)

    ok = (report["recovered"] and report["byte_identical"]
          and report["hit_rate_recovered"]
          and report["healthz"]["after_detect"] == "degraded"
          and (rejoined is None or rejoined))
    report["ok"] = ok
    body = json.dumps(report)
    print(body)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(body)
    return 0 if ok else 1


def _chaos_fleet_resize(args: argparse.Namespace, work: str,
                        report: dict) -> int:
    """``blit chaos --fleet --fault resize`` (ISSUE 17): SIGKILL a
    serving peer DURING the elastic warm handoff — the worst moment:
    the controller is mid-flip, the joiner is computing its incoming
    hot range, and a peer that was supposed to keep serving dies.
    Asserts the resize contract under fire:

    - the membership flip still COMPLETES (the standby is admitted;
      fail-open if the handoff deadline burns),
    - ``/healthz`` answers an honest ``"resizing"`` mid-flip,
    - the killed peer is detected within the lease TTL and ejected,
    - every request completes BYTE-IDENTICAL to a single-process
      oracle,
    - post-resize hit-rate is within 10% of pre-resize.

    The product set is EXTENDED until the joiner's incoming key range
    holds several hot products, so the handoff has real work to
    interrupt (otherwise the flip is sub-millisecond and the kill
    cannot land inside it)."""
    import math
    import os
    import random
    import signal
    import threading
    import time as _time

    import numpy as np

    from blit.observability import Timeline
    from blit.serve import Overloaded, ProductRequest
    from blit.serve.cache import fingerprint_for
    from blit.serve.elastic import FleetController
    from blit.serve.fleet import FleetError, FleetFrontDoor
    from blit.serve.http import http_json
    from blit.serve.scheduler import DeadlineExpired
    from blit.testing import synth_raw

    rng = random.Random(args.seed)
    nfft = args.nfft
    joiner = "standby0"
    total = max(30, args.fleet_requests)
    ntime = (8 + 3) * nfft
    reqs, oracle, fps = [], {}, []

    def add_product(i: int) -> None:
        path = os.path.join(work, f"prod{i:02d}.raw")
        synth_raw(path, nblocks=1, obsnchan=2, ntime_per_block=ntime,
                  seed=args.seed + i)
        req = ProductRequest(raw=path, nfft=nfft, nint=1)
        reqs.append(req)
        fps.append(fingerprint_for(req.reducer(), req.raw_source))
        # The single-process oracle: the same reduction, no fleet.
        _, data = req.reducer().reduce(path)
        oracle[i] = np.asarray(data)

    for i in range(max(2, args.fleet_distinct)):
        add_product(i)
    procs, peers, lease_dir = _spawn_fleet_peers(
        work, args.peers, concurrency=2, queue_depth=32,
        ram_bytes=64 << 20,
        beat_interval_s=min(0.2, args.lease_ttl / 5), standbys=1)
    tl = Timeline()
    door = FleetFrontDoor(
        {f"peer{i}": peers[f"peer{i}"] for i in range(args.peers)},
        lease_dir=lease_dir, timeline=tl, replicas=args.replicas,
        peer_ttl_s=args.lease_ttl, poll_s=args.poll,
        health_poll_s=max(args.poll, 0.5),
        hedge_floor_s=0.05, request_timeout_s=10.0).start()
    door.add_standby(joiner, peers[joiner], proc=args.peers)
    ctl = FleetController(door, None, hysteresis_s=0.0,
                          warm_timeout_s=30.0, min_peers=1,
                          warm_hints=64, timeline=tl)
    # Grow the mix until >= 3 products will MOVE to the joiner on
    # admit — the handoff then computes them on the cold joiner, a
    # window wide enough to kill a peer inside.
    while len(reqs) < 40 and \
            len(door.ring.incoming_keys(joiner, fps)) < 3:
        add_product(len(reqs))
    incoming = door.ring.incoming_keys(joiner, fps)

    victim = door.ring.owners(fps[0])[0]
    victim_proc = procs[int(victim.removeprefix("peer"))][0]
    weights = [1.0 / math.pow(k + 1, 1.2) for k in range(len(reqs))]
    picks = rng.choices(range(len(reqs)), weights=weights, k=total)
    third = total // 3

    def cache_totals() -> dict:
        out = {}
        for name, url in peers.items():
            try:
                _, _, s = http_json("GET", url, "/stats", timeout=2.0)
            except OSError:
                continue
            c = s.get("cache") or {}
            out[name] = (c.get("hit.ram", 0) + c.get("hit.disk", 0),
                         c.get("miss", 0))
        return out

    def window_hit_rate(before: dict, after: dict):
        dh = dm = 0
        for name, (h1, m1) in after.items():
            if name not in before:
                continue
            h0, m0 = before[name]
            dh += max(0, h1 - h0)
            dm += max(0, m1 - m0)
        return (dh / (dh + dm)) if dh + dm else None

    failed: list = []
    diffs: list = []

    def run_slice(idxs) -> None:
        for k in idxs:
            for _attempt in range(8):
                try:
                    _, d = door.get(reqs[k], client="chaos")
                except Overloaded as e:
                    _time.sleep(min(0.25, e.retry_after_s))
                    continue
                except (FleetError, DeadlineExpired, OSError):
                    _time.sleep(0.2)
                    continue
                if not np.array_equal(np.asarray(d), oracle[k]):
                    diffs.append(k)
                failed_here = False
                break
            else:
                failed_here = True
            if failed_here:
                failed.append(k)

    flip_completed = detected = False
    mid_handoff = False
    resizing_status = None
    detect_s = None
    hit_pre = hit_post = None
    out_rec: list = []
    try:
        # Warm every product once (so the door's hot map knows the
        # whole range), then the zipfian pre window.
        run_slice(list(range(len(reqs))) + picks[:third])
        marks = {"warm": cache_totals()}
        run_slice(picks[third:2 * third])
        marks["pre"] = cache_totals()
        hit_pre = window_hit_rate(marks["warm"], marks["pre"])
        health_pre = door.health()

        # The flip, in a thread — and the kill, INSIDE the handoff.
        t = threading.Thread(target=lambda: out_rec.append(
            ctl.scale_out(joiner)))
        t.start()
        gate = _time.monotonic() + 30.0
        while _time.monotonic() < gate:
            if door.resize_reason is not None:
                mid_handoff = True
                break
            _time.sleep(0.001)
        if mid_handoff:
            resizing_status = door.health()["status"]
        t_kill = _time.monotonic()
        victim_proc.send_signal(signal.SIGKILL)
        t.join(timeout=120.0)
        flip_completed = joiner in door.ring

        detect_budget = args.lease_ttl * 3 + 5.0
        while victim in door.ring and \
                _time.monotonic() - t_kill < detect_budget:
            _time.sleep(args.poll / 2)
        detect_s = _time.monotonic() - t_kill
        detected = victim not in door.ring

        tail = picks[2 * third:]
        run_slice(tail[:len(tail) // 2])             # recovery window
        marks["recovering"] = cache_totals()
        run_slice(tail[len(tail) // 2:])             # recovered window
        marks["recovered"] = cache_totals()
        hit_post = window_hit_rate(marks["recovering"],
                                   marks["recovered"])
        health_final = door.health()

        fstats = door.stats()
        hit_recovered = (hit_pre is not None and hit_post is not None
                         and hit_post >= hit_pre - 0.10)
        report.update(
            peers=args.peers,
            replicas=args.replicas,
            requests=total,
            distinct=len(reqs),
            joiner=joiner,
            joiner_incoming=len(incoming),
            victim=victim,
            killed_mid_handoff=mid_handoff,
            resizing_status=resizing_status,
            flip_completed=flip_completed,
            warm=(out_rec[0] if out_rec else None),
            detected=detected,
            detect_s=round(detect_s, 3),
            lease_ttl_s=args.lease_ttl,
            recovered=detected and not failed,
            byte_identical=not diffs,
            differing_products=diffs[:8],
            failed_requests=len(failed),
            hit_rate_pre_resize=(round(hit_pre, 4)
                                 if hit_pre is not None else None),
            hit_rate_post_resize=(round(hit_post, 4)
                                  if hit_post is not None else None),
            hit_rate_recovered=hit_recovered,
            healthz={
                "pre": health_pre["status"],
                "mid_flip": resizing_status,
                "final": health_final["status"],
                "final_reasons": health_final["reasons"],
            },
            counters=fstats["counters"],
            work_dir=work,
        )
    finally:
        ctl.close()
        door.close()
        _reap_fleet_peers(procs)

    ok = (flip_completed and mid_handoff
          and resizing_status == "resizing"
          and report.get("recovered", False)
          and report.get("byte_identical", False)
          and report.get("hit_rate_recovered", False))
    report["ok"] = ok
    body = json.dumps(report)
    print(body)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(body)
    return 0 if ok else 1


def _cmd_session(args: argparse.Namespace) -> int:
    """``blit session`` (ISSUE 18): run (or rejoin) a whole LIVE
    observing session from a spec file — one supervised stream consumer
    per recorder seat, fanned across this host, each crash-rejoinable
    through its StreamCursor.  The spec is JSON::

        {"seats": [{"name": "blc00", "out": "...", "raw": "...",
                    "source": {"kind": "packet", "port": 60000},
                    "knobs": {"nfft": 1024}}, ...],
         "work_dir": "...", "lease_ttl_s": 5.0}

    (seat/source fields: :class:`blit.stream.SessionSupervisor` /
    :func:`blit.stream.source_from_spec`).  Re-running the same spec
    after a host crash REJOINS every seat mid-product.  Prints the
    folded session report; exit 0 = every seat completed."""
    import tempfile

    from blit.observability import Timeline
    from blit.stream import SessionSupervisor

    with open(args.spec) as f:
        spec = json.load(f)
    tl = Timeline()
    pub = _monitor_from_flags(args)
    work = (args.work_dir or spec.get("work_dir")
            or tempfile.mkdtemp(prefix="blit-session-"))
    sup = SessionSupervisor(
        spec["seats"], work_dir=work,
        lease_ttl_s=(args.lease_ttl if args.lease_ttl is not None
                     else spec.get("lease_ttl_s")),
        poll_s=(args.poll if args.poll is not None
                else spec.get("poll_s")),
        max_attempts=(args.attempts if args.attempts is not None
                      else spec.get("max_attempts")),
        faults=spec.get("faults"), timeline=tl,
    )
    rep = sup.run()
    rep["work_dir"] = work
    if pub is not None:
        pub.tick()
        rep["monitor"] = {"port": pub.port, "spool": pub.spool_path}
        from blit import monitor

        monitor.shutdown_publisher()
    body = json.dumps(rep)
    print(body)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(body)
    return 0 if rep["ok"] else 1


def _cmd_chaos(args: argparse.Namespace) -> int:
    """``blit chaos`` (ISSUE 12): run a SEEDED kill/hang schedule
    against a real supervised workload — a multi-process sharded scan
    (``--workload scan`` / ``scan-search``) or a live stream consumer
    (``--workload stream``) — and assert the recovery contract end to
    end: the failure is DETECTED within the lease budget, the scan
    re-plans (reshaped mesh or pool fallback) / the consumer rejoins,
    and the final products are BYTE-IDENTICAL to an uninterrupted
    oracle run.  Prints (and optionally writes) the drill report JSON;
    exit 0 = recovered and identical."""
    import os
    import tempfile

    from blit.observability import Timeline
    from blit.recover import RECOVER_HISTS, ScanSupervisor, StreamSupervisor
    from blit.testing import synth_raw

    tl = Timeline()
    work = args.work_dir or tempfile.mkdtemp(prefix="blit-chaos-")
    os.makedirs(work, exist_ok=True)
    if args.fleet:
        if args.fault == "corrupt":
            print("chaos --fleet supports kill/hang/partition, "
                  "not corrupt", file=sys.stderr)
            return 2
        report = {"workload": "fleet", "fault": args.fault}
        if args.fault == "resize":
            return _chaos_fleet_resize(args, work, report)
        return _chaos_fleet(args, work, report)
    if args.fault == "partition":
        print("--fault partition requires --fleet (a network partition "
              "is a serving-fleet failure shape)", file=sys.stderr)
        return 2
    if args.fault == "resize":
        print("--fault resize requires --fleet (an elastic membership "
              "flip is a serving-fleet failure shape)", file=sys.stderr)
        return 2
    if args.fault == "reorder" and args.workload != "stream":
        print("--fault reorder requires --workload stream (wire "
              "reordering is a packet front-end failure shape)",
              file=sys.stderr)
        return 2
    use_packets = args.workload == "stream" and (
        args.packets or args.fault == "reorder")
    point = args.point or (
        "packet.recv" if args.fault == "reorder"
        else "stream.chunk" if args.workload == "stream"
        else "mesh.window")
    if args.fault == "corrupt":
        # The integrity leg (ISSUE 13) is its own drill shape: no
        # supervisor, no crash — a corrupted delivered frame must be
        # detected and MASKED, whatever the workload flag says.
        report = {"workload": "reduce",
                  "fault": f"guppi.read:corrupt:after={args.after}"}
        return _chaos_corrupt(args, work, report)
    fault = (f"{point}:{args.fault}:after={args.after}"
             + (f":hang={args.hang_s}" if args.fault == "hang" else ""))
    report = {"workload": args.workload, "fault": fault,
              "procs": args.procs}

    if args.workload == "stream":
        raw = os.path.join(work, "chaos.raw")
        nblocks = max(4, args.chunks)
        ntime = (args.chunks * args.window_frames + 3) * args.nfft
        hdr0, blocks = synth_raw(
            raw, nblocks=nblocks, obsnchan=args.nchan,
            ntime_per_block=-(-ntime // nblocks), seed=args.seed)
        out = os.path.join(work, "chaos.fil")
        oracle = os.path.join(work, "oracle.fil")
        from blit.pipeline import RawReducer

        source = None
        oracle_raw = raw
        if use_packets:
            # The packet drill's seeded schedule: with --packets, one
            # whole block is dropped off the wire — the oracle is then
            # the SAME recording with that block zero-filled (gap ≡
            # mask ≡ zero weight, the acceptance identity).  A plain
            # --fault reorder keeps every packet, so the clean batch
            # oracle stands.
            source = {"kind": "packet-replay", "raw": raw,
                      "rate": args.replay_rate,
                      "packet_ntime": args.packet_ntime,
                      "seed": args.seed}
            if args.packets:
                from blit.io.guppi import write_raw

                source.update(drop_blocks=[1], reorder=0.15, dup=0.05)
                report["gapped_blocks"] = [1]
                zb = [b.copy() for b in blocks]
                zb[1][:] = 0
                oracle_raw = os.path.join(work, "chaos_zeroed.raw")
                write_raw(oracle_raw, hdr0, zb)
        RawReducer(nfft=args.nfft, nint=args.nint,
                   chunk_frames=args.window_frames,
                   tune_online=False).reduce_to_file(oracle_raw, oracle)
        sup = StreamSupervisor(
            raw, out, kind="reduce",
            knobs=dict(nfft=args.nfft, nint=args.nint,
                       chunk_frames=args.window_frames,
                       tune_online=False),
            replay_rate=args.replay_rate, source=source, faults=fault,
            lease_ttl_s=args.lease_ttl, poll_s=args.poll,
            max_attempts=args.attempts, timeline=tl,
        )
        rep = _chaos_run(sup)
        products = [(out, oracle)]
    else:
        kind = "search" if args.workload == "scan-search" else "reduce"
        grid = []
        bank_bw = -187.5 / args.nbank
        for b in range(args.nband):
            row = []
            for k in range(args.nbank):
                p = os.path.join(work, f"blc{b}{k}.raw")
                synth_raw(
                    p, nblocks=2, obsnchan=args.nchan,
                    ntime_per_block=-(-(args.chunks * args.window_frames
                                        + 3) * args.nfft // 2),
                    seed=args.seed + b * 8 + k, tone_chan=k % args.nchan,
                    obsbw=bank_bw,
                    obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw,
                )
                row.append(p)
            grid.append(row)
        out_dir = os.path.join(work, "products")
        oracle_dir = os.path.join(work, "oracle")
        os.makedirs(oracle_dir, exist_ok=True)
        search_kw = dict(window_spectra=args.window_spectra, top_k=4,
                         snr_threshold=2.0, max_drift_bins=None,
                         kernel="reference")
        sup = ScanSupervisor(
            grid, out_dir=out_dir, kind=kind, nfft=args.nfft,
            nint=args.nint, despike=False,
            window_frames=args.window_frames,
            search=(search_kw if kind == "search" else None),
            nprocs=args.procs,
            devices_per_proc=(
                args.devices_per_proc if args.devices_per_proc
                else (args.nband * args.nbank) // args.procs),
            lease_ttl_s=args.lease_ttl, poll_s=args.poll,
            max_attempts=args.attempts,
            faults={args.victim: fault}, timeline=tl,
        )
        rep = _chaos_run(sup)
        # The pool oracle over the identical scan, at the SAME window
        # granularity (dispatch shape is part of the identity contract).
        wf = sup.wf
        if kind == "search":
            from blit.search import DedopplerReducer

            products = []
            for b in range(args.nband):
                for k in range(args.nbank):
                    op = os.path.join(oracle_dir, f"band{b}bank{k}.hits")
                    DedopplerReducer(
                        nfft=args.nfft, nint=args.nint, chunk_frames=wf,
                        **search_kw,
                    ).search_to_file(grid[b][k], op)
                    products.append(
                        (os.path.join(out_dir, f"band{b}bank{k}.hits"),
                         op))
        else:
            from blit.parallel.scan import reduce_scan_pool_to_files

            written = reduce_scan_pool_to_files(
                grid, out_dir=oracle_dir, nfft=args.nfft,
                nint=args.nint, despike=False, window_frames=wf)
            products = [
                (os.path.join(out_dir, os.path.basename(path)), path)
                for _, (path, _) in sorted(written.items())
            ]

    import filecmp

    identical = True
    diffs = []
    for got, want in products:
        try:
            # filecmp, not read()==read(): constant memory over
            # realistically-sized products (the PR 8 compare rule).
            same = filecmp.cmp(got, want, shallow=False)
        except OSError:
            same = False
        if not same:
            identical = False
            diffs.append(got)
    hists = tl.report().get("hists", {})
    report.update(
        recovered=rep.get("recovered", False),
        error=rep.get("error"),
        byte_identical=identical,
        differing_products=diffs,
        attempts=rep.get("attempts"),
        result=rep.get("result"),
        recover={h: hists.get(h, {}) for h in RECOVER_HISTS},
        windows_recomputed=sum(
            a.get("windows_recomputed", 0)
            for a in (rep.get("attempts") or [])),
        work_dir=work,
    )
    body = json.dumps(report)
    print(body)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(body)
    # Only the process-grade faults demand a RECOVERY (a restart to
    # detect); a data-plane fault like reorder is absorbed in place —
    # there, "no error and byte-identical" IS the pass.
    crashy = args.fault in ("kill", "hang")
    ok = identical and (report["recovered"] if crashy
                        else not rep.get("error"))
    return 0 if ok else 1


def _cmd_telemetry(args: argparse.Namespace) -> int:
    """Fleet telemetry report (ISSUE 5 tentpole #3).  Three sources:
    ``--from`` renders a saved report JSON; ``--demo`` runs a real
    multi-worker ``reduce_to_file`` fan-out over synthetic recordings and
    harvests the pool (the end-to-end proof: every worker's stage table
    and fault counters in one per-host report, plus a Perfetto-loadable
    trace via ``--trace-out``); the default snapshots this process."""
    import json as _json

    from blit import observability

    if args.watch is not None and not args.demo:
        # Poor-man's live mode (ISSUE 11 satellite): periodic re-harvest
        # + re-render on `blit top`'s refresh path (monitor.watch_loop —
        # same ANSI frame loop, same cadence semantics).
        from blit import monitor

        def frame() -> str:
            if args.from_file:
                with open(args.from_file) as f:
                    rep = _json.load(f)
            else:
                rep = observability.local_fleet_report()
            if args.format == "prom":
                return observability.render_prometheus(rep)
            if args.format == "json":
                return _json.dumps(rep)
            return observability.render_fleet_text(rep)

        monitor.watch_loop(frame, args.watch, count=args.iterations)
        return 0
    if args.from_file:
        with open(args.from_file) as f:
            report = _json.load(f)
    elif args.demo:
        import os
        import tempfile

        from blit import workers
        from blit.parallel.pool import WorkerPool
        from blit.testing import synth_raw

        n = max(1, args.workers)
        with tempfile.TemporaryDirectory(prefix="blit-telemetry-") as td:
            argtuples = []
            for i in range(n):
                raw = os.path.join(td, f"demo{i}.raw")
                synth_raw(raw, nblocks=1, obsnchan=2,
                          ntime_per_block=(8 + 3) * args.nfft, seed=i)
                argtuples.append((raw, os.path.join(td, f"demo{i}.fil")))
            with WorkerPool([f"w{i + 1}" for i in range(n)],
                            backend=args.backend) as pool:
                with observability.span("telemetry-demo", workers=n):
                    pool.run_on(list(range(1, n + 1)), workers.reduce_raw,
                                argtuples, kwargs={"nfft": args.nfft})
                report = pool.harvest_telemetry()
    else:
        report = observability.local_fleet_report()
    if args.trace_out:
        # Works in every source mode: the tracer holds this process's
        # spans, the report carries any harvested (or saved) ones.
        observability.tracer().export_chrome(
            args.trace_out, extra=report.get("spans"))
        print(f"# trace written to {args.trace_out}", file=sys.stderr)
    if args.format == "prom":
        print(observability.render_prometheus(report), end="")
    elif args.format == "json":
        print(_json.dumps(report))
    else:
        print(observability.render_fleet_text(report))
    return 0


def _cmd_top(args: argparse.Namespace) -> int:
    """``blit top`` (ISSUE 11 tentpole): the live terminal dashboard.
    ``--spool DIR`` tails the per-process monitor spool (merging a pod's
    processes through ``merge_fleet``); ``--url`` polls one publisher's
    ``/snapshot`` endpoint.  Refreshes every ``--interval`` seconds with
    an ANSI clear; ``--once`` renders a single frame with no clear.
    ``--history DIR`` appends a sparkline panel per stored series from
    a durable history store (ISSUE 20: the last N finest-tier
    buckets)."""
    from blit import monitor, observability

    def fetch() -> str:
        if args.url:
            import urllib.request

            with urllib.request.urlopen(
                    args.url.rstrip("/") + "/snapshot", timeout=10) as r:
                sample = json.load(r)
            report = observability.merge_fleet([sample])
            samples = [sample]
        else:
            report, samples = monitor.merge_spool(args.spool)
        frame = monitor.render_top(report, samples)
        if args.history:
            from blit.history import HistoryStore, render_history_panel

            store = HistoryStore(args.history, create=False)
            frame += "\n" + render_history_panel(
                store, buckets=args.history_buckets)
        return frame

    if args.once:
        print(fetch())
        return 0
    monitor.watch_loop(fetch, args.interval, count=args.iterations)
    return 0


def _cmd_bench_diff(args: argparse.Namespace) -> int:
    """``blit bench-diff`` (ISSUE 11 tentpole): the perf-regression
    gate.  Loads the fresh record and the baseline trajectory (explicit
    ``--baseline`` files and/or every ``BENCH_*.json`` under
    ``--baseline-dir``, the fresh file itself excluded), compares every
    shared higher-is-better metric against the trajectory's noise band,
    and exits 0 on pass / 2 on regress."""
    import os

    from blit import monitor

    baselines = []
    if args.baseline_dir:
        import glob

        fresh_real = os.path.realpath(args.fresh)
        for p in sorted(glob.glob(
                os.path.join(args.baseline_dir, "BENCH_*.json"))):
            if os.path.realpath(p) == fresh_real:
                continue
            try:
                baselines.append(monitor.load_bench_json(p))
            except ValueError as e:
                # A failed round with no record line is part of history;
                # it thins the trajectory, it doesn't break the gate.
                print(f"# bench-diff: skipping {p}: {e}",
                      file=sys.stderr)
    for p in args.baseline or []:
        baselines.append(monitor.load_bench_json(p))
    if not baselines:
        raise SystemExit("bench-diff needs at least one baseline "
                         "(--baseline / --baseline-dir)")
    fresh = monitor.load_bench_json(args.fresh)
    metrics = args.metrics.split(",") if args.metrics else None
    verdict = monitor.bench_diff(fresh, baselines, rel_tol=args.noise,
                                 metrics=metrics,
                                 cross_rig=args.cross_rig)
    if args.json:
        print(json.dumps(verdict))
    else:
        print(monitor.render_bench_diff(verdict))
    return 0 if verdict["verdict"] == "pass" else 2


def _cmd_trace_view(args: argparse.Namespace) -> int:
    """Render a flight-recorder dump into an incident summary, or
    (``--fleet``, ISSUE 15) stitch span batches from many processes —
    monitor spools, saved snapshots, live ``/snapshot`` endpoints —
    into ONE trace view: a Perfetto export (``--out``), per-trace trees
    (``--trace``), and tail-bucket exemplar resolution
    (``--exemplar METRIC`` → the trace id behind the slowest bucket)."""
    import json as _json

    from blit.observability import render_flight_dump

    if args.fleet:
        return _trace_view_fleet(args)
    if not args.dump:
        raise SystemExit("trace-view needs a flight dump path "
                         "(or --fleet SOURCES)")
    with open(args.dump) as f:
        doc = _json.load(f)
    print(render_flight_dump(doc, tail=args.events))
    if args.trace or args.exemplar or args.out:
        # A flight dump is itself a span batch: reuse the fleet path so
        # `trace-view dump.json --trace <id>` follows the dump's trace.
        args.fleet = [args.dump]
        return _trace_view_fleet(args)
    return 0


def _trace_view_fleet(args: argparse.Namespace) -> int:
    """The fleet half of ``blit trace-view`` (ISSUE 15 tentpole #4)."""
    from blit import monitor, observability

    spans, hists = monitor.gather_trace_sources(args.fleet)
    summary = observability.trace_summary(spans)
    out = {"sources": list(args.fleet), **summary}
    if args.out:
        tr = observability.Tracer(max_spans=max(len(spans), 1),
                                  enabled=True)
        tr.ingest(spans)
        tr.export_chrome(args.out)
        out["out"] = args.out
    exemplar_trace = None
    if args.exemplar:
        h = hists.get(args.exemplar)
        ex = h.tail_exemplar() if h is not None else None
        if ex is None:
            print(json.dumps(out))
            print(f"# no exemplar recorded for {args.exemplar!r} "
                  f"({len(hists)} histogram(s) in the sources)",
                  file=sys.stderr)
            return 1
        out["exemplar"] = {"metric": args.exemplar, **ex}
        exemplar_trace = ex["trace"]
    print(json.dumps(out))
    for trace_id in ([args.trace] if args.trace else []) + (
            [exemplar_trace] if exemplar_trace else []):
        print(observability.render_trace_tree(spans, trace_id))
    return 0


def _cmd_requests(args: argparse.Namespace) -> int:
    """``blit requests`` (ISSUE 15 tentpole #2): tail, filter and
    aggregate a per-request access-record spool — the operator's "which
    requests were slow, and whose trace do I open" surface."""
    from blit import monitor

    since = until = None
    if args.since or args.until:
        import time

        from blit.history import parse_when

        now = time.time()
        since = parse_when(args.since, now) if args.since else None
        until = parse_when(args.until, now) if args.until else None
    records = monitor.read_requests(args.spool, tail=args.tail)
    records = monitor.filter_requests(
        records, slow_ms=args.slow_ms, status=args.status,
        client=args.client, role=args.role, since=since, until=until)
    if args.aggregate:
        agg = monitor.aggregate_requests(records)
        print(json.dumps(agg) if args.json
              else json.dumps(agg, indent=2))
        return 0
    if args.json:
        for r in records:
            print(json.dumps(r))
    else:
        print(monitor.render_requests(records))
    return 0


def _incident_dir(args: argparse.Namespace) -> str:
    from blit.config import history_defaults

    d = args.dir or history_defaults()["incident_dir"]
    if not d:
        raise SystemExit("no incident dir: pass --dir or set "
                         "BLIT_INCIDENT_DIR")
    return d


def _cmd_incidents(args: argparse.Namespace) -> int:
    """``blit incidents`` (ISSUE 20): list the self-contained forensics
    bundles under the incident dir, oldest first."""
    from blit.history import list_incidents, render_incidents

    manifests = list_incidents(_incident_dir(args))
    if args.json:
        for m in manifests:
            print(json.dumps(m))
    else:
        print(render_incidents(manifests))
    return 0


def _cmd_incident(args: argparse.Namespace) -> int:
    """``blit incident show BUNDLE`` (ISSUE 20): render one bundle's
    merged cross-source timeline — flight events, request records,
    trace spans and the triggering alert, wall-clock aligned via the
    stamped anchors.  ``--window`` narrows the timeline around the
    page using the shared grammar (``15m``, ``2h``, an epoch pair)."""
    import time

    from blit.history import load_incident, render_incident, window_seconds

    bundle = load_incident(args.bundle)
    window = None
    if args.window:
        t = float((bundle.get("manifest") or {}).get("t", time.time()))
        half = window_seconds(args.window)
        window = (t - half, t + half / 4.0)
    if args.json:
        print(json.dumps(bundle))
    else:
        print(render_incident(bundle, window))
    return 0


def _cmd_slo_report(args: argparse.Namespace) -> int:
    """``blit slo-report`` (ISSUE 20): attainment + error-budget spend
    per objective over day/week windows, straight from a durable
    history store — text for the operator, ``--json`` for CI (its
    ``metrics`` block rides ``bench_metrics``/``blit bench-diff``, so
    attainment gates like any bench scalar)."""
    from blit.history import (
        HistoryStore,
        render_slo_report,
        slo_report,
        window_seconds,
    )

    store = HistoryStore(args.store, create=False)
    doc = slo_report(store, window_s=window_seconds(args.window))
    body = json.dumps(doc) if args.json else render_slo_report(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write((json.dumps(doc) if args.json else body) + "\n")
    print(body)
    return 0


def _cmd_info(args: argparse.Namespace) -> int:
    path = args.file
    if path.endswith(".raw") or _looks_like_raw(path):
        from blit.io.guppi import open_raw

        raw = open_raw(path)
        hdr = dict(raw.header(0))
        hdr["_nblocks"] = raw.nblocks
        hdr["_files"] = getattr(raw, "paths", [raw.path])
        hdr["_time_span_s"] = raw.time_span_s()
    else:
        from blit.workers import get_header

        hdr = get_header(path)
    print(json.dumps(hdr, indent=2, default=str))
    return 0


def _looks_like_raw(path: str) -> bool:
    import os

    from blit.io.guppi import scan_files

    return not os.path.exists(path) and bool(scan_files(path))


# rawspec's standard product presets (stable contract, mirrored from
# blit.pipeline.PRODUCT_PRESETS — not imported here so `blit info` /
# `blit inventory` never pay the jax import just to build --product
# choices; tests/test_cli.py pins the two lists equal).
_PRODUCTS = ("0000", "0001", "0002")


def _add_monitor_flags(parser: argparse.ArgumentParser) -> None:
    """The shared ``--monitor-*`` flag set (ISSUE 11): commands that run
    long enough to watch grow a live publisher switch."""
    parser.add_argument("--monitor-spool", default=None,
                        help="spool live telemetry samples (JSON lines) "
                             "into this dir; `blit top --spool` tails it")
    parser.add_argument("--monitor-port", type=int, default=None,
                        help="serve /metrics, /healthz and /snapshot on "
                             "this port while running (0 = ephemeral; "
                             "the chosen port prints to stderr)")
    parser.add_argument("--monitor-interval", type=float, default=0.25,
                        help="publisher snapshot cadence in seconds")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="blit", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("reduce", help="RAW → filterbank product")
    pr.add_argument("raw", nargs="+",
                    help="RAW file, .NNNN.raw sequence stem, or member list")
    pr.add_argument("-o", "--output", required=True,
                    help="output product path (.fil streams; .h5 = FBH5)")
    pr.add_argument("--product", choices=list(_PRODUCTS),
                    help="rawspec product preset (else --nfft/--nint)")
    pr.add_argument("--nfft", type=int, default=1024)
    pr.add_argument("--nint", type=int, default=1)
    pr.add_argument("--stokes", default="I")
    pr.add_argument("--fqav", type=int, default=1,
                    help="on-device frequency averaging factor")
    pr.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    pr.add_argument("--compression", default=None,
                    choices=["gzip", "bitshuffle"],
                    help="codec for .h5 (FBH5) output")
    pr.add_argument("--resume", action="store_true",
                    help="crash-resumable streaming (cursor sidecar; "
                         ".fil and .h5)")
    pr.set_defaults(fn=_cmd_reduce)

    ph = sub.add_parser(
        "search",
        help="RAW → .hits drift-rate search product (on-device dedoppler)",
    )
    ph.add_argument("raw", nargs="+",
                    help="RAW file, .NNNN.raw sequence stem, or member list")
    ph.add_argument("-o", "--output", required=True,
                    help="output .hits product path (JSON lines)")
    ph.add_argument("--product", choices=list(_PRODUCTS),
                    help="rawspec product preset for the underlying "
                         "filterbank (else --nfft/--nint)")
    ph.add_argument("--nfft", type=int, default=1024)
    ph.add_argument("--nint", type=int, default=1)
    ph.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ph.add_argument("--window-spectra", type=int, default=None,
                    help="spectra per drift transform (power of two; "
                         "default SiteConfig/BLIT_SEARCH_WINDOW)")
    ph.add_argument("--snr", type=float, default=None,
                    help="device-side SNR threshold "
                         "(default SiteConfig/BLIT_SEARCH_SNR)")
    ph.add_argument("--top-k", type=int, default=None,
                    help="hits kept per band per window "
                         "(default SiteConfig/BLIT_SEARCH_TOP_K)")
    ph.add_argument("--max-drift-bins", type=int, default=None,
                    help="clamp the searched drift range (bins/window; "
                         "default the full ±(window-1))")
    ph.add_argument("--kernel", default="auto",
                    choices=["auto", "reference", "pallas"],
                    help="drift-transform backend")
    ph.add_argument("--interpret", action="store_true",
                    help="run the pallas kernel in interpreter mode "
                         "(CPU smoke tests)")
    ph.add_argument("--resume", action="store_true",
                    help="crash-resumable search (cursor sidecar; resumes "
                         "at the last durable window boundary)")
    ph.set_defaults(fn=_cmd_search)

    pl = sub.add_parser(
        "stream",
        help="LIVE reduction: follow (or replay) a recording and write "
             "the product during the session (ISSUE 7)",
    )
    pl.add_argument("raw",
                    help="RAW file (or growing .NNNN.raw member) to "
                         "follow, or the completed recording to replay")
    pl.add_argument("-o", "--output", required=True,
                    help="product path: .fil / .h5, or .hits with "
                         "--search")
    pl.add_argument("--product", choices=list(_PRODUCTS),
                    help="rawspec product preset (else --nfft/--nint)")
    pl.add_argument("--nfft", type=int, default=1024)
    pl.add_argument("--nint", type=int, default=1)
    pl.add_argument("--stokes", default="I")
    pl.add_argument("--fqav", type=int, default=1)
    pl.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    pl.add_argument("--compression", default=None,
                    choices=["gzip", "bitshuffle"],
                    help="codec for .h5 (FBH5) output")
    pl.add_argument("--search", action="store_true",
                    help="write a .hits drift-search product instead of "
                         "a filterbank")
    pl.add_argument("--window-spectra", type=int, default=None,
                    help="search window (with --search; default "
                         "SiteConfig/BLIT_SEARCH_WINDOW)")
    pl.add_argument("--snr", type=float, default=None,
                    help="search SNR threshold (with --search)")
    pl.add_argument("--top-k", type=int, default=None,
                    help="hits kept per band per window (with --search)")
    pl.add_argument("--replay-rate", type=float, default=None,
                    help="replay a COMPLETED recording at this multiple "
                         "of wall-clock recording rate instead of "
                         "tailing a growing one (1.0 = real time)")
    pl.add_argument("--lateness", type=float, default=None,
                    help="watermark allowed-lateness budget in seconds "
                         "(default SiteConfig/BLIT_STREAM_LATENESS); "
                         "chunks missing past it are masked to zero "
                         "weight, stragglers dropped")
    pl.add_argument("--poll", type=float, default=None,
                    help="growing-file poll cadence in seconds "
                         "(default SiteConfig/BLIT_STREAM_POLL)")
    pl.add_argument("--idle-timeout", type=float, default=None,
                    help="end the tail after this long without file "
                         "growth (default SiteConfig/"
                         "BLIT_STREAM_IDLE_TIMEOUT: wait forever)")
    pl.add_argument("--done-file", default=None,
                    help="end-of-session marker path (default "
                         "<stem>.done)")
    pl.add_argument("--resume", action="store_true",
                    help="rejoinable consumer (ISSUE 12): persist a "
                         ".stream-cursor sidecar so a restarted "
                         "consumer re-attaches to the still-recording "
                         "session mid-file, byte-identical to a "
                         "never-restarted one")
    _add_monitor_flags(pl)
    pl.set_defaults(fn=_cmd_stream)

    pv = sub.add_parser(
        "session",
        help="run (or rejoin) a whole LIVE observing session from a "
             "spec file: one supervised stream consumer per recorder "
             "seat, packet capture included (ISSUE 18)",
    )
    pv.add_argument("spec",
                    help="session spec JSON: {\"seats\": [{name, out, "
                         "source, knobs...}], ...} — see `blit.stream."
                         "SessionSupervisor`")
    pv.add_argument("--work-dir", default=None,
                    help="session lease/spec scratch dir (default: the "
                         "spec's work_dir, else a fresh temp dir); "
                         "re-use it to rejoin after a crash")
    pv.add_argument("--lease-ttl", type=float, default=None,
                    help="per-seat heartbeat lease TTL in seconds (the "
                         "seat-death detection budget)")
    pv.add_argument("--poll", type=float, default=None,
                    help="seat supervisor watch cadence")
    pv.add_argument("--attempts", type=int, default=None,
                    help="per-seat recovery attempt budget")
    pv.add_argument("--json-out", default=None,
                    help="also write the session report JSON here")
    _add_monitor_flags(pv)
    pv.set_defaults(fn=_cmd_session)

    ps = sub.add_parser(
        "scan", help="whole (session, scan) → per-band products via the mesh"
    )
    ps.add_argument("root", help="data tree root (as `blit inventory`)")
    ps.add_argument("session", help="e.g. AGBT22B_999_01")
    ps.add_argument("scan", help="4-digit scan number, e.g. 0011")
    ps.add_argument("-o", "--output-dir", required=True)
    ps.add_argument("--file-re", default=None,
                    help=r"inventory filename filter (default \.raw$)")
    ps.add_argument("--nfft", type=int, default=1024)
    ps.add_argument("--nint", type=int, default=1)
    ps.add_argument("--stokes", default="I")
    ps.add_argument("--fqav", type=int, default=1,
                    help="per-chip frequency averaging before the stitch")
    ps.add_argument("--no-despike", action="store_true")
    ps.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="per-chip channelizer stage dtype (bfloat16 = "
                         "the official bench's lever; product stays f32)")
    ps.add_argument("--window-frames", type=int, default=None,
                    help="PFB frames per device window (bounds HBM, host "
                         "RSS, and per-window readback).  Default: "
                         "8*2^20 samples' worth of frames — i.e. "
                         "max(8, 2^23/nfft), the dispatch size measured "
                         "HBM-safe at the hi-res preset; raise it only "
                         "if you have measured headroom")
    ps.add_argument("--max-frames", type=int, default=None)
    ps.add_argument("--trace-logdir", default=None,
                    help="write a JAX profiler trace of the window loop")
    ps.add_argument("--compression", default=None,
                    choices=["gzip", "bitshuffle"],
                    help="write .h5 (FBH5) band products with this codec")
    ps.add_argument("--resume", action="store_true",
                    help="crash-resumable streaming (cursor sidecar per "
                         "band; .fil and .h5, incl. --compression "
                         "bitshuffle)")
    par = ps.add_mutually_exclusive_group()
    par.add_argument("--sharded", action="store_true",
                     help="the sharded reduction plane (ISSUE 9): "
                          "pipelined per-shard chunk feeds, async "
                          "addressable-shard readback and write-behind "
                          "sinks around the same one-program SPMD "
                          "reduction; byte-identical products (default: "
                          "SiteConfig/BLIT_MESH_SHARDED)")
    par.add_argument("--pool", action="store_true",
                     help="the pool-path fallback: one RawReducer per "
                          "(band, bank) player + main-process stitch — "
                          "the reference's shape, and the sharded "
                          "plane's byte-identity oracle")
    ps.add_argument("--search", action="store_true",
                    help="write per-player .hits drift-search products "
                         "instead of per-band filterbanks (each chip "
                         "searches its own frequency slice)")
    ps.add_argument("--window-spectra", type=int, default=None,
                    help="search window (with --search; default "
                         "SiteConfig/BLIT_SEARCH_WINDOW)")
    ps.add_argument("--snr", type=float, default=None,
                    help="search SNR threshold (with --search)")
    ps.add_argument("--top-k", type=int, default=None,
                    help="hits kept per band per window (with --search)")
    ps.add_argument("--max-drift-bins", type=int, default=None,
                    help="clamp the searched drift range (with --search)")
    ps.add_argument("--kernel", default="auto",
                    choices=["auto", "reference", "pallas"],
                    help="drift-transform backend (with --search)")
    ps.add_argument("--interpret", action="store_true",
                    help="pallas interpreter mode (CPU smoke; with "
                         "--search)")
    ps.set_defaults(fn=_cmd_scan)

    pi = sub.add_parser("inventory", help="crawl a data tree")
    pi.add_argument("root")
    pi.add_argument("--file-re", default=None)
    pi.add_argument("--session-re", default=None)
    pi.add_argument("--extra", default=None)
    pi.add_argument("--sequences", action="store_true",
                    help="group .NNNN.raw members into scan sequences")
    pi.set_defaults(fn=_cmd_inventory)

    pf = sub.add_parser("info", help="print a file's normalized header")
    pf.add_argument("file")
    pf.set_defaults(fn=_cmd_info)

    pg = sub.add_parser(
        "ingest-bench",
        help="file→product throughput probe of the async output plane "
             "(per-stage readback/write table + overlap gauge)",
    )
    pg.add_argument("--nfft", type=int, default=1024)
    pg.add_argument("--nint", type=int, default=1)
    pg.add_argument("--nchan", type=int, default=4)
    pg.add_argument("--chunk-frames", type=int, default=8)
    pg.add_argument("--chunks", type=int, default=8,
                    help="device chunks in the synthetic recording")
    pg.add_argument("--blocks", type=int, default=4,
                    help="RAW blocks the recording is split into")
    pg.add_argument("--fqav", type=int, default=1,
                    help="on-device frequency averaging (shrinks the "
                         "product crossing the readback link)")
    pg.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    pg.add_argument("--nbits", type=int, default=32, choices=[8, 16, 32],
                    help="SIGPROC product quantization: nbits<32 products "
                         "are narrowed ON DEVICE before D2H (4x/2x fewer "
                         "bytes across the readback link; byte-identical "
                         "to the sync path's host quantization)")
    pg.add_argument("--quant-scale", type=float, default=1.0,
                    help="affine quantize scale for --nbits 8/16")
    pg.add_argument("--digests", action="store_true",
                    help="arm a per-block digest sidecar on the "
                         "synthetic recording so every leg ingests "
                         "through integrity verification (ISSUE 13; "
                         "rates must stay inside the bench-diff noise "
                         "band of an unarmed run)")
    pg.add_argument("--sync-compare", action="store_true",
                    help="also run the fully synchronous output path and "
                         "report the async speedup")
    pg.add_argument("--spans-compare", action="store_true",
                    help="A/B the async leg with spans enabled vs disabled "
                         "and report the tracing overhead ratio")
    pg.add_argument("--spans-reps", type=int, default=3,
                    help="interleaved repetitions per spans-compare / "
                         "history-compare arm")
    pg.add_argument("--history-compare", action="store_true",
                    help="A/B the async leg under a fast-ticking "
                         "publisher with the history store + anomaly "
                         "baselines armed vs bare, and report the "
                         "history overhead ratio (ISSUE 20: <= 1%%)")
    pg.add_argument("--dedoppler", action="store_true",
                    help="also run the drift-search science leg over the "
                         "same recording and report drift-rate trials/s")
    pg.add_argument("--dedoppler-window", type=int, default=8,
                    help="search window (spectra per drift transform, "
                         "power of two) for the --dedoppler leg")
    pg.add_argument("--live", action="store_true",
                    help="also replay the recording through the "
                         "streaming ingest plane at --live-rate and "
                         "report p50/p99 chunk→product latency "
                         "(ISSUE 7)")
    pg.add_argument("--live-rate", type=float, default=1.0,
                    help="replay speed as a multiple of wall-clock "
                         "recording rate (1.0 = real time)")
    pg.add_argument("--live-seconds", type=float, default=0.5,
                    help="wall-clock span the live recording is "
                         "stretched to cover (TBIN-scaled)")
    pg.add_argument("--packets", action="store_true",
                    help="run the --live leg through the PACKET front "
                         "end (ISSUE 18): the recording framed as "
                         "datagrams via PacketReplaySource, gaps "
                         "masked not stalled; the leg reports the "
                         "packet gap/reorder/dup counters and block "
                         "assembly tails beside chunk→product latency")
    pg.add_argument("--packet-ntime", type=int, default=None,
                    help="time samples per DATA packet (default "
                         "SiteConfig/BLIT_PACKET_NTIME)")
    pg.add_argument("--packet-drop", type=float, default=0.0,
                    help="seeded fraction of DATA packets dropped in "
                         "the --packets leg (a partial block becomes a "
                         "masked gap)")
    pg.add_argument("--packet-reorder", type=float, default=0.0,
                    help="seeded fraction of DATA packets deferred out "
                         "of order in the --packets leg")
    pg.add_argument("--packet-dup", type=float, default=0.0,
                    help="seeded fraction of DATA packets duplicated "
                         "in the --packets leg")
    pg.add_argument("--live-drill", action="store_true",
                    help="also run the seeded late-chunk drill: one "
                         "chunk past a tightened lateness budget must "
                         "yield a masked (not wedged) product and a "
                         "flight-recorder dump")
    pg.add_argument("--chaos", action="store_true",
                    help="also run the recovery drill (ISSUE 12): "
                         "SIGKILL a supervised live consumer "
                         "mid-session, rejoin via the StreamCursor, "
                         "and report recover.detect_s / "
                         "recover.resume_s + byte-identity")
    pg.add_argument("--chaos-after", type=int, default=2,
                    help="kill the consumer after this many chunks")
    pg.add_argument("--chaos-rate", type=float, default=200.0,
                    help="chaos-leg replay speed multiple")
    _add_monitor_flags(pg)
    pg.set_defaults(fn=_cmd_ingest_bench)

    pn = sub.add_parser(
        "tune",
        help="autotune the ingest knobs on THIS rig and persist the "
             "winner as the per-rig tuning profile (ISSUE 8)",
    )
    pn.add_argument("--raw", default=None,
                    help="tune against this real recording instead of a "
                         "synthetic one")
    pn.add_argument("--nfft", type=int, default=1024)
    pn.add_argument("--nint", type=int, default=1)
    pn.add_argument("--nchan", type=int, default=4,
                    help="synthetic recording coarse channels")
    pn.add_argument("--chunk-frames", type=int, default=8,
                    help="sweep starting point (and synthetic sizing)")
    pn.add_argument("--chunks", type=int, default=8,
                    help="device chunks in the synthetic recording")
    pn.add_argument("--blocks", type=int, default=4,
                    help="RAW blocks the synthetic recording is split into")
    pn.add_argument("--fqav", type=int, default=1)
    pn.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    pn.add_argument("--nbits", type=int, default=32, choices=[8, 16, 32])
    pn.add_argument("--trials", type=int, default=12,
                    help="measurement budget (each new chunk_frames "
                         "candidate costs one compile)")
    pn.add_argument("--reps", type=int, default=1,
                    help="repetitions per measurement (best-of; raise on "
                         "noisy rigs)")
    pn.set_defaults(fn=_cmd_tune)

    pb = sub.add_parser(
        "serve-bench",
        help="replay a zipfian request mix against a ProductService",
    )
    pb.add_argument("--requests", type=int, default=64,
                    help="total requests to replay")
    pb.add_argument("--distinct", type=int, default=8,
                    help="distinct products in the mix")
    pb.add_argument("--clients", type=int, default=4,
                    help="concurrent client threads")
    pb.add_argument("--zipf-s", type=float, default=1.1,
                    help="zipf exponent of the popularity skew")
    pb.add_argument("--concurrency", type=int, default=2,
                    help="scheduler concurrency budget")
    pb.add_argument("--queue-depth", type=int, default=64,
                    help="bounded per-priority queue depth")
    pb.add_argument("--ram-bytes", type=int, default=64 << 20,
                    help="RAM cache tier byte budget")
    pb.add_argument("--disk-bytes", type=int, default=None,
                    help="per-peer HOT disk tier capacity "
                         "(--archive-day; a bound forces demotion "
                         "into each peer's cold tier)")
    pb.add_argument("--nfft", type=int, default=256)
    pb.add_argument("--seed", type=int, default=0)
    pb.add_argument("--disk-cache", action="store_true",
                    help="enable the disk cache tier (tempdir)")
    pb.add_argument("--fleet", action="store_true",
                    help="replay through a REAL multi-process fleet "
                         "front door (ISSUE 14): N fleet-peer "
                         "subprocesses behind consistent-hash routing")
    pb.add_argument("--peers", type=int, default=3,
                    help="fleet peer subprocess count (--fleet)")
    pb.add_argument("--replicas", type=int, default=2,
                    help="ring owner-set size R (--fleet)")
    pb.add_argument("--peer-ttl", type=float, default=3.0,
                    help="peer heartbeat-lease TTL seconds (--fleet)")
    pb.add_argument("--slo-ms", type=float, default=500.0,
                    help="SLO attainment target per request (--fleet)")
    pb.add_argument("--hedge-floor-ms", type=float, default=50.0,
                    help="hedge delay before the live p99 exists "
                         "(--fleet)")
    pb.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline_s propagated through "
                         "the fleet (--fleet)")
    pb.add_argument("--request-log", default=None, metavar="DIR",
                    help="per-request access records land here "
                         "(ISSUE 15; --fleet defaults to a temp spool "
                         "so the report's p50/p99 always come from the "
                         "records — point it somewhere to keep them)")
    pb.add_argument("--request-log-compare", action="store_true",
                    help="A/B the identical replay with request "
                         "logging off then on and report the overhead "
                         "(the --spans-compare discipline; non-fleet)")
    pb.add_argument("--trace-out", default=None, metavar="PATH",
                    help="with --fleet: stitch the peers' span batches "
                         "+ the door's into one Perfetto trace at PATH "
                         "(plus PATH.snapshot.json for trace-view "
                         "--fleet)")
    pb.add_argument("--archive-day", action="store_true",
                    help="replay a zipfian multi-session observing day "
                         "over REAL fleet-peer subprocesses, binary "
                         "wire vs legacy JSON A/B with a byte-identity "
                         "pin (ISSUE 16); emits a bench-diff-gateable "
                         "record")
    pb.add_argument("--sessions", type=int, default=4,
                    help="observing sessions in the day, each with "
                         "--distinct products (--archive-day)")
    pb.add_argument("--deflate", action="store_true",
                    help="advertise Accept-Encoding: deflate on the "
                         "binary pass (--archive-day)")
    pb.add_argument("--out", default=None, metavar="PATH",
                    help="also write the report JSON here "
                         "(--archive-day / --diurnal; the CI artifact)")
    pb.add_argument("--diurnal", action="store_true",
                    help="day-shaped load at accelerated clock over a "
                         "REAL fleet + standbys with the ELASTIC "
                         "controller in the loop (ISSUE 17): peak "
                         "pages scale-out through a warm handoff, "
                         "trough idles into a drain + scale-in; the "
                         "report pins SLO attainment through the "
                         "resizes and the post-resize hit-rate bound")
    pb.add_argument("--cycles", type=int, default=3,
                    help="peak/trough cycles, i.e. scale-out/in pairs "
                         "(--diurnal)")
    pb.add_argument("--standbys", type=int, default=None,
                    help="standby fleet-peer subprocesses to pre-"
                         "register (--diurnal; default --cycles)")
    pb.add_argument("--idle-windows", type=int, default=3,
                    help="consecutive idle controller ticks before "
                         "scale-in (--diurnal)")
    pb.add_argument("--hysteresis", type=float, default=2.0,
                    help="flap-guard cooldown seconds after any resize "
                         "(--diurnal)")
    pb.add_argument("--warm-timeout", type=float, default=60.0,
                    help="warm-handoff ack deadline seconds — the "
                         "joiner's first XLA compile happens inside it "
                         "(--diurnal)")
    pb.add_argument("--burn-threshold-ms", type=float, default=250.0,
                    help="per-request latency SLO the burn-rate "
                         "evaluator pages on (--diurnal)")
    pb.add_argument("--slo-floor", type=float, default=0.5,
                    help="minimum end-to-end SLO attainment the "
                         "diurnal leg must hold through the resizes")
    pb.set_defaults(fn=_cmd_serve_bench)

    pfp = sub.add_parser(
        "fleet-peer",
        help="run ONE serving peer of the fleet: a ProductService "
             "over HTTP with lease heartbeats; SIGTERM drains "
             "gracefully (ISSUE 14)",
    )
    pfp.add_argument("--name", default="peer")
    pfp.add_argument("--port", type=int, default=0,
                     help="bind port (0 = ephemeral; see --port-file)")
    pfp.add_argument("--host", default="127.0.0.1",
                     help="bind address (default loopback; a multi-host "
                          "fleet binds 0.0.0.0, or this host's fabric "
                          "address, which is then advertised in .url)")
    pfp.add_argument("--port-file", default=None,
                     help="publish the bound port here (atomic write) "
                          "so a spawner can find an ephemeral bind")
    pfp.add_argument("--cache-dir", default=None,
                     help="disk cache tier root (None = RAM-only)")
    pfp.add_argument("--lease-dir", default=None,
                     help="shared heartbeat-lease dir the front door "
                          "watches")
    pfp.add_argument("--proc", type=int, default=0,
                     help="this peer's lease proc index")
    pfp.add_argument("--ram-bytes", type=int, default=256 << 20)
    pfp.add_argument("--concurrency", type=int, default=2)
    pfp.add_argument("--queue-depth", type=int, default=64)
    pfp.add_argument("--retry-seed", type=int, default=None,
                     help="seed the jittered Retry-After spread")
    pfp.add_argument("--beat-interval", type=float, default=0.5,
                     help="lease heartbeat cadence (keep well under "
                          "the fleet's peer TTL)")
    pfp.add_argument("--drain-timeout", type=float, default=30.0)
    pfp.add_argument("--catalog-root", default=None,
                     help="archive tree to catalog (ISSUE 19): serves "
                          "kind='catalog' asks and resolves "
                          "session=/scan= logical addressing locally")
    pfp.add_argument("--cold-dir", default=None,
                     help="cold storage tier root (ISSUE 19): disk "
                          "evictees demote here; cold hits are "
                          "CRC-verified and promoted back")
    pfp.add_argument("--disk-bytes", type=int, default=None,
                     help="hot disk tier capacity (None = unbounded; "
                          "a bound is what forces demotion)")
    pfp.add_argument("--standby", action="store_true",
                     help="run as an elastic STANDBY (ISSUE 17): "
                          "process up and lease beating but NOT in the "
                          "ring — the front door's controller admits "
                          "it after a warm handoff when the SLO pages")
    pfp.set_defaults(fn=_cmd_fleet_peer)

    pc = sub.add_parser(
        "chaos",
        help="run a seeded kill/hang schedule against a supervised "
             "scan or live stream and assert recovery + byte-identity "
             "(ISSUE 12)",
    )
    pc.add_argument("--workload", default="scan",
                    choices=["scan", "scan-search", "stream"],
                    help="what to break: a supervised sharded scan, a "
                         "supervised sharded search, or a live consumer")
    pc.add_argument("--fault", default="kill",
                    choices=["kill", "hang", "corrupt", "partition",
                             "resize", "reorder"],
                    help="the injected failure mode (corrupt = the "
                         "ISSUE 13 integrity leg: a bit-flipped "
                         "delivered RAW frame under a digest sidecar "
                         "must be masked, not propagated; partition = "
                         "--fleet only: SIGSTOP then SIGCONT, the peer "
                         "must be ejected AND rejoin; resize = --fleet "
                         "only: SIGKILL a serving peer DURING the "
                         "elastic warm handoff, the flip must still "
                         "complete with byte-identical answers, "
                         "ISSUE 17; reorder = stream workload only, "
                         "ISSUE 18: hold packets back at the "
                         "packet.recv point — the assembler must "
                         "repair the order with the product "
                         "byte-identical and no crash)")
    pc.add_argument("--fleet", action="store_true",
                    help="break a SERVING fleet instead (ISSUE 14): "
                         "SIGKILL/SIGSTOP a real fleet-peer subprocess "
                         "mid-replay and assert detection within the "
                         "lease TTL, re-route, byte-identity vs a "
                         "single-process oracle, and hit-rate recovery")
    pc.add_argument("--peers", type=int, default=3,
                    help="fleet peer subprocess count (--fleet)")
    pc.add_argument("--replicas", type=int, default=2,
                    help="ring owner-set size R (--fleet)")
    pc.add_argument("--fleet-requests", type=int, default=150,
                    help="zipfian requests replayed across the drill "
                         "(--fleet)")
    pc.add_argument("--fleet-distinct", type=int, default=6,
                    help="distinct products in the fleet mix (--fleet)")
    pc.add_argument("--after", type=int, default=2,
                    help="fire after this many windows/chunks")
    pc.add_argument("--hang-s", type=float, default=60.0,
                    help="hang duration (must exceed --lease-ttl)")
    pc.add_argument("--point", default=None,
                    help="injection point override (default mesh.window "
                         "for scans, stream.chunk for streams)")
    pc.add_argument("--victim", type=int, default=0,
                    help="pod process the schedule targets (scan modes)")
    pc.add_argument("--procs", type=int, default=2,
                    help="pod size of the first scan attempt")
    pc.add_argument("--devices-per-proc", type=int, default=None,
                    help="chips per simulated host (default: exactly "
                         "the mesh share, so losing a host forces the "
                         "pool fallback; set it to the WHOLE mesh to "
                         "exercise the reshaped-mesh resume instead)")
    pc.add_argument("--nband", type=int, default=2)
    pc.add_argument("--nbank", type=int, default=2)
    pc.add_argument("--nchan", type=int, default=2)
    pc.add_argument("--nfft", type=int, default=32)
    pc.add_argument("--nint", type=int, default=1)
    pc.add_argument("--window-frames", type=int, default=4)
    pc.add_argument("--window-spectra", type=int, default=4,
                    help="search window (scan-search workload)")
    pc.add_argument("--chunks", type=int, default=6,
                    help="how many windows/chunks the synthetic scan "
                         "spans")
    pc.add_argument("--replay-rate", type=float, default=200.0,
                    help="stream workload replay speed")
    pc.add_argument("--packets", action="store_true",
                    help="feed the stream workload through the PACKET "
                         "front end (ISSUE 18): a PacketReplaySource "
                         "with a seeded whole-block drop + "
                         "reorder/dup schedule — the drill then also "
                         "asserts the gapped block is MASKED "
                         "(byte-identical to the zero-filled oracle), "
                         "and a --fault kill rejoins through the "
                         "packet source")
    pc.add_argument("--packet-ntime", type=int, default=64,
                    help="time samples per DATA packet (--packets)")
    pc.add_argument("--lease-ttl", type=float, default=3.0,
                    help="heartbeat lease TTL (the detection budget)")
    pc.add_argument("--poll", type=float, default=0.1,
                    help="supervisor watch cadence")
    pc.add_argument("--attempts", type=int, default=3,
                    help="recovery attempt budget")
    pc.add_argument("--seed", type=int, default=0)
    pc.add_argument("--work-dir", default=None,
                    help="keep the drill's inputs/products here "
                         "(default: a fresh temp dir)")
    pc.add_argument("--json-out", default=None,
                    help="also write the drill report JSON here "
                         "(the CI chaos-smoke artifact)")
    pc.set_defaults(fn=_cmd_chaos)

    pk = sub.add_parser(
        "fsck",
        help="verify an archive tree (manifests + cache content "
             "digests), quarantining corruption; exit 1 when any is "
             "found (ISSUE 13)",
    )
    pk.add_argument("root", help="tree to walk: product dirs and/or a "
                                 "serve disk-cache dir")
    pk.add_argument("--repair", action="store_true",
                    help="re-derive quarantined cache entries from "
                         "their recorded recipes (the serve layer's "
                         "miss path) and retire quarantined corpses "
                         "superseded by a verified replacement")
    pk.add_argument("--no-quarantine", action="store_true",
                    help="report only; leave corrupt artifacts in "
                         "place (default: move them to a .quarantine/ "
                         "sibling so they stop being served/resumed)")
    pk.add_argument("--cold-dir", default=None,
                    help="ALSO walk this cold storage tier (ISSUE 19): "
                         "cold entries share the hot tier's sidecar "
                         "convention, so quarantine and --repair "
                         "re-derivation apply unchanged")
    pk.add_argument("--json-out", default=None,
                    help="also write the fsck report JSON here "
                         "(the CI drill artifact)")
    pk.set_defaults(fn=_cmd_fsck)

    pbf = sub.add_parser(
        "backfill",
        help="derive+publish every product of an archive root into a "
             "hot(+cold) cache — resumable (fsync-per-line ledger), "
             "budget-paced (ISSUE 19)",
    )
    pbf.add_argument("root", help="archive tree to walk (the catalog "
                                  "crawl's session/GUPPI layout)")
    pbf.add_argument("--cache-dir", required=True,
                     help="hot disk cache tier to publish into")
    pbf.add_argument("--cold-dir", default=None,
                     help="cold tier behind the hot cache (evictees "
                          "demote here)")
    pbf.add_argument("--ledger", default=None,
                     help="completion ledger path (default: "
                          "<cache-dir>/backfill.ledger.jsonl)")
    pbf.add_argument("--product", default=None,
                     help="rawspec preset (0000/0001/0002); otherwise "
                          "--nfft/--nint configure the reduction")
    pbf.add_argument("--nfft", type=int, default=1024)
    pbf.add_argument("--nint", type=int, default=1)
    pbf.add_argument("--ram-bytes", type=int, default=64 << 20)
    pbf.add_argument("--disk-bytes", type=int, default=None,
                     help="hot tier capacity (a bound forces demotion "
                          "into --cold-dir)")
    pbf.add_argument("--bytes-per-s", type=float, default=None,
                     help="pacing budget over input bytes (the "
                          "Scrubber debt discipline; 0 = unpaced; "
                          "default SiteConfig.backfill_bytes_per_s)")
    pbf.add_argument("--limit", type=int, default=None,
                     help="stop after this many products (CI drills)")
    pbf.add_argument("--json-out", default=None,
                     help="also write the backfill report JSON here")
    pbf.set_defaults(fn=_cmd_backfill)

    pt = sub.add_parser(
        "telemetry",
        help="fleet telemetry report (harvest / render / demo run)",
    )
    pt.add_argument("--from", dest="from_file", default=None,
                    help="render a saved fleet report JSON instead of "
                         "harvesting")
    pt.add_argument("--demo", action="store_true",
                    help="run a multi-worker reduce_to_file fan-out over "
                         "synthetic recordings and harvest the pool")
    pt.add_argument("--workers", type=int, default=2,
                    help="demo pool size")
    pt.add_argument("--backend", default="thread",
                    choices=["local", "thread", "process"],
                    help="demo pool backend")
    pt.add_argument("--nfft", type=int, default=256)
    pt.add_argument("--trace-out", default=None,
                    help="also export the run's spans as Chrome-trace-"
                         "event JSON (Perfetto-loadable)")
    pt.add_argument("--format", default="text",
                    choices=["text", "prom", "json"],
                    help="report rendering: human text, Prometheus "
                         "exposition, or raw JSON")
    pt.add_argument("--watch", type=float, default=None, metavar="N",
                    help="re-harvest and re-render every N seconds "
                         "(`blit top`'s refresh loop; Ctrl-C to stop)")
    pt.add_argument("--iterations", type=int, default=None,
                    help="with --watch: stop after this many frames "
                         "(tests/scripts; default: until interrupted)")
    pt.set_defaults(fn=_cmd_telemetry)

    po = sub.add_parser(
        "top",
        help="live terminal dashboard over a monitor spool dir or a "
             "publisher endpoint (ISSUE 11)",
    )
    src = po.add_mutually_exclusive_group(required=True)
    src.add_argument("--spool",
                     help="monitor spool dir to tail (one JSONL file "
                          "per process; merged into one fleet view)")
    src.add_argument("--url",
                     help="publisher base URL to poll "
                          "(e.g. http://127.0.0.1:8080)")
    po.add_argument("--interval", type=float, default=1.0,
                    help="refresh cadence in seconds")
    po.add_argument("--once", action="store_true",
                    help="render one frame (no ANSI clear) and exit")
    po.add_argument("--iterations", type=int, default=None,
                    help="stop after this many frames (tests/scripts)")
    po.add_argument("--history", default=None, metavar="DIR",
                    help="append per-series sparklines from this "
                         "durable history store (BLIT_HISTORY_DIR; "
                         "ISSUE 20)")
    po.add_argument("--history-buckets", type=int, default=32,
                    help="how many finest-tier buckets each sparkline "
                         "spans")
    po.set_defaults(fn=_cmd_top)

    pd = sub.add_parser(
        "bench-diff",
        help="compare a fresh bench.py / ingest-bench JSON against the "
             "checked-in BENCH_*.json trajectory (exit 2 on regress)",
    )
    pd.add_argument("fresh",
                    help="fresh bench record (plain JSON or a "
                         "BENCH_*.json wrapper)")
    pd.add_argument("--baseline", action="append", default=[],
                    help="baseline record (repeatable)")
    pd.add_argument("--baseline-dir", default=None,
                    help="load every BENCH_*.json here as the baseline "
                         "trajectory (the fresh file itself excluded)")
    pd.add_argument("--noise", type=float, default=0.35,
                    help="relative noise band around the trajectory's "
                         "[min, max] envelope (0.35 = ±35%%)")
    pd.add_argument("--metrics", default=None,
                    help="comma-separated metric filter (default: every "
                         "shared metric)")
    pd.add_argument("--cross-rig", action="store_true",
                    help="compare against baselines from OTHER rigs "
                         "(config.backend) too — default: same-rig only")
    pd.add_argument("--json", action="store_true",
                    help="print the verdict as JSON instead of a table")
    pd.set_defaults(fn=_cmd_bench_diff)

    pv = sub.add_parser(
        "trace-view",
        help="render a flight-recorder dump into an incident summary, "
             "or stitch a fleet's span batches into one trace "
             "(--fleet; ISSUE 15)",
    )
    pv.add_argument("dump", nargs="?", default=None,
                    help="flight-recorder JSON "
                         "(blit-flight-<host>-<pid>-<t>-<n>.json)")
    pv.add_argument("--events", type=int, default=40,
                    help="how many trailing ring events to show")
    pv.add_argument("--fleet", nargs="+", default=None, metavar="SRC",
                    help="stitch spans from these sources into one "
                         "trace view: monitor spool dirs / .jsonl "
                         "files, saved *.snapshot.json batches, flight "
                         "dumps, or live http://host:port /snapshot "
                         "endpoints")
    pv.add_argument("--out", default=None,
                    help="write the stitched spans as Chrome-trace-"
                         "event JSON (Perfetto-loadable)")
    pv.add_argument("--trace", default=None, metavar="ID",
                    help="print one trace's span tree")
    pv.add_argument("--exemplar", default=None, metavar="METRIC",
                    help="resolve METRIC's tail-bucket exemplar to its "
                         "trace id (and print that trace's tree when "
                         "the spans are in the sources)")
    pv.set_defaults(fn=_cmd_trace_view)

    pq = sub.add_parser(
        "requests",
        help="tail / filter / aggregate a per-request access-record "
             "spool (BLIT_REQUEST_LOG; ISSUE 15)",
    )
    pq.add_argument("spool",
                    help="request-log spool dir (requests-*.jsonl) or "
                         "one log file")
    pq.add_argument("--tail", type=int, default=None,
                    help="keep only the newest N records")
    pq.add_argument("--slow-ms", type=float, default=None,
                    help="keep records at least this slow")
    pq.add_argument("--status", default=None,
                    help="keep one status (ok/overloaded/deadline/"
                         "timeout/error, or an HTTP code like 503)")
    pq.add_argument("--client", default=None,
                    help="keep one client's records")
    pq.add_argument("--role", default=None,
                    choices=["door", "peer", "serve"],
                    help="keep one component role's records")
    pq.add_argument("--since", default=None, metavar="WHEN",
                    help="keep records at/after WHEN — an epoch, "
                         "'15m'/'2h'/'1d'-style ago-windows, or 'now' "
                         "(the `blit incident show` window grammar)")
    pq.add_argument("--until", default=None, metavar="WHEN",
                    help="keep records at/before WHEN (same grammar)")
    pq.add_argument("--aggregate", action="store_true",
                    help="print one summary (counts by status/tier, "
                         "p50/p99, slowest records w/ trace ids) "
                         "instead of the record table")
    pq.add_argument("--json", action="store_true",
                    help="machine output: one JSON record per line "
                         "(or the compact aggregate)")
    pq.set_defaults(fn=_cmd_requests)

    pin = sub.add_parser(
        "incidents",
        help="list the self-contained incident bundles under the "
             "incident dir (BLIT_INCIDENT_DIR; ISSUE 20)",
    )
    pin.add_argument("--dir", default=None,
                     help="incident bundle dir (default: "
                          "BLIT_INCIDENT_DIR)")
    pin.add_argument("--json", action="store_true",
                     help="one manifest JSON per line")
    pin.set_defaults(fn=_cmd_incidents)

    pic = sub.add_parser(
        "incident",
        help="render one incident bundle's merged cross-source "
             "timeline (ISSUE 20)",
    )
    pic.add_argument("action", choices=["show"],
                     help="'show': render the bundle")
    pic.add_argument("bundle",
                     help="bundle directory (from `blit incidents`)")
    pic.add_argument("--window", default=None, metavar="SPAN",
                     help="narrow the timeline to SPAN around the page "
                          "('15m', '2h', '1d' — the shared window "
                          "grammar)")
    pic.add_argument("--json", action="store_true",
                     help="dump the loaded bundle as one JSON doc")
    pic.set_defaults(fn=_cmd_incident)

    psr = sub.add_parser(
        "slo-report",
        help="attainment + error-budget spend per objective over "
             "day/week windows from a durable history store "
             "(ISSUE 20; --json rides bench-diff)",
    )
    psr.add_argument("store",
                     help="history store dir (BLIT_HISTORY_DIR)")
    psr.add_argument("--window", default="1d",
                     help="report window: '1d', '1w', seconds, ... "
                          "(the shared window grammar; default 1d)")
    psr.add_argument("--json", action="store_true",
                     help="machine output (the 'metrics' block carries "
                          "slo.<name>_attained for bench-diff gating)")
    psr.add_argument("--out", default=None,
                     help="also write the report to this file "
                          "(CI artifact)")
    psr.set_defaults(fn=_cmd_slo_report)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
