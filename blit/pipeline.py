"""Streaming GUPPI RAW → filterbank reduction driver.

Host-side orchestration of the single-chip compute core
(:mod:`blit.ops.channelize`): reads voltage blocks, maintains the PFB state
across block boundaries (the overlap/edge-sample interaction called out as a
hard part in SURVEY.md §7), feeds fixed-shape chunks to the jitted reduction,
and writes SIGPROC ``.fil`` or FBH5 ``.h5`` products — the rawspec-equivalent
stage the reference assumes has already run on each ``blc*`` node
(SURVEY.md §0 "File products").

Design:

- Every chunk handed to the device has the same static shape, so XLA compiles
  the reduction exactly once and the steady state is pure streaming.
- A chunk of ``chunk_frames + ntap - 1`` gross blocks of ``nfft`` samples
  yields ``chunk_frames`` PFB frames; consecutive chunks share a
  ``(ntap-1) * nfft``-sample filter-state overlap — frame continuity across
  chunks is exact (golden-tested against a whole-file reduction).
- ``chunk_frames`` is a multiple of ``nint`` so integration never straddles a
  chunk boundary.  Trailing samples that can't fill an integration are
  dropped, as rawspec does.
- Ingest is PIPELINED: a producer thread fills a rotation of
  ``prefetch_depth`` stable chunk buffers straight from the file (native
  threaded pread per block when built) while the device works on earlier
  chunks.  Each buffer's first ``(ntap-1)*nfft`` samples are memcpy'd from
  the previous buffer's tail (the filter state); every other byte is read
  from disk exactly once, directly into its final position — no ring
  shifting, and no per-chunk stabilization copy before dispatch (the
  buffers themselves are stable until released).
"""

from __future__ import annotations

import functools
import logging
import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from blit import observability
from blit.io.guppi import GuppiRaw, RawSource, open_raw
from blit.observability import Timeline, profile_trace
from blit.ops.channelize import (
    STOKES_NIF,
    channelize,
    output_header,
    pfb_coeffs,
    usable_frames,
)

log = logging.getLogger("blit.pipeline")


@dataclass
class ReductionStats:
    """Aggregate throughput view derived from the reducer's stage
    :class:`~blit.observability.Timeline` (SURVEY.md §5 metrics plan)."""

    input_bytes: int = 0
    output_frames: int = 0
    device_seconds: float = 0.0
    wall_seconds: float = 0.0

    @property
    def gbps(self) -> float:
        return self.input_bytes / self.wall_seconds / 1e9 if self.wall_seconds else 0.0


class _Chunk:
    """A filled chunk buffer handed to the consumer.  ``view`` aliases the
    rotation buffer; it stays valid until :meth:`release`, after which the
    producer may refill it."""

    __slots__ = ("view", "frames", "_idx", "_free")

    def __init__(self, view: np.ndarray, frames: int, idx: int, free) -> None:
        self.view = view
        self.frames = frames
        self._idx = idx
        self._free = free

    def release(self) -> None:
        if self._free is not None:
            free, self._free = self._free, None
            free(self._idx)


_ROT_ERR = object()  # producer-exception marker on the filled queue


class BufferRotation:
    """The prefetch-rotation core behind every pipelined host feed: one
    producer thread fills slots it acquires from a free ring and emits
    ``(slot, payload)`` descriptors; the consumer iterates :meth:`slots`
    and must :meth:`release` every slot once nothing (host or device)
    still reads its buffers.

    Extracted from :class:`RawReducer`'s ingest machinery so the
    collective window feeds (:mod:`blit.parallel.antenna`) pipeline the
    same way the single-chip reducer does (module docstring).  Slot
    STORAGE belongs to the producer callback — slots are just indices the
    callback maps onto whatever stable host arrays it maintains, so one
    rotation can back an int8 chunk ring (RawReducer) or a set of planar
    per-device window buffers (the antenna feeds) unchanged.

    Contract:

    - ``fill(rot)`` runs in a daemon thread.  It calls ``rot.acquire()``
      for a free slot (``None`` means the consumer abandoned the stream —
      return), fills its buffers, and ``rot.emit(slot, payload)``.
      Returning ends the stream; exceptions re-raise in the consumer.
    - Waiting in ``acquire`` is back-pressure from the consumer, not
      producer work — time it outside any ingest stage.
    - A slot is only refilled after the consumer released it; concurrent
      READS of an emitted slot (e.g. copying a filter-state tail into the
      next slot) are safe.
    - ``stall_timeout_s`` arms a producer-progress watchdog: a live
      producer that neither acquires nor emits for that long (a wedged
      NFS read, a hung decoder) raises in the consumer instead of
      hanging the whole run.  Back-pressure waits count as progress
      (the consumer is the slow side there, not the producer).
    """

    def __init__(self, nslots: int, fill, *, name: str = "blit-feed",
                 stall_timeout_s: Optional[float] = None):
        self.nslots = max(2, nslots)
        self.stall_timeout_s = stall_timeout_s
        self._free: "queue.Queue[int]" = queue.Queue()
        for j in range(self.nslots):
            self._free.put(j)
        self._filled: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._fill = fill
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._started = False
        # Slots yielded to the consumer, not yet released.  Lock-guarded:
        # with the async output plane (blit/outplane.py) releases arrive
        # from the readback thread while the consumer thread increments.
        self._held = 0
        self._held_lock = threading.Lock()
        self._wd = observability.StallWatchdog(
            stall_timeout_s, name,
            what="a wedged read would otherwise hang the stream",
        )

    def _run(self) -> None:
        try:
            self._fill(self)
            self._filled.put(None)
        except BaseException as e:  # noqa: BLE001 — forwarded to the consumer
            self._filled.put((_ROT_ERR, e))

    # -- producer side ----------------------------------------------------
    def acquire(self) -> Optional[int]:
        """Next free slot index; ``None`` once the consumer is gone."""
        while not self._stop.is_set():
            try:
                slot = self._free.get(timeout=0.2)
            except queue.Empty:
                # Back-pressure from the consumer is not a producer stall.
                self._wd.beat()
                continue
            self._wd.beat()
            return slot
        return None

    def emit(self, slot: int, payload) -> None:
        self._wd.beat()
        self._filled.put((slot, payload))

    # -- consumer side ----------------------------------------------------
    def release(self, slot: int) -> None:
        with self._held_lock:
            self._held -= 1
        self._free.put(slot)

    def slots(self) -> Iterator[Tuple[int, object]]:
        """Yield ``(slot, payload)`` in stream order, starting the producer
        on first use; re-raises producer exceptions.  A consumer that holds
        every slot unreleased while asking for more gets a loud error, not
        a silent deadlock (the producer can never fill another slot)."""
        self._wd.beat()
        self._thread.start()
        self._started = True
        poll = self._wd.poll_s(0.5)
        try:
            while True:
                try:
                    item = self._filled.get(timeout=poll)
                except queue.Empty:
                    if self._held >= self.nslots:
                        msg = (
                            f"BufferRotation starved: all {self.nslots} "
                            "slots are held unreleased by the consumer — "
                            "release() earlier chunks/windows before "
                            "requesting more, or raise prefetch_depth"
                        )
                        observability.flight_recorder().dump(msg)
                        raise RuntimeError(msg)
                    # The watchdog dumps the incident trail BEFORE the
                    # raise unwinds and teardown noise overwrites the
                    # flight-recorder ring (ISSUE 5 tentpole #4).
                    self._wd.check("producer stalled",
                                   active=self._thread.is_alive())
                    continue
                if item is None:
                    return
                slot, payload = item
                if slot is _ROT_ERR:
                    raise payload
                with self._held_lock:
                    self._held += 1
                yield slot, payload
        finally:
            self.close()

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Stop the producer and join it (idempotent; safe mid-stream).
        The join is bounded: a producer wedged inside a fill (the stall
        watchdog's trigger) must not convert consumer teardown into the
        very hang it detected — the daemon thread is abandoned with a
        warning and exits at its next ``acquire``."""
        self._stop.set()
        if self._started:
            self._thread.join(timeout=join_timeout_s)
            if self._thread.is_alive():
                log.warning(
                    "%s: producer did not exit within %.1fs of close; "
                    "abandoning the daemon thread", self._thread.name,
                    join_timeout_s,
                )


def raw_block_feed(raw: GuppiRaw):
    """The at-rest block feed over an indexed block stream: ``(header,
    kept_samples, read_into)`` triples in stream order — the batch-side
    producer input of :meth:`RawReducer._fill_rotation`.  A live source
    provides the same triples through ``feed_blocks()``
    (blit/stream/plane.py), which is the whole batch≡stream byte-identity
    contract: both paths feed the identical sample stream through the
    identical framing."""
    for i in range(raw.nblocks):
        yield (raw.header(i), raw.block_ntime_kept(i),
               functools.partial(raw.read_block_into, i))


@dataclass
class RawReducer:
    """Configured RAW → filterbank reduction (one worker / one chip).

    Product presets mirror rawspec's (SURVEY.md §0): the hi-res product is
    ``nfft=2**20, nint=1``; the low-res ``0002`` product is small-nfft,
    long-integration.
    """

    nfft: int
    ntap: int = 4
    nint: int = 1
    stokes: str = "I"
    window: str = "hamming"
    fft_method: str = "auto"
    # On-device frequency-averaging epilogue: sum every fqav_by consecutive
    # fine channels before the product leaves the chip (the reference's
    # reduce-before-the-wire lever, src/gbtworkerfunctions.jl:16-20, moved
    # into the jitted kernel).  Headers carry the fqav_range mapping.
    fqav_by: int = 1
    # Chunk buffers in the ingest rotation (>= 2).  2 = classic double
    # buffering: the producer thread reads chunk i+1 from the file while the
    # device works on chunk i.  Host memory held: prefetch_depth chunk-sized
    # int8 buffers.  None (the default) = this rig's tuning profile when
    # one exists (blit/tune.py), else 2.
    prefetch_depth: Optional[int] = None
    # Output-plane depth: device outputs in readback flight + write-behind
    # queue slots (blit/outplane.py).  None = the tuning profile, else
    # prefetch_depth.  Deeper hides a laggier D2H link at the cost of one
    # pinned chunk buffer (and its HBM output) per extra slot.
    out_depth: Optional[int] = None
    # Working dtype of the channelizer's DFT stages ("float32"|"bfloat16").
    # bf16 halves the inter-stage HBM, fitting ~2x the frames per dispatch
    # at a measured accuracy cost (DESIGN.md §8).
    dtype: str = "float32"
    # Output frames per device call; rounded up to a multiple of nint.
    chunk_frames: Optional[int] = None
    # Per-stage timing/byte registry ("ingest" / "state" / "stream" on the
    # source side; "dispatch" / "device" / "readback" / "write" on the
    # output plane — see blit/outplane.py).
    timeline: Timeline = field(default_factory=Timeline)
    # When set, a JAX profiler trace (TensorBoard/Perfetto readable) wraps
    # every streaming run — SURVEY.md §5 "traces around ingest + kernels".
    trace_logdir: Optional[str] = None
    # Asynchronous output plane (ISSUE 4): device outputs are read back on
    # a dedicated thread (device→host overlaps the next chunk's compute)
    # and file products are written write-behind through an AsyncSink.
    # Products are byte-identical either way (tests/test_outplane.py);
    # False — or BLIT_SYNC_OUTPUT=1 in the environment — restores the
    # fully synchronous per-chunk path (the A/B lever and drill escape
    # hatch).
    async_output: bool = True
    # Producer-progress watchdog for the output plane's readback/writer
    # threads (None = wait forever), the BufferRotation stall_timeout_s
    # twin on the result side.
    output_stall_timeout_s: Optional[float] = None
    # Quantized product narrowing (ISSUE 8 tentpole c): nbits=8/16 writes
    # SIGPROC ``.fil`` products in their narrow on-disk integer form —
    # quantized ON DEVICE before D2H on the async plane (4x/2x fewer
    # bytes across the slow link), on the host on the sync path, with
    # bit-identical results either way (blit/ops/narrow.py).  The fixed
    # affine rule is ``clip(rint(x*scale + offset), 0, 2^nbits-1)``;
    # scale/offset are the caller's (global stats don't exist mid-stream).
    nbits: int = 32
    quant_scale: float = 1.0
    quant_offset: float = 0.0
    # Online autotuning (blit/tune.py): after the first windows of a
    # streaming reduction, derive a knob recommendation from the live
    # stage timeline (published as tune.rec_* gauges; persisted as a
    # tuning profile when BLIT_TUNE_ONLINE=1).
    tune_online: bool = True

    def __post_init__(self):
        from blit.ops.narrow import check_quant

        if os.environ.get("BLIT_SYNC_OUTPUT"):
            self.async_output = False
        check_quant(self.nbits)
        self._output_frames = 0
        # Chunk-buffer cache: streams on the same reducer reuse (already
        # page-faulted) rotation buffers — first-touch faults on GB-sized
        # buffers otherwise dominate short runs.  Backed by the process-wide
        # staging pool (blit/hostmem.py): buffers retire to the pool at the
        # end of a completed stream, so the NEXT reducer (a serve-layer
        # request, the next scan window) stages through already-faulted
        # aligned slabs too.  One stream at a time per reducer instance.
        self._buf_cache: List[np.ndarray] = []

        # Per-rig tuning profile (ISSUE 8): knobs the caller left unset
        # resolve from this rig's content-addressed profile when one
        # exists — `blit tune` (or an online-converged run) wrote it; a
        # profile for a different rig/workload shape hashes to a
        # different key and is never found.  BLIT_TUNE=0 disables.
        self._tuning_profile = None
        self._stream_nchan: Optional[int] = None
        self._profile_nchan_mismatch: Optional[int] = None
        self._knob_sources = {
            "chunk_frames": "explicit" if self.chunk_frames is not None
            else "default",
            "prefetch_depth": "explicit" if self.prefetch_depth is not None
            else "default",
            "out_depth": "explicit" if self.out_depth is not None
            else "default",
        }
        if (self.chunk_frames is None or self.prefetch_depth is None
                or self.out_depth is None):
            from blit import tune as _tune

            prof = _tune.lookup(**self._tune_fingerprint_kw())
            if prof is not None:
                self._tuning_profile = prof
                for knob, value in prof.knobs().items():
                    if getattr(self, knob) is None:
                        setattr(self, knob, value)
                        self._knob_sources[knob] = "profile"
        if self.prefetch_depth is None:
            self.prefetch_depth = 2
        if self.out_depth is None:
            self.out_depth = max(2, self.prefetch_depth)
        self.out_depth = max(2, self.out_depth)

        if self.chunk_frames is None:
            # Budget-driven default: ~8M samples per coarse channel per device
            # call.  Small-nfft products get many frames per call (amortizes
            # dispatch); the 1M-point hi-res product gets few (the complex64
            # FFT intermediates are what bound HBM, not dispatch overhead).
            budget = max(1, (1 << 23) // self.nfft)
            self.chunk_frames = self.nint * max(1, min(64, budget) // self.nint)
        if self.chunk_frames % self.nint:
            self.chunk_frames += self.nint - self.chunk_frames % self.nint
        if self.fqav_by > 1 and self.nfft % self.fqav_by:
            # Averaging groups must not straddle coarse-channel boundaries
            # (despike/nfpc consumers key on fine-per-coarse counts).
            raise ValueError(
                f"fqav_by={self.fqav_by} does not divide nfft={self.nfft}"
            )
        self._pfb_coeffs = None  # built lazily by the _coeffs property

    @property
    def _coeffs(self):
        """PFB coefficient bank, built (and device-shipped) on FIRST
        compute use — not at construction.  Throwaway probe reducers
        (scan/ingest-bench resolve tuning knobs through one) must not
        pay a multi-million-coefficient sinc*window build plus device
        transfer just to read provenance."""
        if self._pfb_coeffs is None:
            import jax.numpy as jnp

            self._pfb_coeffs = jnp.asarray(
                pfb_coeffs(self.ntap, self.nfft, self.window))
        return self._pfb_coeffs

    def _tune_fingerprint_kw(self) -> Dict:
        """The (rig, workload-shape) fingerprint components of this
        reduction — what a tuning profile is keyed under
        (:func:`blit.tune.rig_fingerprint`)."""
        return dict(
            nfft=self.nfft, ntap=self.ntap, nint=self.nint,
            stokes=self.stokes, window=self.window, fqav_by=self.fqav_by,
            dtype=self.dtype, fft_method=self.fft_method, nbits=self.nbits,
            workload="reduce",
        )

    def tuning_provenance(self) -> Dict:
        """Where this reducer's ingest knobs came from — embedded in the
        bench/ingest-bench ``ingest_config`` blocks so every recorded
        number names the profile (or default) behind it."""
        prov = {
            "chunk_frames": self.chunk_frames,
            "prefetch_depth": self.prefetch_depth,
            "out_depth": self.out_depth,
            "sources": dict(self._knob_sources),
        }
        if self._tuning_profile is not None:
            prov["profile"] = self._tuning_profile.provenance()
        if self._profile_nchan_mismatch is not None:
            prov["profile_nchan_mismatch"] = {
                "tuned": self._profile_nchan_mismatch,
                "stream": self._stream_nchan,
            }
        return prov

    def _note_stream_nchan(self, nchan: int) -> None:
        """Profile-staleness guard: the rig fingerprint deliberately
        excludes the recording's channel count (lookup happens at
        construction, before any recording is open, and tuning transfers
        across same-shaped workloads) — but per-chunk staging bytes and
        stage cost scale linearly with it.  Warn once per stream when a
        loaded profile was measured on a different-width recording, and
        surface the mismatch in :meth:`tuning_provenance`."""
        if self._stream_nchan == nchan:
            return
        self._stream_nchan = nchan
        prof = self._tuning_profile
        tuned = int(getattr(prof, "tuned_nchan", 0) or 0) if prof else 0
        if tuned and tuned != nchan:
            self._profile_nchan_mismatch = tuned
            log.warning(
                "tuning profile %s was measured on a %d-channel recording "
                "but this stream has %d channels; per-chunk cost scales "
                "with the channel count — re-run `blit tune` on a matching "
                "recording (or set chunk_frames/prefetch_depth/out_depth "
                "explicitly) if ingest underperforms",
                prof.key[:12], tuned, nchan,
            )

    def _narrow_host(self, slab: np.ndarray) -> np.ndarray:
        """The synchronous-path product narrowing (identity at nbits=32):
        the host twin of the device-side narrowing in
        :meth:`_stream_async` (blit/ops/narrow.py pins them bitwise)."""
        from blit.ops.narrow import narrow_host

        if self.nbits == 32:
            return np.ascontiguousarray(slab)
        return narrow_host(slab, self.nbits, self.quant_scale,
                           self.quant_offset)

    def _retire_staging(self) -> None:
        """Return the stream's chunk buffers to the process staging pool
        (blit/hostmem.py) — called only after a TERMINAL sync (stream
        fully drained / sink closed), never on an error path where an
        un-synced dispatch might still read a buffer."""
        from blit import hostmem

        pool = hostmem.slab_pool()
        for b in self._buf_cache:
            pool.give(b)
        self._buf_cache = []

    @property
    def stats(self) -> ReductionStats:
        """Aggregate counters derived from :attr:`timeline`."""
        st = self.timeline.stages
        return ReductionStats(
            input_bytes=st["ingest"].bytes,
            output_frames=self._output_frames,
            device_seconds=st["device"].seconds,
            wall_seconds=st["stream"].seconds,
        )

    # -- core streaming ---------------------------------------------------
    @property
    def _channelize_kw(self) -> Dict:
        """The exact channelize kwarg set (jax.jit caches per call
        signature, so the kwarg set must be bit-stable across callers —
        fqav_by only appears when active, keeping the common-case cache
        signature identical to callers that never heard of it, bench.py
        included)."""
        kw = dict(
            nfft=self.nfft, ntap=self.ntap, nint=self.nint,
            stokes=self.stokes, fft_method=self.fft_method,
        )
        if self.fqav_by > 1:
            kw["fqav_by"] = self.fqav_by
        if self.dtype != "float32":
            kw["dtype"] = self.dtype
        return kw

    def _run_chunk(self, chunk: np.ndarray) -> np.ndarray:
        import jax

        with self.timeline.stage("device", nbytes=chunk.nbytes):
            out = channelize(
                jax.numpy.asarray(chunk), self._coeffs, **self._channelize_kw
            )
            out = np.asarray(jax.block_until_ready(out))
        return out

    def stream(self, raw: GuppiRaw, skip_frames: int = 0) -> Iterator[np.ndarray]:
        """Yield filterbank slabs ``(nspectra, nif, nchan*nfft)`` covering
        the file gap-free (PFB state carried across blocks).  Slabs are
        float32 — or, with ``nbits=8/16``, the same quantized narrow dtype
        :meth:`reduce_to_file` writes (the knob applies uniformly: the
        in-memory product always matches the on-disk bytes).

        ``skip_frames`` skips the first N output frames exactly — frame N's
        PFB window starts at sample ``N*nfft`` of the gap-free stream, so
        skipping that many samples reproduces the remaining frames
        bit-identically (the resume path of :meth:`reduce_resumable`).

        While chunk ``i`` computes, the producer thread is already filling
        the next chunk buffer from the file (module docstring: pipelined
        ingest) and — on the default async output plane — the readback
        thread is fetching chunk ``i-1``'s product, so host read, compute
        and device→host readback all overlap.  Yielded slabs are the
        caller's to keep (never recycled under it); slab VALUES are
        byte-identical to the synchronous path's.
        """
        with profile_trace(self.trace_logdir), observability.span(
            "reduce.stream", nfft=self.nfft, path=getattr(raw, "path", "")
        ):
            if not self.async_output:
                for chunk in self._chunks(raw, skip_frames):
                    try:
                        out = self._run_chunk(chunk.view)
                    finally:
                        chunk.release()
                    self._output_frames += chunk.frames
                    yield self._narrow_host(out)
                self._retire_staging()
                return
            for slab in self._stream_async(raw, skip_frames, reuse=False,
                                           narrow=True):
                data = slab.data
                slab.release()
                yield data
            # Normal exhaustion only: every dispatch synced, so the chunk
            # buffers are safe to hand to the next reducer via the pool.
            self._retire_staging()

    def _stream_async(self, raw: GuppiRaw, skip_frames: int,
                      reuse: bool, narrow: bool = False,
                      tuner=None) -> Iterator["object"]:
        """The overlapped streaming core behind :meth:`stream` and
        :meth:`_pump`: async-dispatch each chunk, hand the in-flight
        output to an :class:`blit.outplane.OutputRotation` readback
        thread, and yield :class:`~blit.outplane.OutputSlab` handles in
        stream order.  ``reuse=True`` recycles host slabs through the
        rotation's bounded ring (callers must release only after the
        slab's bytes are consumed — the AsyncSink wiring); ``reuse=False``
        yields caller-owned arrays (the public :meth:`stream` contract).

        In-flight arithmetic (the :meth:`drain` lag window, one thread
        over): with readback depth ``d``, ``put(chunk_w)`` returns once
        chunk ``w-(d-1)`` has been fetched — chunk ``w`` stays in
        un-synchronized flight while the consumer dispatches ``w+1``, so
        compute and readback overlap.  Un-synced dispatches pin their
        ingest slots (released at ``block_until_ready``, before the
        fetch), so the chunk rotation runs one slot wider
        (``extra_slots=1``) to keep a slot free for the producer's
        read-ahead.
        """
        import jax

        from blit.outplane import OutputRotation, readback_extra_slots

        depth = max(2, self.out_depth)
        rot = OutputRotation(
            depth=depth,
            timeline=self.timeline, reuse=reuse, name="blit-readback",
            stall_timeout_s=self.output_stall_timeout_s,
        )
        do_narrow = narrow and self.nbits < 32
        if do_narrow:
            from blit.ops.narrow import narrow_device
        try:
            extra = readback_extra_slots(depth, self.prefetch_depth)
            for chunk in self._chunks(raw, skip_frames, extra_slots=extra):
                with self.timeline.stage("dispatch", byte_free=True):
                    out = channelize(
                        jax.numpy.asarray(chunk.view), self._coeffs,
                        **self._channelize_kw,
                    )
                    if do_narrow:
                        # Quantize to the product's on-disk integer form
                        # BEFORE D2H: 4x (nbits=8) / 2x (nbits=16) fewer
                        # bytes cross the slow link, bit-identical to the
                        # sync path's host-side narrowing
                        # (blit/ops/narrow.py).
                        out = narrow_device(out, self.nbits,
                                            self.quant_scale,
                                            self.quant_offset)
                self._output_frames += chunk.frames
                if tuner is not None:
                    tuner.observe_chunk()
                for slab in rot.put(out, nbytes=chunk.view.nbytes,
                                    on_consumed=chunk.release):
                    yield slab
            # The chunker's "stream" stage closed when its generator
            # exhausted above; the readback tail it no longer covers is
            # still streaming wall time — account it into the same stage
            # (sequentially, so no double count).
            t0 = time.perf_counter()
            for slab in rot.drain():
                yield slab
            self.timeline.stages["stream"].seconds += time.perf_counter() - t0
        finally:
            rot.close()

    def _pump(self, raw: GuppiRaw, writer, skip_frames: int = 0) -> int:
        """Drive the full reduction chain into a product writer — host
        read → H2D → compute → D2H → disk write, every leg on its own
        thread (ingest producer / main dispatch / readback / sink) with
        back-pressure end to end — and finalize the writer.  Returns the
        spectra written.  On error the writer is ``abort()``ed (its own
        crash contract: ``.partial`` dropped, resumable file + cursor
        kept) and the error re-raised.  The synchronous fallback
        (``async_output=False``) keeps the seed's serialized shape for
        A/B drills.

        Runs under :func:`blit.monitor.publishing` — every reduction
        (batch, stream, serve, search) streams its live timeline to the
        process publisher when ``BLIT_MONITOR_*`` enables one (ISSUE 11);
        disabled, the scope costs two env reads per reduction."""
        from blit.monitor import publishing

        with publishing(self.timeline):
            return self._pump_impl(raw, writer, skip_frames)

    def _pump_impl(self, raw: GuppiRaw, writer, skip_frames: int = 0
                   ) -> int:
        if not self.async_output:
            try:
                # stream() opens the profiler trace itself on this path,
                # and narrows quantized products HOST-side — the twin of
                # the async plane's on-device narrowing (byte-identical,
                # blit/ops/narrow.py).
                for slab in self.stream(raw, skip_frames=skip_frames):
                    writer.append(slab)
                writer.close()
            except BaseException:
                writer.abort()
                raise
            return writer.nsamps

        from blit.outplane import AsyncSink

        tuner = None
        if self.tune_online:
            from blit.tune import OnlineTuner

            tuner = OnlineTuner(
                self.timeline,
                {"chunk_frames": self.chunk_frames,
                 "prefetch_depth": self.prefetch_depth,
                 "out_depth": self.out_depth},
                nint=self.nint,
            )
        sink = AsyncSink(
            writer, depth=max(2, self.out_depth),
            timeline=self.timeline,
            stall_timeout_s=self.output_stall_timeout_s,
        )
        try:
            with profile_trace(self.trace_logdir), observability.span(
                "reduce.pump", nfft=self.nfft,
                out=str(getattr(writer, "path", "")),
            ):
                for slab in self._stream_async(raw, skip_frames,
                                               reuse=True,
                                               narrow=True, tuner=tuner):
                    sink.append(slab.data, release=slab.release)
                # Final flush barrier + writer finalization; the write
                # tail is streaming wall time like the readback tail.
                t0 = time.perf_counter()
                sink.close()
                self.timeline.stages["stream"].seconds += (
                    time.perf_counter() - t0
                )
        except BaseException:
            sink.abort()
            raise
        self.timeline.overlap_efficiency()
        self._retire_staging()
        if tuner is not None:
            tuner.maybe_persist(tuned_nchan=self._stream_nchan or 0,
                                **self._tune_fingerprint_kw())
        return sink.nsamps

    def _producer(
        self,
        raw: GuppiRaw,
        skip_frames: int,
        bufs: List[Optional[np.ndarray]],
        rot: BufferRotation,
    ) -> None:
        """Fill the chunk-buffer rotation (producer thread, the
        :class:`BufferRotation` fill callback).

        The block sequence comes either from the at-rest file
        (:func:`raw_block_feed` over an indexed :class:`GuppiRaw` /
        :class:`GuppiScan`) or, when the source exposes ``feed_blocks()``,
        from a live stream still being recorded (the watermark-ordered
        feed of :class:`blit.stream.LiveRawStream`) — the chunk framing,
        filter-state carry and flush rule below are shared, which is what
        makes a streamed reduction byte-identical to the batch path.
        """
        feed = (raw.feed_blocks() if hasattr(raw, "feed_blocks")
                else raw_block_feed(raw))
        self._fill_rotation(feed, skip_frames, bufs, rot)

    def _fill_rotation(
        self,
        feed,
        skip_frames: int,
        bufs: List[Optional[np.ndarray]],
        rot: BufferRotation,
    ) -> None:
        """The shared rotation-filling core: consume ``(header,
        kept_samples, read_into)`` triples in stream order and emit
        fixed-shape device chunks.

        Buffer ``j``'s first ``(ntap-1)*nfft`` samples are the filter state,
        copied from the previously filled buffer's tail (which the consumer
        may still be reading — concurrent reads are fine; a buffer is only
        *refilled* after its consumer released it).  Everything else is read
        from the source exactly once, directly into place
        (``read_into(dst, t0, take)`` copies samples ``[t0, t0+take)`` of
        the block into ``dst[:, :take]``).
        """
        nfft, ntap, nint = self.nfft, self.ntap, self.nint
        chunk_samps = (self.chunk_frames + ntap - 1) * nfft
        advance = self.chunk_frames * nfft
        state = (ntap - 1) * nfft
        to_skip = skip_frames * nfft

        cur: Optional[int] = None
        prev: Optional[int] = None
        filled = 0
        for hdr, nt, read_into in feed:
            if to_skip >= nt:
                to_skip -= nt
                continue
            t0, nt = to_skip, nt - to_skip
            to_skip = 0
            nchan = hdr["OBSNCHAN"]
            self._note_stream_nchan(nchan)
            npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
            while nt > 0:
                if cur is None:
                    # Waiting for a free buffer is back-pressure from
                    # the device, NOT ingest work — keep it outside the
                    # "ingest" stage so the timeline's GB/s is the true
                    # host read rate.
                    cur = rot.acquire()
                    if cur is None:
                        return  # consumer abandoned the stream
                    if bufs[cur] is None:
                        shape = (nchan, chunk_samps, npol, 2)
                        for j, b in enumerate(self._buf_cache):
                            if b.shape == shape:
                                bufs[cur] = self._buf_cache.pop(j)
                                break
                        else:
                            # Page-aligned, pool-recycled staging slab
                            # (blit/hostmem.py): an already-faulted buffer
                            # from a previous stream when one matches, so
                            # steady-state ingest never allocates.
                            from blit import hostmem

                            bufs[cur] = hostmem.slab_pool().take(
                                shape, np.int8
                            )
                    if prev is not None:
                        # Separate stage: filter-state memcpy between
                        # buffers is not file ingest ("ingest" bytes
                        # must stay == file bytes for ReductionStats).
                        state_bytes = nchan * state * npol * 2
                        with self.timeline.stage("state",
                                                 nbytes=state_bytes):
                            bufs[cur][:, :state] = bufs[prev][:, advance:]
                        filled = state
                    else:
                        filled = 0
                take = min(nt, chunk_samps - filled)
                with self.timeline.stage(
                    "ingest", nbytes=nchan * take * npol * 2
                ):
                    read_into(bufs[cur][:, filled:], t0, take)
                filled += take
                t0 += take
                nt -= take
                if filled == chunk_samps:
                    rot.emit(cur, (self.chunk_frames, chunk_samps))
                    prev, cur = cur, None
        if cur is not None and filled > (state if prev is not None else 0):
            # Flush: whole frames remaining, rounded to the integration.
            frames = usable_frames(filled, nfft, ntap, nint)
            if frames > 0:
                rot.emit(cur, (frames, (frames + ntap - 1) * nfft))

    def _chunks(
        self, raw: GuppiRaw, skip_frames: int = 0, extra_slots: int = 0
    ) -> Iterator["_Chunk"]:
        """The pipelined chunker behind :meth:`stream` / :meth:`drain`:
        yields :class:`_Chunk` handles in stream order.  The caller MUST
        ``release()`` every chunk once nothing (host or device) still reads
        its buffer; the producer blocks on released buffers to read ahead.

        ``extra_slots`` widens the rotation beyond ``prefetch_depth`` —
        the async output plane holds one chunk in un-synchronized flight
        on top of the one being dispatched, and the producer needs a
        slot free beyond those to keep reading (and to keep the
        rotation's all-slots-held starvation heuristic a true bug
        signal rather than a transient of deeper pipelining).
        """
        nbufs = max(2, self.prefetch_depth) + max(0, extra_slots)
        bufs: List[Optional[np.ndarray]] = [None] * nbufs
        rot = BufferRotation(
            nbufs,
            lambda r: self._producer(raw, skip_frames, bufs, r),
            name="blit-ingest",
        )
        with self.timeline.stage("stream"):
            try:
                for idx, (frames, samps) in rot.slots():
                    view = bufs[idx][:, :samps]
                    # The stream stage moves every gross chunk byte it
                    # hands downstream (VERDICT r5 weak #3: the dominant
                    # stage must not report zero bytes).
                    self.timeline.stages["stream"].bytes += view.nbytes
                    yield _Chunk(view, frames, idx, rot.release)
            finally:
                rot.close()
                # Keep the (faulted) buffers for the next stream.
                self._buf_cache = [b for b in bufs if b is not None][:nbufs]

    def drain(self, raw: GuppiRaw) -> float:
        """Run the full streaming reduction with a device-side sink: each
        chunk's product reduces to a scalar checksum on device and only the
        final float crosses back.

        Dispatch is async with a lag-synchronized window: chunk ``i``'s
        scalar is synced (and its buffer released back to the producer) only
        once ``prefetch_depth - 1`` newer chunks are in flight, so host
        block reads, host→device transfers and device compute overlap —
        this is the steady-state shape of the ingest path, and the
        throughput probe for rigs whose device→host link is not
        representative (e.g. the dev tunnel's ~10 MB/s readback,
        DESIGN.md §8).  No stabilization copy is needed: the chunk buffers
        themselves stay untouched until released.  Returns the checksum
        (sum over all products).
        """
        import jax
        import jax.numpy as jnp

        # The final syncs must happen INSIDE the trace context, or the
        # profiler stops before the queued tail of the async work it exists
        # to capture.
        with profile_trace(self.trace_logdir):
            total = 0.0
            pending: deque = deque()
            for chunk in self._chunks(raw):
                with self.timeline.stage("device", nbytes=chunk.view.nbytes):
                    out = channelize(
                        jax.numpy.asarray(chunk.view), self._coeffs,
                        **self._channelize_kw,
                    )
                    pending.append((chunk, jnp.sum(out)))
                self._output_frames += chunk.frames
                while len(pending) >= max(2, self.prefetch_depth):
                    done, s = pending.popleft()
                    total += float(s)  # sync: device is done with the input
                    done.release()
            while pending:
                done, s = pending.popleft()
                total += float(s)
                done.release()
            self._retire_staging()
            return total

    def _surface_integrity(self, raw, hdr: Dict) -> None:
        """Mirror digest-failed (zero-masked) blocks into the product
        header through the ONE mask bookkeeping rule (ISSUE 13: the
        PR 2/7 ``record_mask`` discipline, kind="block") — a degraded
        product says so everywhere a healthy one reports
        (``_masked_blocks``, the ``block.masked`` timeline counter, the
        process-wide ``mask.block`` fault counter)."""
        bad = sorted(getattr(raw, "bad_blocks", None) or ())
        if not bad:
            return
        from blit.parallel.antenna import record_mask

        masked: set = set()
        for b in bad:
            record_mask(masked, b, "failed digest verification",
                        header=hdr, timeline=self.timeline, kind="block")

    # -- whole-file conveniences ------------------------------------------
    def _open_validated(self, raw_src: RawSource):
        """Shared prologue of every whole-recording entry point: open the
        source, reject empty/truncated recordings, derive the product
        header.  Returns ``(raw, header)``."""
        raw = open_raw(raw_src)
        if raw.nblocks == 0:
            raise ValueError(f"empty or fully truncated RAW file: {raw.path}")
        return raw, self.header_for(raw)

    def header_for(self, raw: GuppiRaw) -> Dict:
        hdr = output_header(
            raw.header(0), nfft=self.nfft, nint=self.nint, stokes=self.stokes
        )
        if self.fqav_by > 1:
            from blit.ops.fqav import fqav_range

            fch1, foff, nchans = fqav_range(
                hdr["fch1"], hdr["foff"], hdr["nchans"], self.fqav_by
            )
            hdr.update(
                fch1=fch1, foff=foff, nchans=nchans,
                nfpc=self.nfft // self.fqav_by,
            )
        return hdr

    def reduce(self, raw_src: RawSource) -> Tuple[Dict, np.ndarray]:
        """Reduce a whole RAW file — or a whole multi-file ``.NNNN.raw``
        scan sequence (path list / stem, blit/io/guppi.open_raw) — in memory
        → ``(filterbank_header, data)`` with data ``(nsamps, nif, nchans)``."""
        from blit.ops.narrow import NARROW_DTYPES

        raw, hdr = self._open_validated(raw_src)
        with observability.span("reduce", nfft=self.nfft):
            slabs = list(self.stream(raw))
        if slabs:
            data = np.concatenate(slabs, axis=0)
        else:
            # Zero usable frames: shape the empty product off the header so
            # the channel axis stays consistent (fqav_by included).
            data = np.zeros(
                (0, STOKES_NIF[self.stokes], hdr["nchans"]),
                NARROW_DTYPES[self.nbits],
            )
        # stream() already narrowed nbits=8/16 products; the header must
        # say so or a later write_fil of (hdr, data) lies about the dtype.
        hdr["nbits"] = self.nbits
        hdr["nsamps"] = data.shape[0]
        self._surface_integrity(raw, hdr)
        return hdr, data

    def reduce_to_file(self, raw_src: RawSource, out_path: str,
                       compression: Optional[str] = None,
                       chunks: Optional[Tuple[int, int, int]] = None) -> Dict:
        """Reduce and write a ``.fil`` or (``.h5``) FBH5 product.

        Both formats STREAM slab-by-slab to disk at bounded host memory
        regardless of scan length: ``.fil`` appends raw spectra (SIGPROC
        derives nsamps from file size), ``.h5`` grows a time-resizable
        chunked dataset (:class:`blit.io.fbh5.FBH5Writer` — BL's native
        product format, src/gbtworkerfunctions.jl:141-155).  Either path
        lands in a ``.partial`` sibling renamed on success.

        ``compression`` applies to ``.h5`` output only: None | "gzip" |
        "bitshuffle" (BL's production codec, via the native encoder);
        ``chunks`` overrides the writer's clamped default HDF5 chunk shape.
        """
        if out_path.endswith((".h5", ".hdf5")):
            from blit.io.fbh5 import FBH5Writer

            if self.nbits != 32:
                raise ValueError("nbits=8/16 quantized output is a SIGPROC "
                                 ".fil feature; FBH5 products are float32")
            raw, hdr = self._open_validated(raw_src)
            nif = STOKES_NIF[self.stokes]
            w = FBH5Writer(
                out_path, hdr, nifs=nif, nchans=hdr["nchans"],
                compression=compression, chunks=chunks,
            )
            with observability.span("reduce.to_file", out=out_path):
                hdr["nsamps"] = self._pump(raw, w)
            self._surface_integrity(raw, hdr)
            return hdr
        if compression is not None:
            raise ValueError(".fil products are uncompressed; compression "
                             "applies to .h5 output")
        if chunks is not None:
            raise ValueError("chunks applies to .h5 output")
        from blit.io.sigproc import FilWriter
        from blit.ops.narrow import NARROW_DTYPES

        raw, hdr = self._open_validated(raw_src)
        nif = STOKES_NIF[self.stokes]
        # FilWriter streams into a .partial sibling and renames on success:
        # SIGPROC derives nsamps from file size, so a crash mid-stream must
        # not leave a VALID-looking truncated product at out_path (silent
        # data loss for consumers that treat existence as completion).
        # Resumable partial products are reduce_resumable's job — there the
        # cursor sidecar marks incompleteness.  nbits<32 writes the narrow
        # quantized form (the header's nbits follows the writer dtype).
        w = FilWriter(out_path, hdr, nif, hdr["nchans"],
                      dtype=NARROW_DTYPES[self.nbits])
        with observability.span("reduce.to_file", out=out_path):
            hdr["nsamps"] = self._pump(raw, w)
        self._surface_integrity(raw, hdr)
        return hdr

    def reduce_resumable(self, raw_src: RawSource, out_path: str,
                         compression: Optional[str] = None,
                         chunks: Optional[Tuple[int, int, int]] = None) -> Dict:
        """Reduce to a ``.fil`` or ``.h5`` (FBH5) product with
        crash-resumable streaming.

        A :class:`ReductionCursor` sidecar records frames durably written
        after every slab; re-running after an interruption truncates any
        un-checkpointed tail and continues from the last completed chunk
        (block-boundary restart, SURVEY.md §5 "Checkpoint / resume").  The
        finished product's decoded payload is identical to a non-resumed
        run; the sidecar is removed on completion.  Multi-file scan
        sequences resume the same way — the cursor records every member
        file's identity, and the skip-frames restart lands wherever in the
        sequence the frames do (including across a file boundary).

        ``.fil`` products truncate by byte length
        (:class:`ResumableFilWriter`); ``.h5`` products ``resize``-truncate
        the time-resizable dataset
        (:class:`blit.io.fbh5.ResumableFBH5Writer` — BL's native product
        format, src/gbtworkerfunctions.jl:141-155; under bitshuffle the
        cursor claims only full-chunk-flushed rows, so a resume re-reduces
        at most one chunk row).  ``compression``/``chunks`` apply to
        ``.h5`` output only and are part of the resume identity.
        """
        is_h5 = out_path.endswith((".h5", ".hdf5"))
        if is_h5 and self.nbits != 32:
            raise ValueError("nbits=8/16 quantized output is a SIGPROC "
                             ".fil feature; FBH5 products are float32")
        if not is_h5 and compression is not None:
            raise ValueError(".fil products are uncompressed; compression "
                             "applies to .h5 output")
        if not is_h5 and chunks is not None:
            raise ValueError("chunks applies to .h5 output")
        raw, hdr = self._open_validated(raw_src)
        # Cursor identity: the member path list (single files keep the plain
        # string so pre-existing sidecars stay valid).
        paths = getattr(raw, "paths", None) or raw.path
        nif = STOKES_NIF[self.stokes]
        comp_id = compression or "none"

        chunks_id = list(chunks) if chunks is not None else None
        cur = ReductionCursor.load(out_path)
        resuming = (
            cur is not None
            and cur.matches(self, paths)
            and cur.compression == comp_id
            and cur.chunks == chunks_id
            and os.path.exists(out_path)
        )
        if resuming and is_h5:
            # Crash robustness: libhdf5 metadata is not crash-atomic, so a
            # SIGKILL can leave an unopenable/unreadable target while the
            # cursor still parses — treat that like an identity mismatch
            # (fresh start), never a raise (ADVICE r5 medium).
            from blit.io.fbh5 import resume_target_ok

            if not resume_target_ok(
                out_path, nif, hdr["nchans"], cur.frames_done // self.nint
            ):
                log.warning(
                    "resume target %s is not readable as the claimed HDF5 "
                    "product (crash-corrupted metadata?); discarding %d "
                    "claimed frames and starting fresh",
                    out_path, cur.frames_done,
                )
                resuming = False
        if resuming and not is_h5:
            # The flat-format twin (ISSUE 12 satellite): a cursor claiming
            # bytes the file no longer holds must restart fresh — the
            # writer's truncate-to-claim would otherwise EXTEND the short
            # file with a NUL hole and finish an unreadable product.
            from blit.ops.narrow import NARROW_DTYPES

            if not resume_fil_ok(
                out_path, nif, hdr["nchans"], cur.frames_done // self.nint,
                dtype=NARROW_DTYPES[self.nbits],
            ):
                log.warning(
                    "resume target %s is shorter than (or unreadable as) "
                    "the cursor's claimed %d frames (crash-corrupted?); "
                    "starting fresh", out_path, cur.frames_done,
                )
                resuming = False
        if resuming:
            log.info("resuming %s at frame %d", out_path, cur.frames_done)
        else:
            size, mtime_ns = ReductionCursor.stat_raw(paths)
            cur = ReductionCursor(
                paths, self.nfft, self.ntap, self.nint, self.stokes, 0,
                window=self.window, raw_size=size, raw_mtime_ns=mtime_ns,
                fqav_by=self.fqav_by, dtype=self.dtype,
                compression=comp_id, chunks=chunks_id,
                nbits=self.nbits, quant_scale=self.quant_scale,
                quant_offset=self.quant_offset,
            )
        start_rows = cur.frames_done // self.nint if resuming else 0
        if is_h5:
            from blit.io.fbh5 import ResumableFBH5Writer

            w = ResumableFBH5Writer(
                out_path, hdr, nif, hdr["nchans"], start_rows, self.nint,
                cur, compression=compression, chunks=chunks,
            )
        else:
            from blit.ops.narrow import NARROW_DTYPES

            w = ResumableFilWriter(
                out_path, hdr, nif, hdr["nchans"], start_rows, self.nint,
                cur, dtype=NARROW_DTYPES[self.nbits],
            )
        # _pump aborts the writer on error — file + cursor stay as the
        # resume point (the writer's own crash contract); under the async
        # plane the cursor may simply sit a few queued-but-unwritten slabs
        # earlier, which the skip-frames replay re-reduces identically.
        with observability.span("reduce.resumable", out=out_path,
                                resumed=bool(resuming)):
            hdr["nsamps"] = self._pump(raw, w,
                                       skip_frames=start_rows * self.nint)
        self._surface_integrity(raw, hdr)
        return hdr


def resume_fil_ok(path: str, nif: int, nchans: int, rows: int,
                  dtype=np.float32) -> bool:
    """May a ``.fil`` resume target honor a cursor claiming ``rows``
    spectra?  The file must parse a SIGPROC header AND hold at least the
    claimed bytes: :class:`ResumableFilWriter` truncates *down* to the
    claim, and POSIX ``truncate`` on a SHORTER file would silently
    EXTEND it with a NUL hole — a crash-corrupted (or replaced) product
    must restart fresh instead (the ``resume_target_ok`` discipline of
    blit/io/fbh5.py, applied to the flat format; ISSUE 12 satellite).

    When a manifest sidecar exists the length check is UPGRADED to
    content verification (ISSUE 13): the claimed region's digest must
    match the bytes on disk — a torn write *inside* the claim, a
    tampered sidecar, or a replaced product all fail closed (fresh
    start) where the byte-length probe alone would have resumed onto
    corrupt spectra.  No manifest keeps the length-only behavior
    (legacy products stay resumable)."""
    from blit.io.sigproc import read_fil_header

    try:
        _, off = read_fil_header(path)
        size = os.path.getsize(path)
    except (OSError, ValueError):
        return False
    row_bytes = nif * nchans * np.dtype(dtype).itemsize
    if size < off + rows * row_bytes:
        return False
    from blit import integrity

    return integrity.verify_claim(path, rows, fmt="fil",
                                  row_bytes=row_bytes) is not False


class ResumableFilWriter:
    """Append-directly ``.fil`` writer whose incompleteness marker is a
    :class:`ReductionCursor` sidecar instead of a ``.partial`` rename:
    slabs are fsync'd BEFORE the cursor claims them, so a crash leaves a
    resumable prefix, never a cursor ahead of the bytes.  Backs BOTH
    resumable streaming paths — :meth:`RawReducer.reduce_resumable` and
    the mesh scan writer (blit/parallel/scan.py) — so the durability
    protocol lives in one place (the FilWriter rule, blit/io/sigproc.py).

    ``start_rows`` > 0 resumes: the product is truncated to that many
    spectra (dropping any un-checkpointed tail) and the cursor clamped
    to match; 0 (or a missing file) starts fresh.
    """

    def __init__(self, path: str, header: Dict, nif: int, nchans: int,
                 start_rows: int, nint: int, cursor: "ReductionCursor",
                 dtype=np.float32):
        from blit import integrity
        from blit.io.sigproc import read_fil_header, write_fil

        self.path = path
        self._nint = nint
        self._nif = nif
        self._nchans = nchans
        self.dtype = np.dtype(dtype)
        self.cursor = cursor
        row_bytes = nif * nchans * self.dtype.itemsize
        self._mf = integrity.ManifestWriter(
            path, "fil", row_bytes=row_bytes,
            writer=type(self).__name__)
        if start_rows > 0 and os.path.exists(path):
            # The cursor may record more frames than the agreed restart
            # point (the mesh writer restarts at a pod-wide minimum): clamp
            # it DOWN with the truncation, or a crash before the first new
            # append would leave it claiming bytes the truncate dropped.
            _, off = read_fil_header(path)
            with open(path, "r+b") as f:
                f.truncate(off + start_rows * row_bytes)
            cursor.frames_done = start_rows * nint
            cursor.save(path)
            # Rebuild the manifest's running CRC over the truncated file
            # (one pass; callers already content-verified the claim via
            # resume_fil_ok) so every later claim digests correctly.
            self._mf.data_offset = off
            self._mf.fold_path(path)
            self._mf.claim(start_rows)
            self._mf.save()
        else:
            start_rows = 0
            write_fil(path, header, np.zeros((0, nif, nchans), self.dtype))
            cursor.frames_done = 0
            cursor.save(path)
            self._mf.data_offset = os.path.getsize(path)
            self._mf.fold_path(path)
            self._mf.save()
        self._f = open(path, "ab")
        self.nsamps = start_rows

    def append(self, slab: np.ndarray) -> None:
        from blit.io.sigproc import validate_slab

        slab = validate_slab(slab, self._nif, self._nchans, self.dtype)
        slab.tofile(self._f)
        # Durable data BEFORE the cursor claims it (power-loss ordering).
        self._f.flush()
        os.fsync(self._f.fileno())
        self.nsamps += slab.shape[0]
        # Manifest BETWEEN the data fsync and the cursor claim
        # (ISSUE 13): the ledger then always holds an entry for every
        # row count a cursor can legally claim — a crash between the
        # two leaves the manifest AHEAD of the cursor (a harmless extra
        # entry), never behind (an unverifiable gap a resume would
        # truncate into).
        self._mf.fold(slab)
        self._mf.claim(self.nsamps)
        self._mf.save()
        self.cursor.frames_done = self.nsamps * self._nint
        self.cursor.save(self.path)

    def close(self) -> None:
        """Finish: the sidecar's absence is the completeness marker.
        The cursor names its own sidecar path — StreamCursor rides this
        writer with a ``.stream-cursor`` sibling (blit/stream/cursor.py).
        The manifest flips to complete (whole-file digest) and STAYS —
        it is the finished product's verification surface (blit fsck)."""
        self._f.close()
        self._mf.publish()
        sidecar = self.cursor.path_for(self.path)
        if os.path.exists(sidecar):
            os.unlink(sidecar)

    def abort(self) -> None:
        # The file + cursor ARE the resume point: keep both.
        self._f.close()


# rawspec-equivalent product presets (SURVEY.md §0: products 0000/0001/0002).
PRODUCT_PRESETS = {
    # name: (nfft, nint)
    "0000": (1 << 20, 1),  # hi-res: ~3 Hz channels
    "0001": (1 << 3, 128),  # mid-res time product
    "0002": (1 << 10, 1 << 11),  # low-res survey product
}


def reducer_for_product(product: str, **kw) -> RawReducer:
    """A :class:`RawReducer` configured like rawspec's standard product
    ``product`` ("0000" | "0001" | "0002")."""
    nfft, nint = PRODUCT_PRESETS[product]
    return RawReducer(nfft=nfft, nint=nint, **kw)


@dataclass
class ReductionCursor:
    """Restart state for a streaming reduction, persisted as a JSON sidecar
    next to the output product (SURVEY.md §5 "Checkpoint / resume":
    stream-job cursors restarting at block boundaries).

    ``frames_done`` counts raw PFB frames fully reduced *and written* — a
    multiple of ``nint`` by construction, so resumption never re-splits an
    integration window.

    Identity guards: the full reduction config *including the PFB window*
    must match, and the RAW input must be the same bytes it was
    (size + mtime_ns recorded at cursor creation) — otherwise a resume would
    silently splice spectra from different configs/inputs into one product.
    For multi-file scan sequences ``raw_path``/``raw_size``/``raw_mtime_ns``
    hold per-member lists: every member of the sequence must be unchanged.
    """

    raw_path: Union[str, List[str]]
    nfft: int
    ntap: int
    nint: int
    stokes: str
    frames_done: int = 0
    window: str = "hamming"
    raw_size: Union[int, List[int]] = -1
    raw_mtime_ns: Union[int, List[int]] = -1
    fqav_by: int = 1
    dtype: str = "float32"
    # DC-despike width of the product (mesh scan writer; -1 = the path has
    # no despike, RawReducer's case).  Output-affecting, so it must be part
    # of resume identity: splicing despiked and non-despiked spectra into
    # one product would corrupt it silently.
    despike_nfpc: int = -1
    # Product compression ("none" | "gzip" | "bitshuffle") — .h5 resume
    # identity: a dataset's filter pipeline is fixed at creation, so a
    # writer expecting a different codec must start fresh, not corrupt.
    # Compared at the call sites (not in matches(), whose `red` argument
    # has no compression attribute).
    compression: str = "none"
    # Mesh .h5-bitshuffle resume identity: the writer's chunk rows derive
    # from the window granularity, so a changed --window-frames must start
    # fresh rather than hit the writer's chunk-mismatch refusal.  -1 =
    # not applicable (.fil products and the single-chip path tolerate
    # window changes).
    window_rows: int = -1
    # Explicit .h5 chunk shape (reduce_resumable's chunks= knob) — resume
    # identity for the same reason as compression: a dataset's chunk grid
    # is fixed at creation, so a resume under different chunks must start
    # fresh, not die on the writer's chunk-mismatch refusal.  None = the
    # writer's clamped default (deterministic for a given product shape).
    chunks: Optional[List[int]] = None
    # Quantized-product identity (ISSUE 8): nbits and the affine quantize
    # rule change every product byte, so a resume under different
    # quantization must start fresh — splicing 8-bit and float spectra
    # into one file would corrupt it silently.  Defaults keep pre-existing
    # sidecars loadable (they claim the f32 identity they were).
    nbits: int = 32
    quant_scale: float = 1.0
    quant_offset: float = 0.0

    @staticmethod
    def stat_raw(raw_path: Union[str, Sequence[str]]) -> Tuple:
        """(size, mtime_ns) of a single path, or parallel lists for a
        sequence of paths."""
        if isinstance(raw_path, str):
            st = os.stat(raw_path)
            return st.st_size, st.st_mtime_ns
        stats = [os.stat(p) for p in raw_path]
        return [s.st_size for s in stats], [s.st_mtime_ns for s in stats]

    @staticmethod
    def path_for(out_path: str) -> str:
        return out_path + ".cursor"

    def save(self, out_path: str) -> None:
        import json

        tmp = self.path_for(out_path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.__dict__, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path_for(out_path))

    @classmethod
    def load(cls, out_path: str) -> Optional["ReductionCursor"]:
        import json

        try:
            with open(cls.path_for(out_path)) as f:
                return cls(**json.load(f))
        except (OSError, ValueError, TypeError):
            return None

    @staticmethod
    def normalized_members(
        raw_path: Union[str, Sequence[str]],
        raw_size: Union[int, Sequence[int]],
        raw_mtime_ns: Union[int, Sequence[int]],
    ) -> List[Tuple[str, int, int]]:
        """The raw-input identity as an order-insensitive list of
        ``(path, size, mtime_ns)`` member triples, sorted by path.

        A multi-file scan sequence is the SAME recording whatever order a
        glob happened to list its members in — ``open_raw`` sorts members
        before reading, so the reduced bytes are order-independent and the
        resume/cache identity must be too (ISSUE 3 satellite: cache keys
        must be stable across glob orderings)."""

        def norm(x):
            return list(x) if isinstance(x, (list, tuple)) else [x]

        return sorted(zip(norm(raw_path), norm(raw_size), norm(raw_mtime_ns)))

    def matches(self, red: "RawReducer", raw_path: Union[str, Sequence[str]]) -> bool:
        try:
            size, mtime_ns = self.stat_raw(raw_path)
        except OSError:
            return False

        return (
            self.normalized_members(self.raw_path, self.raw_size,
                                    self.raw_mtime_ns)
            == self.normalized_members(raw_path, size, mtime_ns)
            and self.nfft == red.nfft
            and self.ntap == red.ntap
            and self.nint == red.nint
            and self.stokes == red.stokes
            and self.window == red.window
            and self.fqav_by == red.fqav_by
            and self.dtype == red.dtype
            and self.despike_nfpc == getattr(red, "despike_nfpc", -1)
            and self.nbits == getattr(red, "nbits", 32)
            and self.quant_scale == getattr(red, "quant_scale", 1.0)
            and self.quant_offset == getattr(red, "quant_offset", 0.0)
        )
