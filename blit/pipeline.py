"""Streaming GUPPI RAW → filterbank reduction driver.

Host-side orchestration of the single-chip compute core
(:mod:`blit.ops.channelize`): reads voltage blocks, maintains the PFB state
across block boundaries (the overlap/edge-sample interaction called out as a
hard part in SURVEY.md §7), feeds fixed-shape chunks to the jitted reduction,
and writes SIGPROC ``.fil`` or FBH5 ``.h5`` products — the rawspec-equivalent
stage the reference assumes has already run on each ``blc*`` node
(SURVEY.md §0 "File products").

Design:

- Every chunk handed to the device has the same static shape, so XLA compiles
  the reduction exactly once and the steady state is pure streaming.
- A chunk of ``chunk_frames + ntap - 1`` gross blocks of ``nfft`` samples
  yields ``chunk_frames`` PFB frames; the buffer then advances by
  ``chunk_frames * nfft`` samples, keeping ``(ntap-1) * nfft`` as filter
  state — frame continuity across chunks is exact (golden-tested against a
  whole-file reduction).
- ``chunk_frames`` is a multiple of ``nint`` so integration never straddles a
  chunk boundary.  Trailing samples that can't fill an integration are
  dropped, as rawspec does.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from blit.io.guppi import GuppiRaw
from blit.ops.channelize import STOKES_NIF, channelize, output_header, pfb_coeffs

log = logging.getLogger("blit.pipeline")


@dataclass
class ReductionStats:
    """Throughput counters (SURVEY.md §5 metrics plan)."""

    input_bytes: int = 0
    output_frames: int = 0
    device_seconds: float = 0.0
    wall_seconds: float = 0.0

    @property
    def gbps(self) -> float:
        return self.input_bytes / self.wall_seconds / 1e9 if self.wall_seconds else 0.0


@dataclass
class RawReducer:
    """Configured RAW → filterbank reduction (one worker / one chip).

    Product presets mirror rawspec's (SURVEY.md §0): the hi-res product is
    ``nfft=2**20, nint=1``; the low-res ``0002`` product is small-nfft,
    long-integration.
    """

    nfft: int
    ntap: int = 4
    nint: int = 1
    stokes: str = "I"
    window: str = "hamming"
    fft_method: str = "auto"
    # Output frames per device call; rounded up to a multiple of nint.
    chunk_frames: Optional[int] = None
    stats: ReductionStats = field(default_factory=ReductionStats)

    def __post_init__(self):
        import jax.numpy as jnp

        if self.chunk_frames is None:
            # Budget-driven default: ~8M samples per coarse channel per device
            # call.  Small-nfft products get many frames per call (amortizes
            # dispatch); the 1M-point hi-res product gets few (the complex64
            # FFT intermediates are what bound HBM, not dispatch overhead).
            budget = max(1, (1 << 23) // self.nfft)
            self.chunk_frames = self.nint * max(1, min(64, budget) // self.nint)
        if self.chunk_frames % self.nint:
            self.chunk_frames += self.nint - self.chunk_frames % self.nint
        self._coeffs = jnp.asarray(pfb_coeffs(self.ntap, self.nfft, self.window))

    # -- core streaming ---------------------------------------------------
    def _run_chunk(self, chunk: np.ndarray) -> np.ndarray:
        import jax

        t0 = time.perf_counter()
        out = channelize(
            jax.numpy.asarray(chunk),
            self._coeffs,
            nfft=self.nfft,
            ntap=self.ntap,
            nint=self.nint,
            stokes=self.stokes,
            fft_method=self.fft_method,
        )
        out = np.asarray(jax.block_until_ready(out))
        self.stats.device_seconds += time.perf_counter() - t0
        return out

    def stream(self, raw: GuppiRaw) -> Iterator[np.ndarray]:
        """Yield float32 filterbank slabs ``(nspectra, nif, nchan*nfft)``
        covering the file gap-free (PFB state carried across blocks)."""
        nfft, ntap, nint = self.nfft, self.ntap, self.nint
        chunk_samps = (self.chunk_frames + ntap - 1) * nfft
        advance = self.chunk_frames * nfft
        t_wall = time.perf_counter()
        buf: Optional[np.ndarray] = None
        for _, block in raw.iter_blocks(drop_overlap=True):
            block = np.ascontiguousarray(block)
            self.stats.input_bytes += block.nbytes
            buf = block if buf is None else np.concatenate([buf, block], axis=1)
            while buf.shape[1] >= chunk_samps:
                yield self._run_chunk(buf[:, :chunk_samps])
                self.stats.output_frames += self.chunk_frames
                buf = buf[:, advance:]
        if buf is not None:
            # Flush: whole frames remaining, rounded down to the integration.
            frames = buf.shape[1] // nfft - ntap + 1
            frames = (frames // nint) * nint if frames > 0 else 0
            if frames > 0:
                tail = buf[:, : (frames + ntap - 1) * nfft]
                yield self._run_chunk(tail)
                self.stats.output_frames += frames
        self.stats.wall_seconds += time.perf_counter() - t_wall

    # -- whole-file conveniences ------------------------------------------
    def header_for(self, raw: GuppiRaw) -> Dict:
        return output_header(
            raw.header(0), nfft=self.nfft, nint=self.nint, stokes=self.stokes
        )

    def reduce(self, raw_path: str) -> Tuple[Dict, np.ndarray]:
        """Reduce a whole RAW file in memory → ``(filterbank_header, data)``
        with data shaped ``(nsamps, nif, nchans)``."""
        raw = GuppiRaw(raw_path)
        if raw.nblocks == 0:
            raise ValueError(f"empty or fully truncated RAW file: {raw_path}")
        slabs = list(self.stream(raw))
        if slabs:
            data = np.concatenate(slabs, axis=0)
        else:
            nchan = raw.header(0)["OBSNCHAN"]
            data = np.zeros((0, STOKES_NIF[self.stokes], nchan * self.nfft), np.float32)
        hdr = self.header_for(raw)
        hdr["nsamps"] = data.shape[0]
        return hdr, data

    def reduce_to_file(self, raw_path: str, out_path: str) -> Dict:
        """Reduce and write a ``.fil`` or (``.h5``) FBH5 product."""
        hdr, data = self.reduce(raw_path)
        if out_path.endswith((".h5", ".hdf5")):
            from blit.io.fbh5 import write_fbh5

            write_fbh5(out_path, hdr, data)
        else:
            from blit.io.sigproc import write_fil

            write_fil(out_path, hdr, data)
        return hdr


# rawspec-equivalent product presets (SURVEY.md §0: products 0000/0001/0002).
PRODUCT_PRESETS = {
    # name: (nfft, nint)
    "0000": (1 << 20, 1),  # hi-res: ~3 Hz channels
    "0001": (1 << 3, 128),  # mid-res time product
    "0002": (1 << 10, 1 << 11),  # low-res survey product
}


def reducer_for_product(product: str, **kw) -> RawReducer:
    """A :class:`RawReducer` configured like rawspec's standard product
    ``product`` ("0000" | "0001" | "0002")."""
    nfft, nint = PRODUCT_PRESETS[product]
    return RawReducer(nfft=nfft, nint=nint, **kw)
