"""Deterministic fault injection + recovery-policy primitives.

The failure story of the streaming collective plane (ISSUE 2): long
reductions must *degrade and continue* instead of dying on the first
transient ``OSError``, and every recovery path must be exercisable
deterministically in tests rather than discovered in production.  This
module holds both halves:

- **Injection** (:class:`FaultRule`, :func:`fire`): a seeded,
  config/env-driven registry of named injection points threaded through
  the I/O layer (``guppi.read`` / ``guppi.open`` / ``fbh5.write`` /
  ``workers.read``), the stream producer threads (``antenna.produce``),
  the remote transport (``remote.call``), the product service layer
  (``cache.publish`` — the disk publish of blit/serve/cache.py;
  ``sched.dispatch`` — the scheduler's dispatch path, keyed by client,
  blit/serve/scheduler.py) and the asynchronous output plane
  (``sink.write`` — each write-behind product append on the
  :class:`blit.outplane.AsyncSink` writer thread; ``sink.flush`` — its
  flush barrier; both keyed by the product path, and both surfacing
  writer-THREAD failures as clean consumer-side re-raises — the ISSUE 4
  drill for a dying disk under an overlapped reduction).  Modes:
  ``fail`` (raise
  :class:`InjectedFault` — an ``OSError``, so retry paths treat it like
  a flaky NFS read), ``delay`` (injectable sleep), ``truncate`` (short
  read — a *hard* failure the degraded-antenna masking handles),
  ``corrupt`` (bit-flip the delivered frame), and — for the streaming
  ingest plane's ``stream.chunk`` point (blit/stream; ISSUE 7) —
  ``drop`` (the chunk never arrives: the watermark masks it after the
  lateness budget) and ``dup`` (the chunk is delivered twice: the
  assembler drops the duplicate).  The crash-recovery plane (ISSUE 12)
  adds two process-grade modes for chaos drills at the
  ``mesh.window`` / ``stream.chunk`` / ``remote.call`` points:
  ``kill`` (SIGKILL the calling process — the unclean death a
  :class:`blit.recover.ScanSupervisor` lease detects) and ``hang``
  (sleep ``hang_s``, default far past any watchdog — the wedged-peer
  shape that stalls collectives without dying).  The fleet serve plane
  (ISSUE 14) adds two serving-path points: ``fleet.route`` — fired by
  the front door per peer dispatch, keyed by the peer name, so a drill
  can delay/fail routing to one peer (forcing hedges and failover
  without touching the peer itself) — and ``peer.request`` — fired by
  a serving peer per handled ``/product`` request, keyed by the
  fingerprint, so ``kill``/``hang`` drills take a REAL peer process
  down mid-replay (the ``blit chaos --fleet`` schedule).  The recorder
  packet front end (ISSUE 18) adds the ``packet.recv`` point — fired by
  the :class:`blit.stream.packet.PacketAssembler` per received
  datagram, keyed ``<path>#pkt<pktidx>`` — and the ``reorder`` mode:
  the caller holds the packet back until ``amount`` later packets have
  been processed (default 3), the wire-level reordering a switch under
  load produces (``blit chaos --fault reorder``); ``drop``/``dup``
  apply there too, exercising gap masking and duplicate-tile
  accounting end to end.  Rules fire on exact hit
  counts (``after``/``times``), so a test can target "window 3 of
  antenna 2" and get the same failure every run.  ``BLIT_FAULTS`` in
  the environment arms rules at import time for CLI-level drills (see
  docs/WORKFLOWS.md "Failure modes & runbook").

- **Recovery** (:class:`RetryPolicy`, :func:`retry_call`,
  :class:`CircuitBreaker`): jittered-exponential-backoff retry with
  bounded attempts, *seeded* jitter and an injectable ``sleep`` (tests
  never sleep real backoff time), and a per-host circuit breaker that
  trips into a ``degraded`` state after repeated failures instead of
  hammering a dead host.  Knobs live in :class:`blit.config.SiteConfig`.

- **Counters** (:func:`incr` / :func:`counters`): process-wide
  retry/mask/trip totals, surfaced through
  ``Timeline.report(include_faults=True)`` (blit/observability.py) so a
  degraded run says so in its report.

Imports nothing from the rest of blit at module scope — every layer can
depend on it (telemetry hooks import blit.observability lazily, inside the
functions that use them).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

log = logging.getLogger("blit.faults")

MODES = ("fail", "delay", "truncate", "corrupt", "drop", "dup",
         "kill", "hang", "reorder")


class InjectedFault(OSError):
    """The default injected failure: an ``OSError`` subclass, so the
    transient-I/O retry paths classify it exactly like a flaky NFS read."""


# -- counters ---------------------------------------------------------------

_counters_lock = threading.Lock()
_counters: Dict[str, int] = {}


def incr(name: str, n: int = 1) -> None:
    """Bump a process-wide failure/recovery counter (thread-safe).  Every
    bump also lands in the flight recorder's event ring (failure counters
    ARE the incident trail, blit/observability.py) — lazily imported so
    this module keeps its import-nothing-at-module-scope contract."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n
    try:
        from blit.observability import flight_recorder

        flight_recorder().event("fault", name, n=n)
    except Exception:  # noqa: BLE001 — counters must never fail the caller
        pass


def counters() -> Dict[str, int]:
    """Snapshot of all nonzero counters (``retry.io``, ``retry.remote``,
    ``mask.antenna``, ``breaker.trip``, ``fault.<point>.<mode>`` ...)."""
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()


# -- injection registry -----------------------------------------------------


@dataclass
class FaultRule:
    """One armed injection: fire ``mode`` at ``point`` for matching hits
    ``(after, after + times]`` (``times=-1`` = every matching hit).

    ``match`` filters by substring of the call-site key (a file path, a
    host name, an antenna recording path), so a rule can target one
    antenna of a 64-element array.  ``sleep`` makes ``delay`` (and
    ``hang``) rules interruptible/observable in tests.  ``amount`` is the
    samples cut by ``truncate`` (0 = half the request); ``hang_s`` is how
    long a ``hang`` rule sleeps (default: far past any watchdog/lease
    budget — the chaos drill's wedged-peer shape); ``kill`` lets tests
    swap the SIGKILL-self of a ``kill`` rule for a recordable callable."""

    point: str
    mode: str = "fail"
    times: int = 1
    after: int = 0
    match: Optional[str] = None
    exc: type = InjectedFault
    message: str = "injected fault"
    delay_s: float = 0.1
    hang_s: float = 3600.0
    amount: int = 0
    sleep: Callable[[float], None] = time.sleep
    kill: Optional[Callable[[], None]] = None
    # Mutable bookkeeping (under the registry lock).
    hits: int = 0
    fired: int = 0

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"unknown fault mode {self.mode!r}; one of {MODES}")


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.rules: List[FaultRule] = []

    def install(self, *rules: FaultRule) -> None:
        with self._lock:
            self.rules = self.rules + list(rules)

    def clear(self) -> None:
        with self._lock:
            self.rules = []

    def fire(self, point: str, key=None) -> Optional[FaultRule]:
        """Evaluate every armed rule for ``point``: count the hit, apply
        delays, raise failures, or return the first destructive rule
        (truncate/corrupt) for the caller to apply to its data."""
        todo: List[FaultRule] = []
        with self._lock:
            for r in self.rules:
                if r.point != point:
                    continue
                if r.match is not None and (
                    key is None or r.match not in str(key)
                ):
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.times >= 0 and r.hits > r.after + r.times:
                    continue
                r.fired += 1
                incr(f"fault.{point}.{r.mode}")
                todo.append(r)
                if r.mode != "delay":
                    break  # first destructive rule wins
        act = None
        for r in todo:  # apply OUTSIDE the lock (sleep / raise / kill)
            if r.mode == "delay":
                log.warning("injected delay %.3fs @ %s [%s]", r.delay_s,
                            point, key)
                r.sleep(r.delay_s)
            elif r.mode == "hang":
                # The chaos drill's wedged peer: alive (the process keeps
                # its file handles and collective state) but silent far
                # past any watchdog — detection is the supervisor's job
                # (lease expiry / window-progress stall), not this rule's.
                log.error("injected hang %.1fs @ %s [%s]", r.hang_s,
                          point, key)
                r.sleep(r.hang_s)
            elif r.mode == "kill":
                # The chaos drill's dead peer: SIGKILL-self — no atexit,
                # no writer close, no lease farewell.  The resumable
                # writers' fsync-before-claim state is all that survives,
                # which is exactly the contract the drill asserts.
                log.error("injected SIGKILL @ %s [%s]", point, key)
                if r.kill is not None:
                    r.kill()
                else:
                    import signal

                    os.kill(os.getpid(), signal.SIGKILL)
            elif r.mode == "fail":
                raise r.exc(
                    f"{r.message} @ {point}" + (f" [{key}]" if key else "")
                )
            else:
                act = r
        return act


_REGISTRY = _Registry()


def install(*rules: FaultRule) -> None:
    """Arm injection rules (appended to any already armed)."""
    _REGISTRY.install(*rules)


def clear() -> None:
    """Disarm every rule (tests: pair with :func:`reset_counters`)."""
    _REGISTRY.clear()


def active() -> List[FaultRule]:
    return list(_REGISTRY.rules)


def fire(point: str, key=None) -> Optional[FaultRule]:
    """The injection call sites' entry point.  No armed rules (the
    production fast path) is one attribute read.  May raise (``fail``),
    sleep (``delay``) or return a rule whose ``mode`` in
    ``("truncate", "corrupt")`` the caller applies to its data."""
    if not _REGISTRY.rules:
        return None
    return _REGISTRY.fire(point, key)


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse the ``BLIT_FAULTS`` drill grammar: semicolon-separated
    ``point:mode[:times][:k=v...]`` with ``k`` in
    ``match/after/delay/hang/amount/message`` —
    e.g. ``"guppi.read:fail:2:match=ant1;remote.call:delay:delay=0.5"``
    or, for the chaos drills (ISSUE 12),
    ``"mesh.window:kill:after=2"`` / ``"mesh.window:hang:hang=60"``."""
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"BLIT_FAULTS entry needs point:mode — {part!r}")
        kw: Dict[str, object] = {"point": fields[0], "mode": fields[1]}
        for f in fields[2:]:
            if "=" not in f:
                kw["times"] = int(f)
                continue
            k, v = f.split("=", 1)
            if k in ("times", "after", "amount"):
                kw[k] = int(v)
            elif k == "delay":
                kw["delay_s"] = float(v)
            elif k == "hang":
                kw["hang_s"] = float(v)
            elif k in ("match", "message"):
                kw[k] = v
            else:
                raise ValueError(f"BLIT_FAULTS: unknown key {k!r} in {part!r}")
        rules.append(FaultRule(**kw))
    return rules


def install_spec(spec: str) -> List[FaultRule]:
    rules = parse_spec(spec)
    install(*rules)
    return rules


if os.environ.get("BLIT_FAULTS"):
    try:
        install_spec(os.environ["BLIT_FAULTS"])
        log.warning("BLIT_FAULTS armed: %s", os.environ["BLIT_FAULTS"])
    except Exception as e:  # noqa: BLE001 — a bad drill spec must be loud
        raise ValueError(
            f"malformed BLIT_FAULTS={os.environ['BLIT_FAULTS']!r}: {e}"
        ) from e


# -- retry policy -----------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff with bounded attempts.

    ``attempts`` is the TOTAL number of tries (1 = no retry).  Jitter is
    uniform in ``delay * (1 ± jitter)``; with ``seed`` set the jitter for
    attempt ``k`` is a pure function of ``(seed, k)`` — deterministic
    across runs, different across attempts.  ``sleep`` is injectable so
    tests record delays instead of serving them."""

    attempts: int = 3
    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep

    def delay_s(self, attempt: int) -> float:
        d = min(self.max_s, self.base_s * self.multiplier ** attempt)
        if self.jitter:
            u = (
                random.Random(self.seed * 1_000_003 + attempt).random()
                if self.seed is not None
                else random.random()
            )
            d *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return max(0.0, d)

    def backoff(self, attempt: int) -> None:
        d = self.delay_s(attempt)
        try:
            # The backoff distribution is a first-class load signal
            # (ISSUE 5 tentpole #2): a fleet whose retry.backoff_s p99
            # saturates max_s is in a failure storm, whatever the mean
            # says.  Lazy import keeps this module's no-blit-imports-at-
            # module-scope contract.
            from blit.observability import process_timeline

            process_timeline().observe("retry.backoff_s", d)
        except Exception:  # noqa: BLE001 — telemetry must not break retry
            pass
        self.sleep(d)


# A missing/forbidden file is a caller bug, not NFS weather — never retried.
_NON_TRANSIENT = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


def transient_io(e: BaseException) -> bool:
    """The default transience classifier: any OSError that is not a
    deterministic filesystem refusal."""
    return isinstance(e, OSError) and not isinstance(e, _NON_TRANSIENT)


def retry_call(
    fn: Callable[[], object],
    *,
    policy: RetryPolicy,
    describe: str = "call",
    transient: Callable[[BaseException], bool] = transient_io,
    counter: str = "retry.io",
):
    """Run ``fn`` under ``policy``: transient failures back off and
    retry, everything else (and the last attempt) raises."""
    for attempt in range(max(1, policy.attempts)):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            if not transient(e) or attempt >= policy.attempts - 1:
                raise
            incr(counter)
            log.warning(
                "%s failed (%s: %s); retry %d/%d in %.3fs",
                describe, type(e).__name__, e, attempt + 1,
                policy.attempts - 1, policy.delay_s(attempt),
            )
            policy.backoff(attempt)
    raise AssertionError("unreachable")


_io_policy: Optional[RetryPolicy] = None
_io_policy_lock = threading.Lock()


def io_policy() -> RetryPolicy:
    """The process-wide transient-file-I/O retry policy (guppi/fbh5/worker
    reads).  Defaults from the environment (``BLIT_IO_RETRIES`` total
    attempts, ``BLIT_IO_BACKOFF_S``, ``BLIT_IO_BACKOFF_MAX_S``); override
    with :func:`set_io_policy` — e.g.
    ``set_io_policy(config.io_retry_policy())``."""
    global _io_policy
    with _io_policy_lock:
        if _io_policy is None:
            _io_policy = RetryPolicy(
                attempts=int(os.environ.get("BLIT_IO_RETRIES", 3)),
                base_s=float(os.environ.get("BLIT_IO_BACKOFF_S", 0.05)),
                max_s=float(os.environ.get("BLIT_IO_BACKOFF_MAX_S", 2.0)),
            )
        return _io_policy


def set_io_policy(policy: Optional[RetryPolicy]) -> None:
    """Install the process-wide I/O retry policy (``None`` resets to the
    environment defaults)."""
    global _io_policy
    with _io_policy_lock:
        _io_policy = policy


def retry_io(fn: Callable[[], object], describe: str = "io"):
    """Transient-I/O retry under the process-wide policy — the wrapper
    every worker-side file read/write goes through."""
    return retry_call(fn, policy=io_policy(), describe=describe)


# -- circuit breaker --------------------------------------------------------


class CircuitBreaker:
    """Per-host failure circuit: ``threshold`` CONSECUTIVE failures trip it
    ``open`` (the host is *degraded* — callers fail fast instead of
    hammering it); after ``cooldown_s`` one probe call is allowed
    (``half-open``), whose success re-closes the circuit and whose failure
    re-opens it for another cooldown.  ``clock`` is injectable so tests
    advance time instead of waiting it."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(1, threshold)
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self.failures = 0  # consecutive
        self.trips = 0
        self._opened_at = 0.0
        self._probing = False

    def allow(self) -> bool:
        """May a call be dispatched now?  (Consumes the half-open probe
        slot when it grants one.)"""
        with self._lock:
            if self.state == "closed":
                return True
            if (
                self.state == "open"
                and self.clock() - self._opened_at >= self.cooldown_s
            ):
                self.state = "half-open"
                self._probing = False
            if self.state == "half-open" and not self._probing:
                self._probing = True
                return True
            return False

    def closed(self) -> bool:
        """Non-consuming check: is the circuit fully closed?  (Retry loops
        use this so a mid-loop check cannot eat the half-open probe.)"""
        with self._lock:
            return self.state == "closed"

    def record_success(self) -> None:
        with self._lock:
            self.state = "closed"
            self.failures = 0
            self._probing = False

    def record_failure(self) -> bool:
        """Count a failure; returns True when THIS failure tripped the
        circuit open (callers log/count the trip exactly once)."""
        with self._lock:
            self.failures += 1
            if self.state == "half-open" or (
                self.state == "closed" and self.failures >= self.threshold
            ):
                self.state = "open"
                self._opened_at = self.clock()
                self._probing = False
                self.trips += 1
                return True
            return False

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.failures,
                "trips": self.trips,
            }
