"""Live monitoring & SLO plane (ISSUE 11 tentpole).

PR 5's telemetry plane is pull-at-end: spans, histograms and flight
dumps exist, but nothing watches a run *while it happens*.  A recorder
cluster like BL@GBT's 64-node backend (MacMahon et al. 2018,
arXiv:1707.06024) is operated from dashboards and pages, not post-mortem
reports.  This module is that operating surface:

- :class:`MetricsPublisher` — a background thread that snapshots the
  process :class:`~blit.observability.Timeline` on an interval
  (DELTA-based, via the existing ``HistogramStats.since`` /
  ``Timeline.state`` machinery), appends JSON-lines samples to a spool
  dir (one file per process — a pod's processes spool side by side and
  the driver merges them through
  :func:`~blit.observability.merge_fleet`), and serves a tiny stdlib
  HTTP endpoint: ``/metrics`` (Prometheus text via
  :func:`~blit.observability.render_prometheus`, native histogram
  buckets included), ``/healthz`` and ``/snapshot`` (the latest JSON
  sample).  Device gauges ride each sample where the backend exposes
  them: per-device ``memory_stats()`` HBM in-use/peak, an ICI byte-rate
  derived from the ``mesh.*_ici_bytes`` histograms, the stream
  watermark lag and the scheduler queue depth/running gauges.

- the **SLO layer** — objectives declared on
  :class:`~blit.config.SiteConfig` (:func:`~blit.config.slo_defaults`:
  serve p99 queue-wait ceiling, ``stream.chunk_to_product_s`` p99
  ceiling, ingest GB/s floor), evaluated continuously over the live
  histogram deltas by a multi-window burn-rate evaluator
  (:class:`BurnRateEvaluator`).  A breach produces an alert event, a
  forced flight dump (first breach per objective; later ones ride the
  recorder's rate limit so an alert storm cannot spam dumps), and a
  load-shed hook that tightens :class:`~blit.serve.scheduler.Scheduler`
  admission (``Scheduler.shed``) until the burn clears.

- the **operator surface** — ``blit top`` (:func:`render_top` +
  :func:`watch_loop`): a terminal dashboard that tails the spool or
  polls the endpoint during an in-progress reduce/scan/stream/serve,
  showing per-stage throughput, stage-tail p50/p99, SLO burn and host
  health.  ``blit telemetry --watch N`` shares the same refresh path.

- the **CI perf gate** — ``blit bench-diff`` (:func:`bench_diff`):
  compare a fresh ``bench.py`` / ``ingest-bench`` JSON against the
  checked-in ``BENCH_*.json`` trajectory with noise bands and emit a
  pass/regress verdict, so the perf history becomes an automated
  watchdog instead of an archive.

Import discipline: this module imports only stdlib +
:mod:`blit.config` + :mod:`blit.observability` — every plane can reach
:func:`publishing` without a dependency cycle, and ``blit top`` never
pays the jax import.
"""

from __future__ import annotations

import atexit
import contextlib
import functools
import glob
import json
import logging
import os
import re
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from blit.config import (
    DEFAULT,
    SiteConfig,
    history_defaults,
    monitor_defaults,
    slo_defaults,
)
from blit.observability import (
    HistogramStats,
    Timeline,
    flight_recorder,
    hist_bucket_edges,
    hostname,
    merge_fleet,
    process_timeline,
    render_prometheus,
    wall_anchor,
)

log = logging.getLogger("blit.monitor")

ANSI_CLEAR = "\x1b[2J\x1b[H"


# -- SLO objectives + burn-rate evaluation ----------------------------------


@dataclass(frozen=True)
class SLObjective:
    """One service-level objective over a live metric.

    ``kind="latency"``: ``metric`` names a Timeline histogram
    (``sched.wait_s``, ``stream.chunk_to_product_s``, ...) and
    ``threshold`` is the per-sample ceiling in seconds — a sample above
    it is "bad", and the error budget allows a ``budget`` fraction of
    bad samples (budget 0.01 == a p99 ceiling).

    ``kind="throughput"``: ``metric`` names a Timeline STAGE and
    ``threshold`` is a GB/s floor — an interval where the stage ran
    below the floor is one bad observation (intervals where the stage
    was idle observe nothing: a paused pipeline is not a slow one)."""

    name: str
    metric: str
    threshold: float
    kind: str = "latency"
    budget: float = 0.01

    def __post_init__(self):
        if self.kind not in ("latency", "throughput"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.budget <= 0:
            raise ValueError("SLO budget must be > 0")

    @classmethod
    def from_dict(cls, d: Dict) -> "SLObjective":
        return cls(name=str(d["name"]), metric=str(d["metric"]),
                   threshold=float(d["threshold"]),
                   kind=str(d.get("kind", "latency")),
                   budget=float(d.get("budget", 0.01)))


def objectives_for(config: SiteConfig = DEFAULT) -> List[SLObjective]:
    """The configured objective list (:func:`blit.config.slo_defaults`
    dicts adopted as :class:`SLObjective`)."""
    return [SLObjective.from_dict(d) for d in slo_defaults(config)]


def bad_fraction(hist: HistogramStats, threshold: float) -> Tuple[int, int]:
    """``(bad, total)`` samples of a histogram (usually an interval
    DELTA) relative to a latency ceiling: a sample is bad when its whole
    bucket sits above ``threshold`` (bucket LOWER edge >= threshold —
    conservative by up to one log2 bucket, never spuriously bad)."""
    bad = 0
    edges = hist_bucket_edges()
    for i, c in enumerate(hist.counts):
        if not c:
            continue
        lower = 0.0 if i == 0 else edges[i - 1]
        if lower >= threshold:
            bad += c
    return bad, hist.n


class BurnRateEvaluator:
    """Multi-window error-budget burn over live metric deltas.

    Each evaluation round (one publisher interval) contributes one
    ``(bad, total)`` observation per objective; the burn rate over a
    window of recent rounds is ``(bad fraction) / (error budget)`` —
    burn 1.0 spends the budget exactly, burn 14 torches it.  An
    objective BREACHES when the burn exceeds ``fast_burn`` over the last
    ``fast_window`` rounds AND ``slow_burn`` over the last
    ``slow_window`` rounds (the SRE multi-window page rule: the short
    window reacts fast, the long window stops flapping).

    Breach actions: an alert record (bounded ``alerts`` deque + flight
    ring event + ``slo.breach.<name>`` counter on the process timeline),
    a flight dump (FORCED on an objective's first breach; later breaches
    ride the recorder's rate limit — an alert storm writes one incident
    file, not hundreds, and never blocks the hot path), and the
    registered shed hooks: while any objective is breached the hooks run
    with ``shed_level`` (tightening scheduler admission,
    :meth:`blit.serve.scheduler.Scheduler.shed`); when every burn
    clears they run with 0.0."""

    def __init__(self, objectives: Iterable[SLObjective] = (), *,
                 fast_window: int = 5, slow_window: int = 30,
                 fast_burn: float = 14.0, slow_burn: float = 2.0,
                 shed_level: float = 0.5, recorder=None,
                 clock: Callable[[], float] = time.time):
        self.objectives = [o if isinstance(o, SLObjective)
                           else SLObjective.from_dict(o)
                           for o in objectives]
        self.fast_window = max(1, int(fast_window))
        self.slow_window = max(self.fast_window, int(slow_window))
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.shed_level = float(shed_level)
        self.recorder = recorder
        self.clock = clock
        self._rings: Dict[str, List[Tuple[int, int]]] = {
            o.name: [] for o in self.objectives}
        self._state: Dict[str, Dict] = {
            o.name: {"metric": o.metric, "kind": o.kind,
                     "threshold": o.threshold, "burn_fast": 0.0,
                     "burn_slow": 0.0, "breached": False}
            for o in self.objectives}
        self._dumped: set = set()
        self._shed_hooks: List[Callable[[float], None]] = []
        self._shed = 0.0
        self.alerts: List[Dict] = []
        # The last round's per-objective (bad, total) observations —
        # the history store's SLO burn feed (blit.history folds them
        # into bucket records so slo-report sums the same cut the live
        # evaluator made).
        self.last_obs: Dict[str, Tuple[int, int]] = {}

    @classmethod
    def for_config(cls, config: SiteConfig = DEFAULT, **kw
                   ) -> "BurnRateEvaluator":
        return cls(objectives_for(config),
                   fast_window=config.slo_fast_window,
                   slow_window=config.slo_slow_window,
                   fast_burn=config.slo_fast_burn,
                   slow_burn=config.slo_slow_burn, **kw)

    # -- shed hooks --------------------------------------------------------
    def add_shed_hook(self, hook: Callable[[float], None]) -> None:
        self._shed_hooks.append(hook)

    def attach_scheduler(self, scheduler) -> None:
        """Register ``scheduler.shed`` as a breach action — the
        ROADMAP's "telemetry-hist-driven load shedding" hook."""
        self.add_shed_hook(scheduler.shed)

    def detach_scheduler(self, scheduler) -> None:
        with contextlib.suppress(ValueError):
            self._shed_hooks.remove(scheduler.shed)

    # -- evaluation --------------------------------------------------------
    def burn(self, name: str, window: int) -> float:
        ring = self._rings.get(name) or []
        tail = ring[-max(1, window):]
        total = sum(t for _, t in tail)
        if total == 0:
            return 0.0
        bad = sum(b for b, _ in tail)
        o = next(x for x in self.objectives if x.name == name)
        return (bad / total) / o.budget

    def observe(self, delta: Timeline, interval_s: float) -> List[Dict]:
        """Fold one interval's Timeline DELTA into every objective's
        burn window and fire breach actions.  Returns the alerts raised
        this round.  Cheap and non-blocking by design: bucket sums, a
        bounded ring, and a rate-limited dump."""
        fired: List[Dict] = []
        breached_any = False
        for o in self.objectives:
            if o.kind == "latency":
                h = delta.hists.get(o.metric)
                bad, total = (bad_fraction(h, o.threshold)
                              if h is not None and h.n else (0, 0))
            else:
                s = delta.stages.get(o.metric)
                if s is not None and s.seconds > 0:
                    gbps = s.bytes / s.seconds / 1e9
                    bad, total = (1, 1) if gbps < o.threshold else (0, 1)
                else:
                    bad, total = 0, 0
            ring = self._rings[o.name]
            ring.append((bad, total))
            del ring[:-self.slow_window]
            self.last_obs[o.name] = (bad, total)
            bf = self.burn(o.name, self.fast_window)
            bs = self.burn(o.name, self.slow_window)
            breach = bf >= self.fast_burn and bs >= self.slow_burn
            st = self._state[o.name]
            st.update(burn_fast=round(bf, 3), burn_slow=round(bs, 3),
                      breached=breach)
            if not breach:
                continue
            breached_any = True
            alert = {"t": self.clock(), "class": "slo",
                     "objective": o.name,
                     "kind": o.kind, "metric": o.metric,
                     "threshold": o.threshold, "burn_fast": round(bf, 3),
                     "burn_slow": round(bs, 3), "bad": bad,
                     "total": total}
            rec = self.recorder if self.recorder is not None \
                else flight_recorder()
            rec.event("slo", o.name, burn_fast=round(bf, 2),
                      burn_slow=round(bs, 2))
            process_timeline().count(f"slo.breach.{o.name}")
            # First breach per objective FORCES its incident dump (the
            # triage trail must exist); every later one rides the
            # recorder's rate limit — the LiveRawStream._incident rule.
            path = rec.dump(
                f"SLO breach: {o.name} burning {bf:.1f}x its error "
                f"budget over the last {self.fast_window} samples "
                f"({o.kind} {o.metric!r}, threshold {o.threshold})",
                force=o.name not in self._dumped)
            self._dumped.add(o.name)
            if path:
                alert["flight_dump"] = path
            self.alerts.append(alert)
            del self.alerts[:-256]
            fired.append(alert)
            log.warning("SLO breach: %s (burn fast=%.1f slow=%.1f)",
                        o.name, bf, bs)
        target = self.shed_level if breached_any else 0.0
        if target != self._shed:
            self._shed = target
            for hook in list(self._shed_hooks):
                try:
                    hook(target)
                except Exception:  # noqa: BLE001 — one bad hook must not
                    log.warning("SLO shed hook failed", exc_info=True)
        return fired

    def breached(self) -> List[str]:
        return [n for n, st in self._state.items() if st["breached"]]

    def report(self) -> Dict[str, Dict]:
        """Current burn/breach state per objective (the sample's ``slo``
        block and `blit top`'s SLO row)."""
        return {n: dict(st) for n, st in self._state.items()}


# -- device / derived gauges ------------------------------------------------


def device_gauges(timeline: Timeline) -> int:
    """Sample per-device HBM gauges onto ``timeline`` where the backend
    exposes ``memory_stats()`` (TPU/GPU do; CPU returns nothing).  Never
    *imports* jax — if the process hasn't paid the jax import, there are
    no devices worth sampling and ``blit top`` must stay light.  Returns
    the number of devices sampled."""
    if "jax" not in sys.modules:
        return 0
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — monitoring must not break the run
        return 0
    n = in_use = peak = 0
    for d in devices:
        try:
            st = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend-dependent surface
            st = None
        if not st:
            continue
        bi = int(st.get("bytes_in_use", 0))
        pk = int(st.get("peak_bytes_in_use", bi))
        timeline.gauge(f"dev.hbm_in_use_bytes.{d.id}", bi)
        timeline.gauge(f"dev.hbm_peak_bytes.{d.id}", pk)
        in_use += bi
        peak += pk
        n += 1
    if n:
        timeline.gauge("dev.hbm_in_use_bytes", in_use)
        timeline.gauge("dev.hbm_peak_bytes", peak)
    return n


def _delta_timeline(merged: Timeline, last_state: Optional[Dict]
                    ) -> Timeline:
    """The increment between a merged cumulative Timeline and a prior
    :meth:`Timeline.state` — stages subtract exactly, histograms go
    through ``HistogramStats.since`` (bucket-exact), gauges copy their
    latest level (a level has no meaningful delta)."""
    d = Timeline()
    last_stages = (last_state or {}).get("stages") or {}
    for k, s in list(merged.stages.items()):
        p = last_stages.get(k) or {}
        calls = s.calls - int(p.get("calls", 0))
        seconds = s.seconds - float(p.get("seconds", 0.0))
        nbytes = s.bytes - int(p.get("bytes", 0))
        if calls or nbytes or seconds > 1e-12:
            ds = d.stages[k]
            ds.calls = max(0, calls)
            ds.seconds = max(0.0, seconds)
            ds.bytes = max(0, nbytes)
            ds.byte_free = s.byte_free
    last_hists = (last_state or {}).get("hists") or {}
    for k, h in list(merged.hists.items()):
        dh = h.since(last_hists.get(k) or {})
        if dh.n:
            d.hists[k] = dh
    for k, g in list(merged.gauges.items()):
        if g.n:
            d.gauge(k, g.last)
    return d


# -- the publisher -----------------------------------------------------------


def _make_http_server(publisher, port: int):
    """Lazily built so spool-only publishers never import http.server."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — stdlib contract
            try:
                if self.path.startswith("/healthz"):
                    body = json.dumps(publisher.health()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    from blit.observability import (
                        OPENMETRICS_CTYPE,
                        PROM_CTYPE,
                        wants_openmetrics,
                    )

                    # Exemplars only in the negotiated OpenMetrics
                    # exposition (ISSUE 15) — the legacy text parser
                    # rejects the suffix.
                    om = wants_openmetrics(self.headers.get("Accept"))
                    body = render_prometheus(
                        publisher.fleet_report(),
                        openmetrics=om).encode()
                    ctype = OPENMETRICS_CTYPE if om else PROM_CTYPE
                elif self.path.startswith("/snapshot"):
                    sample = publisher.last_sample or publisher.tick()
                    body = json.dumps(sample).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
            except Exception as e:  # noqa: BLE001 — scrape must not kill
                self.send_error(500, str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet scrape traffic
            log.debug("http: " + fmt, *args)

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    server.daemon_threads = True
    return server


class MetricsPublisher:
    """Continuous telemetry publishing for one process (module
    docstring): interval snapshots of every WATCHED Timeline (merged;
    the process-wide ambient timeline is always in the set), each sample
    carrying the cumulative state (the fleet-merge wire format) plus the
    interval's stage/histogram DELTAS, appended to a per-process spool
    file and served over HTTP.  ``tick()`` takes one sample
    synchronously — tests and the SLO drills drive it directly;
    ``start()`` runs it on a daemon thread every ``interval_s``."""

    def __init__(self, *, interval_s: Optional[float] = None,
                 spool_dir: Optional[str] = None,
                 port: Optional[int] = None,
                 timeline: Optional[Timeline] = None,
                 objectives: Optional[Iterable] = None,
                 config: SiteConfig = DEFAULT,
                 spans: Optional[bool] = None,
                 clock: Callable[[], float] = time.time):
        d = monitor_defaults(config)
        self.interval_s = (d["interval_s"] if interval_s is None
                           else float(interval_s))
        self.spool_dir = spool_dir if spool_dir is not None \
            else d["spool_dir"]
        # Span batches per sample (ISSUE 15 tentpole #4): each tick
        # ships the spans finished since the last, so the spool doubles
        # as a fleet trace source (BLIT_MONITOR_SPANS / ctor arg).
        self.spans = d["spans"] if spans is None else bool(spans)
        self._span_cursor = 0
        self.clock = clock
        # Publisher-owned gauges (device HBM, derived ICI rate) live on
        # their own timeline so sampling never mutates a caller's.
        self._own = Timeline()
        self._watch_lock = threading.Lock()
        self._watched: List[Timeline] = [
            self._own, timeline if timeline is not None
            else process_timeline()]
        if objectives is None:
            self.slo = BurnRateEvaluator.for_config(config, clock=clock)
        else:
            self.slo = BurnRateEvaluator(
                objectives, fast_window=config.slo_fast_window,
                slow_window=config.slo_slow_window,
                fast_burn=config.slo_fast_burn,
                slow_burn=config.slo_slow_burn, clock=clock)
        # History & forensics plane (ISSUE 20): a durable tiered store
        # fed per tick, a median/MAD anomaly baseline scored per tick,
        # and the incident bundler behind every page.  All lazy and all
        # optional — with BLIT_HISTORY_DIR unset the tick path pays one
        # dict lookup and three Nones.
        self._config = config
        self.history = None
        self.anomaly = None
        self._bundler = None
        hd = history_defaults(config)
        if hd["enabled"]:
            from blit import history as _history

            try:
                self.history = _history.HistoryStore(
                    hd["dir"], config=config, clock=clock)
            except (OSError, ValueError):
                log.warning("history store unavailable", exc_info=True)
        if hd["anomaly"] and (hd["enabled"] or hd["incident_dir"]):
            from blit import history as _history

            self.anomaly = _history.AnomalyDetector.for_config(
                config, clock=clock)
        if hd["incident_dir"]:
            from blit import history as _history

            self._bundler = _history.incident_bundler(config)
        self.seq = 0
        self.last_sample: Optional[Dict] = None
        self._last_state: Optional[Dict] = None
        self._last_mono: Optional[float] = None
        self._tick_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._spool_f = None
        self.spool_path: Optional[str] = None
        if self.spool_dir:
            os.makedirs(self.spool_dir, exist_ok=True)
            self.spool_path = os.path.join(
                self.spool_dir, f"{hostname()}-{os.getpid()}.jsonl")
            self._spool_f = open(self.spool_path, "a")
        self._server = None
        self._server_thread = None
        self.port: Optional[int] = None
        if port is None:
            port = d["port"]
        elif int(port) < 0:
            # The planes' -1 "disabled" encoding, honored for EXPLICIT
            # ctor args too: an embedding server (the fleet PeerServer
            # reuses this class for its health/metrics bodies) can pin
            # the endpoint off however the environment is set.
            port = None
        if port is not None:
            self._server = _make_http_server(self, int(port))
            self.port = self._server.server_address[1]

    # -- watch set ---------------------------------------------------------
    def watch(self, timeline: Timeline) -> None:
        """Add a Timeline to the merged sample (refcounted list append —
        nested :func:`publishing` scopes over the same timeline
        balance)."""
        with self._watch_lock:
            self._watched.append(timeline)

    def unwatch(self, timeline: Timeline) -> None:
        with self._watch_lock:
            for i in range(len(self._watched) - 1, 1, -1):
                if self._watched[i] is timeline:
                    del self._watched[i]
                    return

    def merged_timeline(self) -> Timeline:
        """One cumulative fold of every CURRENTLY watched timeline
        (deduped by identity — a timeline watched from two nested scopes
        counts once).  A workload that unwatches leaves the merged view:
        the publisher is a live surface, and a scraper sees the drop as
        an ordinary counter reset (Prometheus ``rate()``/``increase()``
        handle those natively); the workload's full history stays in the
        spool lines it published while attached."""
        with self._watch_lock:
            tls = list(self._watched)
        merged, seen = Timeline(), set()
        for tl in tls:
            if id(tl) in seen:
                continue
            seen.add(id(tl))
            merged.merge(Timeline.from_state(tl.state()))
        return merged

    # -- sampling ----------------------------------------------------------
    def tick(self) -> Dict:
        """Take one sample NOW: merge the watch set, compute the
        interval delta, sample device/derived gauges, evaluate the SLOs,
        spool the record, and return it."""
        with self._tick_lock:
            now_mono = time.monotonic()
            interval = (self.interval_s if self._last_mono is None
                        else max(1e-9, now_mono - self._last_mono))
            self._last_mono = now_mono
            device_gauges(self._own)
            merged = self.merged_timeline()
            delta = _delta_timeline(merged, self._last_state)
            # ICI byte-rate, derived from the mesh.*_ici_bytes hists
            # (each sample in those is one collective's payload).
            ici = sum(h.total for k, h in delta.hists.items()
                      if k.endswith("_ici_bytes"))
            if ici:
                self._own.gauge("mesh.ici_gbps", ici / interval / 1e9)
                merged.gauge("mesh.ici_gbps", ici / interval / 1e9)
            alerts = self.slo.observe(delta, interval)
            now = self.clock()
            anomaly_state: Dict[str, Dict] = {}
            if self.anomaly is not None:
                from blit import history as _history

                gauges_now = {k: g.last
                              for k, g in merged.gauges.items() if g.n}
                alerts = alerts + self.anomaly.observe(
                    _history.series_values(delta, gauges_now), now)
                anomaly_state = self.anomaly.report()
            if self.history is not None:
                try:
                    self.history.append(
                        now, interval, delta,
                        gauges={k: g.last
                                for k, g in merged.gauges.items() if g.n},
                        burn=dict(self.slo.last_obs))
                except Exception:  # noqa: BLE001 — durability is best-
                    log.warning("history append failed", exc_info=True)
            if self._bundler is not None:
                for alert in alerts:
                    kind = (f"slo:{alert['objective']}"
                            if alert.get("objective")
                            else f"anomaly:{alert.get('metric', '?')}")
                    self._bundler.snapshot(
                        kind,
                        f"page: {kind} "
                        f"(flight={alert.get('flight_dump', '-')})",
                        alert=alert, publisher=self, timeline=merged,
                        history=self.history)
            self._last_state = merged.state()
            from blit import faults

            sample = {
                "t": now,
                "seq": self.seq,
                "host": hostname(),
                "pid": os.getpid(),
                "worker": 0,
                "anchor": wall_anchor(),
                "interval_s": round(interval, 6),
                "timeline": self._last_state,
                "faults": faults.counters(),
                "delta": {
                    "stages": {
                        k: {"calls": s.calls,
                            "seconds": round(s.seconds, 6),
                            "bytes": s.bytes,
                            "gbps": round(s.gbps, 4)}
                        for k, s in sorted(delta.stages.items())
                    },
                    "hists": {k: h.report()
                              for k, h in sorted(delta.hists.items())},
                },
                "gauges": {k: round(g.last, 6)
                           for k, g in sorted(merged.gauges.items())},
                "slo": self.slo.report(),
                "alerts": alerts,
            }
            if anomaly_state:
                sample["anomaly"] = anomaly_state
            if self.spans:
                from blit import observability

                self._span_cursor, new_spans = (
                    observability.tracer().spans_since(self._span_cursor))
                sample["spans"] = new_spans
            self.seq += 1
            self.last_sample = sample
            if self._spool_f is not None:
                try:
                    self._spool_f.write(json.dumps(sample) + "\n")
                    self._spool_f.flush()
                except OSError:
                    log.warning("monitor spool write failed",
                                exc_info=True)
            return sample

    def snapshot_dict(self) -> Dict:
        """This process's cumulative telemetry in the fleet-harvest wire
        shape (:func:`~blit.observability.merge_fleet` input) — the
        merged watch set as ONE snapshot, so per-reducer timelines
        cannot collapse into each other through the (host, pid) dedupe."""
        from blit import faults

        return {"host": hostname(), "pid": os.getpid(), "worker": 0,
                "timeline": self.merged_timeline().state(),
                "faults": faults.counters(), "spans": []}

    def fleet_report(self) -> Dict:
        return merge_fleet([self.snapshot_dict()])

    def health(self) -> Dict:
        """The ``/healthz`` body — and it degrades HONESTLY (ISSUE 12
        satellite): ``status`` is ``"degraded"`` (with machine-readable
        ``reasons``) whenever a circuit breaker is not fully closed, a
        recovery supervisor is mid-recovery (health hooks), or an SLO is
        in fast-burn; ``"ok"`` otherwise.  ``ok`` stays the boolean twin
        of ``status`` so existing probes keep working."""
        reasons: List[str] = []
        breached = self.slo.breached()
        for name in breached:
            reasons.append(f"slo-fast-burn:{name}")
        if self.anomaly is not None:
            for metric in self.anomaly.breached():
                reasons.append(f"anomaly:{metric}")
        try:
            # Lazy import (monitor's import discipline): the pool module
            # is stdlib + blit.faults/observability/config, never jax.
            from blit.parallel.pool import current_pool

            pool = current_pool()
        except Exception:  # noqa: BLE001 — health must not raise
            pool = None
        if pool is not None:
            for row in pool.health():
                if row.get("state") != "closed":
                    reasons.append(
                        f"breaker-{row['state'].replace('-', '_')}:"
                        f"{row.get('host')}")
        status_override: Optional[str] = None
        for name, hook in list(_HEALTH_HOOKS.items()):
            try:
                state = hook()
            except Exception:  # noqa: BLE001 — one bad hook must not
                continue
            if state and state.get("degraded"):
                reasons.append(
                    f"{name}:{state.get('reason', 'degraded')}")
                # A hook may name the degradation mode — the elastic
                # controller answers "resizing" mid-flip (ISSUE 17), a
                # more truthful probe verdict than a generic
                # "degraded".
                if state.get("status"):
                    status_override = str(state["status"])
        status = (status_override or "degraded") if reasons else "ok"
        return {"ok": not reasons, "status": status, "reasons": reasons,
                "t": self.clock(), "host": hostname(),
                "pid": os.getpid(), "seq": self.seq,
                "interval_s": self.interval_s,
                "watching": len(self._watched),
                "breached": breached,
                "alerts": len(self.slo.alerts)}

    @property
    def url(self) -> Optional[str]:
        return (f"http://127.0.0.1:{self.port}"
                if self.port is not None else None)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MetricsPublisher":
        if self._server is not None and self._server_thread is None:
            self._server_thread = threading.Thread(
                target=self._server.serve_forever, name="blit-monitor-http",
                daemon=True)
            self._server_thread.start()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="blit-monitor", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — publishing must not die
                log.warning("monitor tick failed", exc_info=True)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._server_thread = None
        if self._spool_f is not None:
            with contextlib.suppress(OSError):
                self._spool_f.close()
            self._spool_f = None
        if self.history is not None:
            self.history.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


def fold_health(own_reasons: Iterable[str],
                peer_health: Dict[str, Optional[Dict]], *,
                clock: Callable[[], float] = time.time) -> Dict:
    """Fold per-peer health documents into ONE fleet ``{ok, status,
    reasons}`` answer (ISSUE 14 satellite) — the front door's
    ``/healthz`` body, so a single probe answers "is the fleet
    serving".

    ``own_reasons`` are the door's local degradations (draining, open
    breakers, ejected peers); ``peer_health`` maps peer name → its last
    fetched ``/healthz`` body (None = unreachable/never fetched).  A
    peer's own reasons fold in prefixed with its name; ``status`` is
    ``"ok"`` only when nothing anywhere is degraded, ``"degraded"``
    while any peer (or the door) carries a reason but the fleet can
    still serve, and the caller may override to ``"down"`` when no
    peers remain routable."""
    reasons: List[str] = list(own_reasons)
    peers_ok = 0
    for name, doc in sorted(peer_health.items()):
        if doc is None:
            reasons.append(f"peer-unreachable:{name}")
            continue
        if doc.get("ok"):
            peers_ok += 1
            continue
        peers_ok += 1  # degraded but answering — still serving
        for r in doc.get("reasons") or ["degraded"]:
            reasons.append(f"peer:{name}:{r}")
    status = "ok" if not reasons else ("degraded" if peers_ok else "down")
    return {"ok": not reasons, "status": status, "reasons": reasons,
            "peers": len(peer_health), "peers_ok": peers_ok,
            "t": clock()}


# -- health hooks -----------------------------------------------------------

# Named callables other planes register so /healthz can degrade honestly
# without this module importing them: each returns None/{} when healthy,
# or {"degraded": True, "reason": "...", ...} while not.  The recovery
# supervisors (blit/recover.py) register here for the duration of a
# supervised run.
_HEALTH_HOOKS: Dict[str, Callable[[], Optional[Dict]]] = {}


def register_health_hook(name: str,
                         hook: Callable[[], Optional[Dict]]) -> None:
    """Register (or replace) a named /healthz contributor."""
    _HEALTH_HOOKS[name] = hook


def unregister_health_hook(name: str) -> None:
    _HEALTH_HOOKS.pop(name, None)


# -- the process-wide auto-publisher ----------------------------------------

_PUB: Optional[MetricsPublisher] = None
_PUB_LOCK = threading.Lock()


def ensure_publisher(config: SiteConfig = DEFAULT
                     ) -> Optional[MetricsPublisher]:
    """The process-wide publisher, started on first use when monitoring
    is enabled (``BLIT_MONITOR_SPOOL`` / ``BLIT_MONITOR_PORT`` or the
    SiteConfig fields — :func:`blit.config.monitor_defaults`) or a
    publisher was installed explicitly (:func:`install_publisher` — the
    CLI ``--monitor-*`` flags); ``None`` when disabled.  Every
    long-running entry point (reduce/scan/stream/serve, via
    :func:`publishing`) calls this, so flipping one env var turns
    continuous publishing on for any workload with no code changes."""
    global _PUB
    with _PUB_LOCK:
        if _PUB is not None:
            return _PUB
    # BLIT_HISTORY_DIR alone also arms the loop (ISSUE 20): the
    # durable store is fed by ticks, so a history-only config still
    # needs the publisher running even with no spool and no port.
    if not (monitor_defaults(config)["enabled"]
            or history_defaults(config)["enabled"]):
        return None
    with _PUB_LOCK:
        if _PUB is None:
            _PUB = MetricsPublisher(config=config).start()
            atexit.register(shutdown_publisher)
        return _PUB


def install_publisher(pub: MetricsPublisher) -> MetricsPublisher:
    """Install ``pub`` (started) as the process-wide publisher — the
    flag-driven twin of the env gate, so CLI ``--monitor-*`` flags reach
    every :func:`publishing` hook without mutating the environment.
    Replaces (and closes) any previous singleton."""
    global _PUB
    with _PUB_LOCK:
        old, _PUB = _PUB, pub
    if old is not None and old is not pub:
        old.close()
    atexit.register(shutdown_publisher)
    return pub


def shutdown_publisher() -> None:
    """Stop and forget the process-wide publisher (tests; atexit)."""
    global _PUB
    with _PUB_LOCK:
        pub, _PUB = _PUB, None
    if pub is not None:
        pub.close()


@contextlib.contextmanager
def publishing(timeline: Optional[Timeline] = None,
               config: SiteConfig = DEFAULT):
    """Scope a workload under the process-wide publisher: when
    monitoring is enabled, ``timeline`` joins the publisher's watch set
    for the duration (so a reducer's private Timeline shows up on
    ``/metrics`` and in the spool while it streams).  Disabled = a
    no-op costing two env reads."""
    pub = ensure_publisher(config)
    if pub is None or timeline is None:
        yield pub
        return
    seq0 = pub.seq
    pub.watch(timeline)
    try:
        yield pub
    finally:
        # A workload that finished between two interval ticks would
        # otherwise leave NO sample carrying its timeline — force one,
        # but only when the background loop didn't already cover it
        # (a busy serve process must not spool one line per request).
        try:
            if pub.seq == seq0:
                pub.tick()
        except Exception:  # noqa: BLE001 — publishing must not fail work
            log.warning("publishing exit tick failed", exc_info=True)
        pub.unwatch(timeline)


def published(fn):
    """Decorator form of :func:`publishing` for entry points with a
    ``timeline=`` kwarg (the scan planes)."""

    @functools.wraps(fn)
    def wrapper(*args, **kw):
        with publishing(kw.get("timeline")):
            return fn(*args, **kw)

    return wrapper


# -- spool reading / fleet merge --------------------------------------------


# How many trailing bytes of a spool file one dashboard frame reads: a
# spool grows without bound over a long session, and `blit top` must
# stay O(1) per frame, not O(session length).
_SPOOL_TAIL_BYTES = 2 << 20


def read_spool(spool_dir: str, tail: int = 1) -> List[Dict]:
    """The newest ``tail`` parseable samples from every per-process
    spool file, flattened oldest→newest per file.  Reads only the last
    ``_SPOOL_TAIL_BYTES`` of each file, so a frame over a multi-hour
    spool costs the same as over a fresh one.

    Torn-tail hardening (ISSUE 20 satellite): a publisher SIGKILLed
    mid-``write`` leaves a truncated trailing line — it HEALS (skipped)
    and COUNTS (``monitor.torn_lines`` on the process timeline), the
    PR 19 backfill-ledger rule, so ``blit top`` keeps rendering while
    the damage stays visible."""
    samples = []
    torn = 0
    for path in sorted(glob.glob(os.path.join(spool_dir, "*.jsonl"))):
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - _SPOOL_TAIL_BYTES))
                blob = f.read()
        except OSError:
            continue
        lines = blob.decode("utf-8", errors="replace").splitlines()
        if size > _SPOOL_TAIL_BYTES and lines:
            lines = lines[1:]  # the seek likely landed mid-line
        got: List[Dict] = []
        for line in reversed(lines):
            line = line.strip()
            if not line:
                continue
            try:
                got.append(json.loads(line))
            except ValueError:
                torn += 1
                continue
            if len(got) >= tail:
                break
        samples.extend(reversed(got))
    if torn:
        process_timeline().count("monitor.torn_lines", torn)
    return samples


def merge_spool(spool_dir: str) -> Tuple[Dict, List[Dict]]:
    """Merge a spool dir's per-process samples into one fleet report
    plus the newest per-process samples for the rate/SLO panel.

    The report folds the recent spool TAIL, not just the newest line —
    samples carry the cumulative ``timeline`` state so they ARE
    :func:`~blit.observability.merge_fleet` snapshots — selecting ONE
    per (host, pid) by (richness, seq): richest first, so a workload
    that already detached from the live publisher (its final lines are
    quiet) still renders the full stage table it spooled while
    running, and NEWEST among equally-rich lines, so a steady-state
    run's dashboard shows current counters, not the oldest line of the
    tail (merge_fleet's own dedupe is first-wins on richness ties —
    right for harvest duplicates, stale for a time-ordered spool)."""
    samples = read_spool(spool_dir, tail=1000)
    best: Dict[Tuple, Tuple] = {}
    latest: Dict[Tuple, Dict] = {}
    for s in samples:
        key = (s.get("host"), s.get("pid"))
        rank = (len((s.get("timeline") or {}).get("stages") or {}),
                s.get("seq", 0))
        if key not in best or rank >= best[key][0]:
            best[key] = (rank, s)
        if key not in latest or s.get("seq", 0) >= \
                latest[key].get("seq", 0):
            latest[key] = s
    report = merge_fleet([s for _, s in best.values()])
    return report, list(latest.values())


# -- rendering ---------------------------------------------------------------


def _fmt_rate(gbps: float) -> str:
    return f"{gbps:8.3f}" if gbps else f"{'-':>8}"


def render_top(report: Dict, samples: Iterable[Dict] = (), *,
               title: str = "blit top",
               now: Optional[float] = None) -> str:
    """One ``blit top`` frame over a fleet report (+ optional live
    per-process samples): per-stage throughput (run-cumulative and
    this-interval), stage-tail p50/p99, SLO burn, and host health."""
    now = time.time() if now is None else now
    samples = list(samples)
    by_proc = {(s.get("host"), s.get("pid")): s for s in samples}
    lines: List[str] = []
    hosts = report.get("hosts") or {}
    nproc = sum(len(e.get("workers") or []) for e in hosts.values())
    breached = sorted({n for s in samples
                       for n, st in (s.get("slo") or {}).items()
                       if st.get("breached")})
    state = (f"SLO BREACH: {', '.join(breached)}" if breached else "ok")
    lines.append(
        f"{title} — {time.strftime('%H:%M:%S', time.gmtime(now))} UTC | "
        f"{len(hosts)} host(s), {nproc} process(es) | {state}")
    for host, e in sorted(hosts.items()):
        procs = [s for (h, _), s in sorted(by_proc.items())
                 if h == host]
        age = min((now - s.get("t", now) for s in procs), default=None)
        age_s = f"  age {age:.1f}s" if age is not None else ""
        lines.append(f"host {host} "
                     f"({len(e.get('workers') or [])} proc){age_s}")
        # Per-stage table: cumulative GB/s beside the newest interval's.
        deltas: Dict[str, Dict] = {}
        for s in procs:
            for k, row in ((s.get("delta") or {}).get("stages")
                           or {}).items():
                d = deltas.setdefault(
                    k, {"bytes": 0, "seconds": 0.0, "calls": 0})
                d["bytes"] += row.get("bytes", 0)
                d["seconds"] += row.get("seconds", 0.0)
                d["calls"] += row.get("calls", 0)
        stages = e.get("stages") or {}
        rows = [(k, v) for k, v in stages.items()
                if isinstance(v, dict) and "calls" in v]
        if rows:
            lines.append(f"  {'stage':<22} {'calls':>8} {'GB/s(run)':>10} "
                         f"{'GB/s(now)':>10}")
            for k, v in sorted(rows):
                d = deltas.get(k)
                now_gbps = (d["bytes"] / d["seconds"] / 1e9
                            if d and d["seconds"] > 0 else 0.0)
                lines.append(
                    f"  {k:<22} {v.get('calls', 0):>8} "
                    f"{_fmt_rate(v.get('gbps', 0.0))} "
                    f"{_fmt_rate(round(now_gbps, 3))}")
        for k, h in sorted((stages.get("hists") or {}).items()):
            lines.append(
                f"  tail {k:<19} n={h.get('n', 0):<7} "
                f"p50={h.get('p50', 0)}s p99={h.get('p99', 0)}s "
                f"max={h.get('max', 0)}s")
        gauges = {}
        for s in procs:
            gauges.update(s.get("gauges") or {})
        if not procs:
            gauges = {k: g.get("last", 0)
                      for k, g in (stages.get("gauges") or {}).items()}
        if gauges:
            shown = " ".join(f"{k}={v}" for k, v in sorted(gauges.items()))
            lines.append(f"  gauges {shown}")
        for k, v in sorted((e.get("faults") or {}).items()):
            lines.append(f"  fault {k:<20} {v}")
    for (host, pid), s in sorted(by_proc.items()):
        slo = s.get("slo") or {}
        if not slo:
            continue
        for name, st in sorted(slo.items()):
            mark = "BREACH" if st.get("breached") else "ok"
            lines.append(
                f"slo {host}/{pid} {name:<20} burn "
                f"{st.get('burn_fast', 0.0):>7.2f}/"
                f"{st.get('burn_slow', 0.0):<7.2f} [{mark}] "
                f"({st.get('kind')} {st.get('metric')} "
                f"@ {st.get('threshold')})")
    alerts = [a for s in samples for a in (s.get("alerts") or [])]
    for a in alerts[-5:]:
        lines.append(f"ALERT {a.get('objective')} burn_fast="
                     f"{a.get('burn_fast')} dump="
                     f"{a.get('flight_dump', '-')}")
    if not hosts:
        lines.append("(no samples yet)")
    return "\n".join(lines)


def watch_loop(render: Callable[[], str], interval_s: float,
               count: Optional[int] = None, out=None,
               clear: bool = True,
               sleep: Callable[[float], None] = time.sleep) -> int:
    """The shared refresh loop behind ``blit top`` and ``blit telemetry
    --watch``: render a frame, clear the terminal (ANSI), repeat.
    ``count`` bounds the frames (tests; None = until interrupted).
    Returns frames rendered."""
    out = sys.stdout if out is None else out
    n = 0
    try:
        while True:
            text = render()
            if clear:
                out.write(ANSI_CLEAR)
            out.write(text if text.endswith("\n") else text + "\n")
            out.flush()
            n += 1
            if count is not None and n >= count:
                return n
            sleep(max(0.01, interval_s))
    except KeyboardInterrupt:
        return n


# -- Prometheus exposition parsing ------------------------------------------

# A sample line, with an optional OpenMetrics exemplar suffix
# (`value # {trace_id="..."} exemplar-value [timestamp]`, ISSUE 15) —
# the exemplar is captured (group 4) but optional, so pre-exemplar
# scrape bodies parse unchanged.
_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*?)\})?\s+(\S+)"
    r"(?:\s+#\s+\{(.*?)\}\s+(\S+)(?:\s+(\S+))?)?$")
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return re.sub(r"\\(.)",
                  lambda m: {"n": "\n"}.get(m.group(1), m.group(1)),
                  value)


def parse_prometheus(text: str
                     ) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse a Prometheus exposition body into ``(name, labels, value)``
    samples — the round-trip check behind the native-histogram
    exposition (tests) and the CI monitor smoke's "parseable /metrics"
    assertion.  OpenMetrics exemplar suffixes on ``_bucket`` lines
    (ISSUE 15) are tolerated and dropped — use
    :func:`parse_prometheus_exemplars` to read them.  Raises
    ``ValueError`` on an unparseable sample line."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labels_s, value = m.groups()[:3]
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labels_s or "")}
        out.append((name, labels, float(value)))
    return out


def parse_prometheus_exemplars(
        text: str) -> List[Tuple[str, Dict[str, str], Dict]]:
    """The exemplars of an exposition body (ISSUE 15): every sample
    line carrying an OpenMetrics ``# {...} value [ts]`` suffix, as
    ``(metric name, labels, {"labels", "value", "t"})``."""
    out: List[Tuple[str, Dict[str, str], Dict]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None or m.group(4) is None:
            continue
        name, labels_s, _, ex_labels, ex_value, ex_t = m.groups()
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labels_s or "")}
        ex = {"labels": {k: _unescape(v)
                         for k, v in _LABEL_RE.findall(ex_labels or "")},
              "value": float(ex_value)}
        if ex_t is not None:
            ex["t"] = float(ex_t)
        out.append((name, labels, ex))
    return out


# -- per-request access records: read / filter / aggregate (ISSUE 15) -------


def read_requests(src: str, tail: Optional[int] = None) -> List[Dict]:
    """Access records from a request-log spool: ``src`` is a directory
    (every ``requests-*.jsonl`` member, rotations included), a single
    ``.jsonl`` file, or a rotated member.  Records come back
    time-ordered; a torn line (a process SIGKILLed mid-write) HEALS
    (skipped) and COUNTS (``monitor.torn_lines``) — the spool-reader
    rule.  ``tail`` keeps only the newest N."""
    paths: List[str] = []
    if os.path.isdir(src):
        paths = sorted(glob.glob(os.path.join(src, "requests-*.jsonl*")))
        if not paths:
            paths = sorted(glob.glob(os.path.join(src, "*.jsonl*")))
    else:
        paths = [src]
    records: List[Dict] = []
    torn = 0
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        torn += 1
                        continue
                    if isinstance(doc, dict):
                        records.append(doc)
        except OSError:
            continue
    if torn:
        process_timeline().count("monitor.torn_lines", torn)
    records.sort(key=lambda r: r.get("t", 0.0))
    if tail is not None:
        records = records[-max(0, int(tail)):]
    return records


def filter_requests(records: Iterable[Dict], *,
                    slow_ms: Optional[float] = None,
                    status: Optional[str] = None,
                    client: Optional[str] = None,
                    role: Optional[str] = None,
                    since: Optional[float] = None,
                    until: Optional[float] = None) -> List[Dict]:
    """The ``blit requests`` filter surface: keep records at least
    ``slow_ms`` slow, matching a status (name like ``overloaded`` or
    HTTP code like ``503``), a client, a role (door/peer/serve), and/or
    inside a ``[since, until]`` epoch window (``blit requests
    --since/--until`` parse the shared window grammar —
    :func:`blit.history.parse_when` — into these)."""
    out = []
    for r in records:
        if slow_ms is not None and r.get("duration_s", 0.0) * 1e3 < slow_ms:
            continue
        if since is not None and float(r.get("t", 0.0)) < since:
            continue
        if until is not None and float(r.get("t", 0.0)) > until:
            continue
        if status is not None and not (
                str(r.get("status")) == status
                or str(r.get("code")) == status):
            continue
        if client is not None and r.get("client") != client:
            continue
        if role is not None and r.get("role") != role:
            continue
        out.append(r)
    return out


def aggregate_requests(records: Iterable[Dict],
                       slowest: int = 5) -> Dict:
    """One summary over a record set: counts by status/tier/role —
    and, for catalog-addressed asks (ISSUE 19: door records carry
    ``session``/``scan``), by ``session/scan`` — latency p50/p99/max
    (via the bounded histogram), total bytes, and the slowest records
    (each carrying its trace id — the page → record → trace pivot)."""
    records = list(records)
    by_status: Dict[str, int] = {}
    by_tier: Dict[str, int] = {}
    by_role: Dict[str, int] = {}
    by_scan: Dict[str, int] = {}
    lat = HistogramStats()
    total_bytes = 0
    hedges = hedge_wins = 0
    for r in records:
        by_status[str(r.get("status"))] = (
            by_status.get(str(r.get("status")), 0) + 1)
        if r.get("tier"):
            by_tier[str(r["tier"])] = by_tier.get(str(r["tier"]), 0) + 1
        by_role[str(r.get("role"))] = by_role.get(str(r.get("role")), 0) + 1
        if r.get("session"):
            key = (f"{r['session']}/{r['scan']}" if r.get("scan")
                   else str(r["session"]))
            by_scan[key] = by_scan.get(key, 0) + 1
        lat.observe(float(r.get("duration_s", 0.0)))
        total_bytes += int(r.get("bytes", 0) or 0)
        if r.get("hedged"):
            hedges += 1
            if r.get("hedge_won"):
                hedge_wins += 1
    slow = sorted(records, key=lambda r: r.get("duration_s", 0.0),
                  reverse=True)[:max(0, int(slowest))]
    return {
        "records": len(records),
        "by_status": by_status,
        "by_tier": by_tier,
        "by_role": by_role,
        "by_scan": by_scan,
        "p50_s": round(lat.percentile(0.50), 6),
        "p99_s": round(lat.percentile(0.99), 6),
        "max_s": round(lat.vmax, 6),
        "bytes": total_bytes,
        "hedged": hedges,
        "hedge_won": hedge_wins,
        "slowest": [
            {k: r.get(k) for k in ("t", "rid", "trace", "role", "client",
                                   "fp", "tier", "peer", "status",
                                   "session", "scan",
                                   "duration_s") if r.get(k) is not None}
            for r in slow
        ],
    }


def render_requests(records: Iterable[Dict]) -> str:
    """Access records as a readable table (`blit requests`' default)."""
    lines = [f"{'when':<8} {'role':<5} {'status':<10} {'tier':<9} "
             f"{'ms':>9} {'client':<10} {'peer':<8} trace"]
    for r in records:
        when = time.strftime("%H:%M:%S", time.gmtime(r.get("t", 0.0)))
        lines.append(
            f"{when:<8} {str(r.get('role', '-')):<5} "
            f"{str(r.get('status', '-')):<10} "
            f"{str(r.get('tier') or '-'):<9} "
            f"{r.get('duration_s', 0.0) * 1e3:>9.2f} "
            f"{str(r.get('client', '-')):<10} "
            f"{str(r.get('peer') or '-'):<8} {r.get('trace', '-')}")
    return "\n".join(lines)


# -- fleet trace gathering (ISSUE 15 tentpole #4) ----------------------------


def gather_trace_sources(sources: Iterable[str], *,
                         timeout: float = 10.0
                         ) -> Tuple[List[Dict], Dict[str, HistogramStats]]:
    """Span dicts + merged histograms from heterogeneous fleet sources
    — what ``blit trace-view --fleet`` stitches.  Each source is:

    - an ``http://...`` base URL → its ``/snapshot`` body (a peer/door
      :class:`~blit.serve.http.PeerServer` or monitor endpoint);
    - a directory → every ``*.jsonl`` monitor-spool file in it (span
      batches per sample, newest cumulative timeline per process) plus
      every ``*.snapshot.json`` saved snapshot;
    - a ``.jsonl`` file → one spool file;
    - any other file → a saved snapshot / fleet report / flight dump
      (anything carrying ``spans`` and optionally a timeline).

    Returns ``(spans, hists)`` with hists merged across processes
    (exemplars keep the newest per bucket)."""
    spans: List[Dict] = []
    hists: Dict[str, HistogramStats] = {}

    def fold_hists(hist_states: Optional[Dict]) -> None:
        for name, st in (hist_states or {}).items():
            if not isinstance(st, dict):
                continue
            h = HistogramStats.from_state(st)
            if name in hists:
                hists[name].merge(h)
            else:
                hists[name] = h

    def fold_doc(doc: Dict) -> None:
        if not isinstance(doc, dict):
            return
        spans.extend(s for s in (doc.get("spans") or [])
                     if isinstance(s, dict))
        tl = doc.get("timeline")
        if isinstance(tl, dict):
            fold_hists(tl.get("hists"))
        fold_hists(doc.get("hists"))
        # A merge_fleet report: per-host raw hist_state blocks.
        for e in (doc.get("hosts") or {}).values():
            if isinstance(e, dict):
                fold_hists(e.get("hist_state"))

    def fold_spool_file(path: str) -> None:
        last_tl: Optional[Dict] = None
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        sample = json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(sample, dict):
                        continue
                    spans.extend(s for s in (sample.get("spans") or [])
                                 if isinstance(s, dict))
                    if isinstance(sample.get("timeline"), dict):
                        last_tl = sample["timeline"]
        except OSError:
            return
        if last_tl:
            fold_hists(last_tl.get("hists"))

    for src in sources:
        if src.startswith("http://") or src.startswith("https://"):
            from blit.serve.http import http_json

            try:
                status, _, body = http_json(
                    "GET", src.rstrip("/"), "/snapshot", timeout=timeout)
            except OSError as e:
                log.warning("trace source %s unreachable: %s", src, e)
                continue
            if status == 200 and isinstance(body, dict):
                fold_doc(body)
        elif os.path.isdir(src):
            for path in sorted(glob.glob(os.path.join(src, "*.jsonl"))):
                fold_spool_file(path)
            for path in sorted(glob.glob(
                    os.path.join(src, "*.snapshot.json"))):
                try:
                    with open(path) as f:
                        fold_doc(json.load(f))
                except (OSError, ValueError):
                    continue
        elif src.endswith(".jsonl"):
            fold_spool_file(src)
        else:
            try:
                with open(src) as f:
                    fold_doc(json.load(f))
            except (OSError, ValueError) as e:
                log.warning("trace source %s unreadable: %s", src, e)
    # Dedupe by span id (a /snapshot and a spool may overlap).
    seen, unique = set(), []
    for s in spans:
        sid = s.get("span")
        if sid and sid in seen:
            continue
        if sid:
            seen.add(sid)
        unique.append(s)
    return unique, hists


# -- bench-diff: the CI perf-regression gate --------------------------------

# Higher-is-better scalar metrics worth tracking across BENCH rounds.
_METRIC_KEY_RE = re.compile(
    r"(_gbps|_per_s|_speedup|^async_speedup$|_efficiency|^hit_rate$"
    r"|_hit_rate$|_attained$)",
)
# Lower-is-better scalars (ISSUE 16: the serve plane gates on request
# latency quantiles) — the noise band inverts for these.
_LOWER_METRIC_KEY_RE = re.compile(r"_p\d+_s$")


def metric_lower_is_better(key: str) -> bool:
    """Is ``key`` a lower-is-better metric (a latency quantile)?  Such
    metrics regress when the fresh value rises ABOVE the noise band."""
    return _LOWER_METRIC_KEY_RE.search(key) is not None


def load_bench_json(path: str) -> Dict:
    """Load a bench record: either a plain ``bench.py`` /
    ``ingest-bench`` JSON document, or a checked-in ``BENCH_*.json``
    wrapper (``{"n", "cmd", "rc", "tail"}`` — the recorded stdout tail,
    whose last JSON line is the bench record)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "tail" in doc and "cmd" in doc:
        if isinstance(doc.get("parsed"), dict):
            return doc["parsed"]
        for line in reversed(str(doc["tail"]).strip().splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                return json.loads(line)
            except ValueError:
                continue
        # A failed round (rc != 0, no record line) is part of history —
        # callers skip it, it must not poison the trajectory.
        raise ValueError(f"no JSON bench record in the tail of {path}")
    if not isinstance(doc, dict):
        raise ValueError(f"{path} is not a bench JSON document")
    return doc


def bench_metrics(doc: Dict) -> Dict[str, float]:
    """Extract the comparable higher-is-better scalars from a bench
    record: for ``ingest-bench`` documents the per-leg ingest rate /
    overlap efficiency and the async speedup; for ``bench.py`` records
    the headline ``value`` (keyed by its ``metric`` name) plus every
    top-level ``*_gbps`` / ``*_per_s`` / speedup / efficiency scalar;
    for serve-bench records (``serve-bench --archive-day``, ISSUE 16)
    the flat ``metrics`` dict — fleet hit rate, wire GB/s, and the
    request/serialize latency quantiles (``*_pNN_s``, which compare
    lower-is-better).  ``blit slo-report --json`` documents ride the
    same ``metrics`` branch (``slo.<name>_attained`` matches
    ``_attained$``), so ``blit bench-diff`` gates attainment like any
    other bench scalar."""
    out: Dict[str, float] = {}

    def num(v) -> Optional[float]:
        return (float(v) if isinstance(v, (int, float))
                and not isinstance(v, bool) else None)

    if isinstance(doc.get("metrics"), dict):
        for k, v in doc["metrics"].items():
            f = num(v)
            if f is None:
                continue
            if _METRIC_KEY_RE.search(k) or metric_lower_is_better(k):
                out[k] = f
        return out
    if "legs" in doc:
        for leg in doc.get("legs") or []:
            name = "async" if leg.get("async_output") else "sync"
            for k in ("ingest_gbps", "overlap_efficiency"):
                v = num(leg.get(k))
                if v is not None:
                    out[f"{name}.{k}"] = v
        v = num(doc.get("async_speedup"))
        if v is not None:
            out["async_speedup"] = v
        v = num((doc.get("dedoppler") or {}).get("drift_rates_per_s"))
        if v is not None:
            out["dedoppler.drift_rates_per_s"] = v
        # The live leg's latency tails (ISSUE 18: the --packets run is
        # the sustained-capture gate) — *_pNN_s keys compare
        # lower-is-better in bench_diff, like the serve quantiles.
        live = doc.get("live") or {}
        for k in ("chunk_to_product_p50_s", "chunk_to_product_p99_s"):
            v = num(live.get(k))
            if v is not None:
                out[f"live.{k}"] = v
        pk = live.get("packet") or {}
        for k in ("assembly_p50_s", "assembly_p99_s"):
            v = num(pk.get(k))
            if v is not None:
                out[f"packet.{k}"] = v
        return out
    metric = doc.get("metric")
    for k, v in doc.items():
        f = num(v)
        if f is None:
            continue
        if k == "value" and metric:
            out[str(metric)] = f
        elif _METRIC_KEY_RE.search(k):
            out[k] = f
    return out


def bench_rig(doc: Dict) -> Optional[str]:
    """The rig a bench record measured (its ``config.backend``; None
    when unrecorded — ingest-bench documents)."""
    return (doc.get("config") or {}).get("backend")


def bench_diff(fresh: Dict, baselines: List[Dict], *,
               rel_tol: float = 0.35,
               metrics: Optional[Iterable[str]] = None,
               cross_rig: bool = False) -> Dict:
    """Compare a fresh bench record against a baseline trajectory with
    noise bands: per metric, the band is ``[min·(1-rel_tol),
    max·(1+rel_tol)]`` over the trajectory — a fresh value below the
    band REGRESSES (throughput-style scalars are higher-is-better),
    above it IMPROVES, inside it is ok.  Latency quantiles
    (:func:`metric_lower_is_better`) invert: rising ABOVE the band
    regresses, dropping below it improves.  The verdict is
    ``"regress"`` iff any tracked metric regressed.  Metrics with no
    baseline datapoint are reported as ``"new"`` and never gate.

    Baselines recorded on a DIFFERENT rig than the fresh record
    (``config.backend`` — the checked-in trajectory mixes TPU and CPU
    rounds) are excluded unless ``cross_rig=True``: a CPU run regressing
    against a TPU number is noise, not signal."""
    fresh_m = bench_metrics(fresh)
    want = set(metrics) if metrics else None
    rig = bench_rig(fresh)
    skipped_rigs = 0
    kept = []
    for b in baselines:
        brig = bench_rig(b)
        if (not cross_rig and rig is not None and brig is not None
                and brig != rig):
            skipped_rigs += 1
            continue
        kept.append(b)
    baselines = kept
    traj: Dict[str, List[float]] = {}
    for b in baselines:
        for k, v in bench_metrics(b).items():
            traj.setdefault(k, []).append(v)
    rows: Dict[str, Dict] = {}
    regressed = []
    for k in sorted(fresh_m):
        if want is not None and k not in want:
            continue
        v = fresh_m[k]
        hist = traj.get(k)
        if not hist:
            rows[k] = {"fresh": v, "status": "new", "n": 0}
            continue
        lo, hi = min(hist), max(hist)
        band_lo = lo * (1.0 - rel_tol)
        band_hi = hi * (1.0 + rel_tol)
        if metric_lower_is_better(k):
            status = ("regress" if v > band_hi
                      else "improved" if v < band_lo else "ok")
        else:
            status = ("regress" if v < band_lo
                      else "improved" if v > band_hi else "ok")
        if status == "regress":
            regressed.append(k)
        rows[k] = {"fresh": v, "lo": lo, "hi": hi,
                   "band_lo": round(band_lo, 6),
                   "band_hi": round(band_hi, 6),
                   "status": status, "n": len(hist)}
    return {
        "verdict": "regress" if regressed else "pass",
        "rel_tol": rel_tol,
        "rig": rig,
        "baselines": len(baselines),
        "baselines_skipped_other_rig": skipped_rigs,
        "regressed": regressed,
        "metrics": rows,
    }


def render_bench_diff(verdict: Dict) -> str:
    """``blit bench-diff``'s human table."""
    lines = [f"bench-diff: {verdict['verdict'].upper()} "
             f"({verdict['baselines']} baseline(s), "
             f"noise ±{verdict['rel_tol'] * 100:.0f}%)"]
    lines.append(f"{'metric':<44} {'fresh':>12} {'band_lo':>12} "
                 f"{'band_hi':>12} status")
    for k, row in verdict["metrics"].items():
        def band(key):
            v = row.get(key)
            return f"{v:>12.4g}" if v is not None else f"{'-':>12}"

        lines.append(
            f"{k:<44} {row['fresh']:>12.4g} {band('band_lo')} "
            f"{band('band_hi')} {row['status']}")
    return "\n".join(lines)
