"""The recorder packet front end (ISSUE 18 tentpole, layer 1).

Everything below :class:`~blit.stream.source.FileTailSource` assumes a
recorder already wrote the bytes to disk.  The real BL@GBT backend
(MacMahon+ 2018) is 64 ``blc`` nodes catching UDP packet streams off
the telescope switch — this module is that front end: datagrams in,
:class:`~blit.stream.source.StreamChunk`\\ s (whole GUPPI RAW blocks)
out, with the gap/reorder arithmetic in between.

**Framing.**  One session is one packet stream: a HEADER packet carries
the session's GUPPI header card text (the template every block shares —
OBSNCHAN/NPOL/NBITS/BLOCSIZE/TBIN/OVERLAP fix the block geometry), DATA
packets carry an int8 payload tile placed by ``(chan0, time0)`` into
block ``block``, and a FIN packet declares the session's total block
count.  Every packet carries a monotonically-increasing send-order
``pktidx`` — the sequence number all reorder/gap accounting keys on.
The 32-byte header is fixed ``!4sBBHQIIIHH`` (magic ``BLPK``, version,
type, reserved, pktidx, block, chan0, time0, nchan, ntime); payloads
are C-order ``(nchan, ntime, npol, 2)`` int8 — the RAW block layout, so
placement is a strided copy, never a transpose.

**Gap discipline.**  :class:`PacketAssembler` only ever emits COMPLETE
blocks.  An incomplete block is withheld, and once packets arrive for
blocks ``reorder_horizon`` past it (or FIN lands) it is ABANDONED:
buffer freed, ``packet.gap`` counted, and its sequence number published
in :attr:`PacketAssembler.gapped` — the proof
:class:`~blit.stream.plane.LiveRawStream` consumes to mask the seat
immediately instead of waiting out the lateness budget.  A gapped block
is therefore masked (zero weight), never garbage: the product is
byte-identical to a batch reduction of the recording with those blocks
zero-filled — the acceptance oracle of tests/test_packet.py.  Packets
for an already-delivered or abandoned block count ``packet.late`` and
drop; duplicate tiles count ``packet.dup``; a ``pktidx`` below the
session's running maximum counts ``packet.reorder``.  First-packet →
block-complete time lands in the ``packet.assembly_s`` histogram (the
``config.slo_defaults`` sustained-capture objective's metric).

**Sources.**  :class:`PacketSource` binds a UDP socket (``SO_RCVBUF``
sized by :func:`blit.config.packet_defaults` — a recorder never pauses,
so the kernel buffer is the only back-pressure) and drains it inside
``get()``.  :class:`PacketReplaySource` replays an at-rest recording AS
its packet stream at ``rate``× recording cadence, with seeded
drop/reorder/dup schedules — the deterministic twin for tests, CI and
``ingest-bench --live --packets``.  Both feed the SAME assembler, so
the replay drills exercise the real wire path end to end.

Chaos: every received packet fires the ``packet.recv`` fault point
(``BLIT_FAULTS`` grammar) — ``drop``/``dup``/``delay``/``fail`` plus
the ``reorder`` mode this PR adds (hold the packet back until
``amount`` later packets have passed — ``blit chaos --fault reorder``).
"""

from __future__ import annotations

import io
import logging
import socket
import struct
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from blit import faults, observability
from blit.config import DEFAULT, SiteConfig, packet_defaults
from blit.io.guppi import CARD_LEN, block_ntime, read_raw_header
from blit.observability import Timeline
from blit.stream.source import ChunkSource, StreamChunk

log = logging.getLogger("blit.stream")

MAGIC = b"BLPK"
VERSION = 1
PKT_DATA, PKT_HEADER, PKT_FIN = 0, 1, 2
# magic, version, ptype, reserved, pktidx, block, chan0, time0, nchan,
# ntime — 32 bytes, network order.
_HDR = struct.Struct("!4sBBHQIIIHH")
HEADER_BYTES = _HDR.size


def encode_packet(ptype: int, pktidx: int, block: int = 0,
                  chan0: int = 0, time0: int = 0, nchan: int = 0,
                  ntime: int = 0, payload: bytes = b"") -> bytes:
    return _HDR.pack(MAGIC, VERSION, ptype, 0, pktidx, block, chan0,
                     time0, nchan, ntime) + payload


def decode_packet(data: bytes) -> Tuple[Dict, bytes]:
    """``(fields, payload)`` of one datagram.  Raises ``ValueError`` on
    anything that is not a well-formed blit packet — a capture socket
    shares its port with whatever else the network sends."""
    if len(data) < HEADER_BYTES:
        raise ValueError(f"short packet: {len(data)} bytes")
    magic, ver, ptype, _, pktidx, block, chan0, time0, nchan, ntime = (
        _HDR.unpack_from(data))
    if magic != MAGIC:
        raise ValueError(f"bad magic {magic!r}")
    if ver != VERSION:
        raise ValueError(f"unsupported packet version {ver}")
    return ({"ptype": ptype, "pktidx": pktidx, "block": block,
             "chan0": chan0, "time0": time0, "nchan": nchan,
             "ntime": ntime}, data[HEADER_BYTES:])


def _header_cards(hdr: Dict) -> bytes:
    from blit.io.guppi import _format_card

    cards = b"".join(_format_card(k, v) for k, v in hdr.items()
                     if not k.startswith("_"))
    return cards + "END".ljust(CARD_LEN).encode("ascii")


def _parse_header_cards(payload: bytes) -> Dict:
    hdr, _ = read_raw_header(io.BytesIO(payload))
    return hdr


def _npol(hdr: Dict) -> int:
    return 2 if hdr["NPOL"] > 2 else hdr["NPOL"]


class PacketFramer:
    """Split a session's blocks into DATA packet tiles: all-channel
    strips of ``packet_ntime`` time samples (optionally split again
    into ``packet_nchan``-channel tiles).  The framing is the session
    sender's and the replay source's SHARED schedule — and the
    assembler accepts any tiling, so a real recorder's geometry needs
    no code change, only different ``(chan0, time0, nchan, ntime)``."""

    def __init__(self, header: Dict, packet_ntime: Optional[int] = None,
                 packet_nchan: Optional[int] = None,
                 config: SiteConfig = DEFAULT):
        d = packet_defaults(config)
        self.header = dict(header)
        self.nchan = int(header["OBSNCHAN"])
        self.ntime = block_ntime(header)
        self.npol = _npol(header)
        pt = d["ntime"] if packet_ntime is None else int(packet_ntime)
        self.packet_ntime = max(1, min(pt, self.ntime, 0xFFFF))
        pc = self.nchan if packet_nchan is None else int(packet_nchan)
        self.packet_nchan = max(1, min(pc, self.nchan, 0xFFFF))

    def tiles(self) -> List[Tuple[int, int, int, int]]:
        """``(chan0, time0, nchan, ntime)`` per DATA packet of one
        block, in send order (time-major, like the recorder writes)."""
        out = []
        for t0 in range(0, self.ntime, self.packet_ntime):
            nt = min(self.packet_ntime, self.ntime - t0)
            for c0 in range(0, self.nchan, self.packet_nchan):
                nc = min(self.packet_nchan, self.nchan - c0)
                out.append((c0, t0, nc, nt))
        return out

    def packets_per_block(self) -> int:
        return len(self.tiles())

    def data_packet(self, pktidx: int, block: int, data: np.ndarray,
                    tile: Tuple[int, int, int, int]) -> bytes:
        c0, t0, nc, nt = tile
        payload = np.ascontiguousarray(
            data[c0:c0 + nc, t0:t0 + nt]).tobytes()
        return encode_packet(PKT_DATA, pktidx, block, c0, t0, nc, nt,
                             payload)

    def header_packet(self, pktidx: int) -> bytes:
        return encode_packet(PKT_HEADER, pktidx,
                             payload=_header_cards(self.header))

    def fin_packet(self, pktidx: int, total_blocks: int) -> bytes:
        return encode_packet(PKT_FIN, pktidx, block=total_blocks)


def packets_of(raw, packet_ntime: Optional[int] = None,
               packet_nchan: Optional[int] = None) -> Iterator[bytes]:
    """A completed recording as its full packet stream (HEADER, every
    DATA tile in send order, FIN) — the loopback test sender and the
    simplest way to feed a :class:`PacketSource` a whole session."""
    from blit.io.guppi import open_raw

    raw = raw if hasattr(raw, "nblocks") else open_raw(raw)
    fr = PacketFramer(raw.header(0), packet_ntime, packet_nchan)
    pktidx = 0
    yield fr.header_packet(pktidx)
    pktidx += 1
    for b in range(raw.nblocks):
        data = raw.read_block(b)
        for tile in fr.tiles():
            yield fr.data_packet(pktidx, b, data, tile)
            pktidx += 1
    yield fr.fin_packet(pktidx, raw.nblocks)


class PacketAssembler:
    """Datagrams → complete :class:`StreamChunk` blocks (module
    docstring).  Single-threaded by design: both sources call
    :meth:`feed` and :meth:`pop` from the consumer's pull loop, so the
    accounting needs no lock."""

    def __init__(self, *, path: str = "<packets>",
                 reorder_horizon: Optional[int] = None,
                 timeline: Optional[Timeline] = None,
                 clock=time.monotonic,
                 config: SiteConfig = DEFAULT):
        d = packet_defaults(config)
        self.path = path
        self.horizon = (d["horizon_blocks"] if reorder_horizon is None
                        else int(reorder_horizon))
        self.timeline = timeline if timeline is not None else Timeline()
        self._clock = clock
        self.header: Optional[Dict] = None
        self._shape: Optional[Tuple[int, int, int, int]] = None
        self._blocsize = 0
        # block → (buffer, {tile keys placed}, bytes_filled, t_first)
        self._partial: Dict[int, list] = {}
        self._complete: deque = deque()
        self._done: set = set()     # delivered or abandoned block idxs
        self._scan = 0              # lowest block not yet resolved
        self.gapped: set = set()    # abandoned — the plane's mask proof
        self.total: Optional[int] = None
        self.fin = False
        self._max_pktidx = -1
        self._max_block = -1
        self._preheader: List[bytes] = []
        # Fault-injected reorder holdback: [(release_after, datagram)].
        self._held: List[list] = []
        self._dumped = False
        self.packets = 0
        self.reorders = 0
        self.late = 0
        self.dups = 0
        self.bad = 0

    # -- receive ----------------------------------------------------------
    def feed(self, datagram: bytes) -> None:
        """Account and place one datagram; releases any fault-held
        packets whose holdback expired."""
        self._feed_one(datagram, held=False)
        if self._held:
            release = [h[1] for h in self._held if h[0] <= 0]
            self._held = [h for h in self._held if h[0] > 0]
            for d in release:
                self._feed_one(d, held=True)

    def _feed_one(self, datagram: bytes, held: bool) -> None:
        try:
            f, payload = decode_packet(datagram)
        except ValueError as e:
            self.bad += 1
            self.timeline.count("packet.bad")
            log.warning("%s: undecodable packet dropped (%s)",
                        self.path, e)
            return
        if not held:
            for h in self._held:
                h[0] -= 1
            act = faults.fire("packet.recv",
                              key=f"{self.path}#pkt{f['pktidx']}")
            if act is not None:
                if act.mode == "drop":
                    log.warning("injected drop of packet %d", f["pktidx"])
                    return
                if act.mode == "dup":
                    self._feed_one(datagram, held=True)
                elif act.mode == "reorder":
                    depth = act.amount if act.amount > 0 else 3
                    log.warning("injected reorder of packet %d "
                                "(held back %d packets)", f["pktidx"],
                                depth)
                    self._held.append([depth, datagram])
                    return
        self.packets += 1
        self.timeline.count("packet.recv")
        if f["pktidx"] < self._max_pktidx:
            self.reorders += 1
            self.timeline.count("packet.reorder")
        else:
            self._max_pktidx = f["pktidx"]
        if f["ptype"] == PKT_HEADER:
            self._on_header(payload)
        elif f["ptype"] == PKT_FIN:
            self._on_fin(f["block"])
        else:
            self._on_data(f, payload)

    def _on_header(self, payload: bytes) -> None:
        if self.header is not None:
            return  # a re-sent template: idempotent
        hdr = _parse_header_cards(payload)
        if hdr.get("NBITS", 8) != 8:
            raise NotImplementedError(
                f"NBITS={hdr['NBITS']} not supported (GBT uses 8)")
        self.header = hdr
        self._shape = (hdr["OBSNCHAN"], block_ntime(hdr), _npol(hdr), 2)
        self._blocsize = int(np.prod(self._shape))
        replay, self._preheader = self._preheader, []
        for d in replay:
            self._feed_one(d, held=True)

    def _on_fin(self, total: int) -> None:
        # Release anything fault-held first: the wire is done, nothing
        # more will overtake a held packet — judging gaps before
        # delivering it would fabricate one.
        release, self._held = [h[1] for h in self._held], []
        for d in release:
            self._feed_one(d, held=True)
        self.fin = True
        self.total = total
        self._max_block = max(self._max_block, total - 1)
        self._resolve_through(total - 1, "end of session")

    def _on_data(self, f: Dict, payload: bytes) -> None:
        if self.header is None:
            # Data before the template (a dropped/late HEADER packet):
            # hold a bounded replay buffer rather than losing the tiles.
            if len(self._preheader) < 65536:
                self._preheader.append(
                    encode_packet(PKT_DATA, f["pktidx"], f["block"],
                                  f["chan0"], f["time0"], f["nchan"],
                                  f["ntime"], payload))
            return
        b = f["block"]
        if b in self._done:
            # The seat was already delivered or abandoned: too late.
            self.late += 1
            self.timeline.count("packet.late")
            return
        nchan, ntime = f["nchan"], f["ntime"]
        want = nchan * ntime * self._shape[2] * 2
        if (len(payload) != want
                or f["chan0"] + nchan > self._shape[0]
                or f["time0"] + ntime > self._shape[1]):
            self.bad += 1
            self.timeline.count("packet.bad")
            log.warning("%s: packet %d payload/geometry mismatch "
                        "(%d bytes for a %d-byte tile); dropped",
                        self.path, f["pktidx"], len(payload), want)
            return
        if b > self._max_block:
            self._max_block = b
        st = self._partial.get(b)
        if st is None:
            st = [np.zeros(self._shape, np.int8), set(), 0,
                  self._clock()]
            self._partial[b] = st
        key = (f["chan0"], f["time0"])
        if key in st[1]:
            self.dups += 1
            self.timeline.count("packet.dup")
            return
        st[1].add(key)
        tile = np.frombuffer(payload, np.int8).reshape(
            nchan, ntime, self._shape[2], 2)
        st[0][f["chan0"]:f["chan0"] + nchan,
              f["time0"]:f["time0"] + ntime] = tile
        st[2] += want
        if st[2] >= self._blocsize:
            del self._partial[b]
            self._done.add(b)
            self.timeline.observe("packet.assembly_s",
                                  self._clock() - st[3])
            hdr = dict(self.header)
            hdr["PKTIDX"] = int(self.header.get("PKTIDX", 0)) + b * (
                self._shape[1] - int(self.header.get("OVERLAP", 0)))
            self._complete.append(StreamChunk(b, hdr, st[0]))
        self._sweep()

    def _sweep(self) -> None:
        """Abandon blocks the stream has provably moved past: packets
        arrived for blocks ``horizon`` beyond them, so their missing
        tiles — or the WHOLE block, if not one packet landed — are a
        GAP, not reordering still in flight."""
        self._resolve_through(
            self._max_block - self.horizon,
            f"packets arrived ≥{self.horizon} blocks past it "
            f"(the reorder horizon)")

    def _resolve_through(self, limit: int, why: str) -> None:
        """Every block ≤ ``limit`` must now be complete or a gap — a
        low-water scan, so each block is judged exactly once."""
        while self._scan <= limit:
            b = self._scan
            self._scan += 1
            if b not in self._done:
                self._abandon(b, why)

    def _abandon(self, b: int, why: str) -> None:
        st = self._partial.pop(b, None)
        got = 0 if st is None else st[2]
        self._done.add(b)
        self.gapped.add(b)
        self.timeline.count("packet.gap")
        faults.incr("packet.gap")
        rec = observability.flight_recorder()
        rec.event("packet", "gap", block=b, path=self.path,
                  bytes_missing=self._blocsize - got)
        rec.dump(
            f"packet gap: block {b} of {self.path} incomplete "
            f"({got}/{self._blocsize} bytes) — {why}; the block will "
            "be masked to zero weight, never delivered partial",
            force=not self._dumped)
        self._dumped = True
        log.warning("%s: block %d abandoned with %d/%d bytes (%s); "
                    "masked downstream", self.path, b, got,
                    self._blocsize, why)

    # -- deliver ----------------------------------------------------------
    def pop(self) -> Optional[StreamChunk]:
        return self._complete.popleft() if self._complete else None

    @property
    def drained(self) -> bool:
        return self.fin and not self._complete

    def report(self) -> Dict:
        """The packet-plane counters for session/bench reports."""
        h = self.timeline.hist_quantiles(["packet.assembly_s"]).get(
            "packet.assembly_s", {})
        return {
            "packets": self.packets,
            "gaps": len(self.gapped),
            "gapped_blocks": sorted(self.gapped),
            "reorders": self.reorders,
            "late": self.late,
            "dups": self.dups,
            "bad": self.bad,
            "assembly_p50_s": h.get("p50"),
            "assembly_p99_s": h.get("p99"),
        }


class PacketSource(ChunkSource):
    """UDP packet capture as a :class:`ChunkSource` (module docstring).
    Binds ``host:port`` (``port=0`` = ephemeral, read it back from
    :attr:`port`), sizes ``SO_RCVBUF`` from
    :func:`blit.config.packet_defaults`, and drains the socket inside
    ``get()`` — no receiver thread, so back-pressure is the kernel
    buffer and anything beyond it sheds as packet loss → gaps → masked
    blocks, never a stalled recorder."""

    def __init__(self, host: Optional[str] = None,
                 port: Optional[int] = None, *,
                 rcvbuf: Optional[int] = None,
                 reorder_horizon: Optional[int] = None,
                 timeline: Optional[Timeline] = None,
                 clock=time.monotonic,
                 config: SiteConfig = DEFAULT):
        d = packet_defaults(config)
        host = d["host"] if host is None else host
        port = d["port"] if port is None else int(port)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            self._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_RCVBUF,
                d["rcvbuf_bytes"] if rcvbuf is None else int(rcvbuf))
        except OSError:  # pragma: no cover — a host policy cap is fine
            pass
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self.path = f"udp://{host}:{self.port}"
        self.timeline = timeline if timeline is not None else Timeline()
        self.assembler = PacketAssembler(
            path=self.path, reorder_horizon=reorder_horizon,
            timeline=self.timeline, clock=clock, config=config)
        self.gapped = self.assembler.gapped
        self._clock = clock
        self._closed = False

    def get(self, timeout: float) -> Optional[StreamChunk]:
        if self.finished:
            return None
        deadline = self._clock() + timeout
        while True:
            c = self.assembler.pop()
            if c is not None:
                return c
            if self.assembler.drained or self._closed:
                self.finished = True
                self.total = self.assembler.total
                return None
            now = self._clock()
            if now >= deadline:
                return None
            self._sock.settimeout(max(0.001, deadline - now))
            try:
                data, _ = self._sock.recvfrom(65535)
            except socket.timeout:
                return None
            except OSError:
                if self._closed:  # closed mid-recv by another thread
                    self.finished = True
                    return None
                raise
            self.assembler.feed(data)
            # Drain the burst non-blocking: a recorder sends packet
            # trains, and one datagram per get() would fall behind.
            self._sock.settimeout(0)
            try:
                while True:
                    data, _ = self._sock.recvfrom(65535)
                    self.assembler.feed(data)
            except (BlockingIOError, socket.timeout):
                pass

    def packet_report(self) -> Dict:
        return self.assembler.report()

    def stop(self) -> None:
        self._closed = True
        super().stop()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class PacketReplaySource(ChunkSource):
    """Replay an at-rest recording as its PACKET stream at ``rate``×
    recording cadence, with seeded drop/reorder/dup schedules (module
    docstring).  The deterministic twin of :class:`PacketSource` for
    tests/CI/bench: same framing, same assembler, same gap discipline —
    only the socket is replaced by a paced schedule.

    ``drop`` is a fraction of DATA packets (seeded uniform) or an
    explicit pktidx iterable; ``drop_blocks`` drops EVERY packet of the
    named blocks (the deterministic whole-block gap the zero-filled
    oracle pins); ``reorder`` is a fraction of DATA packets each
    deferred ``reorder_depth`` send slots; ``dup`` re-sends a fraction
    a few slots later.  All schedules are pure functions of ``seed``."""

    def __init__(self, raw, *, rate: float = 1.0,
                 packet_ntime: Optional[int] = None,
                 packet_nchan: Optional[int] = None,
                 drop: object = None,
                 drop_blocks=None,
                 reorder: float = 0.0,
                 reorder_depth: int = 4,
                 dup: float = 0.0,
                 seed: int = 0,
                 reorder_horizon: Optional[int] = None,
                 timeline: Optional[Timeline] = None,
                 clock=time.monotonic, sleep=time.sleep,
                 config: SiteConfig = DEFAULT):
        import random

        from blit.io.guppi import open_raw

        self.raw = raw if hasattr(raw, "nblocks") else open_raw(raw)
        self.path = getattr(self.raw, "path", "<packet-replay>")
        if rate <= 0:
            raise ValueError(f"replay rate must be > 0, got {rate}")
        self.rate = rate
        self._clock = clock
        self._sleep = sleep
        self.timeline = timeline if timeline is not None else Timeline()
        self.assembler = PacketAssembler(
            path=self.path, reorder_horizon=reorder_horizon,
            timeline=self.timeline, clock=clock, config=config)
        self.gapped = self.assembler.gapped
        hdr0 = self.raw.header(0)
        self._framer = PacketFramer(hdr0, packet_ntime, packet_nchan,
                                    config=config)
        tbin = float(hdr0.get("TBIN", 0.0) or 0.0)
        drop_blocks = set(drop_blocks or ())
        rng = random.Random(seed)
        # The nominal send order: HEADER, every block's tiles, FIN —
        # pktidx IS this order, so a deferred packet arrives with a
        # lower pktidx than its neighbours (a true reorder).
        nominal: List[Tuple[int, float, Optional[int],
                            Optional[tuple]]] = []
        pktidx = 0
        nominal.append((pktidx, 0.0, None, None))  # HEADER, due at t=0
        pktidx += 1
        cum = 0
        tiles = self._framer.tiles()
        for b in range(self.raw.nblocks):
            cum += self.raw.block_ntime_kept(b)
            due = cum * tbin / rate
            for tile in tiles:
                nominal.append((pktidx, due, b, tile))
                pktidx += 1
        fin_idx = pktidx
        drop_set = set()
        if drop is not None:
            if isinstance(drop, float):
                drop_set = {i for i, _, b, _ in nominal
                            if b is not None and rng.random() < drop}
            else:
                drop_set = {int(i) for i in drop}
        sched: List[Tuple[float, int, Tuple]] = []
        slot = 0
        for idx, due, b, tile in nominal:
            if b is not None and (idx in drop_set or b in drop_blocks):
                continue
            slot += 1
            pos = slot
            if b is not None and reorder and rng.random() < reorder:
                pos += max(1, int(reorder_depth))
            sched.append((due, pos, (idx, b, tile)))
            if b is not None and dup and rng.random() < dup:
                sched.append((due, pos + 2, (idx, b, tile)))
        # FIN sorts after every deferred/duplicated packet sharing its
        # due time — a schedule must never strand a reorder past the
        # end of the session (the assembler would call it a gap).
        sched.append((nominal[-1][1] if nominal else 0.0, float("inf"),
                      (fin_idx, None, "FIN")))
        # Due time first, deferred send slot second: a deferred packet
        # genuinely arrives after whatever overtook it.
        self._sched = sorted(sched, key=lambda e: (e[0], e[1]))
        self._pos = 0
        self._t0: Optional[float] = None
        self._nblocks = self.raw.nblocks
        self._cache: Dict[int, np.ndarray] = {}

    def _block(self, b: int) -> np.ndarray:
        data = self._cache.get(b)
        if data is None:
            data = self.raw.read_block(b)
            self._cache[b] = data
            # Reorder depth is small: a handful of blocks covers every
            # deferred tile without holding the recording in RAM.
            for old in sorted(self._cache):
                if len(self._cache) <= 4:
                    break
                if old != b:
                    del self._cache[old]
        return data

    def _emit(self, entry: Tuple) -> None:
        idx, b, tile = entry
        if tile == "FIN":
            self.assembler.feed(
                self._framer.fin_packet(idx, self._nblocks))
        elif b is None:
            self.assembler.feed(self._framer.header_packet(idx))
        else:
            self.assembler.feed(
                self._framer.data_packet(idx, b, self._block(b), tile))

    def get(self, timeout: float) -> Optional[StreamChunk]:
        if self.finished:
            return None
        deadline = self._clock() + timeout
        while True:
            c = self.assembler.pop()
            if c is not None:
                return c
            if self._pos >= len(self._sched):
                self.finished = True
                self.total = self.assembler.total
                return None
            if self._t0 is None:
                self._t0 = self._clock()
            due = self._sched[self._pos][0]
            wait = due - (self._clock() - self._t0)
            if wait > 0:
                if self._clock() + wait > deadline:
                    self._sleep(max(0.0, deadline - self._clock()))
                    return None
                self._sleep(wait)
            self._emit(self._sched[self._pos][2])
            self._pos += 1

    def packet_report(self) -> Dict:
        return self.assembler.report()
