"""The watermark assembler and streaming entry points (ISSUE 7 tentpole).

:class:`LiveRawStream` sits between a :class:`~blit.stream.source.ChunkSource`
and the batch reducers: it repairs chunk arrival (reorders within the
lateness budget, drops duplicates and post-mask stragglers) and exposes
the result as ``feed_blocks()`` — the ``(header, kept_samples,
read_into)`` triples :meth:`blit.pipeline.RawReducer._fill_rotation`
consumes.  Because BOTH paths feed the identical gap-free sample stream
through the identical chunk framing, a stream of a completed recording is
byte-identical to the batch reduction of the same file — the golden
contract of the whole plane (tests/test_stream.py).

Watermark semantics: chunks are identified by sequence number; arrival
times come from the monotonic clock at receipt.  The watermark trails the
newest *evidence* — the earliest arrival time among chunks proving a gap
(any pending chunk with a higher sequence number, or end-of-stream) — by
``lateness_s``.  When the watermark passes a still-missing chunk it is
MASKED: its samples feed as zeros (zero weight — the PR 2 antenna-mask
discipline, :func:`blit.parallel.antenna.record_mask`), so a stalled
recorder node degrades the product instead of wedging the pipeline.  A
chunk arriving after its seat was masked is counted late and dropped;
both incidents land in the flight recorder (one forced dump per stream —
the triage trail of docs/WORKFLOWS.md "Live session").

Latency is a first-class metric: per-product-append
``stream.chunk_to_product_s`` histograms (arrival of the newest sample a
product row depends on → that row durably handed to its writer), the
``stream.watermark_lag_s`` gauge (how far the feed runs behind arrivals)
and ``stream.chunk.*`` counters, all on the reducer's Timeline — so
``blit stream`` / ``ingest-bench --live`` report p50/p99 product latency
with no extra plumbing.

Entry points: :func:`stream_reduce` (``.fil``/``.h5`` filterbank
products) and :func:`stream_search` (``.hits`` drift-search products)
ride :class:`~blit.pipeline.RawReducer` /
:class:`~blit.search.dedoppler.DedopplerReducer` unchanged — same window
pinning, same async output plane, same writers.
"""

from __future__ import annotations

import bisect
import logging
import os
import time
from typing import Dict, Iterator, List, Optional

from blit import faults, observability
from blit.config import DEFAULT, SiteConfig, stream_defaults
from blit.io.guppi import block_ntime
from blit.observability import Timeline
from blit.stream.source import ChunkSource, StreamChunk

log = logging.getLogger("blit.stream")


class LiveRawStream:
    """A recording still being written, as the block feed the streaming
    reducers consume (module docstring).  Duck-types the slice of the
    ``GuppiRaw`` surface the pipelined producer touches: ``path``,
    ``header(0)`` (blocks until the first chunk arrives) and
    ``feed_blocks()`` (the watermark-ordered producer feed).

    One pass per instance: the feed is consumed on the ingest rotation's
    producer thread while ``arrival_for`` is read from the sink side —
    the marks list is append-only, so the cross-thread reads need no
    lock."""

    def __init__(self, source: ChunkSource, *,
                 lateness_s: Optional[float] = None,
                 stall_timeout_s: Optional[float] = None,
                 timeline: Optional[Timeline] = None,
                 premasked=None,
                 clock=time.monotonic, sleep=time.sleep,
                 config: SiteConfig = DEFAULT):
        d = stream_defaults(config)
        self.source = source
        self.lateness_s = (d["lateness_s"] if lateness_s is None
                           else lateness_s)
        self.timeline = timeline if timeline is not None else Timeline()
        self.path = getattr(source, "path", "<stream>")
        self._clock = clock
        self._sleep = sleep
        self._poll = max(0.005, min(0.05, self.lateness_s / 4 or 0.05))
        self._wd = observability.StallWatchdog(
            (d["stall_timeout_s"] if stall_timeout_s is None
             else stall_timeout_s),
            f"blit-stream[{self.path}]",
            what="a wedged chunk source would otherwise hang the live "
                 "feed; late data is the watermark's job, silence this "
                 "long is not",
        )
        self.header0: Optional[Dict] = None
        self._pending: Dict[int, StreamChunk] = {}
        self._next = 0
        self._total: Optional[int] = None
        self._eos_t: Optional[float] = None
        # Degradation ledger (the PR 2 shape): masked seqs mirror into
        # mask_header["_masked_chunks"] via record_mask, and the
        # stream_report() merge puts them on the product header.
        self.masked_chunks: set = set()
        self.mask_header: Dict = {}
        # Rejoin state (ISSUE 12): seats a PREVIOUS consumer's watermark
        # already masked (persisted in the StreamCursor).  They were
        # folded as zeros into rows the product already claims, so a
        # restarted consumer must re-mask them unconditionally — even if
        # the recorder's bytes exist on disk by now; such data counts
        # late, exactly as a straggler after a live mask would.
        self._premasked: set = set(premasked or ())
        self.late_chunks = 0
        self.dup_chunks = 0
        self.chunks_in = 0
        self.flight_dump: Optional[str] = None
        # Arrival marks: (cumulative kept samples, arrival time) of
        # each fed block — ONE tuple append per block, so the sink
        # thread's reads race only against whole entries (append-only;
        # see class docstring).  Masked spans feed degraded_rows().
        self._marks: List[tuple] = []
        self.masked_spans: List[tuple] = []
        # (seq, sample_a, sample_b) per masked seat, append-only like
        # _marks — the sink-thread-safe view the rejoin cursor persists
        # (reading the masked_chunks SET cross-thread would race its
        # producer-side mutation).
        self._masked_log: List[tuple] = []
        self._cum = 0

    # -- receipt + watermark ----------------------------------------------
    def _recv(self, timeout: float) -> bool:
        """Pull one chunk from the source; admit or reject it.  Returns
        True when a chunk was consumed (admitted or not)."""
        c = self.source.get(timeout)
        if c is None:
            if self.source.finished and self._total is None:
                total = self.source.total
                if total is None:
                    total = max(
                        [self._next - 1, *self._pending.keys()]) + 1
                self._total = total
                self._eos_t = self._clock()
            return False
        self._wd.beat()
        now = self._clock()
        act = faults.fire("stream.chunk", key=f"{self.path}#{c.seq}")
        copies = 1
        if act is not None:
            if act.mode == "drop":
                log.warning("injected drop of stream chunk %d", c.seq)
                return True
            if act.mode == "dup":
                copies = 2
        for _ in range(copies):
            self._admit(c, now)
        return True

    def _admit(self, c: StreamChunk, now: float) -> None:
        self.chunks_in += 1
        if c.seq in self._pending or (
                c.seq < self._next and c.seq not in self.masked_chunks):
            # The seat was already filled on time: a duplicate delivery.
            self.dup_chunks += 1
            self.timeline.count("stream.chunk.dup")
            observability.flight_recorder().event(
                "stream", "chunk.dup", seq=c.seq)
            return
        if c.seq < self._next:
            # The watermark already masked this seat: the chunk is LATE —
            # counted and dropped (re-opening an emitted window would
            # re-reduce history; bounded latency means never doing that).
            self.late_chunks += 1
            self.timeline.count("stream.chunk.late")
            rec = observability.flight_recorder()
            rec.event("stream", "chunk.late", seq=c.seq)
            self._incident(
                f"stream chunk {c.seq} of {self.path} arrived after its "
                f"{self.lateness_s}s lateness budget (already masked)")
            return
        c.t_arrival = now
        self._pending[c.seq] = c
        self.timeline.count("stream.chunks")
        self.timeline.gauge("stream.pending_chunks", len(self._pending))

    def _overdue_since(self) -> Optional[float]:
        """The earliest evidence that the head chunk is missing: the
        oldest pending newer arrival, or end-of-stream.  (Every pending
        seq is > ``_next`` by construction.)  None = no evidence — a
        quiet source is a slow recorder, not a gap."""
        ts = [c.t_arrival for c in self._pending.values()]
        if self._total is not None and self._next < self._total:
            ts.append(self._eos_t)
        return min(ts) if ts else None

    def _mask_next(self, now: float) -> StreamChunk:
        """Give up the head seat: emit a zero-fill placeholder (the
        zero-weight antenna discipline applied to time)."""
        from blit.parallel.antenna import record_mask

        seq = self._next
        self._next += 1
        if self._pending:
            template = self._pending[min(self._pending)].header
        else:
            template = self.header0
        record_mask(
            self.masked_chunks, seq,
            f"never arrived within the {self.lateness_s}s lateness "
            f"budget", header=self.mask_header, timeline=self.timeline,
            kind="chunk",
        )
        rec = observability.flight_recorder()
        rec.event("stream", "chunk.masked", seq=seq)
        self._incident(
            f"stream chunk {seq} of {self.path} missing past the "
            f"{self.lateness_s}s watermark; masked (zero weight) — "
            "product degraded, pipeline continuing")
        return StreamChunk(seq, dict(template), None, t_arrival=now,
                           masked=True)

    def _incident(self, reason: str) -> None:
        """One FORCED flight dump per stream (the first incident is the
        triage trail; later ones ride the recorder's own rate limit)."""
        rec = observability.flight_recorder()
        if self.flight_dump is None:
            self.flight_dump = rec.dump(reason, force=True)
        else:
            rec.dump(reason)

    def _ordered(self) -> Iterator[StreamChunk]:
        """Chunks in sequence order — arrivals reordered within the
        lateness budget, overdue seats masked, duplicates/stragglers
        dropped — until end-of-stream."""
        while True:
            if self._next in self._premasked:
                # A seat the pre-crash consumer already masked: re-mask
                # it without waiting out the watermark (the decision was
                # made — and claimed into the product — last run), and
                # drop any now-available data as late.
                c = self._pending.pop(self._next, None)
                if c is not None:
                    self.late_chunks += 1
                    self.timeline.count("stream.chunk.late")
                    observability.flight_recorder().event(
                        "stream", "chunk.late", seq=c.seq, remask=True)
                self.timeline.count("stream.chunk.remask")
                yield self._mask_next(self._clock())
                continue
            if self._next in self._pending:
                c = self._pending.pop(self._next)
                self._next += 1
                yield c
                continue
            gapped = getattr(self.source, "gapped", None)
            if gapped is not None and self._next in gapped:
                # The packet assembler PROVED this seat is a gap (its
                # block was abandoned past the reorder horizon — see
                # blit/stream/packet.py): mask it now instead of
                # waiting out the lateness budget.  Same zero-weight
                # bytes as a watermark mask, lower latency — the
                # assembler's evidence (packets far past the block)
                # is strictly stronger than a timer.
                self.timeline.count("stream.chunk.gap_fastpath")
                yield self._mask_next(self._clock())
                continue
            if (self._total is not None and self._next >= self._total
                    and not self._pending):
                return
            got = self._recv(self._poll)
            now = self._clock()
            since = self._overdue_since()
            if since is not None and now - since > self.lateness_s:
                yield self._mask_next(now)
            elif not got:
                if self.source.finished:
                    # Waiting out the lateness budget for a trailing
                    # gap: a finished source returns instantly, so pace
                    # the loop (and don't call it a stall — this wait
                    # is the watermark working as designed).
                    self._sleep(self._poll)
                else:
                    self._wd.check("live chunk feed stalled")

    # -- the GuppiRaw-shaped surface ---------------------------------------
    def header(self, i: int = 0) -> Dict:
        """The stream's first available block header (blocks until the
        recorder has produced one) — what the product headers derive
        from, exactly as on the batch path."""
        if i != 0:
            raise IndexError("a live stream exposes only header(0)")
        if self.header0 is None:
            while not self._pending:
                got = self._recv(self._poll)
                if (not got and self._total is not None
                        and not self._pending):
                    raise ValueError(
                        f"empty stream: {self.path} delivered no chunks")
                if not got:
                    self._wd.check("waiting for the first chunk")
            self.header0 = dict(self._pending[min(self._pending)].header)
        return self.header0

    def feed_blocks(self):
        """The producer feed (:func:`blit.pipeline.raw_block_feed`'s
        live twin): ``(header, kept_samples, read_into)`` triples in
        stream order.  The overlap-trim rule is the batch one — every
        block but the stream's LAST drops its trailing ``OVERLAP``
        samples — so blocks with overlap are held until their successor
        (or end-of-stream) proves which side of the rule they fall on;
        overlap-free blocks feed with zero added latency."""
        self.header(0)
        held: Optional[StreamChunk] = None
        for c in self._ordered():
            if held is not None:
                yield self._feed_one(held, last=False)
            if c.header.get("OVERLAP", 0):
                held = c
            else:
                held = None
                yield self._feed_one(c, last=False)
        if held is not None:
            yield self._feed_one(held, last=True)

    def _feed_one(self, c: StreamChunk, last: bool):
        hdr = c.header
        nt = block_ntime(hdr)
        if not last:
            nt -= hdr.get("OVERLAP", 0)
        now = self._clock()
        self.timeline.gauge("stream.watermark_lag_s", now - c.t_arrival)
        a = self._cum
        self._cum += nt
        self._marks.append((self._cum, c.t_arrival))
        if c.masked:
            self.masked_spans.append((a, self._cum))
            self._masked_log.append((c.seq, a, self._cum))
        if c.masked:
            def read_into(dst, t0, take):
                dst[:, :take] = 0
                return take
        else:
            def read_into(dst, t0, take, data=c.data):
                dst[:, :take] = data[:, t0:t0 + take]
                return take
        return hdr, nt, read_into

    # -- latency lookup (sink side) ----------------------------------------
    def arrival_for(self, sample: int) -> Optional[float]:
        """Arrival time of the block that delivered gap-free-stream
        sample ``sample`` (clamped to the last fed block for flush
        tails).  None before anything was fed."""
        n = len(self._marks)  # snapshot: the list only grows
        if n == 0:
            return None
        # (sample,) sorts before (sample, t): bisect lands on the first
        # mark with cum >= sample.
        i = min(bisect.bisect_left(self._marks, (sample,), 0, n), n - 1)
        return self._marks[i][1]

    def degraded_rows(self, nfft: int, ntap: int, nint: int = 1,
                      max_rows: Optional[int] = None) -> int:
        """How many OUTPUT rows the masking degraded: rows (of ``nint``
        PFB frames each) whose frames' analysis windows touch any
        zero-filled sample.  ``max_rows`` clamps to what was actually
        written (the flush drops trailing partial frames).  Frame ``f``
        consumes gap-free samples ``[f·nfft, (f+ntap)·nfft)``."""
        rows = set()
        for a, b in self.masked_spans:
            f_lo = max(0, (a - ntap * nfft) // nfft + 1)
            f_hi = (b - 1) // nfft
            r_lo, r_hi = f_lo // nint, f_hi // nint
            if max_rows is not None:
                r_hi = min(r_hi, max_rows - 1)
            rows.update(range(r_lo, r_hi + 1))
        return len(rows)

    # -- reporting ---------------------------------------------------------
    def stream_report(self) -> Dict:
        """The degradation/latency summary merged onto the finished
        product header by the entry points."""
        out = {
            "stream_chunks": self.chunks_in,
            "stream_late_chunks": self.late_chunks,
            "stream_dup_chunks": self.dup_chunks,
            "stream_masked_chunks": len(self.masked_chunks),
        }
        out.update(self.mask_header)  # _masked_chunks, when any
        if self.flight_dump:
            out["stream_flight_dump"] = self.flight_dump
        return out


class _LatencyTap:
    """A transparent product-writer wrapper observing chunk→product
    latency: after each append it maps the product's new end position
    back to the last gap-free-stream sample it depends on (PFB tail
    included), and records ``now - arrival(that sample)`` into the
    ``stream.chunk_to_product_s`` histogram.  Handles both slab writers
    (``FilWriter``/``FBH5Writer``: rows × ``nint`` frames) and the
    ragged ``.hits`` writers (``WindowHits``: windows × ``T`` spectra).
    Rides inside :class:`blit.outplane.AsyncSink` unchanged — appends
    land on the sink thread, which is exactly where "product durable"
    is decided."""

    def __init__(self, writer, live: LiveRawStream, timeline: Timeline,
                 *, nfft: int, ntap: int, nint: int,
                 window_spectra: Optional[int] = None,
                 clock=time.monotonic, cursor=None, heartbeat=None,
                 start_rows: int = 0):
        self._w = writer
        self._live = live
        self._tl = timeline
        self._nfft, self._ntap, self._nint = nfft, ntap, nint
        self._T = window_spectra
        self._rows = start_rows
        self._clock = clock
        self._cursor = cursor
        self._hb = heartbeat
        # Monotone prune index into the live feed's _masked_log: spans
        # land in increasing sample order and the claim frontier only
        # advances, so entries once behind the cut never need
        # re-scanning — per-append mask bookkeeping is O(new masks),
        # not O(session degradation history).
        self._mask_lo = 0
        self.path = getattr(writer, "path", None)

    def append(self, item) -> None:
        if self._cursor is not None:
            # Mask state rides the SAME durable claim as the rows
            # (ISSUE 12): set it on the cursor before the resumable
            # writer's fsync-then-save inside append(), so a crash can
            # never claim rows whose masks it forgot.  Masks observed
            # after the last claim are re-derived by the replay.  Read
            # from the append-only _masked_log (never the producer-
            # mutated set), and PRUNE seats whose samples sit entirely
            # before the claim frontier: frame f consumes samples
            # [f·nfft, (f+ntap)·nfft), so a span ending at or before
            # claimed_frames·nfft can never touch an un-claimed row —
            # the persisted list stays bounded by the claim lag, not
            # the session's degradation history.
            if self._T is not None:
                claimed = (self._cursor.windows_done * self._T
                           * self._nint)
            else:
                claimed = self._cursor.frames_done
            cut = claimed * self._nfft
            log_snap = list(self._live._masked_log)
            while (self._mask_lo < len(log_snap)
                   and log_snap[self._mask_lo][2] <= cut):
                self._mask_lo += 1
            keep = {seq for seq, a, b in log_snap[self._mask_lo:]
                    if b > cut}
            # Premasked seats this run's feed has not re-reached yet
            # (a second crash before them must not forget them; the
            # _premasked set is frozen once the feed starts, so the
            # cross-thread read is safe).
            head = self._live._next
            keep.update(s for s in self._live._premasked if s >= head)
            self._cursor.masked_chunks = sorted(keep)
        self._w.append(item)
        if self._T is not None:  # ragged: one WindowHits per window
            frames = (item.window + 1) * self._T * self._nint
        else:
            self._rows += item.shape[0]
            frames = self._rows * self._nint
        need = (frames + self._ntap - 1) * self._nfft
        t = self._live.arrival_for(need)
        if t is not None:
            self._tl.observe("stream.chunk_to_product_s",
                             self._clock() - t)
        if self._hb is not None:
            # Per-append liveness (the supervisor's lease refresh): a
            # consumer that stops landing product rows stops beating.
            self._hb(frames)

    def flush(self) -> None:
        fl = getattr(self._w, "flush", None)
        if fl is not None:
            fl()

    def close(self) -> None:
        self._w.close()

    def abort(self) -> None:
        self._w.abort()

    @property
    def nsamps(self) -> int:
        return self._w.nsamps

    @property
    def nwindows(self) -> int:
        return getattr(self._w, "nwindows", 0)


def stream_reduce(source: ChunkSource, out_path: str, *,
                  reducer=None, lateness_s: Optional[float] = None,
                  stall_timeout_s: Optional[float] = None,
                  compression: Optional[str] = None,
                  chunks=None, resume: bool = False, heartbeat=None,
                  config: SiteConfig = DEFAULT,
                  **reducer_kw) -> Dict:
    """Reduce a LIVE recording to a ``.fil`` / ``.h5`` product while it
    records: the streaming twin of
    :meth:`blit.pipeline.RawReducer.reduce_to_file`, byte-identical to
    it for a completed stream.  ``reducer`` supplies a configured
    :class:`~blit.pipeline.RawReducer`; otherwise ``reducer_kw``
    (``nfft``/``nint``/...) build one recording on the process-wide
    timeline (so fleet harvest and the CI telemetry artifact see the
    ``stream.*`` histograms).  Returns the product header with the
    stream degradation report merged (``stream_masked_chunks`` et al.).

    ``resume=True`` (ISSUE 12) makes the live consumer REJOINABLE: a
    :class:`~blit.stream.cursor.StreamCursor` sidecar persists the
    product claim + mask state on every durable append, and a restarted
    consumer re-attaches to the still-recording session mid-file —
    truncating any un-checkpointed tail, re-masking previously-masked
    seats, and fast-forwarding through already-claimed rows via the
    skip-frames replay — finishing byte-identical to a never-restarted
    consumer.  ``heartbeat(frames)`` is the per-append liveness callback
    (the :class:`blit.recover.StreamSupervisor` lease refresh)."""
    from blit.ops.channelize import STOKES_NIF
    from blit.pipeline import RawReducer

    if reducer is None:
        reducer_kw.setdefault("timeline",
                              observability.process_timeline())
        reducer = RawReducer(**reducer_kw)
    red = reducer
    cur = None
    resuming = False
    session = getattr(source, "path", "<stream>")
    is_h5 = out_path.endswith((".h5", ".hdf5"))
    if resume:
        from blit.stream.cursor import StreamCursor

        cur = StreamCursor.load(out_path)
        resuming = (
            cur is not None
            and cur.matches(red, session, "filterbank", compression)
            and os.path.exists(out_path)
        )
        if not resuming:
            cur = StreamCursor.fresh(red, session, "filterbank",
                                     compression)
    live = LiveRawStream(
        source, lateness_s=lateness_s, stall_timeout_s=stall_timeout_s,
        timeline=red.timeline, config=config,
        premasked=(cur.masked_chunks if resuming else None),
    )
    # The WHOLE session publishes (ISSUE 11), not just the pump: a live
    # feed can spend minutes waiting for its first chunk, and `blit top`
    # must show the watermark/queue gauges during that wait too.
    from blit.monitor import publishing

    with publishing(red.timeline, config=config), \
            observability.span("stream.reduce", out=out_path,
                               nfft=red.nfft, path=live.path,
                               resumed=bool(resuming)):
        hdr = red.header_for(live)
        nif = STOKES_NIF[red.stokes]
        from blit.ops.narrow import NARROW_DTYPES

        if resuming:
            # The crash guards of the batch resume path, applied before
            # the truncate: a target the crash corrupted past reading —
            # or one shorter than its claim — restarts fresh.
            from blit.pipeline import resume_fil_ok

            rows = cur.frames_done // red.nint
            if is_h5:
                from blit.io.fbh5 import resume_target_ok

                ok = resume_target_ok(out_path, nif, hdr["nchans"], rows)
            else:
                ok = resume_fil_ok(out_path, nif, hdr["nchans"], rows,
                                   dtype=NARROW_DTYPES[red.nbits])
            if not ok:
                log.warning(
                    "stream resume target %s cannot honor the cursor's "
                    "claimed %d frames (crash-corrupted?); restarting "
                    "the session product fresh", out_path,
                    cur.frames_done,
                )
                resuming = False
                cur = StreamCursor.fresh(red, session, "filterbank",
                                         compression)
                live._premasked = set()
        start_rows = (cur.frames_done // red.nint) if resuming else 0
        if resume:
            if is_h5:
                from blit.io.fbh5 import ResumableFBH5Writer

                if red.nbits != 32:
                    raise ValueError(
                        "nbits=8/16 quantized output is a SIGPROC .fil "
                        "feature; FBH5 products are float32")
                w = ResumableFBH5Writer(
                    out_path, hdr, nif, hdr["nchans"], start_rows,
                    red.nint, cur, compression=compression,
                    chunks=chunks)
            else:
                if compression is not None:
                    raise ValueError(".fil products are uncompressed; "
                                     "compression applies to .h5 output")
                if chunks is not None:
                    raise ValueError("chunks applies to .h5 output")
                from blit.pipeline import ResumableFilWriter

                w = ResumableFilWriter(
                    out_path, hdr, nif, hdr["nchans"], start_rows,
                    red.nint, cur, dtype=NARROW_DTYPES[red.nbits])
        elif is_h5:
            from blit.io.fbh5 import FBH5Writer

            if red.nbits != 32:
                raise ValueError("nbits=8/16 quantized output is a SIGPROC "
                                 ".fil feature; FBH5 products are float32")
            w = FBH5Writer(out_path, hdr, nifs=nif,
                           nchans=hdr["nchans"],
                           compression=compression, chunks=chunks)
        else:
            if compression is not None:
                raise ValueError(".fil products are uncompressed; "
                                 "compression applies to .h5 output")
            if chunks is not None:
                raise ValueError("chunks applies to .h5 output")
            from blit.io.sigproc import FilWriter

            # _pump delivers nbits<32 slabs already quantized narrow
            # (reduce_to_file's writer rule) — the live product must
            # carry the same dtype or stream==batch byte-identity breaks.
            w = FilWriter(out_path, hdr, nif, hdr["nchans"],
                          dtype=NARROW_DTYPES[red.nbits])
        tap = _LatencyTap(w, live, red.timeline, nfft=red.nfft,
                          ntap=red.ntap, nint=red.nint,
                          cursor=(cur if resume else None),
                          heartbeat=heartbeat, start_rows=start_rows)
        hdr["nsamps"] = red._pump(live, tap,
                                  skip_frames=start_rows * red.nint)
    # Which ingest knobs the live reduction ran (tuning profile /
    # defaults — blit/tune.py): a slow live session's report names the
    # knob source before anyone reaches for `blit tune`.
    hdr["stream_tuning"] = red.tuning_provenance()
    hdr.update(live.stream_report())
    hdr["stream_degraded_spectra"] = live.degraded_rows(
        red.nfft, red.ntap, red.nint, max_rows=hdr["nsamps"])
    return hdr


def stream_search(source: ChunkSource, out_path: str, *,
                  searcher=None, lateness_s: Optional[float] = None,
                  stall_timeout_s: Optional[float] = None,
                  resume: bool = False, heartbeat=None,
                  config: SiteConfig = DEFAULT, **search_kw) -> Dict:
    """Drift-search a LIVE recording into a ``.hits`` product while it
    records: the streaming twin of
    :meth:`blit.search.dedoppler.DedopplerReducer.search_to_file`,
    byte-identical to it for a completed stream (same window pinning —
    window ``w`` covers spectra ``[w·T, (w+1)·T)`` wherever the chunk
    boundaries fall).  ``searcher`` supplies a configured
    :class:`~blit.search.dedoppler.DedopplerReducer`; otherwise
    ``search_kw`` build one.

    ``resume=True`` / ``heartbeat`` are the :func:`stream_reduce` rejoin
    contract on the ragged product: the
    :class:`~blit.stream.cursor.StreamCursor` claims whole search
    windows (fsync-before-claim through
    :class:`blit.io.hits.ResumableHitsWriter`), and a restarted consumer
    rejoins at the claimed window boundary via the skip-windows replay."""
    from blit.io.hits import HitsWriter, ResumableHitsWriter
    from blit.search import DedopplerReducer

    if searcher is None:
        search_kw.setdefault("timeline",
                             observability.process_timeline())
        searcher = DedopplerReducer(**search_kw)
    red = searcher
    cur = None
    resuming = False
    session = getattr(source, "path", "<stream>")
    if resume:
        from blit.stream.cursor import StreamCursor

        cur = StreamCursor.load(out_path)
        resuming = (
            cur is not None
            and cur.matches(red, session, "hits")
            and os.path.exists(out_path)
            and os.path.getsize(out_path) >= cur.byte_offset
        )
        if resuming:
            # Content verification of the claim (ISSUE 13): the
            # byte-length probe cannot see a flip INSIDE the claimed
            # lines or a tampered sidecar — fail closed to fresh.
            from blit import integrity

            resuming = integrity.verify_claim(
                out_path, cur.windows_done, fmt="hits") is not False
        if not resuming:
            cur = StreamCursor.fresh(red, session, "hits")
    live = LiveRawStream(
        source, lateness_s=lateness_s, stall_timeout_s=stall_timeout_s,
        timeline=red.timeline, config=config,
        premasked=(cur.masked_chunks if resuming else None),
    )
    from blit.monitor import publishing

    with publishing(red.timeline, config=config), \
            observability.span("stream.search", out=out_path,
                               nfft=red.nfft, path=live.path,
                               resumed=bool(resuming)):
        hdr = red.header_for(live)
        skip = cur.windows_done if resuming else 0
        if resume:
            w = ResumableHitsWriter(out_path, hdr, skip, cur)
        else:
            w = HitsWriter(out_path, hdr)
        tap = _LatencyTap(w, live, red.timeline, nfft=red.nfft,
                          ntap=red.ntap, nint=red.nint,
                          window_spectra=red.window_spectra,
                          cursor=(cur if resume else None),
                          heartbeat=heartbeat)
        hdr["search_nhits"] = red._pump(live, hdr, tap,
                                        skip_windows=skip)
    hdr["search_windows"] = tap.nwindows
    hdr["stream_tuning"] = red.tuning_provenance()
    hdr.update(live.stream_report())
    # A "row" of T·nint frames IS one search window: the degraded count
    # lands in window units directly.
    hdr["stream_degraded_windows"] = live.degraded_rows(
        red.nfft, red.ntap, red.nint * red.window_spectra,
        max_rows=hdr["search_windows"])
    return hdr
