"""Chunk sources for the streaming ingest plane (ISSUE 7).

A :class:`ChunkSource` delivers the recorder's output as timestamped
:class:`StreamChunk`\\ s — one GUPPI RAW block each, tagged with its
stream sequence number.  Three shapes cover the deployment, the bench
rig and the tests:

- :class:`FileTailSource` follows a RAW file (or a growing
  ``.NNNN.raw`` sequence) *while the recorder appends to it*: it polls
  for complete blocks — header parsed, full ``BLOCSIZE`` bytes on disk —
  and delivers each exactly once, advancing across sequence members as
  they appear.  The session ends at a ``<stem>.done`` marker, or after
  ``idle_timeout_s`` without growth (a crashed recorder must not tail
  forever).
- :class:`ReplaySource` replays an at-rest recording at wall-clock (or
  ``rate``-accelerated) cadence: block ``i`` is delivered when a real
  recorder would have finished writing it.  ``late={seq: extra_s}``
  defers individual chunks deterministically — the seeded late-chunk
  drill of ``ingest-bench --live``.
- :class:`QueueSource` is the in-memory source: tests push chunks in any
  order (late, duplicated, missing) and the watermark assembler
  (blit/stream/plane.py) is exercised without touching a clock.

The source contract is pull-based and non-blocking beyond ``timeout``:
``get(timeout)`` returns the next available chunk or ``None``;
``finished`` turns True once every chunk has been delivered (after which
``total`` reports the stream's chunk count when the source knows it).
Delivery ORDER is the source's business only — reordering, gaps and
duplicates are the assembler's job to repair or mask.
"""

from __future__ import annotations

import logging
import os
import queue
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from blit import observability
from blit.io.guppi import (
    SEQ_RE,
    block_ntime,
    read_raw_header,
)

log = logging.getLogger("blit.stream")


class StreamChunk:
    """One recorder chunk: a GUPPI RAW block plus its stream identity.
    ``t_arrival`` (monotonic-clock) is stamped by the assembler at
    receipt — the timestamp every latency/watermark decision keys on.
    ``masked`` chunks are watermark placeholders for data that never
    arrived: ``data`` is None and the feed zero-fills their samples."""

    __slots__ = ("seq", "header", "data", "t_arrival", "masked")

    def __init__(self, seq: int, header: Dict,
                 data: Optional[np.ndarray],
                 t_arrival: Optional[float] = None,
                 masked: bool = False) -> None:
        self.seq = seq
        self.header = header
        self.data = data
        self.t_arrival = t_arrival
        self.masked = masked


class ChunkSource:
    """The pull contract (module docstring).  Subclasses implement
    :meth:`get` and keep :attr:`finished` / :attr:`total` honest."""

    path: str = "<stream>"
    finished: bool = False
    total: Optional[int] = None

    def get(self, timeout: float) -> Optional[StreamChunk]:
        raise NotImplementedError

    def close(self) -> None:
        """Release any held resources (idempotent)."""

    def stop(self) -> None:
        """Graceful drain (ISSUE 14 satellite): deliver nothing more —
        the session ends cleanly with the chunks that already arrived
        (the assembler totals what was delivered), so a draining serve
        process finishes its in-flight live product, releases its
        capacity hold, and — with ``resume=True`` — leaves a rejoinable
        cursor for the consumer that takes over."""
        self.finished = True


class QueueSource(ChunkSource):
    """In-memory source: :meth:`push` chunks from the test (any order),
    then :meth:`finish` — optionally declaring the stream's true chunk
    count so never-pushed sequence numbers read as gaps to mask rather
    than an early end."""

    _EOS = object()

    def __init__(self, path: str = "<queue>"):
        self.path = path
        self._q: "queue.Queue" = queue.Queue()
        self.finished = False
        self.total: Optional[int] = None
        self._declared: Optional[int] = None

    def push(self, chunk: StreamChunk) -> None:
        self._q.put(chunk)

    def finish(self, total: Optional[int] = None) -> None:
        self._declared = total
        self._q.put(self._EOS)

    def get(self, timeout: float) -> Optional[StreamChunk]:
        if self.finished:
            return None
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is self._EOS:
            self.finished = True
            self.total = self._declared
            return None
        return item


def chunks_of(raw) -> List[StreamChunk]:
    """An at-rest recording's blocks as a chunk list (QueueSource feed
    for tests): ``chunks_of(open_raw(path))``."""
    return [
        StreamChunk(i, raw.header(i), raw.read_block(i))
        for i in range(raw.nblocks)
    ]


class ReplaySource(ChunkSource):
    """Replay an at-rest recording at recording cadence (module
    docstring).  ``rate`` multiplies wall-clock speed (1.0 = exactly as
    recorded, per TBIN); chunk ``i`` is due once the recorder would have
    finished writing block ``i``.  ``late`` defers individual chunks
    past their natural slot — delivery stays in *due-time* order, so a
    deferred chunk genuinely arrives after its successors (the seeded
    late-chunk drill)."""

    def __init__(self, raw, rate: float = 1.0,
                 late: Optional[Dict[int, float]] = None,
                 clock=time.monotonic, sleep=time.sleep):
        from blit.io.guppi import open_raw

        self.raw = raw if hasattr(raw, "nblocks") else open_raw(raw)
        self.path = getattr(self.raw, "path", "<replay>")
        if rate <= 0:
            raise ValueError(f"replay rate must be > 0, got {rate}")
        self.rate = rate
        self._clock = clock
        self._sleep = sleep
        self.total = None  # published at finish, the source contract
        self._nblocks = self.raw.nblocks
        tbin = float(self.raw.header(0).get("TBIN", 0.0) or 0.0)
        late = late or {}
        cum = 0
        sched: List[Tuple[float, int]] = []
        for i in range(self._nblocks):
            cum += self.raw.block_ntime_kept(i)
            due = cum * tbin / rate + late.get(i, 0.0)
            sched.append((due, i))
        # Due-time order IS delivery order: a deferred chunk arrives
        # after whatever overtook it.
        self._sched = sorted(sched)
        self._pos = 0
        self._t0: Optional[float] = None

    def get(self, timeout: float) -> Optional[StreamChunk]:
        if self.finished:
            return None  # stop() mid-replay: drain with what arrived
        if self._pos >= len(self._sched):
            self.finished = True
            self.total = self._nblocks
            return None
        if self._t0 is None:
            self._t0 = self._clock()
        due, seq = self._sched[self._pos]
        wait = due - (self._clock() - self._t0)
        if wait > 0:
            if wait > timeout:
                self._sleep(timeout)
                return None
            self._sleep(wait)
        self._pos += 1
        return StreamChunk(seq, self.raw.header(seq),
                           self.raw.read_block(seq))


class FileTailSource(ChunkSource):
    """Follow a GUPPI RAW recording as the recorder appends (module
    docstring).  A block is delivered only once COMPLETE on disk — its
    header parses through ``END`` and all ``BLOCSIZE`` payload bytes
    exist — so a half-written tail is simply "not yet", never a
    truncated read.  With ``follow_sequence`` (default) the tailer
    advances into ``<stem>.NNNN+1.raw`` when it appears, treating any
    partial trailing block of the finished member as the recorder's
    truncation (warned, skipped) — the ``GuppiRaw`` constructor's rule.

    End of session: the ``done_path`` marker file (default
    ``<stem>.done``), or ``idle_timeout_s`` without file growth — the
    timeout path flight-dumps once (a recorder that died without its
    ``.done`` marker is an incident, not a clean end) and the current
    idle age is published as the ``stream.tail.idle_s`` gauge, so a
    silently dead recorder shows in ``blit top`` BEFORE the timeout
    fires.  Delivery is strictly in-order, so the assembler's watermark
    never masks behind this source — its job here is purely
    latency/liveness accounting."""

    def __init__(self, path: str, poll_s: Optional[float] = None,
                 idle_timeout_s: Optional[float] = None,
                 done_path: Optional[str] = None,
                 follow_sequence: bool = True,
                 timeline=None,
                 clock=time.monotonic, sleep=time.sleep,
                 config=None):
        from blit.config import DEFAULT, stream_defaults

        d = stream_defaults(DEFAULT if config is None else config)
        self.path = path
        self.poll_s = d["poll_s"] if poll_s is None else poll_s
        self.idle_timeout_s = (d["idle_timeout_s"] if idle_timeout_s is None
                               else idle_timeout_s)
        m = SEQ_RE.match(path)
        self._stem = m.group("stem") if m else path
        self._member = int(m.group("seq")) if m else None
        self.done_path = (done_path if done_path is not None
                          else self._stem + ".done")
        self.follow_sequence = follow_sequence and m is not None
        self._clock = clock
        self._sleep = sleep
        self._cur = path
        self._offset = 0
        self._seq = 0
        self._last_size = -1
        self._last_growth = clock()
        self.total = None
        self._timeline = timeline

    def _next_member(self) -> Optional[str]:
        if not self.follow_sequence:
            return None
        nxt = f"{self._stem}.{self._member + 1:04d}.raw"
        return nxt if os.path.exists(nxt) else None

    def _gauge_idle(self, idle_s: float) -> None:
        """Publish how long the tail has seen no growth — the liveness
        signal ``blit top`` reads while the recorder runs (and the
        early warning before ``idle_timeout_s`` ends the session)."""
        if self._timeline is None:
            self._timeline = observability.process_timeline()
        self._timeline.gauge("stream.tail.idle_s", idle_s)

    def _try_block(self) -> Optional[StreamChunk]:
        """One complete block at the current offset, else None."""
        try:
            size = os.path.getsize(self._cur)
        except OSError:
            size = 0  # recorder has not created the file yet
        if size != self._last_size:
            self._last_size = size
            self._last_growth = self._clock()
        if size <= self._offset:
            return None
        with open(self._cur, "rb") as f:
            f.seek(self._offset)
            try:
                hdr, data_off = read_raw_header(f)
            except (EOFError, ValueError):
                return None  # header still being written
        if hdr.get("NBITS", 8) != 8:
            raise NotImplementedError(
                f"NBITS={hdr['NBITS']} not supported (GBT uses 8)")
        if data_off + hdr["BLOCSIZE"] > size:
            return None  # payload still being written
        npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
        shape = (hdr["OBSNCHAN"], block_ntime(hdr), npol, 2)
        data = np.memmap(self._cur, dtype=np.int8, mode="r",
                         offset=data_off, shape=shape)
        self._offset = data_off + hdr["BLOCSIZE"]
        seq = self._seq
        self._seq += 1
        return StreamChunk(seq, hdr, data)

    def get(self, timeout: float) -> Optional[StreamChunk]:
        if self.finished:
            return None
        deadline = self._clock() + timeout
        while True:
            c = self._try_block()
            if c is not None:
                self._last_growth = self._clock()
                return c
            nxt = self._next_member()
            done_mark = os.path.exists(self.done_path)
            if nxt is not None or done_mark:
                # The marker/member postdates every byte of the current
                # file (the recorder closes it first), but it may have
                # appeared AFTER the poll above saw the final block
                # incomplete — drain once more before treating this as
                # a boundary, or that block would be silently lost.
                c = self._try_block()
                if c is not None:
                    self._last_growth = self._clock()
                    return c
            if nxt is not None:
                # The finished member's leftover bytes are a truncated
                # trailing block (the recorder was killed mid-write, or
                # padding): skip them, exactly as GuppiRaw's index scan
                # would.
                if self._last_size > self._offset:
                    log.warning(
                        "%s: skipping %d trailing bytes (truncated "
                        "block) at member boundary", self._cur,
                        self._last_size - self._offset)
                self._cur = nxt
                self._member += 1
                self._offset = 0
                self._last_size = -1
                self._last_growth = self._clock()
                continue
            if done_mark:
                if self._last_size > self._offset:
                    log.warning(
                        "%s: %d trailing bytes do not form a complete "
                        "block; dropped (truncated recording)",
                        self._cur, self._last_size - self._offset)
                self.finished = True
                self.total = self._seq
                return None
            now = self._clock()
            self._gauge_idle(now - self._last_growth)
            if (self.idle_timeout_s is not None
                    and now - self._last_growth > self.idle_timeout_s):
                log.warning(
                    "%s: no growth for %.1fs and no done marker at %s; "
                    "ending the tail (recorder gone?)", self._cur,
                    now - self._last_growth, self.done_path)
                observability.flight_recorder().dump(
                    f"tail idle: {self._cur} grew nothing for "
                    f"{now - self._last_growth:.1f}s with no done "
                    f"marker at {self.done_path} — recorder presumed "
                    "dead, ending the session at block "
                    f"{self._seq}", force=True)
                self.finished = True
                self.total = self._seq
                return None
            if now >= deadline:
                return None
            self._sleep(min(self.poll_s, deadline - now))
