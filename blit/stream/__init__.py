"""``blit.stream`` — the streaming ingest plane (ISSUE 7): reduce while
the telescope records.

Everything upstream of here assumes GUPPI RAW at rest; this package
feeds the SAME reducers from sources still being written — a growing
file the recorder appends to, a paced replay, an in-memory queue — with
watermark-based windowing, late/duplicate/missing-chunk repair (missing
chunks mask to zero weight, the PR 2 antenna discipline), and bounded
chunk→product latency as a first-class metric.  ``blit stream`` is the
CLI; ``ingest-bench --live`` is the latency rig.

The golden contract: streaming a fully-recorded file through
:func:`stream_reduce` / :func:`stream_search` produces BYTE-IDENTICAL
``.fil``/``.h5``/``.hits`` products to the batch path.
"""

from blit.stream.cursor import StreamCursor
from blit.stream.packet import (
    PacketAssembler,
    PacketFramer,
    PacketReplaySource,
    PacketSource,
    packets_of,
)
from blit.stream.plane import LiveRawStream, stream_reduce, stream_search
from blit.stream.session import SessionSupervisor, source_from_spec
from blit.stream.source import (
    ChunkSource,
    FileTailSource,
    QueueSource,
    ReplaySource,
    StreamChunk,
    chunks_of,
)

__all__ = [
    "ChunkSource",
    "FileTailSource",
    "LiveRawStream",
    "PacketAssembler",
    "PacketFramer",
    "PacketReplaySource",
    "PacketSource",
    "QueueSource",
    "ReplaySource",
    "SessionSupervisor",
    "StreamChunk",
    "StreamCursor",
    "chunks_of",
    "packets_of",
    "source_from_spec",
    "stream_reduce",
    "stream_search",
]
