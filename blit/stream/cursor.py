"""Live-session rejoin state (ISSUE 12): the :class:`StreamCursor`.

A live consumer that restarts mid-session (crashed process, rolling
deploy, OOM kill) must RE-ATTACH to the still-recording session and
finish a product byte-identical to a never-restarted consumer — the
streaming twin of :class:`blit.pipeline.ReductionCursor` /
:class:`blit.search.dedoppler.SearchCursor`.  Three things make a live
resume different from a batch one, and all three live here:

- **identity is the session, not the bytes.**  The recording is still
  growing, so size/mtime guards would reject every legitimate rejoin.
  The cursor binds to the session *path* plus every output-affecting
  knob instead; a changed recording path or config restarts fresh.

- **mask state must survive.**  A chunk the watermark masked before the
  crash was already folded (as zeros) into claimed product rows — and
  its data may well exist on disk by the time the restarted consumer
  re-reads the session.  The cursor persists every masked seat, and the
  restarted :class:`~blit.stream.plane.LiveRawStream` re-masks them
  (``premasked=``), counting any now-available data as late — exactly
  what the never-restarted consumer did.

- **the claim is the product's, not the feed's.**  ``frames_done`` (or,
  for ``.hits``, ``windows_done``/``byte_offset`` plus the per-window
  ``window_claims`` ledger) counts output durably fsync'd BEFORE the
  cursor claimed it — the ResumableFilWriter/ResumableHitsWriter
  ordering — so a restarted consumer truncates any un-checkpointed tail
  and replays it from the re-read session bytes, bit-identically.

The sidecar lives at ``<product>.stream-cursor`` (NOT ``.cursor``: a
stream product and a batch resume of the same path must never parse
each other's state), written with the same tmp-fsync-rename protocol as
the batch cursors, and removed on clean completion.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

log = logging.getLogger("blit.stream")


@dataclass
class StreamCursor:
    """Rejoin state for one live product (module docstring)."""

    path: str                 # the SESSION's path (source.path)
    kind: str                 # "filterbank" | "hits"
    nfft: int
    ntap: int = 4
    nint: int = 1
    stokes: str = "I"
    window: str = "hamming"
    fqav_by: int = 1
    dtype: str = "float32"
    nbits: int = 32
    # The affine quantize rule changes every nbits<32 product byte —
    # identity, like nbits itself (the ReductionCursor rule).
    quant_scale: float = 1.0
    quant_offset: float = 0.0
    compression: str = "none"
    # Search identity (kind="hits"; -1 = not applicable).
    window_spectra: int = -1
    top_k: int = -1
    snr_threshold: float = -1.0
    max_drift_bins: int = -1
    # Progress claims (fsync-before-claim — see module docstring).
    frames_done: int = 0      # filterbank: raw PFB frames written
    windows_done: int = 0     # hits: search windows written
    hits_done: int = 0
    byte_offset: int = 0
    window_claims: Optional[List[List[int]]] = None
    # The degradation ledger: every seat the watermark masked, in seq
    # order — re-masked verbatim on rejoin.
    masked_chunks: List[int] = field(default_factory=list)

    @staticmethod
    def path_for(out_path: str) -> str:
        return out_path + ".stream-cursor"

    def save(self, out_path: str) -> None:
        tmp = self.path_for(out_path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.__dict__, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path_for(out_path))

    @classmethod
    def load(cls, out_path: str) -> Optional["StreamCursor"]:
        try:
            with open(cls.path_for(out_path)) as f:
                return cls(**json.load(f))
        except (OSError, ValueError, TypeError):
            return None

    def claim_at(self, windows: int) -> Optional[Tuple[int, int]]:
        """``(byte_offset, hits_done)`` after ``windows`` full windows —
        :func:`blit.io.hits.ledger_claim_at`, the one ledger-resolution
        rule shared with :class:`blit.search.dedoppler.SearchCursor`."""
        from blit.io.hits import ledger_claim_at

        return ledger_claim_at(windows, self.windows_done,
                               self.byte_offset, self.hits_done,
                               self.window_claims)

    def matches(self, red, session_path: str, kind: str,
                compression: Optional[str] = None) -> bool:
        """Does this cursor describe the same session reduced the same
        way?  ``red`` is the configured reducer (RawReducer or
        DedopplerReducer); every output-affecting knob must match — a
        mismatch splices different spectra into one product."""
        if self.path != session_path or self.kind != kind:
            return False
        if self.compression != (compression or "none"):
            return False
        same = (
            self.nfft == red.nfft
            and self.ntap == red.ntap
            and self.nint == red.nint
            and self.stokes == getattr(red, "stokes", "I")
            and self.window == red.window
            and self.fqav_by == getattr(red, "fqav_by", 1)
            and self.dtype == red.dtype
            and self.nbits == getattr(red, "nbits", 32)
            and self.quant_scale == getattr(red, "quant_scale", 1.0)
            and self.quant_offset == getattr(red, "quant_offset", 0.0)
        )
        if not same:
            return False
        if kind == "hits":
            return (
                self.window_spectra == red.window_spectra
                and self.top_k == red.top_k
                and self.snr_threshold == float(red.snr_threshold)
                and self.max_drift_bins == (
                    -1 if red.max_drift_bins is None
                    else int(red.max_drift_bins)
                )
            )
        return True

    @classmethod
    def fresh(cls, red, session_path: str, kind: str,
              compression: Optional[str] = None) -> "StreamCursor":
        """A zero-progress cursor for ``red`` over ``session_path``."""
        kw = dict(
            path=session_path, kind=kind, nfft=red.nfft, ntap=red.ntap,
            nint=red.nint, stokes=getattr(red, "stokes", "I"),
            window=red.window, fqav_by=getattr(red, "fqav_by", 1),
            dtype=red.dtype, nbits=getattr(red, "nbits", 32),
            quant_scale=getattr(red, "quant_scale", 1.0),
            quant_offset=getattr(red, "quant_offset", 0.0),
            compression=compression or "none",
        )
        if kind == "hits":
            kw.update(
                window_spectra=int(red.window_spectra),
                top_k=int(red.top_k),
                snr_threshold=float(red.snr_threshold),
                max_drift_bins=(
                    -1 if red.max_drift_bins is None
                    else int(red.max_drift_bins)
                ),
                window_claims=[],
            )
        return cls(**kw)
