"""Whole-session live orchestration (ISSUE 18 tentpole, layer 2).

One observing session is many recorder SEATS — at GBT, 64 ``blc``
nodes each catching one band's packet stream.  A single
:class:`~blit.recover.StreamSupervisor` keeps ONE seat's consumer
alive across crash and wedge; this module fans a whole session across
the pool: one supervised ``stream_raw`` per seat, each with its own
lease directory and per-seat :class:`~blit.stream.cursor.StreamCursor`
rejoin (the PR 11 recovery contract — a restarted seat resumes
mid-product, byte-identical to a never-restarted run), all publishing
into one session timeline so fleet ``/healthz`` and the SLO burn
tables see the session as one workload.

The seat's *source* is a SPEC (a plain JSON-able dict), not an object:
the supervisor hands it to the consumer CHILD process, which rebuilds
the source there via :func:`source_from_spec` — the same dispatch the
``blit session`` CLI, ``blit chaos --packets`` and the bench legs use.
Spec kinds::

    {"kind": "tail",   "raw": ..., "idle_timeout_s": ..., "done_path": ...}
    {"kind": "replay", "raw": ..., "rate": ...}
    {"kind": "packet", "host": ..., "port": ..., "rcvbuf": ...,
     "horizon": ...}
    {"kind": "packet-replay", "raw": ..., "rate": ..., "packet_ntime":
     ..., "drop": ..., "drop_blocks": [...], "reorder": ...,
     "dup": ..., "seed": ..., "horizon": ...}

Health: while the session runs, a ``session`` health hook is
registered with :mod:`blit.monitor` — ``/healthz`` degrades (never
hard-fails) while any seat is mid-recovery, and clears when the seat
rejoins.  ``session.seats`` / ``session.seats_recovering`` gauges and
the per-seat ``recover.*`` counters ride the shared timeline onto
``/metrics``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from blit.config import DEFAULT, SiteConfig
from blit.observability import Timeline

log = logging.getLogger("blit.stream")

SOURCE_KINDS = ("tail", "replay", "packet", "packet-replay")


def source_from_spec(spec: Dict, *, timeline: Optional[Timeline] = None,
                     config: SiteConfig = DEFAULT):
    """Build a :class:`~blit.stream.source.ChunkSource` from its spec
    dict (module docstring) — the one constructor the supervisor child,
    the CLI and the benches share, so a seat's source survives the trip
    through a JSON spec file."""
    kind = spec.get("kind", "tail")
    if kind == "tail":
        from blit.stream.source import FileTailSource

        return FileTailSource(
            spec["raw"], idle_timeout_s=spec.get("idle_timeout_s"),
            done_path=spec.get("done_path"), config=config)
    if kind == "replay":
        from blit.stream.source import ReplaySource

        return ReplaySource(spec["raw"], rate=spec.get("rate", 1.0))
    if kind == "packet":
        from blit.stream.packet import PacketSource

        return PacketSource(
            spec.get("host"), spec.get("port"),
            rcvbuf=spec.get("rcvbuf"),
            reorder_horizon=spec.get("horizon"),
            timeline=timeline, config=config)
    if kind == "packet-replay":
        from blit.stream.packet import PacketReplaySource

        return PacketReplaySource(
            spec["raw"], rate=spec.get("rate", 1.0),
            packet_ntime=spec.get("packet_ntime"),
            packet_nchan=spec.get("packet_nchan"),
            drop=spec.get("drop"),
            drop_blocks=spec.get("drop_blocks"),
            reorder=spec.get("reorder", 0.0),
            reorder_depth=spec.get("reorder_depth", 4),
            dup=spec.get("dup", 0.0),
            seed=spec.get("seed", 0),
            reorder_horizon=spec.get("horizon"),
            timeline=timeline, config=config)
    raise ValueError(
        f"unknown source kind {kind!r} (one of {SOURCE_KINDS})")


class SessionSupervisor:
    """Run one live session to completion: one supervised consumer per
    seat, concurrently, each rejoinable (module docstring).

    ``seats`` is a list of seat dicts::

        {"name": "blc00", "out": ".../blc00.fil",
         "source": <source spec>,                  # source_from_spec
         "kind": "reduce" | "search",              # default reduce
         "knobs": {...}, "search": {...},          # consumer knobs
         "lateness_s": ..., "faults": "..."}       # optional

    ``raw`` in the seat dict is optional when the source spec carries
    its own (replay kinds); a ``tail`` seat names the recording there.
    Reports ``{"seats": {name: report}, "ok", "recovered_seats",
    "masked_total"}`` — per-seat reports are the StreamSupervisor's,
    plus the child's packet counters for packet seats.
    """

    def __init__(self, seats: List[Dict], *, work_dir: str,
                 lease_ttl_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 faults: Optional[str] = None,
                 timeline: Optional[Timeline] = None,
                 config: SiteConfig = DEFAULT):
        if not seats:
            raise ValueError("a session needs at least one seat")
        names = [s.get("name", f"seat{i}") for i, s in enumerate(seats)]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate seat names: {sorted(names)}")
        self.seats = [dict(s, name=n) for s, n in zip(seats, names)]
        self.work_dir = work_dir
        self.lease_ttl_s = lease_ttl_s
        self.poll_s = poll_s
        self.max_attempts = max_attempts
        self.faults = faults
        self.timeline = timeline if timeline is not None else Timeline()
        self.config = config
        self._phase: Dict[str, str] = {n: "idle" for n in names}
        self._lock = threading.Lock()

    # -- health -----------------------------------------------------------
    def _health(self) -> Optional[Dict]:
        with self._lock:
            bad = sorted(n for n, p in self._phase.items()
                         if p in ("recovering", "failed"))
        if not bad:
            return None
        return {"degraded": True,
                "reason": f"session seats recovering: {','.join(bad)}"}

    def _seat_supervisor(self, seat: Dict):
        from blit.recover import StreamSupervisor

        src = dict(seat.get("source") or {"kind": "tail"})
        raw = seat.get("raw") or src.get("raw") or ""
        return StreamSupervisor(
            raw, seat["out"],
            kind=seat.get("kind", "reduce"),
            knobs=seat.get("knobs"),
            search=seat.get("search"),
            source=src,
            lateness_s=seat.get("lateness_s"),
            lease_ttl_s=self.lease_ttl_s,
            poll_s=self.poll_s,
            max_attempts=self.max_attempts,
            faults=seat.get("faults", self.faults),
            lease_dir=os.path.join(self.work_dir, "leases",
                                   seat["name"]),
            timeline=self.timeline,
            config=self.config,
        )

    def run(self) -> Dict:
        from blit import monitor

        os.makedirs(self.work_dir, exist_ok=True)
        reports: Dict[str, Dict] = {}
        errors: Dict[str, str] = {}

        def seat_main(seat: Dict) -> None:
            name = seat["name"]
            sup = self._seat_supervisor(seat)
            stop = threading.Event()

            def track() -> None:
                while not stop.is_set():
                    with self._lock:
                        self._phase[name] = sup.state()["phase"]
                    self._gauge_phases()
                    stop.wait(0.1)

            t = threading.Thread(target=track, daemon=True,
                                 name=f"seat-{name}-phase")
            t.start()
            try:
                reports[name] = sup.run()
            except Exception as e:  # noqa: BLE001 — fold into report
                errors[name] = str(e)
                log.error("seat %s failed permanently: %s", name, e)
            finally:
                stop.set()
                t.join(timeout=1.0)
                with self._lock:
                    self._phase[name] = (
                        "failed" if name in errors else "done")
                self._gauge_phases()

        monitor.register_health_hook("session", self._health)
        t0 = time.monotonic()
        try:
            with monitor.publishing(self.timeline, config=self.config):
                self.timeline.gauge("session.seats", len(self.seats))
                threads = [
                    threading.Thread(target=seat_main, args=(s,),
                                     daemon=True,
                                     name=f"seat-{s['name']}")
                    for s in self.seats
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
        finally:
            monitor.unregister_health_hook("session")
        report = self._fold(reports, errors)
        report["wall_s"] = round(time.monotonic() - t0, 3)
        return report

    def _gauge_phases(self) -> None:
        with self._lock:
            rec = sum(1 for p in self._phase.values()
                      if p in ("recovering", "failed"))
        self.timeline.gauge("session.seats_recovering", rec)

    def _fold(self, reports: Dict[str, Dict],
              errors: Dict[str, str]) -> Dict:
        seats: Dict[str, Dict] = {}
        masked_total = 0
        recovered: List[str] = []
        for s in self.seats:
            name = s["name"]
            rep = reports.get(name)
            if rep is None:
                seats[name] = {"ok": False,
                               "error": errors.get(name, "no report")}
                continue
            res = rep.get("result") or {}
            masked_total += int(res.get("masked") or 0)
            if rep.get("recovered"):
                recovered.append(name)
            seats[name] = {
                "ok": bool(rep.get("result")),
                "attempts": len(rep.get("attempts", [])),
                "recovered": bool(rep.get("recovered")),
                "result": res,
            }
        ok = all(v.get("ok") for v in seats.values())
        return {"kind": "session", "ok": ok, "seats": seats,
                "recovered_seats": recovered,
                "masked_total": masked_total}
