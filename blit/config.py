"""Site configuration with BL@GBT defaults.

The reference scatters its site defaults across keyword arguments
(``root="/datax/dibas"``, ``extra="GUPPI"``, regexes — src/gbt.jl:48-53;
ssh options — src/gbt.jl:12-18).  Here they live in one dataclass, and every
API function accepts an optional ``config=`` override (SURVEY.md §5 "Config").
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Pattern, Tuple

from blit import naming


def datahosts(prefix: str = "") -> List[str]:
    """The 64 default BL@GBT host names ``blc00``..``blc77`` — 8 racks
    (bands) x 8 nodes (banks), optionally prefixed for ssh aliases.

    Reference: ``GBT.datahosts`` (src/gbt.jl:8-10).
    """
    return [f"{prefix}blc{band}{bank}" for band in range(8) for bank in range(8)]


# GBT BL backend constants (reference: README.md:17-27, src/gbtworkerfunctions.jl:134)
BAND_MHZ = 1500.0          # one band (8 banks) covers a 1500 MHz IF signal
BANK_MHZ = BAND_MHZ / 8    # each bank owns a contiguous 187.5 MHz slice
COARSE_PER_BANK = 64       # coarse channels recorded per bank (src/gbt.jl:101)
COARSE_MHZ = BANK_MHZ / COARSE_PER_BANK  # ~2.93 MHz coarse channel width


def nfpc_from_foff(foff_mhz: float) -> int:
    """Fine channels per coarse channel implied by a filterbank's channel
    width: ``round(187.5/64/|foff|)`` (reference: src/gbtworkerfunctions.jl:134).
    Returned as int; reference stores Int32 for FBH5 parity."""
    return int(round(COARSE_MHZ / abs(foff_mhz)))


@dataclass
class SiteConfig:
    """Everything site-specific, with BL@GBT defaults.

    Reference keyword defaults: src/gbt.jl:48-53 (inventory) and
    src/gbt.jl:12-18 (worker bring-up).
    """

    root: str = "/datax/dibas"
    extra: str = "GUPPI"
    session_re: Pattern = naming.SESSION_RE
    player_re: Pattern = naming.PLAYER_RE
    file_re: Pattern = naming.DEFAULT_FILE_RE
    # hosts=None derives the default 64-host list from host_prefix (the
    # reference's `prefix` ssh-alias kwarg, src/gbt.jl:14).
    hosts: Optional[List[str]] = None
    host_prefix: str = ""
    # Logical mesh shape (bands, banks) mapped onto the TPU device mesh.
    mesh_shape: Tuple[int, int] = (8, 8)
    # Worker-pool backend: "local" | "thread" | "process" (plugin boundary per
    # BASELINE.json: a backend flag swaps the worker pool implementation).
    backend: str = "thread"
    # Worker liveness deadlines (remote backend; SURVEY.md §5 "health-checked
    # worker pool"): per-call reply deadline and the agent-reuse ping
    # deadline.  The call deadline is OPT-IN (ADVICE r4): no finite default
    # sits safely above every legitimate single call — a whole-scan
    # reduce_raw can run hours, and a deadline that fires on healthy work
    # kills the agent mid-write.  None = block forever (the reference's
    # fetch behavior); sites that want kill-on-deadline liveness set it
    # above their largest sanctioned workload.  The reuse-time ping below
    # still bounds committing NEW work to a wedged agent either way.
    call_timeout: Optional[float] = None
    ping_timeout: Optional[float] = 30.0
    # Transient-failure recovery (blit/faults.py; ISSUE 2).  io_retries is
    # the TOTAL attempts for worker-side file I/O (guppi/fbh5/filterbank
    # reads — flaky NFS weather); call_retries is the number of
    # RE-dispatches of a WorkerPool remote call after AgentDied/CallTimeout
    # (each re-dispatch rides the pool's existing agent respawn).  Backoff
    # is jittered-exponential; retry_seed pins the jitter for
    # deterministic tests.
    io_retries: int = 3
    io_backoff_s: float = 0.05
    io_backoff_max_s: float = 2.0
    call_retries: int = 2
    call_backoff_s: float = 0.5
    call_backoff_max_s: float = 10.0
    retry_jitter: float = 0.5
    retry_seed: Optional[int] = None
    # Per-worker circuit breaker: breaker_threshold CONSECUTIVE remote-call
    # failures trip the host into a "degraded" state (calls fail fast with
    # RemoteError(etype="HostDegraded") instead of hammering it); after
    # breaker_cooldown_s one probe call may re-close the circuit.
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 60.0
    # Product service layer (blit/serve; ISSUE 3).  cache_ram_bytes bounds
    # the in-RAM tier of the content-addressed product cache (LRU by byte
    # budget); cache_dir, when set, enables the disk tier (completed
    # FBH5 products indexed by reduction fingerprint).  serve_max_concurrency
    # is the scheduler's base concurrency budget (shrunk proportionally by
    # degraded hosts when a WorkerPool is attached) and serve_queue_depth
    # bounds each priority's queue — excess submissions are REJECTED with
    # Overloaded(retry_after_s) instead of growing the queue without bound.
    cache_ram_bytes: int = 1 << 30
    cache_dir: Optional[str] = None
    serve_max_concurrency: int = 4
    serve_queue_depth: int = 64
    # Search plane (blit/search; ISSUE 6).  search_window_spectra is the
    # Taylor-tree integration window (spectra per drift transform, power
    # of two — the drift resolution is one bin per window);
    # search_top_k bounds the hits extracted per band per window on
    # device; search_snr_threshold is the device-side SNR cut; and
    # search_max_drift_bins clamps the searched drift range (None = the
    # full ±(window-1) bins the tree computes).  Per-process overrides:
    # BLIT_SEARCH_WINDOW / BLIT_SEARCH_TOP_K / BLIT_SEARCH_SNR /
    # BLIT_SEARCH_MAX_DRIFT (see :func:`search_defaults`).
    search_window_spectra: int = 64
    search_top_k: int = 8
    search_snr_threshold: float = 10.0
    search_max_drift_bins: Optional[int] = None
    # Streaming ingest plane (blit/stream; ISSUE 7).  stream_lateness_s is
    # the watermark's allowed-lateness budget: a missing chunk is masked
    # (zero weight, the PR 2 antenna discipline) once the watermark —
    # newest arrival + this budget — passes it, and a chunk arriving
    # after its seat was masked is counted late and dropped.
    # stream_poll_s is the growing-file tailer's poll cadence;
    # stream_idle_timeout_s ends a tailed session when the recorder
    # neither grows the file nor writes the done marker for that long
    # (None = wait for the marker forever); stream_stall_timeout_s arms
    # the live feed's producer-progress watchdog (flight-dump + raise
    # instead of a silent wedge; None = unarmed).  Per-process overrides:
    # BLIT_STREAM_LATENESS / BLIT_STREAM_POLL / BLIT_STREAM_IDLE_TIMEOUT /
    # BLIT_STREAM_STALL_TIMEOUT (see :func:`stream_defaults`).
    stream_lateness_s: float = 2.0
    stream_poll_s: float = 0.05
    stream_idle_timeout_s: Optional[float] = None
    stream_stall_timeout_s: Optional[float] = None
    # Recorder packet front end (blit/stream/packet.py; ISSUE 18).
    # packet_host/packet_port is where a PacketSource listens (port 0 =
    # ephemeral, read it back from the source); packet_rcvbuf_bytes
    # sizes SO_RCVBUF — a recorder never pauses, so the kernel buffer
    # is the only back-pressure before packets shed as gaps;
    # packet_ntime is the framer's time samples per DATA packet (all
    # channels per packet: with nchan=64 npol=2 that is 8 KiB of
    # payload at the default — under the common 9000-byte jumbo MTU);
    # packet_horizon_blocks is the assembler's reorder horizon — a
    # partial block is abandoned (masked downstream) once packets
    # arrive that many blocks past it.  Per-process overrides:
    # BLIT_PACKET_HOST / BLIT_PACKET_PORT / BLIT_PACKET_RCVBUF /
    # BLIT_PACKET_NTIME / BLIT_PACKET_HORIZON (:func:`packet_defaults`).
    packet_host: str = "127.0.0.1"
    packet_port: int = 60000
    packet_rcvbuf_bytes: int = 32 << 20
    packet_ntime: int = 64
    packet_horizon_blocks: int = 2
    # Ingest performance plane (blit/tune.py + blit/hostmem.py; ISSUE 8).
    # tune_dir overrides where per-rig tuning profiles live (None = the
    # BLIT_TUNE_DIR env, else ~/.cache/blit/tune); staging_pool_bytes is
    # the process-wide staging-slab pool budget (env BLIT_STAGING_BYTES
    # wins; 0 disables pooling).
    tune_dir: Optional[str] = None
    staging_pool_bytes: Optional[int] = None
    # Sharded reduction plane (blit/parallel/sharded.py; ISSUE 9).
    # mesh_sharded makes `blit scan` default to the fully-threaded
    # sharded plane (pipelined per-shard feeds + async addressable-shard
    # readback) instead of the serial window loop; the pool path stays
    # the explicit fallback either way.  mesh_probe_windows is how many
    # leading windows of a sharded scan time the stitch collective
    # honestly (they serialize compute vs gather to sample
    # ``mesh.gather_s``; 0 disables the probe — steady-state windows
    # only account ICI bytes).  mesh_prefetch_depth / mesh_out_depth
    # size the feed rotation and readback/write-behind planes (None =
    # the ingest-plane defaults, or this rig's tuning profile via the
    # CLI).  Per-process overrides: BLIT_MESH_SHARDED / BLIT_MESH_PROBE
    # / BLIT_MESH_PREFETCH / BLIT_MESH_OUT_DEPTH (:func:`mesh_defaults`).
    mesh_sharded: bool = False
    mesh_probe_windows: int = 2
    mesh_prefetch_depth: Optional[int] = None
    mesh_out_depth: Optional[int] = None
    # Live monitoring & SLO plane (blit/monitor.py; ISSUE 11).  The
    # publisher is OFF unless a spool dir or an HTTP port is configured
    # (monitor_port=0 binds an ephemeral port; None = no endpoint) —
    # monitoring must cost nothing when nobody is watching.
    # monitor_interval_s is the snapshot cadence (delta-based: each
    # sample carries only the interval's stage/histogram increments plus
    # the cumulative state for fleet merges).  Per-process overrides:
    # BLIT_MONITOR_INTERVAL / BLIT_MONITOR_PORT / BLIT_MONITOR_SPOOL
    # (:func:`monitor_defaults`).
    monitor_interval_s: float = 1.0
    monitor_port: Optional[int] = None
    monitor_spool_dir: Optional[str] = None
    # Service-level objectives evaluated continuously over the live
    # histogram deltas (multi-window burn rate, blit/monitor.py).  Each
    # enabled objective pages when the error budget (slo_budget: the
    # allowed bad-sample fraction) burns faster than slo_fast_burn over
    # the last slo_fast_window samples AND faster than slo_slow_burn
    # over the last slo_slow_window samples (the SRE multi-window rule:
    # fast to catch an outage, slow to stop flapping).  None disables an
    # objective.  Per-process overrides: BLIT_SLO_SERVE_WAIT_P99 /
    # BLIT_SLO_STREAM_P99 / BLIT_SLO_INGEST_GBPS_FLOOR
    # (:func:`slo_defaults`); slo_objectives appends raw extra objective
    # dicts ({"name","kind","metric","threshold"[,"budget"]}).
    slo_serve_wait_p99_s: Optional[float] = None
    slo_stream_latency_p99_s: Optional[float] = None
    slo_ingest_gbps_floor: Optional[float] = None
    # Sustained-capture objective (ISSUE 18): ceiling on packet block
    # assembly p99 (first packet → complete block) — burning it means
    # the wire is reordering/dropping harder than the horizon absorbs.
    # Env: BLIT_SLO_PACKET_P99.
    slo_packet_assembly_p99_s: Optional[float] = None
    slo_budget: float = 0.01
    slo_fast_burn: float = 14.0
    slo_slow_burn: float = 2.0
    slo_fast_window: int = 5
    slo_slow_window: int = 30
    slo_objectives: Optional[List[Dict]] = None
    # Crash-recovery plane (blit/recover.py; ISSUE 12).  Supervised
    # sharded scans refresh a per-process heartbeat lease between
    # windows; a peer whose lease goes stale for recover_lease_ttl_s is
    # DETECTED (dead via SIGKILL, or wedged in a collective — either
    # way it stopped making window progress) and the supervisor aborts
    # the attempt, re-plans on the survivors, and resumes from the
    # cursors.  recover_poll_s is the supervisor's watch cadence;
    # recover_max_attempts bounds the abort→re-plan→resume loop;
    # recover_grace_s is the bring-up budget before a child's FIRST
    # lease beat (jax import + distributed init — lease staleness is
    # only judged after a process has beaten once).  Per-process
    # overrides: BLIT_RECOVER_LEASE_TTL / BLIT_RECOVER_POLL /
    # BLIT_RECOVER_MAX_ATTEMPTS / BLIT_RECOVER_GRACE
    # (:func:`recover_defaults`).
    recover_lease_ttl_s: float = 10.0
    recover_poll_s: float = 0.2
    recover_max_attempts: int = 3
    recover_grace_s: float = 120.0
    # Data-integrity plane (blit/integrity.py; ISSUE 13).  The
    # background scrubber is OFF unless scrub_interval_s is set —
    # verification between requests must be a deliberate choice; when
    # on, it verifies one disk-tier entry per interval and paces itself
    # so verified bytes/s stays under scrub_bytes_per_s (big entries
    # buy longer pauses — scrubbing samples the archive, it never
    # competes with a request burst).  Per-process overrides:
    # BLIT_SCRUB_INTERVAL / BLIT_SCRUB_BYTES_PER_S
    # (:func:`scrub_defaults`); BLIT_VERIFY_INGEST=0 /
    # BLIT_VERIFY_CACHE=0 are the verification escape hatches
    # (blit.integrity.ingest_verify_enabled / cache_verify_enabled).
    scrub_interval_s: Optional[float] = None
    scrub_bytes_per_s: float = 64e6
    # Fleet serve plane (blit/serve/fleet.py; ISSUE 14).  fleet_replicas
    # is the owner-set size R on the consistent-hash ring (owner + R-1
    # failover/hedge replicas); fleet_vnodes the virtual nodes per peer
    # (load-spread smoothness); fleet_peer_ttl_s the heartbeat-lease TTL
    # after which a silent peer is EJECTED from the ring (the detection
    # budget — the recover-plane lease discipline applied to serving
    # peers); fleet_poll_s the front door's lease-watch cadence;
    # fleet_health_poll_s how often the door refreshes each peer's
    # /healthz body for the aggregated fleet health document.
    # fleet_hedge_floor_s is the hedged-read delay before a peer has
    # enough latency history (fleet_hedge_min_n samples) for its live
    # p99 to drive the hedge; fleet_hot_hits is the per-fingerprint hit
    # count at which the door cache-warms the replicas (losing the
    # owner then degrades hit-rate, not correctness).  Per-process
    # overrides: BLIT_FLEET_* (:func:`fleet_defaults`).
    fleet_replicas: int = 2
    fleet_vnodes: int = 128
    fleet_peer_ttl_s: float = 3.0
    fleet_poll_s: float = 0.25
    fleet_health_poll_s: float = 1.0
    fleet_hedge_floor_s: float = 0.05
    fleet_hedge_min_n: int = 16
    fleet_hot_hits: int = 3
    # Hot-path data plane (blit/serve/http.py; ISSUE 16).  fleet_wire
    # selects the door→peer product encoding: "binary" is the
    # application/x-blit-product frame (no base64 tax, zero-copy
    # decode), "json" the legacy base64 wire — products are
    # bit-identical either way.  fleet_pool_conns bounds the per-peer
    # keep-alive connection pool; fleet_wire_deflate adds whole-frame
    # deflate when the client advertises it (off by default: float
    # spectra compress poorly and the CPU lands on the hot path).
    fleet_wire: str = "binary"
    fleet_pool_conns: int = 4
    fleet_wire_deflate: bool = False
    # Elastic fleet plane (blit/serve/elastic.py; ISSUE 17).  The
    # FleetController scales OUT (admits a lease-fresh standby after a
    # warm handoff bounded by elastic_warm_timeout_s, streaming up to
    # elastic_warm_hints hot recipes from the joiner's incoming key
    # range) when the burn-rate evaluator pages, and scales IN (drains
    # the coldest peer, bounded by elastic_drain_timeout_s, never below
    # elastic_min_peers) after elastic_idle_windows consecutive
    # observation ticks under elastic_idle_rps requests/s.  Any resize
    # arms a flap guard: no further action for elastic_hysteresis_s, so
    # a page→idle→page cycle cannot thrash membership.
    # elastic_poll_s is the controller's observation cadence.
    # Per-process overrides: BLIT_ELASTIC_* (:func:`elastic_defaults`).
    elastic_idle_rps: float = 0.1
    elastic_idle_windows: int = 6
    elastic_hysteresis_s: float = 60.0
    elastic_warm_timeout_s: float = 30.0
    elastic_warm_hints: int = 32
    elastic_min_peers: int = 1
    elastic_poll_s: float = 1.0
    elastic_drain_timeout_s: float = 30.0
    # Fleet request observability (blit/observability.py RequestLog +
    # histogram exemplars; ISSUE 15).  request_log_dir, when set, makes
    # every serving component (ProductService, fleet front door, peer
    # HTTP handler) append one bounded JSON-lines access record per
    # request under that dir (`blit requests` tails/aggregates the
    # spool); request_log_max_bytes/request_log_files bound each
    # component's log by size rotation.  exemplars keeps the
    # most-recent-trace-id-per-bucket exemplars on every histogram
    # (OpenMetrics exemplar syntax on /metrics; `blit trace-view
    # --exemplar` resolves a tail bucket to its trace).  Per-process
    # overrides: BLIT_REQUEST_LOG / BLIT_REQUEST_LOG_MAX_BYTES /
    # BLIT_REQUEST_LOG_FILES / BLIT_EXEMPLARS
    # (:func:`request_log_defaults`).
    request_log_dir: Optional[str] = None
    request_log_max_bytes: int = 8 << 20
    request_log_files: int = 4
    exemplars: bool = True
    # Archive plane (blit/serve/catalog.py + the cold cache tier;
    # ISSUE 19).  catalog_root, when set, enables the session/scan/
    # product catalog: an in-RAM index over the inventory crawl, held
    # by peers (served as ProductRequest(kind="catalog")) and by the
    # fleet front door (which resolves by-(session, scan) asks into
    # explicit member-path recipes BEFORE ring routing, so logical and
    # explicit asks dedupe onto the same owner).  catalog_rescan_s
    # bounds how often a lookup may re-stat the tree for the
    # mtime-invalidated incremental rescan; catalog_negative_ttl_s /
    # catalog_negative_max bound the negative-lookup cache so repeated
    # misses cannot hammer the crawl.  cache_cold_dir enables the COLD
    # storage tier behind the hot disk tier: content-addressed
    # (sharded by fingerprint prefix), filled by demotion of hot-tier
    # evictees, promoted back on hit under the PR-12 CRC manifest
    # rules.  backfill_bytes_per_s paces `blit backfill` derivations
    # (the Scrubber debt discipline) so a backfill never starves
    # foreground serving.  Per-process overrides: BLIT_CATALOG_ROOT /
    # BLIT_CATALOG_RESCAN / BLIT_CATALOG_NEG_TTL / BLIT_CATALOG_NEG_MAX
    # / BLIT_CACHE_COLD_DIR / BLIT_BACKFILL_BYTES_PER_S
    # (:func:`catalog_defaults` / :func:`archive_defaults`).
    catalog_root: Optional[str] = None
    catalog_rescan_s: float = 2.0
    catalog_negative_ttl_s: float = 30.0
    catalog_negative_max: int = 4096
    cache_cold_dir: Optional[str] = None
    backfill_bytes_per_s: float = 256e6
    # History & incident forensics plane (blit/history.py; ISSUE 20).
    # history_dir, when set, makes every MetricsPublisher tick fold its
    # interval delta into an RRD-style tiered ring store (raw →
    # minutes → hours buckets, fixed on-disk budget, oldest-bucket
    # overwrite) that `blit top --history`, `blit slo-report` and the
    # peer/door ``/history`` endpoints read.  The tier knobs fix each
    # ring's bucket width and slot count (disk budget ≈ Σ slots ×
    # history_slot_bytes, paid up front at creation).  history_anomaly
    # layers a rolling median/MAD baseline over every stored series —
    # a robust z-score past history_anomaly_z for
    # history_anomaly_consecutive ticks pages through the flight-dump
    # machinery (the creep static SLO thresholds miss);
    # history_anomaly_overrides maps metric name → per-metric z.
    # incident_dir enables one-artifact incident bundles on any page
    # (SLO breach, anomaly, fleet eject, recover abort), rate-limited
    # by incident_cooldown_s per incident kind, each bundling an
    # incident_window_s history window.  Per-process overrides:
    # BLIT_HISTORY_DIR / BLIT_HISTORY_RAW_S / BLIT_HISTORY_RAW_SLOTS /
    # BLIT_HISTORY_MID_S / BLIT_HISTORY_MID_SLOTS / BLIT_HISTORY_SLOW_S
    # / BLIT_HISTORY_SLOW_SLOTS / BLIT_HISTORY_SLOT_BYTES /
    # BLIT_HISTORY_ANOMALY / BLIT_HISTORY_ANOMALY_Z /
    # BLIT_HISTORY_ANOMALY_WINDOW / BLIT_HISTORY_ANOMALY_MIN_N /
    # BLIT_HISTORY_ANOMALY_CONSEC / BLIT_HISTORY_SENSITIVITY /
    # BLIT_INCIDENT_DIR / BLIT_INCIDENT_WINDOW / BLIT_INCIDENT_COOLDOWN
    # (:func:`history_defaults`).
    history_dir: Optional[str] = None
    history_raw_s: float = 10.0
    history_raw_slots: int = 720          # 2 h of raw buckets
    history_mid_s: float = 60.0
    history_mid_slots: int = 1440         # 1 day of minute buckets
    history_slow_s: float = 3600.0
    history_slow_slots: int = 336         # 2 weeks of hour buckets
    history_slot_bytes: int = 16384
    history_anomaly: bool = True
    history_anomaly_z: float = 6.0
    history_anomaly_window: int = 120
    history_anomaly_min_n: int = 30
    history_anomaly_consecutive: int = 3
    history_anomaly_overrides: Optional[Dict[str, float]] = None
    incident_dir: Optional[str] = None
    incident_window_s: float = 900.0
    incident_cooldown_s: float = 300.0

    def io_retry_policy(self):
        """The :class:`blit.faults.RetryPolicy` for worker-side file I/O —
        install it process-wide with ``faults.set_io_policy(...)``."""
        from blit import faults

        return faults.RetryPolicy(
            attempts=max(1, self.io_retries), base_s=self.io_backoff_s,
            max_s=self.io_backoff_max_s, jitter=self.retry_jitter,
            seed=self.retry_seed,
        )

    def call_retry_policy(self):
        """The :class:`blit.faults.RetryPolicy` for WorkerPool remote-call
        re-dispatch (``attempts = call_retries + 1``)."""
        from blit import faults

        return faults.RetryPolicy(
            attempts=max(0, self.call_retries) + 1,
            base_s=self.call_backoff_s, max_s=self.call_backoff_max_s,
            jitter=self.retry_jitter, seed=self.retry_seed,
        )

    def __post_init__(self):
        if self.hosts is None:
            self.hosts = datahosts(self.host_prefix)

    def with_(self, **kw) -> "SiteConfig":
        from dataclasses import replace

        if "host_prefix" in kw and "hosts" not in kw:
            kw["hosts"] = None  # re-derive from the new prefix in __post_init__
        return replace(self, **kw)


DEFAULT = SiteConfig()

# Default device-window budget in SAMPLES per chip for windowed mesh
# reductions: 8 PFB frames at the hi-res preset (nfft=2^20) — the
# production dispatch size the kernel pipeline was measured HBM-safe at
# (DESIGN.md §3) — scaled to whole frames at other nfft.  Lives here (not
# blit.parallel.scan) so the CLI can derive it without importing jax.
WINDOW_SAMPLES = 8 << 20


def search_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective search-plane knob set: ``config``'s values with
    per-process ``BLIT_SEARCH_*`` environment overrides applied — the
    faults-layer pattern (``BLIT_IO_RETRIES``) for the search knobs, so
    a deployment can retune a worker fleet without code changes.
    Resolved at reducer construction, not import, so tests and drills
    can flip the env per run."""
    max_drift = os.environ.get("BLIT_SEARCH_MAX_DRIFT")
    max_drift = int(max_drift) if max_drift else config.search_max_drift_bins
    if max_drift is not None and max_drift < 0:
        # Headers/cursors encode "no limit" as -1 (JSON has no None-safe
        # int); feeding that back in must mean unlimited again, not a
        # drift mask that silently rejects every row.
        max_drift = None
    return {
        "window_spectra": int(os.environ.get(
            "BLIT_SEARCH_WINDOW", config.search_window_spectra)),
        "top_k": int(os.environ.get(
            "BLIT_SEARCH_TOP_K", config.search_top_k)),
        "snr_threshold": float(os.environ.get(
            "BLIT_SEARCH_SNR", config.search_snr_threshold)),
        "max_drift_bins": max_drift,
    }


def stream_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective streaming-ingest knob set: ``config``'s values with
    per-process ``BLIT_STREAM_*`` environment overrides applied (the
    :func:`search_defaults` pattern) — resolved at stream construction,
    not import, so drills and deployments retune per run."""

    def opt_s(env: str, fallback: Optional[float]) -> Optional[float]:
        v = os.environ.get(env)
        if v is None:
            return fallback
        # "" / "none" / negative all mean "unarmed" (the -1 encoding of
        # the search knobs: JSON/env have no None-safe float).
        if not v or v.lower() == "none":
            return None
        f = float(v)
        return None if f < 0 else f

    return {
        "lateness_s": float(os.environ.get(
            "BLIT_STREAM_LATENESS", config.stream_lateness_s)),
        "poll_s": float(os.environ.get(
            "BLIT_STREAM_POLL", config.stream_poll_s)),
        "idle_timeout_s": opt_s(
            "BLIT_STREAM_IDLE_TIMEOUT", config.stream_idle_timeout_s),
        "stall_timeout_s": opt_s(
            "BLIT_STREAM_STALL_TIMEOUT", config.stream_stall_timeout_s),
    }


def packet_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective packet-capture knob set (ISSUE 18): ``config``'s
    values with per-process ``BLIT_PACKET_*`` environment overrides
    applied — the :func:`stream_defaults` pattern, resolved when a
    packet source/assembler is constructed so drills retune per run."""
    return {
        "host": os.environ.get("BLIT_PACKET_HOST", config.packet_host),
        "port": int(os.environ.get(
            "BLIT_PACKET_PORT", config.packet_port)),
        "rcvbuf_bytes": int(os.environ.get(
            "BLIT_PACKET_RCVBUF", config.packet_rcvbuf_bytes)),
        "ntime": int(os.environ.get(
            "BLIT_PACKET_NTIME", config.packet_ntime)),
        "horizon_blocks": int(os.environ.get(
            "BLIT_PACKET_HORIZON", config.packet_horizon_blocks)),
    }


def mesh_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective sharded-plane knob set (ISSUE 9): ``config``'s
    values with per-process ``BLIT_MESH_*`` environment overrides
    applied — the :func:`search_defaults` pattern, resolved at scan
    construction so tests and deployments retune per run."""

    def opt_int(env: str, fallback: Optional[int]) -> Optional[int]:
        v = os.environ.get(env)
        if v is None or v == "":
            return fallback
        i = int(v)
        return None if i < 0 else i

    sharded = os.environ.get("BLIT_MESH_SHARDED")
    return {
        "sharded": (
            config.mesh_sharded if sharded is None
            else sharded not in ("", "0", "false", "False")
        ),
        "probe_windows": int(os.environ.get(
            "BLIT_MESH_PROBE", config.mesh_probe_windows)),
        "prefetch_depth": opt_int(
            "BLIT_MESH_PREFETCH", config.mesh_prefetch_depth),
        "out_depth": opt_int(
            "BLIT_MESH_OUT_DEPTH", config.mesh_out_depth),
    }


def monitor_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective monitoring knob set (ISSUE 11): ``config``'s values
    with per-process ``BLIT_MONITOR_*`` environment overrides applied —
    the :func:`stream_defaults` pattern, resolved when a publisher (or
    the process-wide auto-publisher, :func:`blit.monitor.ensure_publisher`)
    is constructed.  ``enabled`` is derived: monitoring is on only when a
    spool dir or an HTTP port is configured."""
    port_env = os.environ.get("BLIT_MONITOR_PORT")
    port = (int(port_env) if port_env not in (None, "")
            else config.monitor_port)
    if port is not None and port < 0:
        port = None  # the -1 "disabled" encoding of the other planes
    spool = os.environ.get("BLIT_MONITOR_SPOOL")
    if spool is None:
        spool = config.monitor_spool_dir
    elif not spool:
        spool = None
    return {
        "interval_s": float(os.environ.get(
            "BLIT_MONITOR_INTERVAL", config.monitor_interval_s)),
        "port": port,
        "spool_dir": spool,
        # Span batches on each spool sample (ISSUE 15 tentpole #4):
        # every tick ships the spans finished since the last one, so a
        # spool is a stitchable trace source (`blit trace-view --fleet`).
        "spans": os.environ.get(
            "BLIT_MONITOR_SPANS", "").lower() not in ("", "0", "false",
                                                      "off"),
        "enabled": port is not None or spool is not None,
    }


def slo_defaults(config: SiteConfig = DEFAULT) -> List[Dict]:
    """The effective SLO objective list (ISSUE 11): the three built-in
    site objectives (serve queue-wait p99 ceiling, live chunk→product
    p99 ceiling, ingest GB/s floor), each enabled by its SiteConfig
    field or ``BLIT_SLO_*`` env override, plus any raw extras from
    ``config.slo_objectives``.  Returned as plain dicts —
    :class:`blit.monitor.SLObjective` adopts them — so declaring an
    objective never imports the monitoring plane."""

    def opt_f(env: str, fallback: Optional[float]) -> Optional[float]:
        v = os.environ.get(env)
        if v is None:
            return fallback
        if not v or v.lower() == "none":
            return None
        f = float(v)
        return None if f < 0 else f

    objs: List[Dict] = []
    wait = opt_f("BLIT_SLO_SERVE_WAIT_P99", config.slo_serve_wait_p99_s)
    if wait is not None:
        objs.append({"name": "serve-queue-wait", "kind": "latency",
                     "metric": "sched.wait_s", "threshold": wait,
                     "budget": config.slo_budget})
    lat = opt_f("BLIT_SLO_STREAM_P99", config.slo_stream_latency_p99_s)
    if lat is not None:
        objs.append({"name": "stream-latency", "kind": "latency",
                     "metric": "stream.chunk_to_product_s",
                     "threshold": lat, "budget": config.slo_budget})
    floor = opt_f("BLIT_SLO_INGEST_GBPS_FLOOR",
                  config.slo_ingest_gbps_floor)
    if floor is not None:
        objs.append({"name": "ingest-throughput", "kind": "throughput",
                     "metric": "ingest", "threshold": floor,
                     "budget": config.slo_budget})
    asm = opt_f("BLIT_SLO_PACKET_P99", config.slo_packet_assembly_p99_s)
    if asm is not None:
        objs.append({"name": "packet-assembly", "kind": "latency",
                     "metric": "packet.assembly_s", "threshold": asm,
                     "budget": config.slo_budget})
    objs.extend(config.slo_objectives or [])
    return objs


def recover_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective crash-recovery knob set (ISSUE 12): ``config``'s
    values with per-process ``BLIT_RECOVER_*`` environment overrides
    applied — the :func:`stream_defaults` pattern, resolved at
    supervisor construction so drills retune per run."""
    return {
        "lease_ttl_s": float(os.environ.get(
            "BLIT_RECOVER_LEASE_TTL", config.recover_lease_ttl_s)),
        "poll_s": float(os.environ.get(
            "BLIT_RECOVER_POLL", config.recover_poll_s)),
        "max_attempts": int(os.environ.get(
            "BLIT_RECOVER_MAX_ATTEMPTS", config.recover_max_attempts)),
        "grace_s": float(os.environ.get(
            "BLIT_RECOVER_GRACE", config.recover_grace_s)),
    }


def scrub_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective integrity-scrubber knob set (ISSUE 13): ``config``'s
    values with per-process ``BLIT_SCRUB_*`` environment overrides
    applied — the :func:`stream_defaults` pattern, resolved at service
    construction so drills and deployments retune per run.  ``enabled``
    is derived: scrubbing is on only when an interval is configured."""
    v = os.environ.get("BLIT_SCRUB_INTERVAL")
    if v is None:
        interval = config.scrub_interval_s
    elif not v or v.lower() == "none" or float(v) <= 0:
        # "", "none", 0 and negatives all DISABLE (the health_port=0
        # convention) — 0 must never mean a busy verification loop.
        interval = None
    else:
        interval = float(v)
    return {
        "interval_s": interval,
        "bytes_per_s": float(os.environ.get(
            "BLIT_SCRUB_BYTES_PER_S", config.scrub_bytes_per_s)),
        "enabled": interval is not None,
    }


def fleet_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective fleet-serve knob set (ISSUE 14): ``config``'s
    values with per-process ``BLIT_FLEET_*`` environment overrides
    applied — the :func:`stream_defaults` pattern, resolved at front
    door construction so drills and deployments retune per run."""
    return {
        "replicas": int(os.environ.get(
            "BLIT_FLEET_REPLICAS", config.fleet_replicas)),
        "vnodes": int(os.environ.get(
            "BLIT_FLEET_VNODES", config.fleet_vnodes)),
        "peer_ttl_s": float(os.environ.get(
            "BLIT_FLEET_PEER_TTL", config.fleet_peer_ttl_s)),
        "poll_s": float(os.environ.get(
            "BLIT_FLEET_POLL", config.fleet_poll_s)),
        "health_poll_s": float(os.environ.get(
            "BLIT_FLEET_HEALTH_POLL", config.fleet_health_poll_s)),
        "hedge_floor_s": float(os.environ.get(
            "BLIT_FLEET_HEDGE_FLOOR", config.fleet_hedge_floor_s)),
        "hedge_min_n": int(os.environ.get(
            "BLIT_FLEET_HEDGE_MIN_N", config.fleet_hedge_min_n)),
        "hot_hits": int(os.environ.get(
            "BLIT_FLEET_HOT_HITS", config.fleet_hot_hits)),
        "wire": str(os.environ.get(
            "BLIT_FLEET_WIRE", config.fleet_wire)).strip().lower(),
        "pool_conns": int(os.environ.get(
            "BLIT_FLEET_POOL_CONNS", config.fleet_pool_conns)),
        "wire_deflate": str(os.environ.get(
            "BLIT_FLEET_WIRE_DEFLATE",
            config.fleet_wire_deflate)) not in (
                "0", "false", "False"),
    }


def elastic_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective elastic-fleet knob set (ISSUE 17): ``config``'s
    values with per-process ``BLIT_ELASTIC_*`` environment overrides
    applied — the :func:`stream_defaults` pattern, resolved at
    FleetController construction so the diurnal bench and chaos drills
    retune per run."""
    return {
        "idle_rps": float(os.environ.get(
            "BLIT_ELASTIC_IDLE_RPS", config.elastic_idle_rps)),
        "idle_windows": int(os.environ.get(
            "BLIT_ELASTIC_IDLE_WINDOWS", config.elastic_idle_windows)),
        "hysteresis_s": float(os.environ.get(
            "BLIT_ELASTIC_HYSTERESIS", config.elastic_hysteresis_s)),
        "warm_timeout_s": float(os.environ.get(
            "BLIT_ELASTIC_WARM_TIMEOUT", config.elastic_warm_timeout_s)),
        "warm_hints": int(os.environ.get(
            "BLIT_ELASTIC_WARM_HINTS", config.elastic_warm_hints)),
        "min_peers": int(os.environ.get(
            "BLIT_ELASTIC_MIN_PEERS", config.elastic_min_peers)),
        "poll_s": float(os.environ.get(
            "BLIT_ELASTIC_POLL", config.elastic_poll_s)),
        "drain_timeout_s": float(os.environ.get(
            "BLIT_ELASTIC_DRAIN_TIMEOUT",
            config.elastic_drain_timeout_s)),
    }


def request_log_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective request-observability knob set (ISSUE 15):
    ``config``'s values with per-process ``BLIT_REQUEST_LOG*`` /
    ``BLIT_EXEMPLARS`` environment overrides applied — the
    :func:`stream_defaults` pattern, resolved when a serving component
    constructs its :class:`blit.observability.RequestLog`.  ``dir`` is
    None when request logging is disabled (the default — disabled must
    cost one dict lookup per request)."""
    d = os.environ.get("BLIT_REQUEST_LOG")
    if d is None:
        d = config.request_log_dir
    elif not d:
        d = None
    ex = os.environ.get("BLIT_EXEMPLARS")
    return {
        "dir": d,
        "max_bytes": int(os.environ.get(
            "BLIT_REQUEST_LOG_MAX_BYTES", config.request_log_max_bytes)),
        "files": int(os.environ.get(
            "BLIT_REQUEST_LOG_FILES", config.request_log_files)),
        "exemplars": (config.exemplars if ex is None
                      else ex.lower() not in ("", "0", "false", "off")),
    }


def catalog_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective archive-catalog knob set (ISSUE 19): ``config``'s
    values with per-process ``BLIT_CATALOG_*`` environment overrides
    applied — the :func:`stream_defaults` pattern, resolved when a
    :class:`blit.serve.catalog.CatalogIndex` is constructed so peers,
    the front door and drills retune per run.  ``enabled`` is derived:
    the catalog is on only when a root is configured."""
    root = os.environ.get("BLIT_CATALOG_ROOT")
    if root is None:
        root = config.catalog_root
    elif not root:
        root = None
    return {
        "root": root,
        "rescan_s": float(os.environ.get(
            "BLIT_CATALOG_RESCAN", config.catalog_rescan_s)),
        "negative_ttl_s": float(os.environ.get(
            "BLIT_CATALOG_NEG_TTL", config.catalog_negative_ttl_s)),
        "negative_max": int(os.environ.get(
            "BLIT_CATALOG_NEG_MAX", config.catalog_negative_max)),
        "enabled": root is not None,
    }


def archive_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective archive-storage knob set (ISSUE 19): the cold
    cache tier's root and the backfill pacing budget, with per-process
    ``BLIT_CACHE_COLD_DIR`` / ``BLIT_BACKFILL_BYTES_PER_S`` overrides
    — resolved at cache / backfill construction."""
    cold = os.environ.get("BLIT_CACHE_COLD_DIR")
    if cold is None:
        cold = config.cache_cold_dir
    elif not cold:
        cold = None
    v = os.environ.get("BLIT_BACKFILL_BYTES_PER_S")
    bps = float(v) if v else config.backfill_bytes_per_s
    if bps is not None and bps <= 0:
        bps = None  # unpaced (the scrubber's "no budget" encoding)
    return {"cold_dir": cold, "backfill_bytes_per_s": bps}


def history_defaults(config: SiteConfig = DEFAULT) -> Dict:
    """The effective history/forensics knob set (ISSUE 20): ``config``'s
    values with per-process ``BLIT_HISTORY_*`` / ``BLIT_INCIDENT_*``
    environment overrides applied — the :func:`stream_defaults` pattern,
    resolved when a :class:`blit.history.HistoryStore` /
    :class:`blit.history.AnomalyDetector` / bundler is constructed.
    ``enabled`` is derived: the store is on only when a dir is
    configured; ``anomaly`` is additionally gated by its kill switch
    (``BLIT_HISTORY_ANOMALY=0`` silences the baseline pager without
    touching the store).  ``BLIT_HISTORY_SENSITIVITY`` is a
    ``metric=z,metric=z`` list of per-metric z overrides folded over
    ``config.history_anomaly_overrides``."""

    def opt_dir(env: str, fallback: Optional[str]) -> Optional[str]:
        v = os.environ.get(env)
        if v is None:
            return fallback
        return v or None

    d = opt_dir("BLIT_HISTORY_DIR", config.history_dir)
    inc = opt_dir("BLIT_INCIDENT_DIR", config.incident_dir)
    an = os.environ.get("BLIT_HISTORY_ANOMALY")
    anomaly = (config.history_anomaly if an is None
               else an.lower() not in ("", "0", "false", "off"))
    overrides: Dict[str, float] = dict(config.history_anomaly_overrides
                                       or {})
    for part in os.environ.get("BLIT_HISTORY_SENSITIVITY", "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, _, v = part.partition("=")
        try:
            overrides[k.strip()] = float(v)
        except ValueError:
            continue
    return {
        "dir": d,
        "raw_s": float(os.environ.get(
            "BLIT_HISTORY_RAW_S", config.history_raw_s)),
        "raw_slots": int(os.environ.get(
            "BLIT_HISTORY_RAW_SLOTS", config.history_raw_slots)),
        "mid_s": float(os.environ.get(
            "BLIT_HISTORY_MID_S", config.history_mid_s)),
        "mid_slots": int(os.environ.get(
            "BLIT_HISTORY_MID_SLOTS", config.history_mid_slots)),
        "slow_s": float(os.environ.get(
            "BLIT_HISTORY_SLOW_S", config.history_slow_s)),
        "slow_slots": int(os.environ.get(
            "BLIT_HISTORY_SLOW_SLOTS", config.history_slow_slots)),
        "slot_bytes": int(os.environ.get(
            "BLIT_HISTORY_SLOT_BYTES", config.history_slot_bytes)),
        "anomaly": anomaly,
        "anomaly_z": float(os.environ.get(
            "BLIT_HISTORY_ANOMALY_Z", config.history_anomaly_z)),
        "anomaly_window": int(os.environ.get(
            "BLIT_HISTORY_ANOMALY_WINDOW", config.history_anomaly_window)),
        "anomaly_min_n": int(os.environ.get(
            "BLIT_HISTORY_ANOMALY_MIN_N", config.history_anomaly_min_n)),
        "anomaly_consecutive": int(os.environ.get(
            "BLIT_HISTORY_ANOMALY_CONSEC",
            config.history_anomaly_consecutive)),
        "anomaly_overrides": overrides,
        "incident_dir": inc,
        "incident_window_s": float(os.environ.get(
            "BLIT_INCIDENT_WINDOW", config.incident_window_s)),
        "incident_cooldown_s": float(os.environ.get(
            "BLIT_INCIDENT_COOLDOWN", config.incident_cooldown_s)),
        "enabled": d is not None,
    }


def default_window_frames(nfft: int) -> int:
    """HBM-bounded default ``window_frames`` for a given ``nfft``: the
    scan's device windows hold ~``WINDOW_SAMPLES`` samples per chip, with
    a floor of 8 whole frames."""
    return max(8, WINDOW_SAMPLES // nfft)


def _compile(p) -> Pattern:
    """Accept str or compiled pattern for all regex-valued options."""
    return re.compile(p) if isinstance(p, str) else p
