"""Distributed FX correlator over the ``(band, bank)`` mesh.

BASELINE.json config 5: "4-band × 8-bank FX correlator: per-chip F-engine +
cross-bank psum visibilities over ICI".

Layout (the scaling-book recipe — pick a mesh, shard the big axes, let the
collectives ride ICI):

- **Frequency** (coarse channels) is sharded over ``bank`` — the same
  frequency-domain sharding the whole framework is built on.  Visibilities
  are per-frequency, so the X-engine's baseline cross-products never need
  cross-bank communication at all.
- **Time** is sharded over ``band`` — each band row correlates a disjoint
  time segment, and the visibility integration completes with one ``psum``
  over ``band``.  That psum is the only collective in the correlator.

Per chip: F-engine = the same PFB frontend + FFT as the single-chip
filterbank path (blit/ops/channelize), applied to complex voltages; X-engine
= one einsum forming the (ant, ant, fine-chan, pol, pol) products summed over
frames — a batched matmul on the MXU.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blit.ops.channelize import pfb_frontend

BAND_AXIS = "band"
BANK_AXIS = "bank"


def f_engine(v: jax.Array, coeffs: jax.Array) -> jax.Array:
    """Fine-channelize complex voltages: ``(..., ntime)`` →
    ``(..., nframes, nfft)`` fftshifted spectra.

    The complex-input twin of the filterbank path's PFB+FFT (the FIR runs on
    the real/imag planes separately, so it stays real VPU work).
    """
    fr = pfb_frontend(v.real, coeffs)
    fi = pfb_frontend(v.imag, coeffs)
    return jnp.fft.fftshift(jnp.fft.fft(jax.lax.complex(fr, fi)), axes=-1)


def _xengine(spec: jax.Array) -> jax.Array:
    """Cross-multiply and time-integrate.  ``spec``: (nant, nchan, npol,
    nframes, nfft) → visibilities (nant, nant, nchan, nfft, npol, npol)."""
    return jnp.einsum("acptf,bcqtf->abcfpq", spec, jnp.conj(spec))


@functools.partial(
    jax.jit, static_argnames=("mesh", "nfft", "ntap")
)
def correlate(
    voltages: jax.Array,
    coeffs: jax.Array,
    *,
    mesh: Mesh,
    nfft: int,
    ntap: int = 4,
) -> jax.Array:
    """Full FX correlation over the mesh.

    Args:
      voltages: complex64 ``(nant, nchan, ntime, npol)`` with ``nchan``
        sharded over ``bank`` and ``ntime`` sharded over ``band`` (see
        :func:`correlator_sharding`); ``ntime`` per band must be a multiple
        of ``nfft`` with at least ``ntap`` blocks.
      coeffs: (ntap, nfft) PFB prototype (replicated).

    Returns:
      complex64 visibilities ``(nant, nant, nchan, nfft, npol, npol)``
      integrated over *all* time (psum over ``band``), with the fine-channel
      axes sharded over ``bank`` like the input.  Entry ``[a, b]`` is
      ``⟨S_a S_b*⟩``; the diagonal holds autocorrelation spectra.

    Segment semantics: each band row F-engines its time segment
    independently — the PFB does not run across segment boundaries, so
    ``ntap-1`` frames per boundary are not formed (standard chunked-
    correlator behavior; :func:`correlate_np` with ``nsegments=nband`` is
    the exact golden reference).
    """

    def step(v, h):
        # v: (nant, nchan_local, ntime_local, npol) — move pol before time so
        # the F-engine framing acts on the last axis.
        spec = f_engine(jnp.moveaxis(v, 3, 2), h)  # (a, c, p, frames, nfft)
        vis = _xengine(spec)
        return jax.lax.psum(vis, BAND_AXIS)

    return jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(None, BANK_AXIS, BAND_AXIS), P()),
        out_specs=P(None, None, BANK_AXIS),
        check_vma=False,  # psum output is band-invariant
    )(voltages, coeffs)


def correlator_sharding(mesh: Mesh) -> NamedSharding:
    """Input sharding for (nant, nchan, ntime, npol) voltages: frequency
    over ``bank``, time over ``band``."""
    return NamedSharding(mesh, P(None, BANK_AXIS, BAND_AXIS))


def visibility_sharding(mesh: Mesh) -> NamedSharding:
    """Output sharding: (nant, nant, nchan, nfft, npol, npol), frequency
    over ``bank``, replicated over ``band``."""
    return NamedSharding(mesh, P(None, None, BANK_AXIS))


def correlate_np(
    voltages: np.ndarray,
    coeffs: np.ndarray,
    nfft: int,
    ntap: int = 4,
    nsegments: int = 1,
) -> np.ndarray:
    """NumPy golden reference for :func:`correlate` (tests).

    ``nsegments`` mirrors the band-axis time sharding: each segment is
    F-engined independently (the PFB does not run across segment
    boundaries — ``ntap-1`` frames per boundary stay local, matching the
    sharded semantics) and the visibilities sum over segments.
    """
    v = np.moveaxis(voltages, 3, 2)  # (a, c, p, t)
    seg_len = v.shape[-1] // nsegments
    vis = None
    for s in range(nsegments):
        seg = v[..., s * seg_len : (s + 1) * seg_len]
        nblk = seg.shape[-1] // nfft
        nframes = nblk - ntap + 1
        blocks = seg.reshape(seg.shape[:-1] + (nblk, nfft))
        frames = np.zeros(seg.shape[:-1] + (nframes, nfft), dtype=np.complex64)
        for k in range(ntap):
            frames += coeffs[k] * blocks[..., k : k + nframes, :]
        spec = np.fft.fftshift(np.fft.fft(frames, axis=-1), axes=-1)
        part = np.einsum("acptf,bcqtf->abcfpq", spec, np.conj(spec))
        vis = part if vis is None else vis + part
    return vis
