"""Distributed FX correlator over the ``(band, bank)`` mesh.

BASELINE.json config 5: "4-band × 8-bank FX correlator: per-chip F-engine +
cross-bank psum visibilities over ICI".

Layout (the scaling-book recipe — pick a mesh, shard the big axes, let the
collectives ride ICI):

- **Frequency** (coarse channels) is sharded over ``bank`` — the same
  frequency-domain sharding the whole framework is built on.  Visibilities
  are per-frequency, so the X-engine's baseline cross-products never need
  cross-bank communication at all.
- **Time** is sharded over ``band`` — each band row correlates a disjoint
  time segment, and the visibility integration completes with one ``psum``
  over ``band``.  That psum is the only collective in the correlator.

Per chip: F-engine = the same PFB frontend + planar matmul DFT as the
single-chip filterbank path (blit/ops/channelize), applied to complex
voltages held as ``(re, im)`` planes; X-engine = the baseline cross-products
summed over frames — 4 real batched einsums per complex product on the MXU.

TPU note: everything is **planar** (blit/ops/dft.py convention) because this
TPU backend has no complex-dtype HLOs at all (DESIGN.md §1).  The public
``correlate`` accepts planar pairs (TPU path) or complex arrays (CPU/GPU
convenience; output dtype follows input).  The fftshift every fine spectrum
needs is folded into the PFB window by the shift theorem — the same
two-HBM-passes saving the filterbank path uses (DESIGN.md §2).
"""

from __future__ import annotations

import functools
import time
from typing import Iterable, Optional

from blit.ops.dft import ComplexOrPlanar, Planar, as_planar

import numpy as np

import jax

from blit.compat import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blit.ops.channelize import fft_planar, pfb_frontend

BAND_AXIS = "band"
BANK_AXIS = "bank"

# Dispatch resolution of the most recent X-engine TRACE (the
# blit.ops.channelize._LAST_PLAN convention, mirrored from
# blit.parallel.beamform.last_beamform_plan): the pallas-vs-einsum gate
# evaluates on per-shard LOCAL shapes inside shard_map, so provenance
# consumers (bench.py) must read the actual decision here instead of
# re-deriving it from global shapes (ADVICE r5 low finding).
_LAST_PLAN: dict = {}


def last_xengine_plan() -> dict:
    """The most recent X-engine dispatch decision (``{"layout": ...,
    "engine": "pallas" | "einsum"}``; empty until a trace happens — a jit
    cache hit does not refresh it)."""
    return dict(_LAST_PLAN)


def f_engine_planar(
    vr: jax.Array, vi: jax.Array, coeffs: jax.Array
) -> Planar:
    """Fine-channelize complex voltages held as (re, im) planes:
    ``(..., ntime)`` → ``(..., nframes, nfft)`` fftshifted planar spectra.

    The complex-input twin of the filterbank path's PFB+FFT: the FIR runs on
    each plane separately (real VPU work), the DFT is the planar matmul path
    on TPU (complex FFT elsewhere, picked by ``fft_planar``), and the
    fftshift is folded into the window coefficients via the shift theorem
    (input sign flip ↔ spectrum roll by nfft/2; DESIGN.md §2).
    """
    ntap, nfft = coeffs.shape
    if nfft % 2:
        raise ValueError("f_engine_planar: nfft must be even")
    # ±1 is exact in every float dtype: follow the coeffs (bf16 coeffs
    # must not promote the whole FIR back to f32).
    sign = jnp.asarray(
        np.where(np.arange(nfft) % 2 == 0, 1.0, -1.0).astype(np.float32)
    ).astype(coeffs.dtype)
    shifted = coeffs * sign[None, :]
    fr = pfb_frontend(vr, shifted)
    fi = pfb_frontend(vi, shifted)
    return fft_planar(fr, fi)


def f_engine(v: jax.Array, coeffs: jax.Array) -> jax.Array:
    """Complex-dtype convenience over :func:`f_engine_planar` (CPU/GPU)."""
    sr, si = f_engine_planar(jnp.real(v), jnp.imag(v), coeffs)
    return jax.lax.complex(sr, si)


def _xengine_planar(sr: jax.Array, si: jax.Array) -> Planar:
    """Cross-multiply and time-integrate, planar.  ``s``: (nant, nchan, npol,
    nframes, nfft) → visibilities (nant, nant, nchan, nfft, npol, npol) as a
    (re, im) pair.

    ``V[a,b] = Σ_t S_a S_b*``: with planar S the real part is
    ``Σ (ar·br + ai·bi)`` and the imaginary part ``Σ (ai·br − ar·bi)`` —
    4 real batched einsums (MXU) instead of one complex einsum.
    Accumulation is pinned to f32 so bf16 spectra (the bf16-staged path)
    integrate losslessly.

    Measured dead end (DESIGN.md §9 round-4 addendum): computing all four
    block products as ONE einsum over the re/im-stacked operand (a
    (2·nant·npol)² matmul per (chan, fine) batch entry, 4x the work per
    MXU tile) LOSES on the chip — 18.9 vs 20.7 GB/s input rate
    end-to-end (interleaved A/B, tools/ab_fx.py): the stack's
    concatenate materializes an extra copy of both spectra planes, and
    the MXU tiles were not the binding resource.
    """
    _LAST_PLAN.clear()
    _LAST_PLAN.update({"layout": "standard", "engine": "einsum"})
    return _xengine_einsums(sr, si, "abcfpq")


def _xengine_einsums(sr: jax.Array, si: jax.Array, out: str) -> Planar:
    """The four real cross-products as einsums, output layout chosen by
    ``out`` subscripts ("abcfpq" standard / "cfapbq" packed) — one copy
    of the rr/ii/ir/ri structure and the f32-accumulation pin."""
    kw = dict(preferred_element_type=jnp.float32)
    rr = jnp.einsum(f"acptf,bcqtf->{out}", sr, sr, **kw)
    ii = jnp.einsum(f"acptf,bcqtf->{out}", si, si, **kw)
    ir = jnp.einsum(f"acptf,bcqtf->{out}", si, sr, **kw)
    ri = jnp.einsum(f"acptf,bcqtf->{out}", sr, si, **kw)
    return rr + ii, ir - ri


def _xengine_packed(sr: jax.Array, si: jax.Array) -> Planar:
    """X-engine emitting the packed ``(c, f, a, p, b, q)`` layout.

    On TPU backends at MXU-sized baseline counts this is the VMEM-resident
    Pallas kernel (blit/ops/pallas_xengine.py — measured +19% on the whole
    correlate call at nant=64, the un-parking of DESIGN.md §9's round-4
    decision); elsewhere, packed-layout einsums (measured at parity with
    the standard layout, tools/ab_fx64.py, so the fallback costs nothing).
    """
    from blit.ops import pallas_xengine
    from blit.ops.channelize import _MATMUL_ONLY_BACKENDS

    nant, _c, npol = sr.shape[0], sr.shape[1], sr.shape[2]
    nap = nant * npol
    ft = pallas_xengine.pick_ft(
        nap, sr.shape[-1], sr.shape[3], itemsize=sr.dtype.itemsize
    )
    fused = jax.default_backend() in _MATMUL_ONLY_BACKENDS and ft is not None
    _LAST_PLAN.clear()
    _LAST_PLAN.update(
        {"layout": "packed", "engine": "pallas" if fused else "einsum"}
    )
    if fused:
        vr, vi = pallas_xengine.xengine_packed(sr, si, ft=ft)
        shape6 = vr.shape[:2] + (nant, npol, nant, npol)
        return vr.reshape(shape6), vi.reshape(shape6)
    return _xengine_einsums(sr, si, "cfapbq")


def _fx_spectra(vr: jax.Array, vi: jax.Array, h: jax.Array,
                bf16: bool) -> Planar:
    """Per-chip F-engine body shared by every correlator entry point:
    planar voltages ``(nant, nchan_local, ntime_local, npol)`` → fftshifted
    planar spectra ``(nant, nchan_local, npol, nframes, nfft)``, staged in
    bf16 when the planes are bf16-resident (DESIGN.md §9 r5)."""
    if bf16:
        h = h.astype(jnp.bfloat16)
    # Move pol before time so the F-engine framing acts on the last axis.
    sr, si = f_engine_planar(
        jnp.moveaxis(vr, 3, 2), jnp.moveaxis(vi, 3, 2), h
    )
    if bf16:
        sr = sr.astype(jnp.bfloat16)
        si = si.astype(jnp.bfloat16)
    return sr, si


def _fx_xengine(sr: jax.Array, si: jax.Array, vis_layout: str) -> Planar:
    """X-engine dispatch by output layout (shared per-chip body)."""
    if vis_layout == "packed":
        return _xengine_packed(sr, si)
    return _xengine_planar(sr, si)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "nfft", "ntap", "vis_layout", "acc_frames"),
)
def correlate(
    voltages: ComplexOrPlanar,
    coeffs: jax.Array,
    *,
    mesh: Mesh,
    nfft: int,
    ntap: int = 4,
    vis_layout: str = "standard",
    acc_frames: Optional[int] = None,
):
    """Full FX correlation over the mesh.

    Args:
      voltages: ``(nant, nchan, ntime, npol)`` — a planar ``(re, im)``
        float32 pair (TPU path) or one complex64 array (CPU/GPU convenience)
        with ``nchan`` sharded over ``bank`` and ``ntime`` sharded over
        ``band`` (see :func:`correlator_sharding`); ``ntime`` per band must
        be a multiple of ``nfft`` with at least ``ntap`` blocks.
      coeffs: (ntap, nfft) PFB prototype (replicated).
      vis_layout: ``"standard"`` → ``(nant, nant, nchan, nfft, npol,
        npol)``; ``"packed"`` → ``(nchan, nfft, nant, npol, nant, npol)``,
        the TPU-fast layout emitted directly by the VMEM-resident Pallas
        X-engine at MXU-sized baseline counts (nant·npol >= 128; +19%
        whole-call at nant=64 — transposing to the standard layout would
        move 2×vis bytes and eat the win, so the layout is the opt-in).
        Integrations and layout-indifferent reductions should prefer it
        at array scale.

    Returns:
      Visibilities integrated over *all* time (psum over ``band``), with
      the channel axes sharded over ``bank`` like the input — complex64
      when the input was complex, else a planar float32 pair.  Entry
      ``[a, b]`` (standard) or ``[c, f, a, p, b, q]`` (packed) is
      ``⟨S_a S_b*⟩``; the antenna diagonal holds autocorrelation spectra.

    Segment semantics: each band row F-engines its time segment
    independently — the PFB does not run across segment boundaries, so
    ``ntap-1`` frames per boundary are not formed (standard chunked-
    correlator behavior; :func:`correlate_np` with ``nsegments=nband`` is
    the exact golden reference).

    ``acc_frames`` pins the visibility accumulation granularity: each band
    row's frame contraction folds tile-by-tile (``acc_frames`` frames per
    tile, time-ascending) instead of as one contraction.  This is the
    accumulation structure of the windowed streaming path
    (:func:`correlate_stream` with ``window_frames=acc_frames``), so the
    float32 results are byte-identical between the two — the equivalence
    the long-recording tests pin.  ``None`` (default) keeps the single
    contraction (same result to float rounding; one big MXU contraction
    is the fast shape).
    """
    if vis_layout not in ("standard", "packed"):
        raise ValueError(f"bad vis_layout {vis_layout!r}")
    vr, vi, was_complex = as_planar(voltages)
    # bf16-RESIDENT voltages run the F-engine and spectra in bf16
    # (measured +25% end-to-end at nant=64, DESIGN.md §9 r5 addendum:
    # 8-bit RAW samples are exact in bf16, and the MXU truncates f32
    # operands to bf16 anyway — bf16 SPECTRA alone measured visibilities
    # byte-identical to the f32-spectra path).  Visibilities always
    # accumulate and psum in f32.  Opt in by loading bf16 planes
    # (``load_correlator_mesh(dtype="bfloat16")``).
    bf16 = vr.dtype == jnp.bfloat16

    def step(vr, vi, h):
        sr, si = _fx_spectra(vr, vi, h, bf16)  # (a, c, p, frames, nfft)
        nframes = sr.shape[3]
        if acc_frames is None or acc_frames >= nframes:
            visr, visi = _fx_xengine(sr, si, vis_layout)
        else:
            # Tile-by-tile fold, time-ascending — the windowed stream's
            # exact accumulation order (first tile un-added, like the
            # stream's first window, so even signed zeros match).
            visr = visi = None
            for t0 in range(0, nframes, acc_frames):
                pr, pi = _fx_xengine(
                    sr[..., t0:t0 + acc_frames, :],
                    si[..., t0:t0 + acc_frames, :],
                    vis_layout,
                )
                visr = pr if visr is None else visr + pr
                visi = pi if visi is None else visi + pi
        return jax.lax.psum((visr, visi), BAND_AXIS)

    spec_v = P(None, BANK_AXIS, BAND_AXIS)
    out_spec = (
        P(BANK_AXIS) if vis_layout == "packed" else P(None, None, BANK_AXIS)
    )
    visr, visi = shard_map(
        step,
        mesh=mesh,
        in_specs=(spec_v, spec_v, P()),
        out_specs=(out_spec, out_spec),
        check_vma=False,  # psum output is band-invariant
    )(vr, vi, coeffs)
    if was_complex:
        return jax.lax.complex(visr, visi)
    return visr, visi


def correlator_sharding(mesh: Mesh) -> NamedSharding:
    """Input sharding for (nant, nchan, ntime, npol) voltages: frequency
    over ``bank``, time over ``band``.  ``jax.device_put`` applies it to a
    planar pair and a complex array alike."""
    return NamedSharding(mesh, P(None, BANK_AXIS, BAND_AXIS))


def visibility_sharding(mesh: Mesh) -> NamedSharding:
    """Output sharding: (nant, nant, nchan, nfft, npol, npol), frequency
    over ``bank``, replicated over ``band``."""
    return NamedSharding(mesh, P(None, None, BANK_AXIS))


# -- windowed streaming correlation ----------------------------------------
#
# The accumulator is BAND-SHARDED partial visibilities with a leading band
# axis — each band row folds its own windows locally and the band psum runs
# exactly once, at the end (``psum(fold(local))``, the same structure as
# ``correlate(acc_frames=...)``'s in-step fold, which is what makes the
# float32 stream byte-identical to the one-shot call).

def _acc_rule(vis_layout: str) -> str:
    """The accumulator's :data:`blit.parallel.mesh.PARTITION_RULES` role."""
    return "vis_acc_packed" if vis_layout == "packed" else "vis_acc_standard"


def _acc_spec(vis_layout: str) -> P:
    """PartitionSpec of the band-sharded partial-visibility accumulator:
    standard ``(nband, nant, nant, nchan, nfft, npol, npol)`` / packed
    ``(nband, nchan, nfft, nant, npol, nant, npol)`` — resolved through
    the sharded plane's partition-rule registry (ISSUE 9: the fold
    accumulator carries its spec; dispatch and readback cannot drift)."""
    from blit.parallel.mesh import partition_rule

    return partition_rule(_acc_rule(vis_layout))


_SPEC_V = P(None, BANK_AXIS, BAND_AXIS)


@functools.partial(jax.jit, static_argnames=("mesh", "vis_layout"))
def _window_vis(vr, vi, h, *, mesh: Mesh, vis_layout: str):
    """First window: per-chip F-engine + X-engine partials, NO psum —
    the band-sharded accumulator's initial value."""
    bf16 = vr.dtype == jnp.bfloat16

    def step(vr, vi, h):
        sr, si = _fx_spectra(vr, vi, h, bf16)
        pr, pi = _fx_xengine(sr, si, vis_layout)
        return pr[None], pi[None]  # leading band block axis

    spec = _acc_spec(vis_layout)
    return shard_map(
        step, mesh=mesh, in_specs=(_SPEC_V, _SPEC_V, P()),
        out_specs=(spec, spec), check_vma=False,
    )(vr, vi, h)


@functools.partial(
    jax.jit, static_argnames=("mesh", "vis_layout"), donate_argnums=(0, 1)
)
def _accum_vis(accr, acci, vr, vi, h, *, mesh: Mesh, vis_layout: str):
    """Subsequent windows: fold this window's partials into the donated
    accumulator (HBM reused in place across the whole stream)."""
    bf16 = vr.dtype == jnp.bfloat16

    def step(ar, ai, vr, vi, h):
        sr, si = _fx_spectra(vr, vi, h, bf16)
        pr, pi = _fx_xengine(sr, si, vis_layout)
        return ar + pr[None], ai + pi[None]

    spec = _acc_spec(vis_layout)
    return shard_map(
        step, mesh=mesh, in_specs=(spec, spec, _SPEC_V, _SPEC_V, P()),
        out_specs=(spec, spec), check_vma=False,
    )(accr, acci, vr, vi, h)


def _fold_vis(value, vr, vi, h, *, mesh: Mesh, vis_layout: str):
    """The :class:`blit.parallel.mesh.ShardedAccumulator` fold adapter:
    ``value`` is the live ``(accr, acci)`` pair, donated through
    :func:`_accum_vis` (its ``donate_argnums``)."""
    accr, acci = value
    return _accum_vis(accr, acci, vr, vi, h, mesh=mesh,
                      vis_layout=vis_layout)


@functools.partial(jax.jit, static_argnames=("mesh", "vis_layout"))
def _finish_vis(accr, acci, *, mesh: Mesh, vis_layout: str):
    """The stream's ONE collective: psum the band-local partials into the
    integrated visibilities, with :func:`correlate`'s output sharding."""

    def step(ar, ai):
        ar, ai = jax.lax.psum((ar, ai), BAND_AXIS)
        return ar[0], ai[0]  # drop the leading band block axis

    spec = _acc_spec(vis_layout)
    out = (
        P(BANK_AXIS) if vis_layout == "packed" else P(None, None, BANK_AXIS)
    )
    return shard_map(
        step, mesh=mesh, in_specs=(spec, spec), out_specs=(out, out),
        check_vma=False,  # psum output is band-invariant
    )(accr, acci)


def correlate_stream(
    feed: Iterable,
    coeffs: jax.Array,
    *,
    mesh: Mesh,
    nfft: int,
    ntap: int = 4,
    vis_layout: str = "standard",
    timeline=None,
) -> Planar:
    """Full FX correlation over a windowed feed
    (:class:`blit.parallel.antenna.CorrelatorStream`) — the arbitrarily-
    long-recording form of :func:`correlate`: per-window local partials
    fold into an on-device band-sharded accumulator (donated, so windows
    reuse HBM), the band psum runs once at the end, and only the final
    visibilities exist whole.

    Pipelining: window ``w``'s dispatch is asynchronous; the blocking wait
    on window ``w-1``'s fold happens AFTER the feed has already
    transferred window ``w`` (and while its producer thread reads window
    ``w+1``), so host reads, host→device transfer and device compute
    overlap — the ``RawReducer.drain`` lag pattern.

    Numerics: byte-identical (float32) to
    ``correlate(..., acc_frames=window_frames)`` on the same span — same
    per-window contractions, same time-ascending fold (the long-recording
    equivalence tests pin this, arbitrary ``start_sample`` included); the
    default one-shot ``correlate`` differs only by float summation order.

    Returns the planar ``(visr, visi)`` pair with :func:`correlate`'s
    output contract.  Stage timings land in ``timeline``: ``dispatch``
    (async window fold), ``device`` (lag-synchronized wait).
    """
    from blit.observability import Timeline

    if vis_layout not in ("standard", "packed"):
        raise ValueError(f"bad vis_layout {vis_layout!r}")
    if coeffs.shape != (ntap, nfft):
        raise ValueError(
            f"coeffs shape {coeffs.shape} != (ntap={ntap}, nfft={nfft})"
        )
    from blit.outplane import FoldInFlight
    from blit.parallel.mesh import (
        ShardedAccumulator,
        psum_ici_bytes,
        record_ici,
    )

    from blit import observability

    tl = timeline if timeline is not None else Timeline()
    # The fold accumulator CARRIES its partition rule (ISSUE 9): the
    # band-sharded partial visibilities and the spec that shards them
    # travel together, donated window to window.
    acc = ShardedAccumulator(mesh, _acc_rule(vis_layout))
    flight = FoldInFlight(tl, depth=1)
    with observability.span("correlate.stream"):
        for win in feed:
            if win.masked:
                # Degraded continuation: the band-sharded accumulator folds
                # this window with the failed antenna zero-weighted; the flag
                # rides the driver's stage tables and the feed's metadata
                # (``masked_antennas`` / header ``_masked_antennas``).
                tl.count("masked_antennas", len(win.masked))
            vr, vi = win.arrays
            # Lag-1 sync (shared FoldInFlight core, ISSUE 4): wait for window
            # w-1's fold only now — the feed already moved window w and is
            # reading w+1 behind it.  The synced fold consumed w-1's arrays,
            # so its slot can refill (Window.release contract).  Must happen
            # BEFORE the next dispatch: _accum_vis donates the accumulator,
            # and a donated token can no longer be waited on.
            flight.make_room()
            with observability.span("correlate.window", i=win.index), \
                    tl.stage("dispatch", byte_free=True):
                if acc.value is None:
                    acc.init(_window_vis(
                        vr, vi, coeffs, mesh=mesh, vis_layout=vis_layout
                    ))
                else:
                    acc.fold(_fold_vis, vr, vi, coeffs,
                             mesh=mesh, vis_layout=vis_layout)
            flight.admit(win, acc.value[0])
        if acc.value is None:
            raise ValueError("correlate_stream: feed yielded no windows")
        nband = mesh.shape[BAND_AXIS]
        with tl.stage("device", byte_free=True):
            if nband > 1:
                # Warm-up dispatch: this is _finish_vis's first call of
                # the stream, so a timed cold call would sample
                # trace+XLA compile, not the collective (the PR 8
                # OnlineTuner chunk-1 lesson; .lower().compile() does
                # NOT warm the jit call cache on supported jax).  The
                # warm-up also syncs every fold, so the timed
                # re-dispatch below is the psum program alone — the
                # honest mesh.psum_s sample, one extra end-of-stream
                # collective, never per-window.
                jax.block_until_ready(_finish_vis(
                    *acc.value, mesh=mesh, vis_layout=vis_layout
                ))
                t0 = time.perf_counter()
                visr, visi = _finish_vis(
                    *acc.value, mesh=mesh, vis_layout=vis_layout
                )
                jax.block_until_ready((visr, visi))
                psum_s = time.perf_counter() - t0
            else:
                # Single-band mesh: the psum is the identity, there is
                # no ICI sample to take — one dispatch, no warm-up.
                visr, visi = _finish_vis(
                    *acc.value, mesh=mesh, vis_layout=vis_layout
                )
                jax.block_until_ready((visr, visi))
        if nband > 1:
            per_chip = sum(a.nbytes for a in acc.value) // mesh.size
            record_ici(tl, "psum", psum_ici_bytes(per_chip, nband), psum_s)
        # The finish fetch just proved every fold complete — release the last
        # window without the old second sync of the accumulator (ISSUE 4:
        # "double sync today").
        flight.drain(synced=True)
    return visr, visi


def correlate_np(
    voltages: np.ndarray,
    coeffs: np.ndarray,
    nfft: int,
    ntap: int = 4,
    nsegments: int = 1,
) -> np.ndarray:
    """NumPy golden reference for :func:`correlate` (tests).

    ``nsegments`` mirrors the band-axis time sharding: each segment is
    F-engined independently (the PFB does not run across segment
    boundaries — ``ntap-1`` frames per boundary stay local, matching the
    sharded semantics) and the visibilities sum over segments.
    """
    v = np.moveaxis(voltages, 3, 2)  # (a, c, p, t)
    seg_len = v.shape[-1] // nsegments
    vis = None
    for s in range(nsegments):
        seg = v[..., s * seg_len : (s + 1) * seg_len]
        nblk = seg.shape[-1] // nfft
        nframes = nblk - ntap + 1
        blocks = seg.reshape(seg.shape[:-1] + (nblk, nfft))
        frames = np.zeros(seg.shape[:-1] + (nframes, nfft), dtype=np.complex64)
        for k in range(ntap):
            frames += coeffs[k] * blocks[..., k : k + nframes, :]
        spec = np.fft.fftshift(np.fft.fft(frames, axis=-1), axes=-1)
        part = np.einsum("acptf,bcqtf->abcfpq", spec, np.conj(spec))
        vis = part if vis is None else vis + part
    return vis
