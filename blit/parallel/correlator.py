"""Distributed FX correlator over the ``(band, bank)`` mesh.

BASELINE.json config 5: "4-band × 8-bank FX correlator: per-chip F-engine +
cross-bank psum visibilities over ICI".

Layout (the scaling-book recipe — pick a mesh, shard the big axes, let the
collectives ride ICI):

- **Frequency** (coarse channels) is sharded over ``bank`` — the same
  frequency-domain sharding the whole framework is built on.  Visibilities
  are per-frequency, so the X-engine's baseline cross-products never need
  cross-bank communication at all.
- **Time** is sharded over ``band`` — each band row correlates a disjoint
  time segment, and the visibility integration completes with one ``psum``
  over ``band``.  That psum is the only collective in the correlator.

Per chip: F-engine = the same PFB frontend + planar matmul DFT as the
single-chip filterbank path (blit/ops/channelize), applied to complex
voltages held as ``(re, im)`` planes; X-engine = the baseline cross-products
summed over frames — 4 real batched einsums per complex product on the MXU.

TPU note: everything is **planar** (blit/ops/dft.py convention) because this
TPU backend has no complex-dtype HLOs at all (DESIGN.md §1).  The public
``correlate`` accepts planar pairs (TPU path) or complex arrays (CPU/GPU
convenience; output dtype follows input).  The fftshift every fine spectrum
needs is folded into the PFB window by the shift theorem — the same
two-HBM-passes saving the filterbank path uses (DESIGN.md §2).
"""

from __future__ import annotations

import functools
from blit.ops.dft import ComplexOrPlanar, Planar, as_planar

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blit.ops.channelize import fft_planar, pfb_frontend

BAND_AXIS = "band"
BANK_AXIS = "bank"


def f_engine_planar(
    vr: jax.Array, vi: jax.Array, coeffs: jax.Array
) -> Planar:
    """Fine-channelize complex voltages held as (re, im) planes:
    ``(..., ntime)`` → ``(..., nframes, nfft)`` fftshifted planar spectra.

    The complex-input twin of the filterbank path's PFB+FFT: the FIR runs on
    each plane separately (real VPU work), the DFT is the planar matmul path
    on TPU (complex FFT elsewhere, picked by ``fft_planar``), and the
    fftshift is folded into the window coefficients via the shift theorem
    (input sign flip ↔ spectrum roll by nfft/2; DESIGN.md §2).
    """
    ntap, nfft = coeffs.shape
    if nfft % 2:
        raise ValueError("f_engine_planar: nfft must be even")
    # ±1 is exact in every float dtype: follow the coeffs (bf16 coeffs
    # must not promote the whole FIR back to f32).
    sign = jnp.asarray(
        np.where(np.arange(nfft) % 2 == 0, 1.0, -1.0).astype(np.float32)
    ).astype(coeffs.dtype)
    shifted = coeffs * sign[None, :]
    fr = pfb_frontend(vr, shifted)
    fi = pfb_frontend(vi, shifted)
    return fft_planar(fr, fi)


def f_engine(v: jax.Array, coeffs: jax.Array) -> jax.Array:
    """Complex-dtype convenience over :func:`f_engine_planar` (CPU/GPU)."""
    sr, si = f_engine_planar(jnp.real(v), jnp.imag(v), coeffs)
    return jax.lax.complex(sr, si)


def _xengine_planar(sr: jax.Array, si: jax.Array) -> Planar:
    """Cross-multiply and time-integrate, planar.  ``s``: (nant, nchan, npol,
    nframes, nfft) → visibilities (nant, nant, nchan, nfft, npol, npol) as a
    (re, im) pair.

    ``V[a,b] = Σ_t S_a S_b*``: with planar S the real part is
    ``Σ (ar·br + ai·bi)`` and the imaginary part ``Σ (ai·br − ar·bi)`` —
    4 real batched einsums (MXU) instead of one complex einsum.
    Accumulation is pinned to f32 so bf16 spectra (the bf16-staged path)
    integrate losslessly.

    Measured dead end (DESIGN.md §9 round-4 addendum): computing all four
    block products as ONE einsum over the re/im-stacked operand (a
    (2·nant·npol)² matmul per (chan, fine) batch entry, 4x the work per
    MXU tile) LOSES on the chip — 18.9 vs 20.7 GB/s input rate
    end-to-end (interleaved A/B, tools/ab_fx.py): the stack's
    concatenate materializes an extra copy of both spectra planes, and
    the MXU tiles were not the binding resource.
    """
    return _xengine_einsums(sr, si, "abcfpq")


def _xengine_einsums(sr: jax.Array, si: jax.Array, out: str) -> Planar:
    """The four real cross-products as einsums, output layout chosen by
    ``out`` subscripts ("abcfpq" standard / "cfapbq" packed) — one copy
    of the rr/ii/ir/ri structure and the f32-accumulation pin."""
    kw = dict(preferred_element_type=jnp.float32)
    rr = jnp.einsum(f"acptf,bcqtf->{out}", sr, sr, **kw)
    ii = jnp.einsum(f"acptf,bcqtf->{out}", si, si, **kw)
    ir = jnp.einsum(f"acptf,bcqtf->{out}", si, sr, **kw)
    ri = jnp.einsum(f"acptf,bcqtf->{out}", sr, si, **kw)
    return rr + ii, ir - ri


def _xengine_packed(sr: jax.Array, si: jax.Array) -> Planar:
    """X-engine emitting the packed ``(c, f, a, p, b, q)`` layout.

    On TPU backends at MXU-sized baseline counts this is the VMEM-resident
    Pallas kernel (blit/ops/pallas_xengine.py — measured +19% on the whole
    correlate call at nant=64, the un-parking of DESIGN.md §9's round-4
    decision); elsewhere, packed-layout einsums (measured at parity with
    the standard layout, tools/ab_fx64.py, so the fallback costs nothing).
    """
    from blit.ops import pallas_xengine
    from blit.ops.channelize import _MATMUL_ONLY_BACKENDS

    nant, _c, npol = sr.shape[0], sr.shape[1], sr.shape[2]
    nap = nant * npol
    ft = pallas_xengine.pick_ft(
        nap, sr.shape[-1], sr.shape[3], itemsize=sr.dtype.itemsize
    )
    if jax.default_backend() in _MATMUL_ONLY_BACKENDS and ft is not None:
        vr, vi = pallas_xengine.xengine_packed(sr, si, ft=ft)
        shape6 = vr.shape[:2] + (nant, npol, nant, npol)
        return vr.reshape(shape6), vi.reshape(shape6)
    return _xengine_einsums(sr, si, "cfapbq")


@functools.partial(
    jax.jit, static_argnames=("mesh", "nfft", "ntap", "vis_layout")
)
def correlate(
    voltages: ComplexOrPlanar,
    coeffs: jax.Array,
    *,
    mesh: Mesh,
    nfft: int,
    ntap: int = 4,
    vis_layout: str = "standard",
):
    """Full FX correlation over the mesh.

    Args:
      voltages: ``(nant, nchan, ntime, npol)`` — a planar ``(re, im)``
        float32 pair (TPU path) or one complex64 array (CPU/GPU convenience)
        with ``nchan`` sharded over ``bank`` and ``ntime`` sharded over
        ``band`` (see :func:`correlator_sharding`); ``ntime`` per band must
        be a multiple of ``nfft`` with at least ``ntap`` blocks.
      coeffs: (ntap, nfft) PFB prototype (replicated).
      vis_layout: ``"standard"`` → ``(nant, nant, nchan, nfft, npol,
        npol)``; ``"packed"`` → ``(nchan, nfft, nant, npol, nant, npol)``,
        the TPU-fast layout emitted directly by the VMEM-resident Pallas
        X-engine at MXU-sized baseline counts (nant·npol >= 128; +19%
        whole-call at nant=64 — transposing to the standard layout would
        move 2×vis bytes and eat the win, so the layout is the opt-in).
        Integrations and layout-indifferent reductions should prefer it
        at array scale.

    Returns:
      Visibilities integrated over *all* time (psum over ``band``), with
      the channel axes sharded over ``bank`` like the input — complex64
      when the input was complex, else a planar float32 pair.  Entry
      ``[a, b]`` (standard) or ``[c, f, a, p, b, q]`` (packed) is
      ``⟨S_a S_b*⟩``; the antenna diagonal holds autocorrelation spectra.

    Segment semantics: each band row F-engines its time segment
    independently — the PFB does not run across segment boundaries, so
    ``ntap-1`` frames per boundary are not formed (standard chunked-
    correlator behavior; :func:`correlate_np` with ``nsegments=nband`` is
    the exact golden reference).
    """
    if vis_layout not in ("standard", "packed"):
        raise ValueError(f"bad vis_layout {vis_layout!r}")
    vr, vi, was_complex = as_planar(voltages)
    # bf16-RESIDENT voltages run the F-engine and spectra in bf16
    # (measured +25% end-to-end at nant=64, DESIGN.md §9 r5 addendum:
    # 8-bit RAW samples are exact in bf16, and the MXU truncates f32
    # operands to bf16 anyway — bf16 SPECTRA alone measured visibilities
    # byte-identical to the f32-spectra path).  Visibilities always
    # accumulate and psum in f32.  Opt in by loading bf16 planes
    # (``load_correlator_mesh(dtype="bfloat16")``).
    bf16 = vr.dtype == jnp.bfloat16

    def step(vr, vi, h):
        if bf16:
            h = h.astype(jnp.bfloat16)
        # v: (nant, nchan_local, ntime_local, npol) — move pol before time so
        # the F-engine framing acts on the last axis.
        sr, si = f_engine_planar(
            jnp.moveaxis(vr, 3, 2), jnp.moveaxis(vi, 3, 2), h
        )  # (a, c, p, frames, nfft) each
        if bf16:
            sr = sr.astype(jnp.bfloat16)
            si = si.astype(jnp.bfloat16)
        if vis_layout == "packed":
            visr, visi = _xengine_packed(sr, si)
        else:
            visr, visi = _xengine_planar(sr, si)
        return jax.lax.psum((visr, visi), BAND_AXIS)

    spec_v = P(None, BANK_AXIS, BAND_AXIS)
    out_spec = (
        P(BANK_AXIS) if vis_layout == "packed" else P(None, None, BANK_AXIS)
    )
    visr, visi = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(spec_v, spec_v, P()),
        out_specs=(out_spec, out_spec),
        check_vma=False,  # psum output is band-invariant
    )(vr, vi, coeffs)
    if was_complex:
        return jax.lax.complex(visr, visi)
    return visr, visi


def correlator_sharding(mesh: Mesh) -> NamedSharding:
    """Input sharding for (nant, nchan, ntime, npol) voltages: frequency
    over ``bank``, time over ``band``.  ``jax.device_put`` applies it to a
    planar pair and a complex array alike."""
    return NamedSharding(mesh, P(None, BANK_AXIS, BAND_AXIS))


def visibility_sharding(mesh: Mesh) -> NamedSharding:
    """Output sharding: (nant, nant, nchan, nfft, npol, npol), frequency
    over ``bank``, replicated over ``band``."""
    return NamedSharding(mesh, P(None, None, BANK_AXIS))


def correlate_np(
    voltages: np.ndarray,
    coeffs: np.ndarray,
    nfft: int,
    ntap: int = 4,
    nsegments: int = 1,
) -> np.ndarray:
    """NumPy golden reference for :func:`correlate` (tests).

    ``nsegments`` mirrors the band-axis time sharding: each segment is
    F-engined independently (the PFB does not run across segment
    boundaries — ``ntap-1`` frames per boundary stay local, matching the
    sharded semantics) and the visibilities sum over segments.
    """
    v = np.moveaxis(voltages, 3, 2)  # (a, c, p, t)
    seg_len = v.shape[-1] // nsegments
    vis = None
    for s in range(nsegments):
        seg = v[..., s * seg_len : (s + 1) * seg_len]
        nblk = seg.shape[-1] // nfft
        nframes = nblk - ntap + 1
        blocks = seg.reshape(seg.shape[:-1] + (nblk, nfft))
        frames = np.zeros(seg.shape[:-1] + (nframes, nfft), dtype=np.complex64)
        for k in range(ntap):
            frames += coeffs[k] * blocks[..., k : k + nframes, :]
        spec = np.fft.fftshift(np.fft.fft(frames, axis=-1), axes=-1)
        part = np.einsum("acptf,bcqtf->abcfpq", spec, np.conj(spec))
        vis = part if vis is None else vis + part
    return vis
