"""Remote worker transport: one ``blit.agent`` subprocess per host over ssh.

The rebuild of the reference's ``Distributed.addprocs(hosts; tunnel=true)``
star topology (src/gbt.jl:28-34): the main process starts one agent per
host, ships ``(function, args)`` requests, and gathers pickled results.
ssh provides the authenticated, tunneled byte stream exactly as it does for
Distributed.jl; there are no worker↔worker channels (the TPU data plane in
blit.parallel.mesh is where cross-worker reduction lives).

``RemoteWorker`` is used by :class:`blit.parallel.pool.WorkerPool` with
``backend="remote"``.  Tests exercise the full wire protocol with a local
``python -m blit.agent`` transport (no sshd needed); production uses
:func:`ssh_command`.
"""

from __future__ import annotations

import logging
import os
import pickle
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence

from blit import faults, observability
from blit.agent import MAGIC, _SAFE_GLOBALS_RESPONSE, read_msg, write_msg

log = logging.getLogger("blit.remote")

# Max bytes of ssh/rc banner noise tolerated before the agent's handshake.
_BANNER_SCAN_LIMIT = 1 << 16


def _await_banner(stream, host: str) -> None:
    """Consume bytes until the agent's MAGIC handshake appears (discarding
    any login-shell banner a remote rc file printed), or fail loudly."""
    window = b""
    scanned = 0
    while True:
        b = stream.read(1)
        if not b:
            raise RemoteError(
                host, "AgentDied",
                f"agent stream closed before handshake (scanned {scanned}B)",
                "",
            )
        scanned += 1
        window = (window + b)[-len(MAGIC):]
        if window == MAGIC:
            if scanned > len(MAGIC):
                log.info("%s: skipped %dB of pre-handshake banner",
                         host, scanned - len(MAGIC))
            return
        if scanned > _BANNER_SCAN_LIMIT:
            raise RemoteError(
                host, "NoHandshake",
                f"no agent handshake within {_BANNER_SCAN_LIMIT}B — is "
                "blit importable on the remote host?", "",
            )


class RemoteError(RuntimeError):
    """A worker-side exception, carrying the remote type/message/traceback."""

    def __init__(self, host: str, etype: str, msg: str, tb: str):
        super().__init__(f"[{host}] {etype}: {msg}")
        self.host = host
        self.etype = etype
        self.remote_traceback = tb


def ssh_command(
    host: str,
    python: str = "python3",
    ssh_opts: Sequence[str] = ("-o", "BatchMode=yes"),
    remote_env: Optional[dict] = None,
) -> List[str]:
    """The production transport: ``ssh <host> <python> -m blit.agent``.

    blit must be importable on the remote host: deploy it with
    ``pip install`` per docs/WORKFLOWS.md "Deploying to worker hosts" —
    the packaged install (pyproject.toml) is the analog of the
    reference's shared ``@BLDistributedDataProducts`` project environment
    (src/gbt.jl:17).  ``agent_env_with_repo`` remains a dev/test
    convenience for uninstalled checkouts.

    ``remote_env`` entries are injected as an ``env K=V ...`` prefix in
    the REMOTE command — sshd does not forward arbitrary client
    environment variables, so identity stamps like ``BLIT_WORKER_ID``
    (ISSUE 5) must ride the command line to reach the agent."""
    prefix: List[str] = []
    if remote_env:
        prefix = ["env"] + [f"{k}={v}" for k, v in sorted(remote_env.items())]
    return ["ssh", *ssh_opts, host, *prefix, python, "-m", "blit.agent"]


def local_agent_command() -> List[str]:
    """In-host transport (tests; single-machine use): the same agent,
    spawned directly."""
    return [sys.executable, "-m", "blit.agent"]


class RemoteWorker:
    """One agent subprocess + the request/response framing to talk to it.

    One outstanding call at a time (guarded by a lock), matching the
    reference's one-``@spawnat``-per-worker usage; the pool's thread
    executor provides cross-worker concurrency.

    Liveness is bounded two ways (SURVEY.md §5 "health-checked worker
    pool" — the reference's blocking ``fetch`` has neither):

    - every call runs under a ``call_timeout`` deadline enforced by a
      watchdog that KILLS the agent when it fires (the only way to unblock
      a read from a wedged-but-alive transport: hung NFS under the worker
      fn, a stuck ssh, a partitioned network).  The caller gets a
      ``RemoteError(etype="CallTimeout")`` and the next use respawns.
    - reusing a live agent first round-trips a ``blit.agent.ping`` under
      the (much shorter) ``ping_timeout``; an agent that cannot answer is
      killed and respawned BEFORE the real request is committed to it.

    ``call_timeout=None`` (the default — the reference's blocking behavior)
    disables the deadline: worker functions legitimately stream multi-GB
    files for hours, so kill-on-deadline is opt-in, sized by the caller
    above their largest sanctioned workload (ADVICE r4).  The reuse-time
    ping still applies either way.
    """

    def __init__(self, host: str, command: Optional[Sequence[str]] = None,
                 env: Optional[dict] = None,
                 call_timeout: Optional[float] = None,
                 ping_timeout: Optional[float] = 30.0,
                 ping_min_idle: float = 5.0):
        self.host = host
        self.command = list(command) if command else ssh_command(host)
        self.call_timeout = call_timeout
        self.ping_timeout = ping_timeout
        # Skip the reuse-time ping when the agent answered this recently —
        # a chatty fan-out must not pay 2x the WAN round trips; the ping is
        # for agents that have sat idle long enough to have wedged.
        self.ping_min_idle = ping_min_idle
        self._last_ok = float("-inf")  # monotonic time of last good reply
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._env = env

    def _spawn(self) -> subprocess.Popen:
        proc = subprocess.Popen(
            self.command,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            env=self._env,
        )
        try:
            _await_banner(proc.stdout, self.host)
        except BaseException:
            # A live-but-unframed process (ssh stuck at a prompt, rc noise
            # past the scan limit) must not be left as self._proc — the
            # next call would waste a full ping_timeout probing it.
            proc.kill()
            proc.wait()
            self._proc = None
            raise
        self._proc = proc
        log.info("agent for %s started (pid %d)", self.host, proc.pid)
        return proc

    def _kill_reap(self, proc: subprocess.Popen) -> None:
        proc.kill()
        proc.wait()
        self._proc = None

    def _ensure(self) -> subprocess.Popen:
        if self._proc is None or self._proc.poll() is not None:
            return self._spawn()
        # Reused agent that has sat idle past ping_min_idle: health-check it
        # with the cheapest full-path round trip before committing the real
        # request.  A fresh spawn needs no ping — the banner handshake just
        # proved the path — and a recently-responsive agent skips it too.
        if self.ping_timeout and (
            time.monotonic() - self._last_ok > self.ping_min_idle
        ):
            proc = self._proc
            try:
                reply = self._transact(
                    proc, ("blit.agent.ping", (), {}), "ping",
                    self.ping_timeout,
                )
                # ANY well-formed reply proves the agent alive and framed —
                # including ("err", ...) from an older remote blit without
                # agent.ping() (killing+respawning there would degrade every
                # call to a full ssh round trip forever).
                alive = (
                    isinstance(reply, tuple) and reply
                    and reply[0] in ("ok", "err")
                )
                if alive and self._proc is proc:
                    if reply[0] == "err":
                        log.info(
                            "%s: remote blit lacks agent.ping (%s); agent "
                            "alive, continuing", self.host, reply[1],
                        )
                    return proc
                log.warning("%s: unexpected ping reply %r; respawning",
                            self.host, reply)
            except RemoteError as e:
                log.warning("%s: agent failed health check (%s); respawning",
                            self.host, e.etype)
            if self._proc is proc:  # _transact may already have reaped it
                self._kill_reap(proc)
            return self._spawn()
        return self._proc

    def _transact(self, proc: subprocess.Popen, request: tuple,
                  fn_path: str, timeout: Optional[float]):
        """One write+read exchange under a kill-on-deadline watchdog.

        Blocking pipe reads cannot be cancelled portably; killing the agent
        makes them fail with EOF/BrokenPipe, which is mapped to
        ``CallTimeout`` when the watchdog fired (vs ``AgentDied`` when the
        agent really died on its own)."""
        timed_out = threading.Event()
        done = threading.Event()
        # Serializes the reply-landed / deadline-fired decision: exactly one
        # of {done, timed_out} is set first, and the other side observes it
        # (a bare check-then-kill would let a preempted _fire kill a healthy
        # agent AFTER the success path declared no timeout).
        verdict = threading.Lock()
        timer = None
        if timeout is not None:
            def _fire(p=proc):
                with verdict:
                    if done.is_set():  # reply landed first; stand down
                        return
                    timed_out.set()
                if fn_path == "ping":
                    # Routine self-healing: _ensure logs the respawn at
                    # WARNING and the remedy knob is ping_timeout, not
                    # call_timeout — don't raise a spurious ERROR here.
                    log.debug("%s: ping watchdog fired after %ss",
                              self.host, timeout)
                else:
                    # Prominent by design (ADVICE r4): a deadline sized
                    # below a legitimate long call would otherwise kill
                    # healthy work with only an exception in some caller's
                    # future to show for it.
                    log.error(
                        "%s: call watchdog fired after %ss during %s — "
                        "killing agent (raise call_timeout if this call "
                        "was healthy)",
                        self.host, timeout, fn_path,
                    )
                try:
                    p.kill()
                except OSError:
                    pass

            timer = threading.Timer(timeout, _fire)
            timer.daemon = True
            timer.start()
        try:
            write_msg(proc.stdin, request)
            # Responses get the narrower allow-list: no ``re._compile``
            # (a compromised peer must not hand the client a pathological
            # regex; results are arrays/records/dicts only).  No drain on
            # oversize either — the refusal below kills the worker, so
            # pulling a multi-GiB body through the ssh pipe first would
            # be pure waste.
            reply = read_msg(
                proc.stdout,
                safe_globals=_SAFE_GLOBALS_RESPONSE,
                drain_oversized=False,
            )
            with verdict:
                done.set()
                fired = timed_out.is_set()
            if fired:
                # The watchdog fired while the reply was mid-flight: the
                # reply is whole (the frame read completed) but the agent
                # is dead — reap it so the next use respawns instead of
                # surfacing a spurious AgentDied.
                self._kill_reap(proc)
            else:
                self._last_ok = time.monotonic()
            return reply
        except (BrokenPipeError, EOFError, OSError) as e:
            try:
                rc = proc.wait(timeout=5)  # reap; no zombie
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
            self._proc = None
            if timed_out.is_set():
                raise RemoteError(
                    self.host, "CallTimeout",
                    f"no reply to {fn_path} within {timeout}s; agent killed "
                    "(will respawn on next use)", "",
                ) from e
            raise RemoteError(
                self.host, "AgentDied",
                f"agent exited (rc={rc}) during {fn_path}: {e}", "",
            ) from e
        except pickle.UnpicklingError as e:
            # A refused response (oversized / disallowed global) means
            # the peer is misbehaving or compromised; don't trust the
            # stream again — kill and respawn on next use.
            self._kill_reap(proc)
            raise RemoteError(
                self.host, "WireRefused",
                f"response refused during {fn_path}: {e}", "",
            ) from e
        finally:
            if timer is not None:
                timer.cancel()

    def call(self, fn: Callable, *args, **kwargs):
        """Invoke ``fn`` (a blit callable) on the remote host, bounded by
        ``call_timeout``.

        Trace propagation (ISSUE 5): when the calling thread is inside a
        span, its ``{"trace", "span"}`` context rides the request as the
        reserved ``_blit_trace`` kwarg — :func:`blit.agent.serve` strips
        it before invoking the worker function and opens the worker-side
        span under it, so the fan-out's remote spans parent onto the
        driver's."""
        fn_path = f"{fn.__module__}.{fn.__qualname__}"
        ctx = observability.tracer().context()
        if ctx is not None:
            kwargs = dict(kwargs)
            kwargs["_blit_trace"] = ctx
        try:
            # Transport-level injection point: a "fail" rule here looks to
            # the pool exactly like the agent dying mid-call (the retry /
            # circuit-breaker path); a "delay" rule models a slow dispatch
            # (it runs BEFORE the _transact watchdog is armed, so it can
            # never fire call_timeout — drill CallTimeout with a wedged
            # agent instead, tests/_wedged_agent.py).
            faults.fire("remote.call", key=self.host)
        except Exception as e:  # noqa: BLE001 — injected
            raise RemoteError(
                self.host, "AgentDied", f"injected fault: {e}", ""
            ) from e
        with self._lock:
            proc = self._ensure()
            reply = self._transact(
                proc, (fn_path, args, kwargs), fn_path, self.call_timeout
            )
        if reply[0] == "ok":
            return reply[1]
        _tag, etype, msg, tb = reply
        raise RemoteError(self.host, etype, msg, tb)

    def close(self) -> None:
        with self._lock:
            if self._proc is not None:
                try:
                    if self._proc.stdin:
                        self._proc.stdin.close()  # EOF → agent loop returns
                    self._proc.wait(timeout=10)
                except (subprocess.TimeoutExpired, OSError):
                    # A wedged transport (e.g. partitioned ssh) must not
                    # block or abort shutdown — kill and reap.
                    self._proc.kill()
                    self._proc.wait()
                finally:
                    self._proc = None

    def __del__(self):  # best-effort cleanup
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def agent_env_with_repo() -> dict:
    """Subprocess env whose PYTHONPATH can import this blit checkout (local
    agents in tests/dev trees; installed deployments don't need it)."""
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env
