"""Remote worker transport: one ``blit.agent`` subprocess per host over ssh.

The rebuild of the reference's ``Distributed.addprocs(hosts; tunnel=true)``
star topology (src/gbt.jl:28-34): the main process starts one agent per
host, ships ``(function, args)`` requests, and gathers pickled results.
ssh provides the authenticated, tunneled byte stream exactly as it does for
Distributed.jl; there are no worker↔worker channels (the TPU data plane in
blit.parallel.mesh is where cross-worker reduction lives).

``RemoteWorker`` is used by :class:`blit.parallel.pool.WorkerPool` with
``backend="remote"``.  Tests exercise the full wire protocol with a local
``python -m blit.agent`` transport (no sshd needed); production uses
:func:`ssh_command`.
"""

from __future__ import annotations

import logging
import os
import pickle
import subprocess
import sys
import threading
from typing import Callable, List, Optional, Sequence

from blit.agent import MAGIC, _SAFE_GLOBALS_RESPONSE, read_msg, write_msg

log = logging.getLogger("blit.remote")

# Max bytes of ssh/rc banner noise tolerated before the agent's handshake.
_BANNER_SCAN_LIMIT = 1 << 16


def _await_banner(stream, host: str) -> None:
    """Consume bytes until the agent's MAGIC handshake appears (discarding
    any login-shell banner a remote rc file printed), or fail loudly."""
    window = b""
    scanned = 0
    while True:
        b = stream.read(1)
        if not b:
            raise RemoteError(
                host, "AgentDied",
                f"agent stream closed before handshake (scanned {scanned}B)",
                "",
            )
        scanned += 1
        window = (window + b)[-len(MAGIC):]
        if window == MAGIC:
            if scanned > len(MAGIC):
                log.info("%s: skipped %dB of pre-handshake banner",
                         host, scanned - len(MAGIC))
            return
        if scanned > _BANNER_SCAN_LIMIT:
            raise RemoteError(
                host, "NoHandshake",
                f"no agent handshake within {_BANNER_SCAN_LIMIT}B — is "
                "blit importable on the remote host?", "",
            )


class RemoteError(RuntimeError):
    """A worker-side exception, carrying the remote type/message/traceback."""

    def __init__(self, host: str, etype: str, msg: str, tb: str):
        super().__init__(f"[{host}] {etype}: {msg}")
        self.host = host
        self.etype = etype
        self.remote_traceback = tb


def ssh_command(
    host: str,
    python: str = "python3",
    ssh_opts: Sequence[str] = ("-o", "BatchMode=yes"),
) -> List[str]:
    """The production transport: ``ssh <host> <python> -m blit.agent``
    (blit must be importable on the remote host, the analog of the
    reference's shared ``@BLDistributedDataProducts`` project environment,
    src/gbt.jl:17)."""
    return ["ssh", *ssh_opts, host, python, "-m", "blit.agent"]


def local_agent_command() -> List[str]:
    """In-host transport (tests; single-machine use): the same agent,
    spawned directly."""
    return [sys.executable, "-m", "blit.agent"]


class RemoteWorker:
    """One agent subprocess + the request/response framing to talk to it.

    One outstanding call at a time (guarded by a lock), matching the
    reference's one-``@spawnat``-per-worker usage; the pool's thread
    executor provides cross-worker concurrency.
    """

    def __init__(self, host: str, command: Optional[Sequence[str]] = None,
                 env: Optional[dict] = None):
        self.host = host
        self.command = list(command) if command else ssh_command(host)
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._env = env

    def _ensure(self) -> subprocess.Popen:
        if self._proc is None or self._proc.poll() is not None:
            self._proc = subprocess.Popen(
                self.command,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                env=self._env,
            )
            _await_banner(self._proc.stdout, self.host)
            log.info("agent for %s started (pid %d)", self.host, self._proc.pid)
        return self._proc

    def call(self, fn: Callable, *args, **kwargs):
        """Invoke ``fn`` (a blit callable) on the remote host."""
        fn_path = f"{fn.__module__}.{fn.__qualname__}"
        with self._lock:
            proc = self._ensure()
            try:
                write_msg(proc.stdin, (fn_path, args, kwargs))
                # Responses get the narrower allow-list: no ``re._compile``
                # (a compromised peer must not hand the client a pathological
                # regex; results are arrays/records/dicts only).  No drain on
                # oversize either — the refusal below kills the worker, so
                # pulling a multi-GiB body through the ssh pipe first would
                # be pure waste.
                reply = read_msg(
                    proc.stdout,
                    safe_globals=_SAFE_GLOBALS_RESPONSE,
                    drain_oversized=False,
                )
            except (BrokenPipeError, EOFError) as e:
                try:
                    rc = proc.wait(timeout=5)  # reap; no zombie
                except subprocess.TimeoutExpired:
                    proc.kill()
                    rc = proc.wait()
                self._proc = None
                raise RemoteError(
                    self.host, "AgentDied",
                    f"agent exited (rc={rc}) during {fn_path}: {e}", "",
                ) from e
            except pickle.UnpicklingError as e:
                # A refused response (oversized / disallowed global) means
                # the peer is misbehaving or compromised; don't trust the
                # stream again — kill and respawn on next use.
                proc.kill()
                proc.wait()
                self._proc = None
                raise RemoteError(
                    self.host, "WireRefused",
                    f"response refused during {fn_path}: {e}", "",
                ) from e
        if reply[0] == "ok":
            return reply[1]
        _tag, etype, msg, tb = reply
        raise RemoteError(self.host, etype, msg, tb)

    def close(self) -> None:
        with self._lock:
            if self._proc is not None:
                try:
                    if self._proc.stdin:
                        self._proc.stdin.close()  # EOF → agent loop returns
                    self._proc.wait(timeout=10)
                except (subprocess.TimeoutExpired, OSError):
                    # A wedged transport (e.g. partitioned ssh) must not
                    # block or abort shutdown — kill and reap.
                    self._proc.kill()
                    self._proc.wait()
                finally:
                    self._proc = None

    def __del__(self):  # best-effort cleanup
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def agent_env_with_repo() -> dict:
    """Subprocess env whose PYTHONPATH can import this blit checkout (local
    agents in tests/dev trees; installed deployments don't need it)."""
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env
