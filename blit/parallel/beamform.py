"""Coherent multibeam (tied-array) beamforming over the device mesh.

BASELINE.json config 4: "per-bank phase-rotate + psum across 8 chips →
64-beam tied-array filterbank".  The structural analog in SURVEY.md §2.4:
coherent beamforming's cross-chip ``psum`` is the tensor-parallel reduction
of this framework.

Data model: the *antenna* axis is sharded across a mesh axis (default
``bank``) — each chip holds a contiguous block of antennas' voltages for the
whole (local) frequency range.  Per beam, each chip phase-rotates its
antennas by the geometric-delay phasor and partially sums them (one MXU
matmul over the antenna axis); the ``psum`` over the mesh axis completes the
tied-array sum.  Detection + integration then reuse the single-chip kernels.

The reference has no beamforming (it reads post-rawspec products) — this is
the capability extension BASELINE.json prescribes, built so the per-chip
math is plain jnp and the collective is a single explicit ``psum``.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blit.ops.channelize import integrate

ANT_AXIS_DEFAULT = "bank"


def delay_weights(
    delays_s: jax.Array, freqs_hz: jax.Array, amplitudes: Optional[jax.Array] = None
) -> jax.Array:
    """Per-(beam, antenna, channel) phasors from geometric delays.

    ``delays_s``: (nbeam, nant) seconds; ``freqs_hz``: (nchan,) sky
    frequencies of the coarse channels.  Returns complex64 weights
    ``exp(-2πi f τ)`` shaped (nbeam, nant, nchan), optionally scaled by
    per-antenna ``amplitudes`` (nbeam, nant) or (nant,).
    """
    phase = -2.0 * jnp.pi * delays_s[..., None] * freqs_hz[None, None, :]
    w = jnp.exp(1j * phase.astype(jnp.float32))
    if amplitudes is not None:
        amp = jnp.asarray(amplitudes)
        if amp.ndim == 1:
            amp = amp[None, :]
        w = w * amp[..., None]
    return w.astype(jnp.complex64)


def _local_beams(v: jax.Array, w: jax.Array) -> jax.Array:
    """Partial tied-array sum over this chip's antennas.

    ``v``: (nant_local, nchan, ntime, npol) complex voltages;
    ``w``: (nbeam, nant_local, nchan) weights.
    Returns (nbeam, nchan, ntime, npol) partial beam voltages.  The
    contraction over antennas is a batched matmul (MXU work).
    """
    return jnp.einsum("bac,actp->bctp", w, v)


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "nint", "detect")
)
def beamform(
    voltages: jax.Array,
    weights: jax.Array,
    *,
    mesh: Mesh,
    axis: str = ANT_AXIS_DEFAULT,
    nint: int = 1,
    detect: bool = True,
) -> jax.Array:
    """Form tied-array beams across the mesh.

    Args:
      voltages: complex64 ``(nant, nchan, ntime, npol)``, antenna axis
        sharded over ``axis`` (see :func:`antenna_sharding`).
      weights: complex64 ``(nbeam, nant, nchan)`` phasors (antenna axis
        sharded identically).
      detect: True → per-beam total power ``(nbeam, nchan, ntime_out, npol)``
        float32 integrated by ``nint``; False → raw beam voltages
        ``(nbeam, nchan, ntime, npol)`` complex64 (for downstream fine
        channelization).

    The only communication is one ``psum`` over ``axis`` — partial antenna
    sums travel, never raw voltages.
    """
    def step(v, w):
        beams = _local_beams(v, w)
        beams = jax.lax.psum(beams, axis)
        if detect:
            p = (beams.real**2 + beams.imag**2).astype(jnp.float32)
            # (nbeam, nchan, ntime, npol): integrate() groups along axis -2,
            # which is time here.
            return integrate(p, nint)
        return beams

    return jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(axis), P(None, axis)),
        out_specs=P(),
        check_vma=False,  # psum output is axis-invariant
    )(voltages, weights)


def antenna_sharding(mesh: Mesh, axis: str = ANT_AXIS_DEFAULT) -> NamedSharding:
    """Sharding for (nant, nchan, ntime, npol) voltages: antennas over
    ``axis``, everything else replicated."""
    return NamedSharding(mesh, P(axis))


def weight_sharding(mesh: Mesh, axis: str = ANT_AXIS_DEFAULT) -> NamedSharding:
    """Sharding for (nbeam, nant, nchan) weights, matching
    :func:`antenna_sharding`."""
    return NamedSharding(mesh, P(None, axis))


def beamform_np(voltages: np.ndarray, weights: np.ndarray, nint: int = 1,
                detect: bool = True) -> np.ndarray:
    """NumPy golden reference for :func:`beamform` (tests)."""
    beams = np.einsum("bac,actp->bctp", weights, voltages)
    if not detect:
        return beams
    p = (beams.real**2 + beams.imag**2).astype(np.float32)
    if nint > 1:
        b, c, t, q = p.shape
        p = p.reshape(b, c, t // nint, nint, q).sum(axis=3)
    return p
