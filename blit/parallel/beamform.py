"""Coherent multibeam (tied-array) beamforming over the device mesh.

BASELINE.json config 4: "per-bank phase-rotate + psum across 8 chips →
64-beam tied-array filterbank".  The structural analog in SURVEY.md §2.4:
coherent beamforming's cross-chip ``psum`` is the tensor-parallel reduction
of this framework.

Data model: the *antenna* axis is sharded across a mesh axis (default
``bank``) — each chip holds a contiguous block of antennas' voltages for the
whole (local) frequency range.  Per beam, each chip phase-rotates its
antennas by the geometric-delay phasor and partially sums them (one MXU
matmul over the antenna axis); the ``psum`` over the mesh axis completes the
tied-array sum.  Detection + integration then reuse the single-chip kernels.

TPU note: the compute is **planar** — complex values travel as ``(re, im)``
pairs of float32 arrays, the blit-wide convention (blit/ops/dft.py), because
this TPU backend implements no complex-dtype HLOs (DESIGN.md §1; not even
complex ``device_put`` executes).  The public entry points accept either
planar pairs (the TPU path) or complex arrays (CPU/GPU convenience — output
dtype follows input).  One complex contraction = 4 real MXU einsums.

The reference has no beamforming (it reads post-rawspec products) — this is
the capability extension BASELINE.json prescribes, built so the per-chip
math is plain jnp and the collective is a single explicit ``psum``.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax

from blit.compat import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blit.ops.channelize import integrate
from blit.ops.dft import ComplexOrPlanar, Planar, as_planar

ANT_AXIS_DEFAULT = "bank"

# Dispatch resolution of the most recent beamform(layout="chan") TRACE
# (the blit.ops.channelize._LAST_PLAN convention): silent fallbacks must
# be attributable — the bench asserts the fused kernel actually ran
# behind its beamform_fused_gbps number.
_LAST_PLAN: dict = {}


def last_beamform_plan() -> dict:
    """The most recent chan-layout dispatch decision (``{"layout":
    "chan", "fused": bool}``; empty until a trace happens — a jit cache
    hit does not refresh it)."""
    return dict(_LAST_PLAN)


def delay_weights_planar(
    delays_s: jax.Array,
    freqs_hz: jax.Array,
    amplitudes: Optional[jax.Array] = None,
) -> Planar:
    """Per-(beam, antenna, channel) phasors from geometric delays, planar.

    ``delays_s``: (nbeam, nant) seconds; ``freqs_hz``: (nchan,) sky
    frequencies of the coarse channels.  Returns ``(wr, wi)`` float32 pairs
    shaped (nbeam, nant, nchan) holding ``cos/sin`` of ``-2π f τ`` —
    real-valued trig only, so this runs on the complex-free TPU backend.
    Optionally scaled by per-antenna ``amplitudes`` (nbeam, nant) or (nant,).
    """
    phase = (-2.0 * jnp.pi * delays_s[..., None] * freqs_hz[None, None, :]).astype(
        jnp.float32
    )
    wr, wi = jnp.cos(phase), jnp.sin(phase)
    if amplitudes is not None:
        amp = jnp.asarray(amplitudes)
        if amp.ndim == 1:
            amp = amp[None, :]
        wr = wr * amp[..., None]
        wi = wi * amp[..., None]
    return wr, wi


def delay_weights(
    delays_s: jax.Array, freqs_hz: jax.Array, amplitudes: Optional[jax.Array] = None
) -> jax.Array:
    """Complex-dtype convenience over :func:`delay_weights_planar`:
    ``exp(-2πi f τ)`` shaped (nbeam, nant, nchan) complex64.  CPU/GPU only —
    on the complex-free TPU backend use the planar form directly."""
    wr, wi = delay_weights_planar(delays_s, freqs_hz, amplitudes)
    return jax.lax.complex(wr, wi).astype(jnp.complex64)


def _local_beams_planar(
    vr: jax.Array, vi: jax.Array, wr: jax.Array, wi: jax.Array
) -> Planar:
    """Partial tied-array sum over this chip's antennas, planar.

    ``v``: (nant_local, nchan, ntime, npol); ``w``: (nbeam, nant_local,
    nchan).  Returns (nbeam, nchan, ntime, npol) partial beam voltages as a
    (re, im) pair.  One complex contraction over antennas = 4 real batched
    matmuls (MXU work); XLA fuses the combines.
    """
    rr = jnp.einsum("bac,actp->bctp", wr, vr)
    ii = jnp.einsum("bac,actp->bctp", wi, vi)
    ri = jnp.einsum("bac,actp->bctp", wr, vi)
    ir = jnp.einsum("bac,actp->bctp", wi, vr)
    return rr - ii, ri + ir


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "nint", "detect", "layout")
)
def beamform(
    voltages: ComplexOrPlanar,
    weights: ComplexOrPlanar,
    *,
    mesh: Mesh,
    axis: str = ANT_AXIS_DEFAULT,
    nint: int = 1,
    detect: bool = True,
    layout: str = "antenna",
):
    """Form tied-array beams across the mesh.

    Args:
      voltages: ``(nant, nchan, ntime, npol)`` antenna voltages — a planar
        ``(re, im)`` float32 pair (TPU path) or one complex64 array (CPU/GPU
        convenience).  Antenna axis sharded over ``axis`` (see
        :func:`antenna_sharding`).
      weights: ``(nbeam, nant, nchan)`` phasors from
        :func:`delay_weights_planar` (planar) or :func:`delay_weights`
        (complex), antenna axis sharded identically.
      detect: True → per-beam total power ``(nbeam, nchan, ntime_out, npol)``
        float32 integrated by ``nint``; False → raw beam voltages
        ``(nbeam, nchan, ntime, npol)`` — planar pair unless *both* inputs
        were complex (then complex64, for downstream fine channelization on
        complex-capable backends).
      layout: ``"antenna"`` (the shapes above) or ``"chan"`` — the packed,
        chan-major opt-in (voltages ``(nchan, nant, npol, ntime)``,
        weights ``(nchan, nbeam, nant)``, detected output ``(nchan,
        nbeam, npol, ntime_out)``; load packed planes via
        ``load_antennas_mesh(layout="chan")`` and pack weights with
        :func:`blit.ops.pallas_beamform.pack_weights`).  When every
        antenna is chip-local (``mesh.shape[axis] == 1``), ``detect=True``
        runs the VMEM-resident fused beamform+detect kernel — beam planes
        never touch HBM; measured **2.1x** the einsum path at the bench
        shape (DESIGN.md §9 r5) — with einsum fallback elsewhere.

    The only communication is one ``psum`` over ``axis`` — partial antenna
    sums travel, never raw voltages.
    """
    if layout not in ("antenna", "chan"):
        raise ValueError(f"bad layout {layout!r}")
    if layout == "chan":
        return _beamform_chan(
            voltages, weights, mesh=mesh, axis=axis, nint=nint,
            detect=detect,
        )
    vr, vi, v_cplx = as_planar(voltages)
    wr, wi, w_cplx = as_planar(weights)
    complex_out = v_cplx and w_cplx
    # bf16-RESIDENT voltages run the whole contraction + psum in bf16
    # (measured +26% end-to-end at the bench shape, DESIGN.md §9 r5
    # addendum: half the HBM voltage reads and half the ICI psum bytes;
    # 8-bit RAW samples are exact in bf16, the MXU multiplies at bf16
    # precision either way, so the only new rounding is the weight
    # phasors and the bf16 partial sums — ~1e-2 max rel err on detected
    # power).  Opt in by loading bf16 planes
    # (``load_antennas_mesh(dtype="bfloat16")``).
    bf16 = vr.dtype == jnp.bfloat16

    def step(vr, vi, wr, wi):
        if bf16:
            wr, wi = wr.astype(jnp.bfloat16), wi.astype(jnp.bfloat16)
        br, bi = _local_beams_planar(vr, vi, wr, wi)
        br, bi = jax.lax.psum((br, bi), axis)
        if detect:
            br = br.astype(jnp.float32)
            bi = bi.astype(jnp.float32)
            return integrate(br**2 + bi**2, nint)
        return br, bi

    out_specs = P() if detect else (P(), P())
    out = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(None, axis), P(None, axis)),
        out_specs=out_specs,
        check_vma=False,  # psum output is axis-invariant
    )(vr, vi, wr, wi)
    if detect:
        return out
    br, bi = out
    return jax.lax.complex(br, bi) if complex_out else (br, bi)


def _beamform_chan(
    voltages: ComplexOrPlanar,
    weights: ComplexOrPlanar,
    *,
    mesh: Mesh,
    axis: str,
    nint: int,
    detect: bool,
):
    """The packed chan-major path behind ``beamform(layout="chan")``.

    Dispatch: all-antennas-local + detect + TPU backend + eligible shape
    → the fused Pallas kernel (blit/ops/pallas_beamform.py); otherwise
    packed einsums with the same psum/detect semantics as the antenna
    layout.  Detection under a psum is only fusable when the antenna
    axis is whole per chip (power of the sum != sum of powers), hence
    the ``mesh.shape[axis] == 1`` gate.
    """
    from blit.ops import pallas_beamform as PB
    from blit.ops.channelize import _MATMUL_ONLY_BACKENDS

    vr, vi, v_cplx = as_planar(voltages)
    wr, wi, w_cplx = as_planar(weights)
    complex_out = v_cplx and w_cplx
    bf16 = vr.dtype == jnp.bfloat16
    nchan, nant, npol, ntime = vr.shape
    nbeam = wr.shape[1]
    if detect and nint > 1 and ntime % nint:
        # Same clear error as integrate() on the antenna path — the raw
        # reshape below would fail with a cryptic trace-time message.
        raise ValueError(
            f"integrate: nint={nint} does not divide ntime={ntime}"
        )
    fuse = (
        detect
        and mesh.shape[axis] == 1
        and jax.default_backend() in _MATMUL_ONLY_BACKENDS
        and PB.pick_tile(nant, nbeam, npol, ntime, nint,
                         itemsize=vr.dtype.itemsize) is not None
    )
    # Dispatch provenance, the channelize _LAST_PLAN convention: the
    # fuse/fallback decision is otherwise invisible, and the bench/smoke
    # must be able to assert the pallas path actually ran.
    _LAST_PLAN.clear()
    _LAST_PLAN.update({"layout": "chan", "fused": fuse})

    def step(vr, vi, wr, wi):
        if bf16:
            wr, wi = wr.astype(jnp.bfloat16), wi.astype(jnp.bfloat16)
        if fuse:
            return PB.fused_beamform_detect(vr, vi, wr, wi, nint=nint)
        kw = dict(preferred_element_type=jnp.float32) if not bf16 else {}
        rr = jnp.einsum("cba,capt->cbpt", wr, vr, **kw)
        ii = jnp.einsum("cba,capt->cbpt", wi, vi, **kw)
        ri = jnp.einsum("cba,capt->cbpt", wr, vi, **kw)
        ir = jnp.einsum("cba,capt->cbpt", wi, vr, **kw)
        br, bi = rr - ii, ri + ir
        br, bi = jax.lax.psum((br, bi), axis)
        if detect:
            br = br.astype(jnp.float32)
            bi = bi.astype(jnp.float32)
            power = br**2 + bi**2  # (c, b, p, t): time is LAST here,
            # so blit.ops.channelize.integrate (axis -2) does not apply.
            if nint > 1:
                c_, b_, p_, t_ = power.shape
                power = power.reshape(c_, b_, p_, t_ // nint, nint).sum(-1)
            return power
        return br, bi

    out_specs = P() if (detect or fuse) else (P(), P())
    out = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, None, axis),
                  P(None, None, axis)),
        out_specs=out_specs,
        check_vma=False,
    )(vr, vi, wr, wi)
    if detect:
        return out
    br, bi = out
    # Same complex-output contract as the antenna layout: complex64 when
    # BOTH inputs were complex, else the planar pair.
    return jax.lax.complex(br, bi) if complex_out else (br, bi)


# -- windowed streaming beamforming ----------------------------------------

def beamform_stream(
    feed,
    weights: ComplexOrPlanar,
    *,
    mesh: Mesh,
    axis: str = ANT_AXIS_DEFAULT,
    nint: int = 1,
    layout: str = "antenna",
    timeline=None,
    stall_timeout_s=None,
):
    """Stream detected tied-array beam powers over a windowed feed
    (:class:`blit.parallel.antenna.AntennaStream`) — the arbitrarily-
    long-recording form of ``beamform(detect=True)``.

    Yields one float32 power slab per window, in time order:
    ``(nbeam, nchan, wt // nint, npol)`` (antenna layout) /
    ``(nchan, nbeam, npol, wt // nint)`` (chan layout).  Concatenated
    along the time axis the slabs are byte-identical to the one-shot
    ``beamform`` on the same span — per-sample phase/detect math and the
    per-``nint`` integration folds are window-local, so windowing changes
    no float operation (the equivalence tests pin this, arbitrary
    ``start_sample`` included).

    Every window must hold a whole number of integrations (pick
    ``window_samples`` — and a total span — divisible by ``nint``);
    integration therefore never straddles a window boundary, the same
    chunk rule :class:`blit.pipeline.RawReducer` applies via
    ``chunk_frames``.

    Pipelining rides the shared output plane (blit/outplane.py, ISSUE 4):
    window ``w`` dispatches asynchronously and its device output goes to
    the :class:`~blit.outplane.OutputRotation` readback thread, which
    waits out the collectives and fetches the power slab while this
    thread dispatches ``w+1`` and the feed's producer reads ``w+2`` —
    host read, H2D transfer, compute and D2H readback all overlap.  A
    window's host slot refills the moment its compute synchronized (the
    ``on_consumed`` hook), exactly the old lag-1 release point.

    Stage timings land in ``timeline``: ``dispatch`` (async), ``device``
    (readback-thread wait on a window's collectives), ``readback``
    (device→host slab fetch, bytes).
    """
    from blit.observability import Timeline
    from blit.outplane import OutputRotation
    from blit.parallel.mesh import psum_ici_bytes, record_ici

    tl = timeline if timeline is not None else Timeline()
    # depth=2 reproduces the old lag-1 overlap: put(window w) returns
    # once w-1's slab is fetched, leaving w in un-synchronized flight
    # while this thread dispatches w+1 — and a window's feed slot frees
    # at its sync (before the fetch), so the double-buffered feed
    # (prefetch_depth=2) always has a slot free when the consumer asks
    # for the next window.
    rot = OutputRotation(depth=2, timeline=tl, reuse=False,
                         name="blit-bf-readback",
                         stall_timeout_s=stall_timeout_s)
    from blit import observability

    axis_size = mesh.shape[axis]
    nbeam = np.shape(weights[0] if isinstance(weights, tuple) else weights)[
        1 if layout == "chan" else 0
    ]
    try:
        with observability.span("beamform.stream"):
            for win in feed:
                if win.ntime % nint:
                    raise ValueError(
                        f"window {win.index} holds {win.ntime} samples — not a "
                        f"whole number of nint={nint} integrations; choose "
                        "window_samples (and span) divisible by nint"
                    )
                if win.masked:
                    # Degraded continuation (feed masked a failed antenna): the
                    # accumulated powers carry its zero weight; flag it in the
                    # driver's per-window stage tables too.
                    tl.count("masked_antennas", len(win.masked))
                with observability.span("beamform.window", i=win.index), \
                        tl.stage("dispatch", byte_free=True):
                    out = beamform(
                        win.arrays, weights, mesh=mesh, axis=axis, nint=nint,
                        detect=True, layout=layout,
                    )
                if axis_size > 1:
                    # The fused per-window psum moves the partial beam
                    # planes (pre-detect, full time extent) over ICI —
                    # account it per window (mesh.ici stage + byte hist;
                    # its latency is only separable on the bench's pure
                    # collective leg, MESH_HISTS).
                    vr0 = win.arrays[0]
                    nchan_w = (vr0.shape[0] if layout == "chan"
                               else vr0.shape[1])
                    plane = (2 * nbeam * nchan_w * win.ntime
                             * (vr0.shape[-1 if layout != "chan" else 2])
                             * vr0.dtype.itemsize)
                    record_ici(tl, "psum",
                               psum_ici_bytes(plane, axis_size))
                for slab in rot.put(out, on_consumed=win.release):
                    yield slab.data
            for slab in rot.drain():
                yield slab.data
    finally:
        rot.close()


def beamform_accumulate(
    feed,
    weights: ComplexOrPlanar,
    *,
    mesh: Mesh,
    axis: str = ANT_AXIS_DEFAULT,
    layout: str = "antenna",
    timeline=None,
):
    """Total integrated beam power over a whole windowed feed, the
    integration state carried across window boundaries ON-DEVICE: each
    window's power (integrated over its full extent) folds into a donated
    float32 accumulator, and one ``(nbeam, nchan, 1, npol)`` (antenna
    layout) / ``(nchan, nbeam, npol, 1)`` (chan layout) array crosses
    back at the end — the bounded-output companion to
    :func:`beamform_stream` for total-power monitoring of recordings of
    any length."""
    import jax as _jax

    from blit import observability
    from blit.observability import Timeline
    from blit.outplane import FoldInFlight
    from blit.parallel.mesh import ShardedAccumulator

    tl = timeline if timeline is not None else Timeline()
    # The total-power accumulator carries its partition rule (ISSUE 9):
    # psum output is replicated ("beamform_acc"), and the donated add
    # below preserves that — ShardedAccumulator asserts it per fold.
    acc = ShardedAccumulator(mesh, "beamform_acc")
    flight = FoldInFlight(tl, depth=1)
    add = _jax.jit(lambda a, p: a + p, donate_argnums=0)
    with observability.span("beamform.accumulate"):
        for win in feed:
            if win.masked:
                tl.count("masked_antennas", len(win.masked))
            # Lag-1 (shared FoldInFlight core, ISSUE 4): wait for the
            # previous window's fold (its power output implies its input
            # was consumed) and recycle its slot BEFORE dispatching the
            # next fold.
            flight.make_room()
            with tl.stage("dispatch", byte_free=True):
                p = beamform(
                    win.arrays, weights, mesh=mesh, axis=axis,
                    nint=win.ntime, detect=True, layout=layout,
                )
                if acc.value is None:
                    acc.init(p)
                else:
                    acc.fold(add, p)
            flight.admit(win, p)
        if acc.value is None:
            raise ValueError("beamform_accumulate: feed yielded no windows")
        with tl.stage("device", byte_free=True):
            acc.value.block_until_ready()
        # The terminal sync above proved every fold complete — release the
        # tail without a second wait.
        flight.drain(synced=True)
    return acc.value


def antenna_sharding(mesh: Mesh, axis: str = ANT_AXIS_DEFAULT) -> NamedSharding:
    """Sharding for (nant, nchan, ntime, npol) voltages: antennas over
    ``axis``, everything else replicated.  ``jax.device_put`` applies it to a
    planar pair and a complex array alike (pytree leaves share it)."""
    return NamedSharding(mesh, P(axis))


def weight_sharding(mesh: Mesh, axis: str = ANT_AXIS_DEFAULT) -> NamedSharding:
    """Sharding for (nbeam, nant, nchan) weights, matching
    :func:`antenna_sharding`."""
    return NamedSharding(mesh, P(None, axis))


def beamform_np(voltages: np.ndarray, weights: np.ndarray, nint: int = 1,
                detect: bool = True) -> np.ndarray:
    """NumPy golden reference for :func:`beamform` (tests)."""
    beams = np.einsum("bac,actp->bctp", weights, voltages)
    if not detect:
        return beams
    p = (beams.real**2 + beams.imag**2).astype(np.float32)
    if nint > 1:
        b, c, t, q = p.shape
        p = p.reshape(b, c, t // nint, nint, q).sum(axis=3)
    return p
