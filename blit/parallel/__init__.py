"""blit.parallel — worker pools (host fan-out) and the TPU device mesh
(collective data plane).

The reference's single parallelism is embarrassingly-parallel fan-out over
ssh workers (SURVEY.md §2.4).  blit splits that into:

- ``pool``: the control plane — a host-side worker pool with pluggable
  backends (local / thread / process), per-call error capture, and ragged
  per-worker results.
- ``mesh`` / ``stitch`` / ``beamform`` / ``correlator``: the data plane —
  the (band, bank) ``jax.sharding.Mesh`` where cross-worker reductions run
  as XLA collectives over ICI instead of main-process concatenation.
"""

from blit.parallel.pool import WorkerError, WorkerPool, setup_workers, current_pool

__all__ = ["WorkerError", "WorkerPool", "setup_workers", "current_pool"]


def __getattr__(name):
    # Lazy: mesh/beamform/correlator pull in JAX; pool-only users stay light.
    if name in ("mesh", "beamform", "correlator", "scan", "antenna",
                "multihost", "remote"):
        import importlib

        return importlib.import_module(f"blit.parallel.{name}")
    raise AttributeError(f"module 'blit.parallel' has no attribute {name!r}")
