"""Host-side worker pool — the control plane.

Rebuild of the reference's ``Distributed.addprocs``-over-ssh star topology
(``GBT.setupworkers``, src/gbt.jl:12-46) as a pluggable pool:

- ``local``   — synchronous in-process calls (debugging, tests);
- ``thread``  — one thread per worker (I/O-bound crawls and reads; the
  default, since the heavy lifting releases the GIL in NumPy/HDF5);
- ``process`` — a process pool (CPU-bound host-side work).

Differences from the reference, by design (SURVEY.md §5 "Failure detection"):

- ``setup_workers`` with a live pool returns *the live pool* (the reference
  warns and returns an empty list — src/gbt.jl:20-22, listed as a wart);
- every fan-out supports ``on_error="capture"`` returning ``WorkerError``
  placeholders instead of aborting the whole broadcast on one bad worker
  (the reference's ``fetch.`` raises on the first RemoteException).
"""

from __future__ import annotations

import logging
from concurrent.futures import Future, ProcessPoolExecutor, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

from blit.config import DEFAULT, SiteConfig

log = logging.getLogger("blit.pool")


@dataclass
class WorkerError:
    """Captured per-worker failure (returned, not raised, under
    ``on_error='capture'``)."""

    worker: int
    host: str
    error: Exception

    def __bool__(self):
        return False


@dataclass
class _Worker:
    wid: int
    host: str


class WorkerPool:
    """A pool with one logical worker per host, ordered 1:1 with ``hosts``
    (reference contract: README.md:58-64 — worker i serves hosts[i])."""

    def __init__(
        self,
        hosts: Sequence[str],
        backend: str = "thread",
        config: SiteConfig = DEFAULT,
    ):
        if backend not in ("local", "thread", "process"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.config = config
        # Worker ids start at 1; id 0 is "the main process" by convention,
        # mirroring Distributed.jl's pid-1 master.
        self.workers: List[_Worker] = [
            _Worker(i + 1, h) for i, h in enumerate(hosts)
        ]
        self._exec = None
        if backend == "thread":
            self._exec = ThreadPoolExecutor(
                max_workers=max(1, len(self.workers)), thread_name_prefix="blit-w"
            )
        elif backend == "process":
            self._exec = ProcessPoolExecutor()

    # -- introspection ----------------------------------------------------
    @property
    def worker_ids(self) -> List[int]:
        return [w.wid for w in self.workers]

    @property
    def hosts(self) -> List[str]:
        return [w.host for w in self.workers]

    def host_of(self, wid: int) -> str:
        return self.workers[wid - 1].host

    def __len__(self):
        return len(self.workers)

    # -- execution --------------------------------------------------------
    def _submit(self, fn: Callable, *args, **kw) -> Future:
        if self._exec is None:
            f: Future = Future()
            try:
                f.set_result(fn(*args, **kw))
            except Exception as e:  # noqa: BLE001 - captured per-call
                f.set_exception(e)
            return f
        return self._exec.submit(fn, *args, **kw)

    def run_on(
        self,
        wids: Sequence[int],
        fn: Callable,
        argtuples: Sequence[tuple],
        kwargs: Optional[dict] = None,
        on_error: str = "raise",
    ) -> List[Any]:
        """One call per (worker, argtuple) pair — the reference's
        ``@spawnat worker fn(args...)`` + ``fetch.`` fan-out/fan-in
        (src/gbt.jl:54-57, 75-78).  Results are ordered like ``wids``."""
        if len(wids) != len(argtuples):
            raise ValueError("wids and argtuples must have the same length")
        kwargs = kwargs or {}
        futures = [
            self._submit(fn, *args, **kwargs) for args in argtuples
        ]
        results: List[Any] = []
        for wid, fut in zip(wids, futures):
            try:
                results.append(fut.result())
            except Exception as e:  # noqa: BLE001
                if on_error == "capture":
                    log.warning("worker %d (%s) failed: %s", wid, self.host_of(wid), e)
                    results.append(WorkerError(wid, self.host_of(wid), e))
                else:
                    raise
        return results

    def broadcast(
        self,
        fn: Callable,
        kwargs_per_worker: Optional[Callable[[_Worker], dict]] = None,
        on_error: str = "raise",
    ) -> List[Any]:
        """Call ``fn`` once on every worker (reference: the getinventories
        fan-out, src/gbt.jl:54-57)."""
        futures = []
        for w in self.workers:
            kw = kwargs_per_worker(w) if kwargs_per_worker else {}
            futures.append(self._submit(fn, **kw))
        results = []
        for w, fut in zip(self.workers, futures):
            try:
                results.append(fut.result())
            except Exception as e:  # noqa: BLE001
                if on_error == "capture":
                    log.warning("worker %d (%s) failed: %s", w.wid, w.host, e)
                    results.append(WorkerError(w.wid, w.host, e))
                else:
                    raise
        return results

    def shutdown(self):
        if self._exec is not None:
            self._exec.shutdown(wait=True)
            self._exec = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


_current: Optional[WorkerPool] = None


def setup_workers(
    hosts: Optional[Sequence[str]] = None,
    backend: Optional[str] = None,
    config: SiteConfig = DEFAULT,
) -> WorkerPool:
    """Create (or return) the process-wide worker pool.

    Reference: ``GBT.setupworkers`` (src/gbt.jl:12-46).  Where the reference
    refuses to run twice and returns an *empty* pid list, blit returns the
    live pool (the documented fix for that wart, SURVEY.md §2.1)."""
    global _current
    if _current is not None:
        log.warning("workers already set up; returning the live pool")
        return _current
    if hosts is None:
        hosts = config.hosts
    _current = WorkerPool(hosts, backend=backend or config.backend, config=config)
    return _current


def current_pool() -> Optional[WorkerPool]:
    return _current


def reset_pool():
    """Tear down the process-wide pool (tests; elastic re-spawn)."""
    global _current
    if _current is not None:
        _current.shutdown()
        _current = None
