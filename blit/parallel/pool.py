"""Host-side worker pool — the control plane.

Rebuild of the reference's ``Distributed.addprocs``-over-ssh star topology
(``GBT.setupworkers``, src/gbt.jl:12-46) as a pluggable pool:

- ``local``   — synchronous in-process calls (debugging, tests);
- ``thread``  — one thread per worker (I/O-bound crawls and reads; the
  default, since the heavy lifting releases the GIL in NumPy/HDF5);
- ``process`` — a process pool (CPU-bound host-side work);
- ``remote``  — one ``blit.agent`` subprocess per host over ssh
  (blit/parallel/remote.py) — the true analog of the reference's
  ``addprocs``-over-ssh workers, with calls routed to the host that owns
  the files.

Differences from the reference, by design (SURVEY.md §5 "Failure detection"):

- ``setup_workers`` with a live pool returns *the live pool* (the reference
  warns and returns an empty list — src/gbt.jl:20-22, listed as a wart);
- every fan-out supports ``on_error="capture"`` returning ``WorkerError``
  placeholders instead of aborting the whole broadcast on one bad worker
  (the reference's ``fetch.`` raises on the first RemoteException).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, ProcessPoolExecutor, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from blit import faults, observability
from blit.config import DEFAULT, SiteConfig

log = logging.getLogger("blit.pool")


def _traced_call(ctx, wid: int, host: str, fn: Callable, args, kw):
    """Executor-side wrapper for the in-process backends: adopt the
    driver's trace context (thread-locals do not flow into pool threads)
    and record the dispatch as a child span.  Module-level so the process
    backend can pickle it."""
    tr = observability.tracer()
    with tr.activate(ctx):
        with tr.span(f"pool.{getattr(fn, '__name__', 'call')}",
                     worker=wid, host=host):
            return fn(*args, **kw)

# Distinguishes "not given" (inherit SiteConfig) from an explicit None
# (disable the deadline — the reference's blocking behavior).
_UNSET = object()


def _remaining(deadline: Optional[float]) -> Optional[float]:
    """Seconds left until a shared ``time.monotonic()`` deadline (0 once
    past — ``Future.result`` treats 0 as an immediate-expiry poll)."""
    return None if deadline is None else max(0.0, deadline - time.monotonic())


@dataclass
class WorkerError:
    """Captured per-worker failure (returned, not raised, under
    ``on_error='capture'``)."""

    worker: int
    host: str
    error: Exception

    def __bool__(self):
        return False


@dataclass
class _Worker:
    wid: int
    host: str
    remote: Optional[object] = None  # RemoteWorker for backend="remote"
    # Per-host failure circuit (consulted on the remote call path only):
    # repeated AgentDied/CallTimeout trips the host into "degraded" and
    # calls fail fast instead of hammering it (ISSUE 2 tentpole).
    breaker: Optional[faults.CircuitBreaker] = None


class WorkerPool:
    """A pool with one logical worker per host, ordered 1:1 with ``hosts``
    (reference contract: README.md:58-64 — worker i serves hosts[i])."""

    def __init__(
        self,
        hosts: Sequence[str],
        backend: str = "thread",
        config: SiteConfig = DEFAULT,
        transport: Optional[Callable[[str], Sequence[str]]] = None,
        agent_env: Optional[dict] = None,
        call_timeout=_UNSET,
        ping_timeout=_UNSET,
    ):
        """``transport``/``agent_env`` apply to ``backend="remote"`` only:
        ``transport(host)`` returns the agent-spawning command (default:
        ``remote.ssh_command``); tests pass a local-subprocess transport.

        ``call_timeout``/``ping_timeout`` (remote backend) override the
        site config's worker liveness deadlines
        (:class:`blit.parallel.remote.RemoteWorker`); an explicit ``None``
        DISABLES the deadline (blocking ``fetch``, the reference's
        behavior) — omit them to inherit the config."""
        if backend not in ("local", "thread", "process", "remote"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.config = config
        self.call_timeout = (
            config.call_timeout if call_timeout is _UNSET else call_timeout
        )
        self.ping_timeout = (
            config.ping_timeout if ping_timeout is _UNSET else ping_timeout
        )
        # Worker ids start at 1; id 0 is "the main process" by convention,
        # mirroring Distributed.jl's pid-1 master.
        self.workers: List[_Worker] = [
            _Worker(i + 1, h, breaker=faults.CircuitBreaker(
                config.breaker_threshold, config.breaker_cooldown_s))
            for i, h in enumerate(hosts)
        ]
        # Remote-call re-dispatch policy (AgentDied/CallTimeout retries
        # through the existing agent respawn; seeded jitter, injectable
        # sleep — see blit/faults.py).  The policy is the ONE source of
        # truth for both the attempt count and the backoff curve.
        self.retry_policy = config.call_retry_policy()
        self._exec = None
        if backend in ("thread", "remote"):
            self._exec = ThreadPoolExecutor(
                max_workers=max(1, len(self.workers)), thread_name_prefix="blit-w"
            )
        elif backend == "process":
            self._exec = ProcessPoolExecutor()
        if backend == "remote":
            import os

            from blit.parallel.remote import RemoteWorker, ssh_command

            make_cmd = transport or ssh_command
            for w in self.workers:
                # Stamp the agent's identity so its log records and
                # telemetry snapshots carry the worker id (blit/agent.py
                # main reads BLIT_WORKER_ID — ISSUE 5 satellite).  Two
                # routes, because sshd does NOT forward the client's
                # environment: transports that accept ``remote_env``
                # (ssh_command) splice an ``env K=V`` prefix into the
                # remote command line; the local subprocess env below
                # covers direct transports (tests, same-host agents).
                stamp = {"BLIT_WORKER_ID": str(w.wid)}
                if os.environ.get("BLIT_LOG_JSON"):
                    stamp["BLIT_LOG_JSON"] = os.environ["BLIT_LOG_JSON"]
                try:
                    cmd = make_cmd(w.host, remote_env=stamp)
                except TypeError:  # transport without remote_env support
                    cmd = make_cmd(w.host)
                env = dict(agent_env if agent_env is not None else os.environ)
                env.update(stamp)
                w.remote = RemoteWorker(
                    w.host, cmd, env=env,
                    call_timeout=self.call_timeout,
                    ping_timeout=self.ping_timeout,
                )

    # -- introspection ----------------------------------------------------
    @property
    def worker_ids(self) -> List[int]:
        return [w.wid for w in self.workers]

    @property
    def hosts(self) -> List[str]:
        return [w.host for w in self.workers]

    def host_of(self, wid: int) -> str:
        return self.workers[wid - 1].host

    def __len__(self):
        return len(self.workers)

    def health(self) -> List[Dict[str, object]]:
        """Per-worker circuit state for the run report: a degraded run
        must SAY so (``state == "open"`` means the host is degraded and
        calls fail fast until the cooldown probe re-closes it;
        ``half_open`` marks the probe phase — ONE call is in flight
        deciding whether the host re-closes or re-trips, and capacity
        consumers must keep treating it as degraded until it closes,
        or a recovered-then-flaky host flaps the budget — ISSUE 12
        satellite)."""
        out = []
        for w in self.workers:
            snap = w.breaker.snapshot()
            snap["half_open"] = snap["state"] == "half-open"
            out.append({"worker": w.wid, "host": w.host, **snap})
        return out

    # -- execution --------------------------------------------------------
    def _remote_call(self, w: _Worker, fn: Callable, ctx, /, *args, **kw):
        """One remote dispatch under the recovery policy: retry transient
        worker-loss failures (``AgentDied``/``CallTimeout`` — the next
        ``RemoteWorker.call`` respawns the agent) with jittered backoff,
        feeding the per-host circuit breaker.  A tripped breaker fails
        fast with ``RemoteError(etype="HostDegraded")`` until its cooldown
        probe — repeated failures must degrade the host, not hammer it.

        ``ctx`` is the driver's trace context captured at submit time:
        the whole dispatch (attempts included) records as one child span,
        and :meth:`blit.parallel.remote.RemoteWorker.call` ships the
        span's context over the wire so the agent's spans parent onto it
        (ISSUE 5 tentpole #1)."""
        tr = observability.tracer()
        with tr.activate(ctx), tr.span(
            f"pool.{getattr(fn, '__name__', 'call')}",
            worker=w.wid, host=w.host,
        ):
            return self._remote_call_inner(w, fn, *args, **kw)

    def _remote_call_inner(self, w: _Worker, fn: Callable, /, *args, **kw):
        from blit.parallel.remote import RemoteError

        br = w.breaker
        if not br.allow():
            faults.incr("breaker.fastfail")
            raise RemoteError(
                w.host, "HostDegraded",
                f"circuit open after {br.failures} consecutive failures; "
                f"next probe within {br.cooldown_s}s", "",
            )
        attempts = max(1, self.retry_policy.attempts)
        for attempt in range(attempts):
            try:
                result = w.remote.call(fn, *args, **kw)
            except RemoteError as e:
                if e.etype == "AgentDied":
                    # One of the flight recorder's trip conditions
                    # (ISSUE 5 tentpole #4): the incident evidence — the
                    # recent span/stage/fault ring — is dumped while it is
                    # still recent.  Rate-limited inside dump().
                    observability.flight_recorder().dump(
                        f"agent for worker {w.wid} ({w.host}) died: {e}"
                    )
                if br.record_failure():
                    faults.incr("breaker.trip")
                    log.error(
                        "worker %d (%s) tripped its circuit breaker after "
                        "%d consecutive failures (%s); host degraded for "
                        "%.0fs", w.wid, w.host, br.failures, e.etype,
                        br.cooldown_s,
                    )
                    observability.flight_recorder().dump(
                        f"circuit breaker tripped for worker {w.wid} "
                        f"({w.host}) after {br.failures} consecutive "
                        f"failures ({e.etype})"
                    )
                transient = e.etype in ("AgentDied", "CallTimeout")
                # br.closed() is the non-consuming check: once the breaker
                # tripped mid-loop, stop re-dispatching to the sick host.
                if (not transient or attempt == attempts - 1
                        or not br.closed()):
                    raise
                faults.incr("retry.remote")
                log.warning(
                    "worker %d (%s): %s; re-dispatch %d/%d after backoff",
                    w.wid, w.host, e.etype, attempt + 1, attempts - 1,
                )
                self.retry_policy.backoff(attempt)
            else:
                br.record_success()
                return result
        raise AssertionError("unreachable")

    def _submit(self, worker: _Worker, fn: Callable, /, *args, **kw) -> Future:
        """Dispatch one call for ``worker``.  Shared-filesystem backends run
        it anywhere; the remote backend routes it to that worker's host —
        the reference's ``@spawnat worker`` placement (src/gbt.jl:54-57).

        The caller's ambient trace context is captured HERE (the submit
        thread) and re-activated executor-side, so every backend's
        dispatch records as a child span of the driver operation that
        fanned it out."""
        ctx = observability.tracer().context()
        if worker.remote is not None:
            return self._exec.submit(
                self._remote_call, worker, fn, ctx, *args, **kw)
        if self._exec is None:
            f: Future = Future()
            try:
                f.set_result(
                    _traced_call(ctx, worker.wid, worker.host, fn, args, kw))
            except Exception as e:  # noqa: BLE001 - captured per-call
                f.set_exception(e)
            return f
        return self._exec.submit(
            _traced_call, ctx, worker.wid, worker.host, fn, args, kw)

    def run_on(
        self,
        wids: Sequence[int],
        fn: Callable,
        argtuples: Sequence[tuple],
        kwargs: Optional[dict] = None,
        on_error: str = "raise",
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """One call per (worker, argtuple) pair — the reference's
        ``@spawnat worker fn(args...)`` + ``fetch.`` fan-out/fan-in
        (src/gbt.jl:54-57, 75-78).  Results are ordered like ``wids``.

        ``timeout`` bounds the WHOLE fan-in (seconds, one shared deadline
        across the ordered waits — the calls run concurrently, so waiting
        per-future would let worst-case wall clock grow to
        ``len(wids) * timeout``); a late worker raises ``TimeoutError``
        (or becomes a ``WorkerError`` under ``on_error="capture"``).  The
        remote backend's own call deadline also KILLS the wedged agent
        (blit/parallel/remote.py); for the thread/process backends the
        abandoned call keeps running to completion in the background —
        Python offers no safe cancel."""
        if len(wids) != len(argtuples):
            raise ValueError("wids and argtuples must have the same length")
        bad = [w for w in wids if not 1 <= w <= len(self.workers)]
        if bad:
            # wid 0 is the main process and negative/oversized ids are
            # caller bugs — never let them alias a worker via indexing.
            raise ValueError(f"invalid worker ids {bad}; valid range is "
                             f"1..{len(self.workers)}")
        kwargs = kwargs or {}
        futures = [
            self._submit(self.workers[wid - 1], fn, *args, **kwargs)
            for wid, args in zip(wids, argtuples)
        ]
        deadline = None if timeout is None else time.monotonic() + timeout
        results: List[Any] = []
        for i, (wid, fut) in enumerate(zip(wids, futures)):
            try:
                results.append(fut.result(timeout=_remaining(deadline)))
            except Exception as e:  # noqa: BLE001
                if isinstance(e, _FutTimeout) and not fut.done():
                    # A pending future past the deadline is OUR fan-in
                    # timeout: normalize to the builtin with the worker
                    # named (on Py < 3.11 concurrent.futures.TimeoutError
                    # is not even the builtin; on 3.11+ it is, but arrives
                    # message-less).  A TimeoutError RAISED BY the worker
                    # fn leaves fut.done() true and passes through as-is.
                    e = TimeoutError(
                        f"worker {wid} ({self.host_of(wid)}): fan-in "
                        f"deadline of {timeout}s exceeded"
                    )
                if on_error == "capture":
                    log.warning("worker %d (%s) failed: %s", wid, self.host_of(wid), e)
                    results.append(WorkerError(wid, self.host_of(wid), e))
                else:
                    # Aborting the fan-in must not leak the rest of the
                    # broadcast as orphaned background work: cancel every
                    # future the executor has not started yet (started
                    # ones run to completion — Python offers no safe
                    # cancel; the timed-out fut itself is in this range).
                    for later in futures[i:]:
                        later.cancel()
                    raise e
        return results

    def broadcast(
        self,
        fn: Callable,
        kwargs_per_worker: Optional[Callable[[_Worker], dict]] = None,
        on_error: str = "raise",
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """Call ``fn`` once on every worker (reference: the getinventories
        fan-out, src/gbt.jl:54-57).  ``timeout`` bounds the whole fan-in
        (one shared deadline) as in :meth:`run_on`."""
        futures = []
        for w in self.workers:
            kw = kwargs_per_worker(w) if kwargs_per_worker else {}
            futures.append(self._submit(w, fn, **kw))
        deadline = None if timeout is None else time.monotonic() + timeout
        results = []
        for i, (w, fut) in enumerate(zip(self.workers, futures)):
            try:
                results.append(fut.result(timeout=_remaining(deadline)))
            except Exception as e:  # noqa: BLE001
                if isinstance(e, _FutTimeout) and not fut.done():
                    e = TimeoutError(  # as in run_on: one catchable type
                        f"worker {w.wid} ({w.host}): fan-in deadline of "
                        f"{timeout}s exceeded"
                    )
                if on_error == "capture":
                    log.warning("worker %d (%s) failed: %s", w.wid, w.host, e)
                    results.append(WorkerError(w.wid, w.host, e))
                else:
                    for later in futures[i:]:  # as in run_on: no orphans
                        later.cancel()
                    raise e
        return results

    def harvest_telemetry(self, timeout: Optional[float] = None,
                          reset: bool = False) -> Dict[str, object]:
        """Pull every worker's telemetry (Timeline state, fault counters,
        spans — :func:`blit.observability.telemetry_snapshot`) and fold it
        with the driver's own into ONE per-host-keyed fleet report
        (ISSUE 5 tentpole #3).

        Harvest failures degrade, never abort: a host that cannot answer
        lands under ``report["errors"]`` and the rest of the fleet still
        reports.  ``reset=True`` zeroes each worker's telemetry after
        snapshotting (interval-scrape mode).  The report also carries
        :meth:`health` so a degraded run says so in the same document."""
        results = self.broadcast(
            observability.telemetry_snapshot,
            kwargs_per_worker=lambda w: {"reset": reset},
            on_error="capture", timeout=timeout,
        )
        errors: Dict[str, str] = {}
        snaps = []
        for w, r in zip(self.workers, results):
            if isinstance(r, WorkerError):
                errors[w.host] = repr(r.error)
            else:
                snaps.append(r)
        # The driver's own telemetry rides along; with the in-process
        # backends it is the same (host, pid) as the workers' answers and
        # merge_fleet's dedupe counts it once.
        snaps.append(observability.telemetry_snapshot())
        report = observability.merge_fleet(snaps, errors=errors or None)
        report["health"] = self.health()
        return report

    def shutdown(self):
        # Drain in-flight calls BEFORE closing agents — a queued remote call
        # would otherwise respawn an agent nobody closes.
        if self._exec is not None:
            self._exec.shutdown(wait=True)
            self._exec = None
        for w in self.workers:
            if w.remote is not None:
                try:
                    w.remote.close()
                except Exception as e:  # noqa: BLE001 — close the rest anyway
                    log.warning("closing agent for %s failed: %s", w.host, e)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


_current: Optional[WorkerPool] = None
# Guards the read-modify-write on _current: two racing setup_workers calls
# must get the SAME pool, not each build (and one leak) a full pool of
# threads/agents (ISSUE 2 satellite).
_current_lock = threading.Lock()


def setup_workers(
    hosts: Optional[Sequence[str]] = None,
    backend: Optional[str] = None,
    config: SiteConfig = DEFAULT,
) -> WorkerPool:
    """Create (or return) the process-wide worker pool.  Thread-safe.

    Reference: ``GBT.setupworkers`` (src/gbt.jl:12-46).  Where the reference
    refuses to run twice and returns an *empty* pid list, blit returns the
    live pool (the documented fix for that wart, SURVEY.md §2.1)."""
    global _current
    with _current_lock:
        if _current is not None:
            log.warning("workers already set up; returning the live pool")
            return _current
        if hosts is None:
            hosts = config.hosts
        _current = WorkerPool(
            hosts, backend=backend or config.backend, config=config
        )
        return _current


def current_pool() -> Optional[WorkerPool]:
    return _current


def reset_pool():
    """Tear down the process-wide pool (tests; elastic re-spawn).
    Thread-safe; the (possibly slow) shutdown happens outside the lock."""
    global _current
    with _current_lock:
        pool, _current = _current, None
    if pool is not None:
        pool.shutdown()
