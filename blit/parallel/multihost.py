"""Multi-host bring-up: the TPU-pod analog of ``GBT.setupworkers``.

SURVEY.md §5 "Distributed communication backend": the reference's control
plane is ``Distributed.addprocs`` over ssh (src/gbt.jl:28-34).  On TPU the
control plane is the JAX distributed runtime — one Python process per host,
all chips visible as one global device list — and the data plane is XLA
collectives over ICI/DCN.  This module wraps the bring-up and maps the
global device list back onto `(band, bank)` players so each host knows which
banks' files it must feed.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("blit.multihost")

_initialized = False

# Environment markers that mean "this process is part of a pod/cluster" even
# when no explicit coordinator_address argument was given.
_CLUSTER_ENV_VARS = (
    "COORDINATOR_ADDRESS",
    "JAX_COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "JAX_NUM_PROCESSES",
)


def _cluster_env_hints() -> bool:
    import os

    return any(os.environ.get(v) for v in _CLUSTER_ENV_VARS)


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    cpu_collectives: Optional[str] = None,
    **kw,
) -> bool:
    """Initialize the JAX distributed runtime (idempotent).

    With no arguments, relies on environment auto-detection (TPU pod
    metadata / cluster env vars), which is also correct for single-process
    runs — ``jax.distributed.initialize`` is then a no-op.  Returns True if
    a multi-process runtime is active afterwards.

    ``cpu_collectives``: cross-process collective implementation for the
    CPU backend (``"gloo"`` / ``"mpi"``) — required for a multi-process CPU
    pod (the multi-host test rig); TPU pods ignore it (ICI/DCN collectives
    are built in).
    """
    global _initialized
    import jax

    if not _initialized:
        if cpu_collectives:
            jax.config.update(
                "jax_cpu_collectives_implementation", cpu_collectives
            )
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kw,
            )
        except RuntimeError as e:
            # jax 0.9 raises "should only be called once" on re-init and
            # "must be called before any JAX calls" once a backend exists.
            # The latter is only tolerable for implicit single-process
            # bring-up — with an explicit coordinator the caller wanted a
            # pod, and silently degrading would deadlock the collectives.
            msg = str(e).lower()
            if "once" in msg:
                pass
            elif (
                # jax <= 0.5 says "before any JAX calls"; newer jax says
                # "before any JAX computations are executed" — match the
                # shared prefix so a message tweak cannot re-break this.
                "before any jax" in msg
                and coordinator_address is None
                and not _cluster_env_hints()
            ):
                log.info("backend already up without a cluster; single-process")
            else:
                # An intended pod (explicit coordinator, or cluster env vars
                # present) must not silently degrade — the collectives would
                # deadlock across hosts.  Initialize before any JAX call.
                raise
        except ValueError as e:
            # No cluster auto-detection and no explicit coordinator: a plain
            # single-process run ("coordinator_address should be defined").
            if coordinator_address is not None:
                raise
            log.info("no cluster detected (%s); single-process mode", e)
        _initialized = True
    active = jax.process_count() > 1
    log.info(
        "distributed runtime: %d process(es), %d device(s), this is process %d",
        jax.process_count(), jax.device_count(), jax.process_index(),
    )
    return active


def player_map(mesh) -> Dict[Tuple[int, int], "object"]:
    """{(band, bank): device} for a ``(band, bank)`` mesh — which chip plays
    which ``BLP<band><bank>`` (README.md:21-23 naming)."""
    out = {}
    nband, nbank = mesh.devices.shape
    for b in range(nband):
        for k in range(nbank):
            out[(b, k)] = mesh.devices[b, k]
    return out


def local_players(mesh) -> List[Tuple[int, int]]:
    """The (band, bank) players whose chips belong to *this* process — the
    banks whose files this host must feed (addressable shards of the global
    voltage array)."""
    import jax

    mine = {d.id for d in jax.local_devices()}
    return [pb for pb, dev in player_map(mesh).items() if dev.id in mine]
