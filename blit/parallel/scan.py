"""Mesh-backed scan loading: RAW files → sharded reduction → stitched band.

The end-to-end BASELINE.json config-3 path: every bank's GUPPI RAW voltages
feed the chip that plays that ``BLP<band><bank>`` player, the per-chip
channelization runs under ``shard_map``, and the 8 banks of each band stitch
over ICI (blit/parallel/mesh.band_reduce).  The host holds at most one
bank's int8 voltages at a time — each player's block is placed directly on
its chip and the global sharded array is assembled from those per-device
shards.  This is the TPU rebuild of the reference's whole-scan workflow
(``loadscan``, src/gbt.jl:90-114, which fetched per-bank arrays to the main
process and ``vcat``-ed them there).
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from blit.io.guppi import GuppiRaw, open_raw
from blit.monitor import published
from blit.ops.channelize import (
    STOKES_NIF,
    output_header,
    pfb_coeffs,
    usable_frames,
)
from blit.parallel import mesh as M

log = logging.getLogger("blit.scan")


def _kept_samples(raw: GuppiRaw) -> int:
    """Gap-free samples the file yields — header arithmetic only (block
    sizes and OVERLAP are in the scanned headers; no data read)."""
    return sum(raw.block_ntime_kept(i) for i in range(raw.nblocks))


def _gapless(
    raw: GuppiRaw,
    max_samples: Optional[int],
    skip: int = 0,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """A RAW file's overlap-trimmed voltages — gap-free samples
    ``[skip, skip + max_samples)`` — read ONCE directly into the final
    ``(nchan, total, npol, 2)`` buffer (native threaded pread per block when
    built) — no per-block concatenation, no second pass.  ``skip`` indexes
    the gap-free sample stream (each block's kept prefix), so windowed
    readers can re-enter mid-recording without touching earlier bytes.

    ``out`` reuses a caller-held scratch buffer (``(nchan, >=total, npol,
    2)`` int8) instead of allocating — the window feeds read every window
    into the same scratch rather than churning a fresh GB-scale buffer per
    window.  Returns the filled ``(nchan, total, npol, 2)`` view."""
    hdr = raw.header(0)
    nchan = hdr["OBSNCHAN"]
    npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
    total = max(_kept_samples(raw) - skip, 0)
    if max_samples is not None:
        total = min(total, max_samples)
    if out is not None:
        if (out.dtype != np.int8 or out.shape[0] != nchan
                or out.shape[1] < total or out.shape[2:] != (npol, 2)):
            raise ValueError(
                f"_gapless: scratch shape {out.shape}/{out.dtype} cannot "
                f"hold (nchan={nchan}, total={total}, npol={npol}, 2) int8"
            )
        out = out[:, :total]
    else:
        out = np.empty((nchan, total, npol, 2), np.int8)
    filled = 0
    to_skip = skip
    for i in range(raw.nblocks):
        if filled >= total:
            break
        kept = raw.block_ntime_kept(i)
        if to_skip >= kept:
            to_skip -= kept
            continue
        nt = min(kept - to_skip, total - filled)
        got = raw.read_block_into(i, out[:, filled:], t0=to_skip, ntime_keep=nt)
        to_skip = 0
        filled += got
        if got < nt:
            # Short read (truncated recording, injected truncate fault):
            # return what actually landed — every caller length-checks the
            # result, so the shortfall surfaces as a hard error there
            # instead of shipping a stale-byte tail into the collectives.
            break
    return out[:, :filled]


# Per-player markers riding the pod-wide sample-count agreement.  ERR < UNFED
# so an owner's failure wins the cross-process MIN over "nobody fed it", and
# both exceed any real sample count (~1e11 for a 10-minute bank recording).
_SAMPS_ERR = 1 << 60  # the owning process failed to open/read the player
_SAMPS_UNFED = 1 << 61  # no process fed this player


def _gather_int64(local: np.ndarray) -> np.ndarray:
    """Allgather an int64 array across every process → ``(nproc, ...)`` —
    the pod-wide agreement primitive behind the common-frame-span decision.
    Every process sees every process's values, so any consistency check made
    on the result raises (or passes) SYMMETRICALLY — no process can proceed
    into the collectives while a peer errors out (that asymmetry would trade
    a clean error for a distributed hang).

    ``process_allgather`` canonicalizes dtypes (int64 → int32 with x64 off),
    which would corrupt sample counts past 2^31 — so values ride as exact
    (hi, lo) int32 pairs.  Single-process: ``local[None]``.
    """
    import jax

    if jax.process_count() == 1:
        return local[None]
    from jax.experimental import multihost_utils

    if (local < 0).any() or (local >= (1 << 62)).any():
        raise ValueError("_gather_int64: values must be in [0, 2^62)")
    hi = (local >> 31).astype(np.int32)
    lo = (local & 0x7FFFFFFF).astype(np.int32)
    g = multihost_utils.process_allgather(
        np.stack([hi, lo]).reshape((2,) + local.shape)
    )  # (nproc, 2, ...)
    g = np.asarray(g, np.int64)
    return (g[:, 0] << 31) | g[:, 1]  # (nproc, ...)


def _resolve_grid(raw_paths, scan, inventories):
    """Accept either an explicit ``raw_paths[band][bank]`` grid or the
    inventory-driven ``(session, scan)`` form (the reference's whole-scan
    call shape, ``loadscan(session, scan, suffix)``, src/gbt.jl:99) and
    return ``(band_ids, raw_paths)``.  ``band_ids`` labels each grid row
    with its real band number when resolved from an inventory; an explicit
    grid is labeled 0..nband-1."""
    if isinstance(raw_paths, str):
        if scan is None or inventories is None:
            raise ValueError(
                "session-form call needs load_scan_mesh(session, scan, "
                "inventories=...)"
            )
        from blit.inventory import scan_grid

        band_ids, _, grid = scan_grid(inventories, raw_paths, scan)
        return band_ids, grid
    if scan is not None or inventories is not None:
        raise ValueError(
            "scan=/inventories= only apply to the session-form call; an "
            "explicit raw_paths grid already names every file"
        )
    return list(range(len(raw_paths))), raw_paths


def _open_players(raw_paths, mesh):
    """Shared prologue of the mesh scan entry points: validate the grid,
    build the mesh, open THIS process's players, and agree the usable
    sample span / geometry / per-player failures pod-wide (symmetric
    errors — see ``_gather_int64``).

    Returns ``(mesh, local, raws, nchan, npol, min_samps)`` where ``local``
    is this process's sorted (band, bank) list and ``raws`` maps each of
    its openable entries to a GuppiRaw."""
    import jax

    from blit.parallel.multihost import local_players

    nband = len(raw_paths)
    nbank = len(raw_paths[0])
    if any(len(row) != nbank for row in raw_paths):
        raise ValueError("raw_paths must be rectangular (nband x nbank)")
    if mesh is None:
        mesh = M.make_mesh(nband, nbank)

    local = sorted(local_players(mesh))
    if not local:
        raise ValueError(
            "this process owns no device of the scan mesh "
            f"(process {jax.process_index()}/{jax.process_count()})"
        )
    # Open this process's players.  Failures do NOT raise yet: the owner
    # must first tell the pod via the agreement below, so every process
    # raises together instead of the peers hanging in the collectives.
    raws = {}
    local_errs = {}
    for b, k in local:
        try:
            r = open_raw(raw_paths[b][k])
            if r.nblocks == 0:
                raise ValueError(f"empty RAW file: {r.path}")
            raws[(b, k)] = r
        except Exception as e:  # noqa: BLE001 — reported pod-wide below
            local_errs[(b, k)] = e

    if raws:
        first = raws[sorted(raws)[0]].header(0)
        nchan = first["OBSNCHAN"]
        npol = 2 if first["NPOL"] > 2 else first["NPOL"]
    else:
        nchan = npol = 0  # nothing openable; the ERR agreement raises below

    # Common whole-frame span across every player (ragged recordings trim),
    # via the same frame-accounting invariant the streaming pipeline uses.
    # Header arithmetic only — each file's data is read exactly once, later.
    # The span, the (nchan, npol) geometry, and any per-player failures are
    # agreed across processes: every process must assemble the same global
    # array shape — and must error together — or the collectives deadlock.
    samps = np.full((nband, nbank), _SAMPS_UNFED, np.int64)
    for (b, k), r in raws.items():
        samps[b, k] = _kept_samples(r)
    for bk in local_errs:
        samps[bk] = _SAMPS_ERR
    gathered = _gather_int64(np.concatenate([samps.ravel(), [nchan, npol]]))
    samps = gathered[:, :-2].min(axis=0).reshape(nband, nbank)
    failed = [tuple(i) for i in np.argwhere(samps == _SAMPS_ERR)]
    if failed:
        mine = "; ".join(
            f"{bk}: {type(e).__name__}: {e}" for bk, e in sorted(local_errs.items())
        )
        cause = next(iter(local_errs.values()), None)
        raise ValueError(
            f"players {failed} failed to open on their owning process"
            + (f" (this process: {mine})" if mine else "")
        ) from cause
    unfed = [tuple(i) for i in np.argwhere(samps == _SAMPS_UNFED)]
    if unfed:
        raise ValueError(f"no process fed players {unfed}")
    geo = gathered[:, -2:]
    geo = geo[(geo != 0).any(axis=1)]
    if not (geo == geo[0]).all():
        raise ValueError(
            f"processes disagree on (nchan, npol): {[tuple(g) for g in geo]}"
        )
    return mesh, local, raws, int(geo[0][0]), int(geo[0][1]), int(samps.min())


def _feed_window(raws, local, mesh, nchan, npol, start, ntime):
    """Assemble the global sharded voltage array for gap-free samples
    ``[start, start + ntime)`` of every player.  One bank in host memory at
    a time: each local player's block goes straight onto its chip, and the
    global array is built from the single-device shards (no whole-scan host
    buffer, no device_put to any non-addressable device) — the assembly
    itself is :func:`blit.parallel.mesh.put_local_shards`, the ONE
    partition-rule-driven implementation the sharded plane shares."""
    nband, nbank = mesh.devices.shape
    blocks = {}
    for b, k in local:
        r = raws[(b, k)]
        v = _gapless(r, ntime, skip=start)
        if v.shape[0] != nchan or v.shape[1] < ntime or v.shape[2:] != (npol, 2):
            raise ValueError(
                f"{r.path}: shape {v.shape} incompatible with "
                f"(nchan={nchan}, ntime>={ntime}, npol={npol}, 2)"
            )
        blocks[(b, k)] = np.ascontiguousarray(v[None, None, :, :ntime])
    return M.put_local_shards(
        blocks, mesh, (nband, nbank, nchan, ntime, npol, 2)
    )


def _scan_headers(raws, local, *, nfft, nint, stokes, fqav_by):
    """Per-band product headers from the players THIS process can see.

    Per-bank headers must tile contiguously in frequency: each local bank k
    implies the band's bank-0 fch1 (``fch1_k - k*per_bank*foff``), and all
    must agree.  With ``fqav_by > 1`` the fine-channel range maps through
    :func:`blit.ops.fqav.fqav_range` (the reference's worker-side ``fqav``
    header math, src/gbtworkerfunctions.jl:16-20).

    Returns ``(h0, bases, per_bank)``: the lowest local player's product
    header, the per-band bank-0 base frequency dict, and the per-bank
    output channel count."""
    from blit.ops.fqav import fqav_range

    hdrs = {}
    for (b, k), r in raws.items():
        h = output_header(r.header(0), nfft=nfft, nint=nint, stokes=stokes)
        if fqav_by > 1:
            fch1, foff, nchans = fqav_range(
                h["fch1"], h["foff"], h["nchans"], fqav_by
            )
            h.update(fch1=fch1, foff=foff, nchans=nchans, nfpc=nfft // fqav_by)
        hdrs[(b, k)] = h
    h0 = hdrs[local[0]]
    foff = h0["foff"]
    per_bank = h0["nchans"]
    bases: Dict[int, float] = {}
    for (b, k), h in sorted(hdrs.items()):
        if abs(h["foff"] - foff) > 1e-12:
            raise ValueError("banks disagree on fine channel width")
        base = h["fch1"] - k * per_bank * foff
        if b in bases and abs(base - bases[b]) > abs(foff) / 2:
            log.warning(
                "band %d bank %d not contiguous: fch1=%.6f expected %.6f",
                b, k, h["fch1"], bases[b] + k * per_bank * foff,
            )
        bases.setdefault(b, base)
    return h0, bases, per_bank


def _bitshuffle_window_chunk_rows(base: int, wrows: int) -> int:
    """Chunk rows for a windowed bitshuffle product: the pod-wide restart
    offset is window-aligned and bitshuffle resume points must be
    chunk-aligned, so the rows are ``gcd(default, window rows)`` — which
    silently collapses (to 1 for any window rows coprime with the 16-row
    default), degrading compression ratio and write throughput with no
    operator signal (ADVICE r5).  Output stays correct; warn so the knob
    gets fixed instead of silently eating the regression."""
    import math

    rows = math.gcd(base, wrows)
    if rows < min(base, wrows):
        log.warning(
            "bitshuffle chunk rows collapse to %d: window rows %d share "
            "no larger factor with the default %d-row chunk — pick "
            "window_frames/nint so the window rows divide (or are a "
            "multiple of) %d to keep compression and write throughput",
            rows, wrows, base, base,
        )
    return rows


def _despike_nfpc(despike: bool, nfft: int, fqav_by: int) -> int:
    """DC-despike width in OUTPUT channels (0 disables).  After fqav the
    repairable fine grid is nfft//fqav_by wide; below 2 channels there is
    no neighbor to clone from, so despike is skipped with a warning — the
    host-side ``load_scan`` parity rule (blit/gbt.py)."""
    if not despike:
        return 0
    nfpc = nfft // fqav_by
    if nfpc < 2:
        log.warning("skipping despike (nfpc=%d after fqav_by=%d)", nfpc, fqav_by)
        return 0
    return nfpc


def _slab_writer(path: str, header: Dict, nif: int, nchans: int,
                 compression: Optional[str]):
    """Per-band product writer by extension: ``.h5``/``.hdf5`` streams
    through :class:`blit.io.fbh5.FBH5Writer` (BL's native product format),
    anything else through :class:`_FilWriter`.  Both append slabs at
    bounded memory and land in ``.partial`` siblings renamed on close."""
    if path.endswith((".h5", ".hdf5")):
        from blit.io.fbh5 import FBH5Writer

        return FBH5Writer(path, header, nifs=nif, nchans=nchans,
                          compression=compression)
    if compression is not None:
        raise ValueError(".fil products are uncompressed; use .h5 paths "
                         "with compression=")
    from blit.io.sigproc import FilWriter

    return FilWriter(path, header, nif, nchans)


def _resolve_out_paths(band_ids, nband, out_dir, out_paths, compression):
    """Per-band product path resolution + the pre-collective compression
    validation (shared by the sync mesh writer and the sharded plane —
    a raise here happens on EVERY process, before any collective)."""
    import os

    if out_paths is None:
        if out_dir is None:
            raise ValueError("pass out_dir= or out_paths=")
        ext = "h5" if compression else "fil"
        out_paths = [
            os.path.join(out_dir, f"band{band_ids[b]}.{ext}")
            for b in range(nband)
        ]
    if len(out_paths) != nband:
        raise ValueError(f"need {nband} out_paths, got {len(out_paths)}")
    if compression is not None:
        bad = [p for p in out_paths if not p.endswith((".h5", ".hdf5"))]
        if bad:
            # Validate BEFORE any collective, on every process: a raise
            # inside the per-band writer loop would fire only on band-
            # owning processes and leave the rest blocked in the window
            # loop's collectives (the deadlock the caller docstrings
            # warn about).
            raise ValueError(
                ".fil products are uncompressed; compression= needs .h5 "
                f"paths, got {bad}"
            )
    return list(out_paths)


def _open_band_writers(
    mesh, raws, out_paths, *, h0, bases, per_bank, stokes,
    nfft, ntap, nint, window, fqav_by, dtype, despike_nfpc,
    compression, resume, wf, total,
):
    """The product-side prologue shared by the sync mesh writer and the
    sharded reduction plane (blit/parallel/sharded.py): which band rows
    THIS process persists (the bank-0 chip owner), their headers, the
    pod-wide-agreed resume restart offset, and the opened writers.

    Returns ``(mine, headers, writers, f0_start)``.  On a construction
    failure the already-built writers are aborted (their own crash
    contracts) before the error re-raises — callers' stream-error paths
    only ever see fully-constructed writer sets."""
    import os

    import jax

    nband, nbank = mesh.devices.shape
    nif = STOKES_NIF[stokes]
    nchans = nbank * per_bank
    mine = [
        b for b in range(nband)
        if mesh.devices[b, 0].process_index == jax.process_index()
    ]
    headers: Dict[int, Dict] = {}
    for b in mine:
        hdr = dict(h0)
        hdr["fch1"] = bases[b]
        hdr["nchans"] = nchans
        hdr["nifs"] = nif
        headers[b] = hdr

    f0_start = 0
    cursors = {}
    h5_chunk_rows = None
    if resume:
        from types import SimpleNamespace

        from blit.pipeline import ReductionCursor

        comp_id = compression or "none"
        # Mesh .h5-bitshuffle products tie the writer's chunk rows to the
        # window granularity (the pod-wide restart offset is window-
        # aligned, and bitshuffle resume points must be chunk-aligned), so
        # the granularity joins the resume identity: a changed
        # --window-frames restarts fresh instead of splicing mismatched
        # chunk grids.  .fil and plain/gzip .h5 truncate at any row.
        wrows_ident = -1
        if comp_id == "bitshuffle" and any(
            p.endswith((".h5", ".hdf5")) for p in out_paths
        ):
            from blit.io.fbh5 import default_chunks

            wrows = wf // nint
            base = default_chunks(nif, nchans, 4, whole_spectrum=True)[0]
            h5_chunk_rows = _bitshuffle_window_chunk_rows(base, wrows)
            wrows_ident = wrows
        # dtype is output-affecting (bf16 stages round differently), so
        # it joins the resume identity like every other config knob.
        ident = SimpleNamespace(
            nfft=nfft, ntap=ntap, nint=nint, stokes=stokes, window=window,
            fqav_by=fqav_by, dtype=dtype, despike_nfpc=despike_nfpc,
        )
        # This process's fed member files: the input identity a resume
        # must match (a changed recording would splice different spectra).
        members = sorted(
            p
            for r in raws.values()
            for p in (getattr(r, "paths", None) or [r.path])
        )
        local_done = []
        for b in mine:
            cur = ReductionCursor.load(out_paths[b])
            ok = (
                cur is not None
                and cur.matches(ident, members)
                and cur.compression == comp_id
                and cur.window_rows == wrows_ident
                and os.path.exists(out_paths[b])
            )
            if ok and not out_paths[b].endswith((".h5", ".hdf5")):
                # The flat-format crash guard (ISSUE 12): a cursor
                # claiming bytes the file no longer holds restarts the
                # band fresh — BEFORE the pod-wide restart agreement, so
                # every process folds the (now zero) offset symmetrically.
                from blit.pipeline import resume_fil_ok

                if not resume_fil_ok(out_paths[b], nif, nchans,
                                     cur.frames_done // nint):
                    log.warning(
                        "resume target %s is shorter than (or unreadable "
                        "as) the cursor's claimed %d frames "
                        "(crash-corrupted?); restarting the band fresh",
                        out_paths[b], cur.frames_done,
                    )
                    ok = False
            if ok and out_paths[b].endswith((".h5", ".hdf5")):
                # Crash robustness (ADVICE r5 medium): an HDF5 target a
                # SIGKILL left unopenable/unreadable restarts this band
                # fresh, like an identity mismatch — the check runs
                # BEFORE the pod-wide restart agreement, so every
                # process agrees on the (now zero) restart offset
                # instead of deadlocking or wedging on a raise.
                from blit.io.fbh5 import resume_target_ok

                if not resume_target_ok(
                    out_paths[b], nif, nchans, cur.frames_done // nint
                ):
                    log.warning(
                        "resume target %s is not readable as the claimed "
                        "HDF5 product (crash-corrupted metadata?); "
                        "discarding %d claimed frames and restarting the "
                        "band fresh", out_paths[b], cur.frames_done,
                    )
                    ok = False
            if not ok:
                size, mtime_ns = ReductionCursor.stat_raw(members)
                cur = ReductionCursor(
                    members, nfft, ntap, nint, stokes, 0, window=window,
                    raw_size=size, raw_mtime_ns=mtime_ns, fqav_by=fqav_by,
                    dtype=dtype, despike_nfpc=despike_nfpc,
                    compression=comp_id, window_rows=wrows_ident,
                )
            cursors[b] = cur
            local_done.append(cur.frames_done if ok else 0)
        # Pod-wide agreement: the window loop is collective-synchronized,
        # so every process must restart at the SAME offset.  Processes
        # owning no band rows ride a sentinel above any real count.
        local_min = min(local_done) if local_done else 1 << 61
        agreed = int(_gather_int64(
            np.asarray([local_min], np.int64)
        ).min())
        f0_start = min((agreed // wf) * wf, total)

    writers = {}
    try:
        for b in mine:
            if resume and out_paths[b].endswith((".h5", ".hdf5")):
                from blit.io.fbh5 import ResumableFBH5Writer

                writers[b] = ResumableFBH5Writer(
                    out_paths[b], headers[b], nif, nchans,
                    f0_start // nint, nint, cursors[b],
                    compression=compression,
                    chunks=(
                        (h5_chunk_rows, nif, nchans)
                        if h5_chunk_rows else None
                    ),
                )
            elif resume:
                from blit.pipeline import ResumableFilWriter

                writers[b] = ResumableFilWriter(
                    out_paths[b], headers[b], nif, nchans,
                    f0_start // nint, nint, cursors[b],
                )
            else:
                writers[b] = _slab_writer(
                    out_paths[b], headers[b], nif, nchans, compression
                )
    except BaseException:
        for w in writers.values():
            w.abort()
        raise
    return mine, headers, writers, f0_start


def load_scan_mesh(
    raw_paths,
    scan: Optional[str] = None,
    *,
    inventories=None,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fqav_by: int = 1,
    fft_method: str = "auto",
    window: str = "hamming",
    despike: bool = True,
    max_frames: Optional[int] = None,
    mesh=None,
    dtype: str = "float32",
) -> Tuple[Dict, "object"]:
    """Reduce one scan's RAW files across the mesh and stitch each band.

    Two call shapes:

    - ``load_scan_mesh(raw_paths, ...)`` with an explicit rectangular grid
      ``raw_paths[band][bank]`` — one RAW source per player, all covering
      the same scan (bank-ascending within each band).  Each source may be
      a single file path, a ``.NNNN.raw`` sequence stem, or a path list
      (blit/io/guppi.open_raw): a whole multi-file recording streams as
      one gap-free span per player.
    - ``load_scan_mesh(session, scan, inventories=...)`` — the reference's
      whole-scan call shape (``loadscan(session, scan, suffix)``,
      src/gbt.jl:99): the grid is resolved from ``get_inventories()``
      output via :func:`blit.inventory.scan_grid` (RAW sequences grouped
      per player, bands/banks sorted).

    Multi-process pods are first-class: under ``jax.distributed`` each
    process opens and feeds ONLY the players whose chips it owns
    (:func:`blit.parallel.multihost.local_players`) — the TPU analog of the
    reference's one-worker-per-host file locality (src/gbt.jl:28-42), where
    each ``blc*`` host serves its own disks.  Non-local entries of
    ``raw_paths`` are never touched, so they may name files that exist only
    on the owning host.  The common whole-frame span is agreed pod-wide
    (every process must build the same global array shape).

    Args:
      fqav_by: on-device frequency averaging applied per chip BEFORE the
        stitch collective (reduce before the wire); the returned header's
        fch1/foff/nchans/nfpc map through ``fqav_range``.
      max_frames: cap the PFB frames reduced (bounds HBM for long scans);
        None reduces the longest common whole-frame span.  For long scans
        at bounded memory end-to-end, use
        :func:`reduce_scan_mesh_to_files` (windowed streaming writer).
      mesh: an existing ``(band, bank)`` Mesh; None builds one matching
        the grid's shape over the available devices.

    Returns:
      ``(header, stitched)`` where stitched is a jax.Array
      ``(nband, ntime_out, nif, nbank*nchan*nfft//fqav_by)`` sharded over
      ``band`` (replicated across each band's banks), and ``header`` is the
      full-band filterbank header, derived from this process's lowest
      (band, bank) player.
    """
    import jax.numpy as jnp

    _, raw_paths = _resolve_grid(raw_paths, scan, inventories)
    mesh, local, raws, nchan, npol, min_samps = _open_players(raw_paths, mesh)
    nbank = mesh.devices.shape[1]

    frames = usable_frames(min_samps, nfft, ntap, nint)
    if max_frames is not None:
        frames = min(frames, (max_frames // nint) * nint)
    if frames <= 0:
        raise ValueError(
            f"scan too short: {min_samps} samples for nfft={nfft}"
        )
    ntime = (frames + ntap - 1) * nfft

    volt = _feed_window(raws, local, mesh, nchan, npol, 0, ntime)
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft, window))
    out = M.band_reduce(
        volt,
        coeffs,
        mesh=mesh,
        nfft=nfft,
        ntap=ntap,
        nint=nint,
        stokes=stokes,
        fft_method=fft_method,
        stitch=True,
        despike_nfpc=_despike_nfpc(despike, nfft, fqav_by),
        fqav_by=fqav_by,
        dtype=dtype,
    )

    h0, bases, per_bank = _scan_headers(
        raws, local, nfft=nfft, nint=nint, stokes=stokes, fqav_by=fqav_by,
    )
    hdr = dict(h0)
    hdr["fch1"] = bases[local[0][0]]
    hdr["nchans"] = nbank * per_bank
    hdr["nsamps"] = int(out.shape[1])
    hdr["nifs"] = STOKES_NIF[stokes]
    return hdr, out


@published
def reduce_scan_mesh_to_files(
    raw_paths,
    scan: Optional[str] = None,
    *,
    inventories=None,
    out_dir: Optional[str] = None,
    out_paths: Optional[Sequence[str]] = None,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fqav_by: int = 1,
    fft_method: str = "auto",
    window: str = "hamming",
    despike: bool = True,
    max_frames: Optional[int] = None,
    window_frames: Optional[int] = None,
    compression: Optional[str] = None,
    resume: bool = False,
    mesh=None,
    dtype: str = "float32",
    timeline=None,
    trace_logdir: Optional[str] = None,
) -> Dict[int, Tuple[str, Dict]]:
    """Reduce one scan across the mesh and STREAM each stitched band to a
    ``.fil`` product — the persistence epilogue ``load_scan_mesh`` lacks.

    The reduction runs ``window_frames`` PFB frames per dispatch (each
    window re-reads the (ntap-1)*nfft-sample PFB prologue), so host RSS,
    HBM, and per-window readback stay bounded no matter the scan length —
    the mesh analog of ``RawReducer.reduce_to_file``'s slab streaming
    (blit/pipeline.py).  ``window_frames=None`` (the default) derives an
    HBM-safe bound from ``nfft``
    (:func:`blit.config.default_window_frames`); pass a value >= the
    scan length for a deliberate one-window run.  Products append slab-by-slab into ``.partial``
    siblings and rename on success (SIGPROC derives nsamps from file size,
    so a crash mid-stream must not leave a valid-looking truncated file).

    Call shapes and reduction parameters match :func:`load_scan_mesh`
    (explicit grid or ``(session, scan, inventories=...)``).

    ``dtype`` selects the per-chip channelizer stage dtype ("float32" |
    "bfloat16" — the official bench's biggest lever, DESIGN.md §3; the
    products stay float32 and dtype joins the resume identity since
    bf16 stages round differently).

    Observability (SURVEY.md §5 metrics bar): pass ``timeline`` (a
    :class:`blit.observability.Timeline`) to accumulate per-window stage
    timings with byte counts — ``read`` (host RAW ingest + device feed),
    ``dispatch`` (async window dispatch, ~0 after the first compile),
    ``device`` (the blocking wait on the window's compute+collectives),
    ``readback`` (stitched-band device→host), ``write`` (product
    append) — mirroring the single-chip ``RawReducer`` stages;
    ``blit scan`` prints the report as a stats JSON line.
    ``trace_logdir`` wraps the window loop in a JAX profiler trace
    (TensorBoard/Perfetto).

    Output naming: ``out_paths`` (band-ascending, one per band; ``.fil``
    or ``.h5`` per path) or ``out_dir`` + ``band<id>.fil`` (``.h5`` when
    ``compression`` is set) where ``<id>`` is the real band number from
    the inventory (grid-row index for an explicit grid).  ``.h5`` products
    stream through :class:`blit.io.fbh5.FBH5Writer` — BL's native product
    format — with ``compression`` None | "gzip" | "bitshuffle".

    Multi-process pods: each band's file is written by the process owning
    that band row's bank-0 chip (the stitched product is replicated across
    the row, so one owner suffices and ``out_dir`` may be process-local
    disk).  Returns ``{band_id: (path, header)}`` for the bands THIS
    process wrote.

    ``resume=True`` makes the stream crash-resumable, the mesh twin of
    ``RawReducer.reduce_resumable``: a
    :class:`~blit.pipeline.ReductionCursor` sidecar per band records
    frames durably written after every window (data fsync'd before the
    cursor claims it); re-running truncates any un-checkpointed tail and
    continues from the last window boundary every process agrees on
    (pod-wide MIN, window-aligned — the restart offset must be identical
    on every process or the collectives deadlock).  ``.fil`` products
    truncate by byte length; ``.h5`` products ``resize``-truncate the
    time-resizable dataset
    (:class:`blit.io.fbh5.ResumableFBH5Writer`), including under
    ``compression="bitshuffle"``, whose chunk rows are tied to the window
    granularity so pod restart offsets stay chunk-aligned (a changed
    ``window_frames`` therefore restarts bitshuffle ``.h5`` products
    fresh — it is part of their cursor identity, as is the compression).
    Cursor identity covers the reduction config and this process's
    locally-fed member files; the finished product is identical to an
    uninterrupted run and the sidecars are removed on completion.
    """
    import jax.numpy as jnp

    band_ids, raw_paths = _resolve_grid(raw_paths, scan, inventories)
    mesh, local, raws, nchan, npol, min_samps = _open_players(raw_paths, mesh)
    nband, nbank = mesh.devices.shape

    total = usable_frames(min_samps, nfft, ntap, nint)
    if max_frames is not None:
        total = min(total, (max_frames // nint) * nint)
    if total <= 0:
        raise ValueError(
            f"scan too short: {min_samps} samples for nfft={nfft}"
        )
    if window_frames is None:
        # Bounded by default at EVERY entry point (VERDICT r4: an
        # unbounded whole-scan window on the command whose purpose is
        # bounded-window streaming): the HBM-safe sample budget, scaled
        # to whole frames.  Pass an explicit window_frames >= the scan
        # length for a deliberate one-window run.
        from blit.config import default_window_frames

        window_frames = default_window_frames(nfft)
    wf = max((window_frames // nint) * nint, nint)

    out_paths = _resolve_out_paths(
        band_ids, nband, out_dir, out_paths, compression
    )

    h0, bases, per_bank = _scan_headers(
        raws, local, nfft=nfft, nint=nint, stokes=stokes, fqav_by=fqav_by,
    )
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft, window))
    despike_nfpc = _despike_nfpc(despike, nfft, fqav_by)

    mine, headers, writers, f0_start = _open_band_writers(
        mesh, raws, out_paths, h0=h0, bases=bases,
        per_bank=per_bank, stokes=stokes, nfft=nfft, ntap=ntap, nint=nint,
        window=window, fqav_by=fqav_by, dtype=dtype,
        despike_nfpc=despike_nfpc, compression=compression, resume=resume,
        wf=wf, total=total,
    )
    try:
        from blit.observability import Timeline, profile_trace

        tl = timeline if timeline is not None else Timeline()

        def flush(out):
            # Blocking readback of one window's stitched bands -> disk.
            # The compute wait is charged to "device" here (not at the
            # async dispatch): this is where the host actually blocks on
            # the window's collectives, mirroring RawReducer's stage
            # semantics.  (On rigs whose tunnel makes block_until_ready
            # lazy — DESIGN.md §8 — that wait lands in "readback".)
            with tl.stage("device", byte_free=True):
                out.block_until_ready()
            by_dev = {s.device: s for s in out.addressable_shards}
            for b in mine:
                with tl.stage("readback"):
                    slab = np.ascontiguousarray(
                        np.asarray(by_dev[mesh.devices[b, 0]].data)[0]
                    )
                tl.stages["readback"].bytes += slab.nbytes
                with tl.stage("write", slab.nbytes):
                    writers[b].append(slab)

        # One window in flight: window N+1's host RAW reads + device_put +
        # dispatch happen BEFORE blocking on window N's readback, so host
        # I/O overlaps device compute at one extra window of HBM.
        pending = None
        f0 = f0_start
        with profile_trace(trace_logdir):
            while f0 < total:
                n = min(wf, total - f0)
                ntime = (n + ntap - 1) * nfft
                # Locally fed voltage bytes: complex int8 = 2 B/sample.
                fed = len(raws) * nchan * ntime * npol * 2
                with tl.stage("read", fed):
                    volt = _feed_window(
                        raws, local, mesh, nchan, npol, f0 * nfft, ntime
                    )
                with tl.stage("dispatch", byte_free=True):
                    out = M.band_reduce(
                        volt,
                        coeffs,
                        mesh=mesh,
                        nfft=nfft,
                        ntap=ntap,
                        nint=nint,
                        stokes=stokes,
                        fft_method=fft_method,
                        stitch=True,
                        despike_nfpc=despike_nfpc,
                        fqav_by=fqav_by,
                        dtype=dtype,
                    )
                if pending is not None:
                    flush(pending)
                pending = out
                f0 += n
            if pending is not None:
                flush(pending)
        done = {}
        for b in list(writers):
            writers[b].close()  # on failure the finally aborts the rest
            done[b] = writers.pop(b)
    finally:
        for w in writers.values():  # exception path: drop partials
            w.abort()
    for b in mine:
        headers[b]["nsamps"] = done[b].nsamps
    return {band_ids[b]: (out_paths[b], headers[b]) for b in mine}


@published
def reduce_scan_pool_to_files(
    raw_paths,
    scan: Optional[str] = None,
    *,
    inventories=None,
    out_dir: Optional[str] = None,
    out_paths: Optional[Sequence[str]] = None,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fqav_by: int = 1,
    fft_method: str = "auto",
    window: str = "hamming",
    despike: bool = True,
    max_frames: Optional[int] = None,
    window_frames: Optional[int] = None,
    compression: Optional[str] = None,
    dtype: str = "float32",
    pool=None,
    worker_ids: Optional[Sequence[int]] = None,
    timeline=None,
) -> Dict[int, Tuple[str, Dict]]:
    """The POOL path of a whole-scan reduction — the reference's shape
    ("64 workers doing 64 small jobs", ``loadscan``'s main-process
    ``vcat``, src/gbt.jl:90-114) kept as the sharded plane's fallback and
    its CORRECTNESS ORACLE (ISSUE 9): one :class:`blit.pipeline.RawReducer`
    per (band, bank) player, fanned over a :class:`~blit.parallel.pool.
    WorkerPool` when one is given (``pool=``/``worker_ids=``, the
    ``gbt.reduce_raw`` discipline) or run inline, then a host-side
    channel-axis ``vcat`` + DC despike per band and one product write.

    Byte-identity contract (tests/test_sharded.py): with
    ``window_frames`` equal to the sharded path's and a common whole-frame
    span across players, the per-band products are BYTE-IDENTICAL to
    ``reduce_scan_sharded_to_files`` / ``reduce_scan_mesh_to_files``
    output — the per-bank reduction is the same jitted ``channelize`` at
    the same dispatch shapes (``chunk_frames = window_frames``), the
    stitch is an exact concatenation, and the despike an exact
    neighbor-clone, on host here and over ICI there.

    Bounded memory is NOT this path's goal (each band's stitched array is
    materialized host-side, exactly like the reference); the sharded
    plane is the production path.  Returns ``{band_id: (path, header)}``
    for every band (this process writes them all — there is no pod here).
    """
    band_ids, raw_paths = _resolve_grid(raw_paths, scan, inventories)
    nband = len(raw_paths)
    nbank = len(raw_paths[0])
    if any(len(row) != nbank for row in raw_paths):
        raise ValueError("raw_paths must be rectangular (nband x nbank)")

    # Open every player host-side for the span/header agreement (the pool
    # path has no pod: one process sees every file).
    raws = {}
    for b in range(nband):
        for k in range(nbank):
            r = open_raw(raw_paths[b][k])
            if r.nblocks == 0:
                raise ValueError(f"empty RAW file: {r.path}")
            raws[(b, k)] = r
    local = sorted(raws)
    total = min(usable_frames(_kept_samples(r), nfft, ntap, nint)
                for r in raws.values())
    if max_frames is not None:
        total = min(total, (max_frames // nint) * nint)
    if total <= 0:
        raise ValueError("scan too short")
    if window_frames is None:
        from blit.config import default_window_frames

        window_frames = default_window_frames(nfft)
    wf = max((window_frames // nint) * nint, nint)

    out_paths = _resolve_out_paths(
        band_ids, nband, out_dir, out_paths, compression
    )
    h0, bases, per_bank = _scan_headers(
        raws, local, nfft=nfft, nint=nint, stokes=stokes, fqav_by=fqav_by,
    )
    nif = STOKES_NIF[stokes]
    nchans = nbank * per_bank
    despike_nfpc = _despike_nfpc(despike, nfft, fqav_by)
    rows_total = total // nint

    from blit.observability import Timeline

    tl = timeline if timeline is not None else Timeline()
    red_kw = dict(
        nfft=nfft, ntap=ntap, nint=nint, stokes=stokes, window=window,
        fft_method=fft_method, fqav_by=fqav_by, dtype=dtype,
        chunk_frames=wf, tune_online=False,
    )

    def reduce_bank(b, k):
        from blit.pipeline import RawReducer

        _, data = RawReducer(**red_kw).reduce(raw_paths[b][k])
        return data

    written: Dict[int, Tuple[str, Dict]] = {}
    for b in range(nband):
        with tl.stage("read", byte_free=True):
            if pool is not None:
                from blit import workers as wf_mod

                wids = (list(worker_ids) if worker_ids is not None
                        else [(b * nbank + k) % len(pool) + 1
                              for k in range(nbank)])
                results = pool.run_on(
                    wids, wf_mod.reduce_raw,
                    [(raw_paths[b][k],) for k in range(nbank)],
                    kwargs=red_kw,
                )
                banks = [data for _hdr, data in results]
            else:
                banks = [reduce_bank(b, k) for k in range(nbank)]
        short = [k for k, d in enumerate(banks) if d.shape[0] < rows_total]
        if short:
            raise ValueError(
                f"band {band_ids[b]} banks {short} yielded fewer than the "
                f"agreed {rows_total} spectra — players disagree on span"
            )
        # The main-process vcat (exact) + host despike (exact clone) —
        # the reference's stitch, trimmed to the pod-agreed common span.
        stitched = np.concatenate(
            [d[:rows_total] for d in banks], axis=-1
        )
        if despike_nfpc >= 2:
            from blit.ops.despike import despike as _despike

            stitched = np.asarray(_despike(stitched, despike_nfpc))
        hdr = dict(h0)
        hdr["fch1"] = bases[b]
        hdr["nchans"] = nchans
        hdr["nifs"] = nif
        w = _slab_writer(out_paths[b], hdr, nif, nchans, compression)
        try:
            with tl.stage("write", stitched.nbytes):
                w.append(stitched)
            w.close()
        except BaseException:
            w.abort()
            raise
        hdr["nsamps"] = rows_total
        written[band_ids[b]] = (out_paths[b], hdr)
    return written
