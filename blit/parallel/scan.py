"""Mesh-backed scan loading: RAW files → sharded reduction → stitched band.

The end-to-end BASELINE.json config-3 path: every bank's GUPPI RAW voltages
feed the chip that plays that ``BLP<band><bank>`` player, the per-chip
channelization runs under ``shard_map``, and the 8 banks of each band stitch
over ICI (blit/parallel/mesh.band_reduce).  The host holds at most one
bank's int8 voltages at a time — each player's block is placed directly on
its chip and the global sharded array is assembled from those per-device
shards.  This is the TPU rebuild of the reference's whole-scan workflow
(``loadscan``, src/gbt.jl:90-114, which fetched per-bank arrays to the main
process and ``vcat``-ed them there).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from blit.io.guppi import GuppiRaw, open_raw
from blit.ops.channelize import (
    STOKES_NIF,
    output_header,
    pfb_coeffs,
    usable_frames,
)
from blit.parallel import mesh as M

log = logging.getLogger("blit.scan")


def _kept_samples(raw: GuppiRaw) -> int:
    """Gap-free samples the file yields — header arithmetic only (block
    sizes and OVERLAP are in the scanned headers; no data read)."""
    return sum(raw.block_ntime_kept(i) for i in range(raw.nblocks))


def _gapless(raw: GuppiRaw, max_samples: Optional[int]) -> np.ndarray:
    """A RAW file's overlap-trimmed voltages, read ONCE directly into the
    final ``(nchan, total, npol, 2)`` buffer (native threaded pread per
    block when built) — no per-block concatenation, no second pass."""
    hdr = raw.header(0)
    nchan = hdr["OBSNCHAN"]
    npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
    total = _kept_samples(raw)
    if max_samples is not None:
        total = min(total, max_samples)
    out = np.empty((nchan, total, npol, 2), np.int8)
    filled = 0
    for i in range(raw.nblocks):
        if filled >= total:
            break
        nt = min(raw.block_ntime_kept(i), total - filled)
        raw.read_block_into(i, out[:, filled:], t0=0, ntime_keep=nt)
        filled += nt
    return out


# Per-player markers riding the pod-wide sample-count agreement.  ERR < UNFED
# so an owner's failure wins the cross-process MIN over "nobody fed it", and
# both exceed any real sample count (~1e11 for a 10-minute bank recording).
_SAMPS_ERR = 1 << 60  # the owning process failed to open/read the player
_SAMPS_UNFED = 1 << 61  # no process fed this player


def _gather_int64(local: np.ndarray) -> np.ndarray:
    """Allgather an int64 array across every process → ``(nproc, ...)`` —
    the pod-wide agreement primitive behind the common-frame-span decision.
    Every process sees every process's values, so any consistency check made
    on the result raises (or passes) SYMMETRICALLY — no process can proceed
    into the collectives while a peer errors out (that asymmetry would trade
    a clean error for a distributed hang).

    ``process_allgather`` canonicalizes dtypes (int64 → int32 with x64 off),
    which would corrupt sample counts past 2^31 — so values ride as exact
    (hi, lo) int32 pairs.  Single-process: ``local[None]``.
    """
    import jax

    if jax.process_count() == 1:
        return local[None]
    from jax.experimental import multihost_utils

    if (local < 0).any() or (local >= (1 << 62)).any():
        raise ValueError("_gather_int64: values must be in [0, 2^62)")
    hi = (local >> 31).astype(np.int32)
    lo = (local & 0x7FFFFFFF).astype(np.int32)
    g = multihost_utils.process_allgather(
        np.stack([hi, lo]).reshape((2,) + local.shape)
    )  # (nproc, 2, ...)
    g = np.asarray(g, np.int64)
    return (g[:, 0] << 31) | g[:, 1]  # (nproc, ...)


def load_scan_mesh(
    raw_paths: Sequence[Sequence[str]],
    *,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fft_method: str = "auto",
    window: str = "hamming",
    despike: bool = True,
    max_frames: Optional[int] = None,
    mesh=None,
) -> Tuple[Dict, "object"]:
    """Reduce one scan's RAW files across the mesh and stitch each band.

    Multi-process pods are first-class: under ``jax.distributed`` each
    process opens and feeds ONLY the players whose chips it owns
    (:func:`blit.parallel.multihost.local_players`) — the TPU analog of the
    reference's one-worker-per-host file locality (src/gbt.jl:28-42), where
    each ``blc*`` host serves its own disks.  Non-local entries of
    ``raw_paths`` are never touched, so they may name files that exist only
    on the owning host.  The common whole-frame span is agreed pod-wide
    (every process must build the same global array shape).

    Args:
      raw_paths: ``raw_paths[band][bank]`` — one RAW source per player, all
        covering the same scan (bank-ascending within each band, as the
        inventory's (band, bank) sort yields them).  Each source may be a
        single file path, a ``.NNNN.raw`` sequence stem, or a path list
        (blit/io/guppi.open_raw): a whole multi-file recording streams as
        one gap-free span per player.
      max_frames: cap the PFB frames reduced (bounds HBM for long scans);
        None reduces the longest common whole-frame span.
      mesh: an existing ``(band, bank)`` Mesh; None builds one matching
        ``raw_paths``' shape over the available devices.

    Returns:
      ``(header, stitched)`` where stitched is a jax.Array
      ``(nband, ntime_out, nif, nbank*nchan*nfft)`` sharded over ``band``
      (replicated across each band's banks), and ``header`` is the full-band
      filterbank header.  Contiguity across banks is validated from the
      headers this process can see (all of them single-process; the local
      players' in a pod); the header is derived from this process's lowest
      (band, bank) player, which describes every band of the same scan.
    """
    import jax
    import jax.numpy as jnp

    from blit.parallel.multihost import local_players

    nband = len(raw_paths)
    nbank = len(raw_paths[0])
    if any(len(row) != nbank for row in raw_paths):
        raise ValueError("raw_paths must be rectangular (nband x nbank)")
    if mesh is None:
        mesh = M.make_mesh(nband, nbank)

    local = sorted(local_players(mesh))
    if not local:
        raise ValueError(
            "this process owns no device of the scan mesh "
            f"(process {jax.process_index()}/{jax.process_count()})"
        )
    # Open this process's players.  Failures do NOT raise yet: the owner
    # must first tell the pod via the agreement below, so every process
    # raises together instead of the peers hanging in the collectives.
    raws = {}
    local_errs = {}
    for b, k in local:
        try:
            r = open_raw(raw_paths[b][k])
            if r.nblocks == 0:
                raise ValueError(f"empty RAW file: {r.path}")
            raws[(b, k)] = r
        except Exception as e:  # noqa: BLE001 — reported pod-wide below
            local_errs[(b, k)] = e

    if raws:
        first = raws[sorted(raws)[0]].header(0)
        nchan = first["OBSNCHAN"]
        npol = 2 if first["NPOL"] > 2 else first["NPOL"]
    else:
        nchan = npol = 0  # nothing openable; the ERR agreement raises below

    # Common whole-frame span across every player (ragged recordings trim),
    # via the same frame-accounting invariant the streaming pipeline uses.
    # Header arithmetic only — each file's data is read exactly once, below.
    # The span, the (nchan, npol) geometry, and any per-player failures are
    # agreed across processes: every process must assemble the same global
    # array shape — and must error together — or the collectives deadlock.
    samps = np.full((nband, nbank), _SAMPS_UNFED, np.int64)
    for (b, k), r in raws.items():
        samps[b, k] = _kept_samples(r)
    for bk in local_errs:
        samps[bk] = _SAMPS_ERR
    gathered = _gather_int64(np.concatenate([samps.ravel(), [nchan, npol]]))
    samps = gathered[:, :-2].min(axis=0).reshape(nband, nbank)
    failed = [tuple(i) for i in np.argwhere(samps == _SAMPS_ERR)]
    if failed:
        mine = "; ".join(
            f"{bk}: {type(e).__name__}: {e}" for bk, e in sorted(local_errs.items())
        )
        cause = next(iter(local_errs.values()), None)
        raise ValueError(
            f"players {failed} failed to open on their owning process"
            + (f" (this process: {mine})" if mine else "")
        ) from cause
    unfed = [tuple(i) for i in np.argwhere(samps == _SAMPS_UNFED)]
    if unfed:
        raise ValueError(f"no process fed players {unfed}")
    geo = gathered[:, -2:]
    geo = geo[(geo != 0).any(axis=1)]
    if not (geo == geo[0]).all():
        raise ValueError(
            f"processes disagree on (nchan, npol): {[tuple(g) for g in geo]}"
        )
    min_samps = int(samps.min())
    frames = usable_frames(min_samps, nfft, ntap, nint)
    if max_frames is not None:
        frames = min(frames, (max_frames // nint) * nint)
    if frames <= 0:
        raise ValueError(
            f"scan too short: {min_samps} samples for nfft={nfft}"
        )
    ntime = (frames + ntap - 1) * nfft

    # One bank in host memory at a time: each local player's block goes
    # straight onto its chip, and the global array is assembled from the
    # single-device shards (no whole-scan host buffer, no device_put to any
    # non-addressable device).
    sharding = M.voltage_sharding(mesh)
    global_shape = (nband, nbank, nchan, ntime, npol, 2)
    shards = []
    for b, k in local:
        r = raws[(b, k)]
        v = _gapless(r, ntime)
        if v.shape[0] != nchan or v.shape[1] < ntime or v.shape[2:] != (npol, 2):
            raise ValueError(
                f"{r.path}: shape {v.shape} incompatible with "
                f"(nchan={nchan}, ntime>={ntime}, npol={npol}, 2)"
            )
        block = np.ascontiguousarray(v[None, None, :, :ntime])
        shards.append(jax.device_put(block, mesh.devices[b, k]))
    volt = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards
    )

    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft, window))
    out = M.band_reduce(
        volt,
        coeffs,
        mesh=mesh,
        nfft=nfft,
        ntap=ntap,
        nint=nint,
        stokes=stokes,
        fft_method=fft_method,
        stitch=True,
        despike_nfpc=nfft if despike else 0,
    )

    # Full-band header: per-bank headers must tile contiguously in
    # frequency.  Validated over the headers this process can see; each
    # local bank k implies the band's bank-0 fch1 (fch1_k - k*per_bank*foff),
    # and all must agree.
    hdrs = {
        (b, k): output_header(r.header(0), nfft=nfft, nint=nint, stokes=stokes)
        for (b, k), r in raws.items()
    }
    h0 = hdrs[local[0]]
    foff = h0["foff"]
    per_bank = h0["nchans"]
    bases: Dict[int, float] = {}
    for (b, k), h in sorted(hdrs.items()):
        if abs(h["foff"] - foff) > 1e-12:
            raise ValueError("banks disagree on fine channel width")
        base = h["fch1"] - k * per_bank * foff
        if b in bases and abs(base - bases[b]) > abs(foff) / 2:
            log.warning(
                "band %d bank %d not contiguous: fch1=%.6f expected %.6f",
                b, k, h["fch1"], bases[b] + k * per_bank * foff,
            )
        bases.setdefault(b, base)
    hdr = dict(h0)
    hdr["fch1"] = bases[local[0][0]]
    hdr["nchans"] = nbank * per_bank
    hdr["nsamps"] = int(out.shape[1])
    hdr["nifs"] = STOKES_NIF[stokes]
    return hdr, out
