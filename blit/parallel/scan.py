"""Mesh-backed scan loading: RAW files → sharded reduction → stitched band.

The end-to-end BASELINE.json config-3 path: every bank's GUPPI RAW voltages
feed the chip that plays that ``BLP<band><bank>`` player, the per-chip
channelization runs under ``shard_map``, and the 8 banks of each band stitch
over ICI (blit/parallel/mesh.band_reduce).  The host holds at most one
bank's int8 voltages at a time — each player's block is placed directly on
its chip and the global sharded array is assembled from those per-device
shards.  This is the TPU rebuild of the reference's whole-scan workflow
(``loadscan``, src/gbt.jl:90-114, which fetched per-bank arrays to the main
process and ``vcat``-ed them there).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from blit.io.guppi import GuppiRaw, open_raw
from blit.ops.channelize import (
    STOKES_NIF,
    output_header,
    pfb_coeffs,
    usable_frames,
)
from blit.parallel import mesh as M

log = logging.getLogger("blit.scan")


def _kept_samples(raw: GuppiRaw) -> int:
    """Gap-free samples the file yields — header arithmetic only (block
    sizes and OVERLAP are in the scanned headers; no data read)."""
    return sum(raw.block_ntime_kept(i) for i in range(raw.nblocks))


def _gapless(raw: GuppiRaw, max_samples: Optional[int]) -> np.ndarray:
    """A RAW file's overlap-trimmed voltages, read ONCE directly into the
    final ``(nchan, total, npol, 2)`` buffer (native threaded pread per
    block when built) — no per-block concatenation, no second pass."""
    hdr = raw.header(0)
    nchan = hdr["OBSNCHAN"]
    npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
    total = _kept_samples(raw)
    if max_samples is not None:
        total = min(total, max_samples)
    out = np.empty((nchan, total, npol, 2), np.int8)
    filled = 0
    for i in range(raw.nblocks):
        if filled >= total:
            break
        nt = min(raw.block_ntime_kept(i), total - filled)
        raw.read_block_into(i, out[:, filled:], t0=0, ntime_keep=nt)
        filled += nt
    return out


def load_scan_mesh(
    raw_paths: Sequence[Sequence[str]],
    *,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fft_method: str = "auto",
    window: str = "hamming",
    despike: bool = True,
    max_frames: Optional[int] = None,
    mesh=None,
) -> Tuple[Dict, "object"]:
    """Reduce one scan's RAW files across the mesh and stitch each band.

    Args:
      raw_paths: ``raw_paths[band][bank]`` — one RAW source per player, all
        covering the same scan (bank-ascending within each band, as the
        inventory's (band, bank) sort yields them).  Each source may be a
        single file path, a ``.NNNN.raw`` sequence stem, or a path list
        (blit/io/guppi.open_raw): a whole multi-file recording streams as
        one gap-free span per player.
      max_frames: cap the PFB frames reduced (bounds HBM for long scans);
        None reduces the longest common whole-frame span.
      mesh: an existing ``(band, bank)`` Mesh; None builds one matching
        ``raw_paths``' shape over the available devices.

    Returns:
      ``(header, stitched)`` where stitched is a jax.Array
      ``(nband, ntime_out, nif, nbank*nchan*nfft)`` sharded over ``band``
      (replicated across each band's banks), and ``header`` is the full-band
      filterbank header (validated contiguous across banks).
    """
    import jax.numpy as jnp

    nband = len(raw_paths)
    nbank = len(raw_paths[0])
    if any(len(row) != nbank for row in raw_paths):
        raise ValueError("raw_paths must be rectangular (nband x nbank)")
    if mesh is None:
        mesh = M.make_mesh(nband, nbank)

    raws = [[open_raw(p) for p in row] for row in raw_paths]
    for row in raws:
        for r in row:
            if r.nblocks == 0:
                raise ValueError(f"empty RAW file: {r.path}")

    # Common whole-frame span across every player (ragged recordings trim),
    # via the same frame-accounting invariant the streaming pipeline uses.
    # Header arithmetic only — each file's data is read exactly once, below.
    min_samps = min(_kept_samples(r) for row in raws for r in row)
    frames = usable_frames(min_samps, nfft, ntap, nint)
    if max_frames is not None:
        frames = min(frames, (max_frames // nint) * nint)
    if frames <= 0:
        raise ValueError(
            f"scan too short: {min_samps} samples for nfft={nfft}"
        )
    ntime = (frames + ntap - 1) * nfft

    first = raws[0][0].header(0)
    nchan = first["OBSNCHAN"]
    npol = 2 if first["NPOL"] > 2 else first["NPOL"]
    # One bank in host memory at a time: each player's block goes straight
    # onto its chip, and the global array is assembled from the
    # single-device shards (no whole-scan host buffer).
    import jax

    sharding = M.voltage_sharding(mesh)
    global_shape = (nband, nbank, nchan, ntime, npol, 2)
    shards = []
    for b, row in enumerate(raws):
        for k, r in enumerate(row):
            v = _gapless(r, ntime)
            if v.shape[0] != nchan or v.shape[1] < ntime or v.shape[2:] != (npol, 2):
                raise ValueError(
                    f"{r.path}: shape {v.shape} incompatible with "
                    f"(nchan={nchan}, ntime>={ntime}, npol={npol}, 2)"
                )
            block = np.ascontiguousarray(v[None, None, :, :ntime])
            shards.append(jax.device_put(block, mesh.devices[b, k]))
    volt = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards
    )

    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft, window))
    out = M.band_reduce(
        volt,
        coeffs,
        mesh=mesh,
        nfft=nfft,
        ntap=ntap,
        nint=nint,
        stokes=stokes,
        fft_method=fft_method,
        stitch=True,
        despike_nfpc=nfft if despike else 0,
    )

    # Full-band header: per-bank headers must tile contiguously in frequency.
    hdrs = [output_header(r.header(0), nfft=nfft, nint=nint, stokes=stokes)
            for r in raws[0]]
    foff = hdrs[0]["foff"]
    per_bank = hdrs[0]["nchans"]
    for k, h in enumerate(hdrs):
        if abs(h["foff"] - foff) > 1e-12:
            raise ValueError("banks disagree on fine channel width")
        expect = hdrs[0]["fch1"] + k * per_bank * foff
        if abs(h["fch1"] - expect) > abs(foff) / 2:
            log.warning(
                "bank %d not contiguous: fch1=%.6f expected %.6f",
                k, h["fch1"], expect,
            )
    hdr = dict(hdrs[0])
    hdr["nchans"] = nbank * per_bank
    hdr["nsamps"] = int(out.shape[1])
    hdr["nifs"] = STOKES_NIF[stokes]
    return hdr, out
