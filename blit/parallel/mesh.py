"""The TPU data plane: the BL@GBT ``(band, bank)`` topology as a device mesh.

SURVEY.md §2.4/§5: the reference's only parallelism is frequency-domain
sharding — 8 banks each own a contiguous 187.5 MHz slice of a 1500 MHz band,
and the sole cross-node reduction (band stitching) runs as a main-process
``vcat`` in the commented-out ``loadscan`` (src/gbt.jl:103).  Here the
topology is a ``jax.sharding.Mesh`` with axes ``('band', 'bank')``, each chip
plays one ``BLP<band><bank>`` player, the frequency axis is sharded over
``bank``, and the stitch is an ``all_gather`` over ICI — no host
materialization anywhere (BASELINE.json config 3).

Everything is built on ``shard_map`` so the collectives are explicit and the
per-chip body is exactly the single-chip reduction from
:mod:`blit.ops.channelize` — one code path from 1 chip to a 64-chip pod.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax

from blit.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blit.ops.channelize import channelize
from blit.ops.despike import despike

BAND_AXIS = "band"
BANK_AXIS = "bank"


def make_mesh(
    nband: int = 1, nbank: int = 8, devices: Optional[list] = None
) -> Mesh:
    """A ``(band, bank)`` mesh over the first ``nband*nbank`` devices.

    The bank axis should ride ICI (it carries the stitch/beamform
    collectives); keeping it minor in the device order does that on TPU
    slices, mirroring how the racks' 8 banks share a 1500 MHz IF
    (README.md:17-24).
    """
    if devices is None:
        devices = jax.devices()
    n = nband * nbank
    if len(devices) < n:
        raise ValueError(f"need {n} devices for a {nband}x{nbank} mesh, "
                         f"have {len(devices)}")
    dev = np.asarray(devices[:n]).reshape(nband, nbank)
    return Mesh(dev, (BAND_AXIS, BANK_AXIS))


def voltage_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a global voltage array ``(nband, nbank, nchan, ntime,
    npol, 2)``: one (band, bank) block per chip."""
    return NamedSharding(mesh, P(BAND_AXIS, BANK_AXIS))


def filterbank_sharding(mesh: Mesh, stitched: bool) -> NamedSharding:
    """Sharding of the reduced product ``(nband, ntime, nif, nchans)``:
    channel axis sharded over ``bank`` (unstitched) or replicated across the
    bank axis (stitched)."""
    if stitched:
        return NamedSharding(mesh, P(BAND_AXIS, None, None, None))
    return NamedSharding(mesh, P(BAND_AXIS, None, None, BANK_AXIS))


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "nfft", "ntap", "nint", "stokes", "fft_method", "stitch",
        "despike_nfpc", "fqav_by", "dtype",
    ),
)
def band_reduce(
    voltages: jax.Array,
    coeffs: jax.Array,
    *,
    mesh: Mesh,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fft_method: str = "auto",
    stitch: bool = True,
    despike_nfpc: int = 0,
    fqav_by: int = 1,
    dtype: str = "float32",
) -> jax.Array:
    """The full multi-chip reduction step: every chip channelizes its own
    bank's voltage block, then the 8 banks of each band stitch their fine
    spectra into a contiguous band over ICI.

    Args:
      voltages: int8 ``(nband, nbank, nchan, ntime, npol, 2)``, sharded with
        :func:`voltage_sharding` (one leading block per chip).
      stitch: gather the bank-sharded channel axis into a contiguous band on
        every chip of the band row (``all_gather`` over ``bank`` — the ICI
        rebuild of the reference's main-process ``vcat``, src/gbt.jl:103).
        When False the product stays frequency-sharded (the SP-like layout)
        and no collective runs at all.
      despike_nfpc: if >= 2, repair each coarse channel's DC fine channel
        post-stitch (src/gbt.jl:101-111 semantics, vectorized).  In OUTPUT
        channel units: with ``fqav_by > 1`` pass ``nfft // fqav_by``.
      fqav_by: on-device frequency-averaging epilogue applied per chip
        BEFORE the stitch collective — the reference's reduce-before-the-
        wire lever (src/gbtworkerfunctions.jl:16-20) mapped onto ICI: the
        all_gather moves ``fqav_by``x fewer bytes.
      dtype: working dtype of the per-chip channelizer stages ("float32"
        | "bfloat16") — the single-chip pipeline's biggest measured lever
        (DESIGN.md §3: bf16 stages halve the HBM intermediates and run
        the official bench), now reachable from the mesh path too.  The
        product stays float32 either way.

    Returns:
      float32 ``(nband, ntime_out, nif, nchans)`` where ``nchans`` is the
      full band (stitched) or the global concatenation of per-bank channels
      (unstitched, sharded over ``bank``).
    """
    in_specs = (P(BAND_AXIS, BANK_AXIS), P())
    out_specs = (
        P(BAND_AXIS, None, None, None)
        if stitch
        else P(BAND_AXIS, None, None, BANK_AXIS)
    )

    def step(v, h):
        # v: (1, 1, nchan, ntime, npol, 2) — this chip's block.
        out = channelize(
            v[0, 0], h, nfft=nfft, ntap=ntap, nint=nint, stokes=stokes,
            fft_method=fft_method, fqav_by=fqav_by, dtype=dtype,
        )  # (t, nif, nchan*nfft//fqav_by)
        if stitch:
            out = jax.lax.all_gather(out, BANK_AXIS, axis=2, tiled=True)
            if despike_nfpc >= 2:
                out = despike(out, despike_nfpc)
        elif despike_nfpc >= 2:
            # Coarse channels never straddle banks, so the per-bank despike
            # is exact in the sharded layout too.
            out = despike(out, despike_nfpc)
        return out[None]  # leading band axis block

    # check_vma=False when stitching: the varying-mesh-axes analysis cannot
    # statically see that all_gather's output is bank-invariant.
    return shard_map(
        step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=not stitch,
    )(voltages, coeffs)


def stitch_bands(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Standalone stitch: gather a bank-sharded filterbank ``(nband, t, nif,
    nchans_sharded)`` into a contiguous band, replicated across each band's
    banks.  Equivalent to ``band_reduce(..., stitch=True)``'s epilogue; kept
    separate so host-read products (e.g. FBH5 slabs loaded via
    :mod:`blit.gbt`) can be stitched on-device too."""

    def gather(blk):
        return jax.lax.all_gather(blk, BANK_AXIS, axis=3, tiled=True)

    return shard_map(
        gather,
        mesh=mesh,
        in_specs=P(BAND_AXIS, None, None, BANK_AXIS),
        out_specs=P(BAND_AXIS, None, None, None),
        check_vma=False,  # all_gather output is bank-invariant
    )(x)


def shard_voltages(
    voltages: np.ndarray, mesh: Mesh
) -> jax.Array:
    """Place a host ``(nband, nbank, ...)`` voltage array onto the mesh with
    one block per chip (the host→device feed for tests and the dry run)."""
    return jax.device_put(voltages, voltage_sharding(mesh))
