"""The TPU data plane: the BL@GBT ``(band, bank)`` topology as a device mesh.

SURVEY.md §2.4/§5: the reference's only parallelism is frequency-domain
sharding — 8 banks each own a contiguous 187.5 MHz slice of a 1500 MHz band,
and the sole cross-node reduction (band stitching) runs as a main-process
``vcat`` in the commented-out ``loadscan`` (src/gbt.jl:103).  Here the
topology is a ``jax.sharding.Mesh`` with axes ``('band', 'bank')``, each chip
plays one ``BLP<band><bank>`` player, the frequency axis is sharded over
``bank``, and the stitch is an ``all_gather`` over ICI — no host
materialization anywhere (BASELINE.json config 3).

Everything is built on ``shard_map`` so the collectives are explicit and the
per-chip body is exactly the single-chip reduction from
:mod:`blit.ops.channelize` — one code path from 1 chip to a 64-chip pod.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Union

import numpy as np

import jax

from blit.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blit.ops.channelize import channelize
from blit.ops.despike import despike

BAND_AXIS = "band"
BANK_AXIS = "bank"

# -- partition rules ---------------------------------------------------------
#
# Every array role of the sharded reduction plane (ISSUE 9) names its
# PartitionSpec HERE, in one registry, instead of each call site hand-rolling
# specs: the feed (`put_local_shards`), the fold accumulators
# (:class:`ShardedAccumulator` — beamform/correlate carry these specs across
# donated windows), and the product/readback side all resolve through
# `partition_rule`, so a layout change is one edit and the specs cannot
# drift between the dispatch and the readback that interprets its shards.

PARTITION_RULES: Dict[str, P] = {
    # Ingest: int8 voltage blocks (nband, nbank, nchan, ntime, npol, 2) —
    # one (band, bank) block per chip.
    "voltages": P(BAND_AXIS, BANK_AXIS),
    # Replicated small operands (PFB coefficient banks, thresholds).
    "replicated": P(),
    # Products (nband, ntime, nif, nchans): channel axis sharded over bank
    # (pre-stitch), or replicated across each band row (post-stitch).
    "filterbank_sharded": P(BAND_AXIS, None, None, BANK_AXIS),
    "filterbank_stitched": P(BAND_AXIS, None, None, None),
    # Packed per-chip hit tables (nband, nbank, nbands, k, 4) — the search
    # plane's device-side extraction output (blit/ops/pallas_dedoppler).
    "packed_hits": P(BAND_AXIS, BANK_AXIS),
    # Fold accumulators (donated across windows).  The beamform total-power
    # accumulator is psum output, replicated; the correlator's partial
    # visibilities stay band-sharded (leading band block axis) with the
    # channel axis over bank — standard (nband, a, b, c, f, p, q) vs packed
    # (nband, c, f, a, p, b, q) layouts.
    "beamform_acc": P(),
    "vis_acc_standard": P(BAND_AXIS, None, None, BANK_AXIS),
    "vis_acc_packed": P(BAND_AXIS, BANK_AXIS),
}

# The collective-latency histograms of the sharded plane (ISSUE 9): every
# honestly-timeable collective observes into these Timeline hists, and the
# bench's mesh_collectives leg reports their p50/p99.
MESH_HISTS = ("mesh.gather_s", "mesh.psum_s")


def partition_rule(role: Union[str, P]) -> P:
    """The registry's PartitionSpec for ``role`` (a spec passes through —
    callers that already hold one can use the same entry points)."""
    if isinstance(role, str):
        try:
            return PARTITION_RULES[role]
        except KeyError:
            raise KeyError(
                f"unknown partition rule {role!r}; known roles: "
                f"{sorted(PARTITION_RULES)}"
            ) from None
    return role


def sharding_for(mesh: Mesh, role: Union[str, P]) -> NamedSharding:
    """``NamedSharding`` of ``role`` on ``mesh`` (partition-rule-driven —
    the one way array placement is spelled on the sharded plane)."""
    return NamedSharding(mesh, partition_rule(role))


def gather_ici_bytes(shard_bytes: int, axis_size: int) -> int:
    """Per-chip ICI bytes one ``all_gather`` moves: each chip RECEIVES
    every other shard of its axis row — ``(axis_size - 1) * shard_bytes``
    (ring schedule; send volume is the same, counted once)."""
    return max(0, axis_size - 1) * shard_bytes


def psum_ici_bytes(nbytes: int, axis_size: int) -> int:
    """Per-chip ICI bytes one ``psum`` moves for an ``nbytes`` operand:
    ring all-reduce = reduce-scatter + all-gather, ``2 * (n-1)/n *
    nbytes`` received per chip."""
    if axis_size <= 1:
        return 0
    return int(2 * (axis_size - 1) * nbytes // axis_size)


def record_ici(timeline, collective: str, nbytes: int,
               seconds: Optional[float] = None) -> None:
    """Account one collective on a Timeline (ISSUE 9 telemetry contract):
    cumulative per-chip ICI traffic on the ``mesh.ici`` stage, a
    per-dispatch byte histogram (``mesh.<collective>_ici_bytes``), and —
    when the caller could honestly time the collective's own dispatch
    (a probe window, the correlator's closing psum, the bench's pure
    collective legs) — a latency sample into ``mesh.<collective>_s``
    (:data:`MESH_HISTS`).  ``collective`` is ``"gather"`` or ``"psum"``."""
    s = timeline.stages["mesh.ici"]
    s.calls += 1
    s.bytes += int(nbytes)
    timeline.observe(f"mesh.{collective}_ici_bytes", float(nbytes))
    if seconds is not None:
        s.seconds += seconds
        timeline.observe(f"mesh.{collective}_s", seconds)


def make_mesh(
    nband: int = 1, nbank: int = 8, devices: Optional[list] = None
) -> Mesh:
    """A ``(band, bank)`` mesh over the first ``nband*nbank`` devices.

    The bank axis should ride ICI (it carries the stitch/beamform
    collectives); keeping it minor in the device order does that on TPU
    slices, mirroring how the racks' 8 banks share a 1500 MHz IF
    (README.md:17-24).
    """
    if devices is None:
        devices = jax.devices()
    n = nband * nbank
    if len(devices) < n:
        raise ValueError(f"need {n} devices for a {nband}x{nbank} mesh, "
                         f"have {len(devices)}")
    dev = np.asarray(devices[:n]).reshape(nband, nbank)
    return Mesh(dev, (BAND_AXIS, BANK_AXIS))


def voltage_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a global voltage array ``(nband, nbank, nchan, ntime,
    npol, 2)``: one (band, bank) block per chip."""
    return sharding_for(mesh, "voltages")


def filterbank_sharding(mesh: Mesh, stitched: bool) -> NamedSharding:
    """Sharding of the reduced product ``(nband, ntime, nif, nchans)``:
    channel axis sharded over ``bank`` (unstitched) or replicated across the
    bank axis (stitched)."""
    return sharding_for(
        mesh, "filterbank_stitched" if stitched else "filterbank_sharded"
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "nfft", "ntap", "nint", "stokes", "fft_method", "stitch",
        "despike_nfpc", "fqav_by", "dtype",
    ),
)
def band_reduce(
    voltages: jax.Array,
    coeffs: jax.Array,
    *,
    mesh: Mesh,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fft_method: str = "auto",
    stitch: bool = True,
    despike_nfpc: int = 0,
    fqav_by: int = 1,
    dtype: str = "float32",
) -> jax.Array:
    """The full multi-chip reduction step: every chip channelizes its own
    bank's voltage block, then the 8 banks of each band stitch their fine
    spectra into a contiguous band over ICI.

    Args:
      voltages: int8 ``(nband, nbank, nchan, ntime, npol, 2)``, sharded with
        :func:`voltage_sharding` (one leading block per chip).
      stitch: gather the bank-sharded channel axis into a contiguous band on
        every chip of the band row (``all_gather`` over ``bank`` — the ICI
        rebuild of the reference's main-process ``vcat``, src/gbt.jl:103).
        When False the product stays frequency-sharded (the SP-like layout)
        and no collective runs at all.
      despike_nfpc: if >= 2, repair each coarse channel's DC fine channel
        post-stitch (src/gbt.jl:101-111 semantics, vectorized).  In OUTPUT
        channel units: with ``fqav_by > 1`` pass ``nfft // fqav_by``.
      fqav_by: on-device frequency-averaging epilogue applied per chip
        BEFORE the stitch collective — the reference's reduce-before-the-
        wire lever (src/gbtworkerfunctions.jl:16-20) mapped onto ICI: the
        all_gather moves ``fqav_by``x fewer bytes.
      dtype: working dtype of the per-chip channelizer stages ("float32"
        | "bfloat16") — the single-chip pipeline's biggest measured lever
        (DESIGN.md §3: bf16 stages halve the HBM intermediates and run
        the official bench), now reachable from the mesh path too.  The
        product stays float32 either way.

    Returns:
      float32 ``(nband, ntime_out, nif, nchans)`` where ``nchans`` is the
      full band (stitched) or the global concatenation of per-bank channels
      (unstitched, sharded over ``bank``).
    """
    in_specs = (P(BAND_AXIS, BANK_AXIS), P())
    out_specs = (
        P(BAND_AXIS, None, None, None)
        if stitch
        else P(BAND_AXIS, None, None, BANK_AXIS)
    )

    def step(v, h):
        # v: (1, 1, nchan, ntime, npol, 2) — this chip's block.
        out = channelize(
            v[0, 0], h, nfft=nfft, ntap=ntap, nint=nint, stokes=stokes,
            fft_method=fft_method, fqav_by=fqav_by, dtype=dtype,
        )  # (t, nif, nchan*nfft//fqav_by)
        if stitch:
            out = jax.lax.all_gather(out, BANK_AXIS, axis=2, tiled=True)
            if despike_nfpc >= 2:
                out = despike(out, despike_nfpc)
        elif despike_nfpc >= 2:
            # Coarse channels never straddle banks, so the per-bank despike
            # is exact in the sharded layout too.
            out = despike(out, despike_nfpc)
        return out[None]  # leading band axis block

    # check_vma=False when stitching: the varying-mesh-axes analysis cannot
    # statically see that all_gather's output is bank-invariant.
    return shard_map(
        step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=not stitch,
    )(voltages, coeffs)


def stitch_bands(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Standalone stitch: gather a bank-sharded filterbank ``(nband, t, nif,
    nchans_sharded)`` into a contiguous band, replicated across each band's
    banks.  Equivalent to ``band_reduce(..., stitch=True)``'s epilogue; kept
    separate so host-read products (e.g. FBH5 slabs loaded via
    :mod:`blit.gbt`) can be stitched on-device too.  The despike-free case
    of :func:`stitch_despike` — ONE stitch program, not two to keep in
    sync."""
    return stitch_despike(x, mesh=mesh, despike_nfpc=0)


@functools.partial(jax.jit, static_argnames=("mesh", "despike_nfpc"))
def stitch_despike(x: jax.Array, *, mesh: Mesh, despike_nfpc: int = 0):
    """The sharded plane's standalone stitch program: gather a bank-sharded
    filterbank ``(nband, t, nif, nchans_sharded)`` into a contiguous band
    (replicated across each band's banks) and optionally repair the
    per-coarse-channel DC spikes post-stitch.

    This is ``band_reduce(stitch=True)``'s epilogue split into its own
    dispatch so the window loop can TIME the all_gather honestly
    (``mesh.gather_s``) and account its ICI bytes per window — the
    per-chip channelize and the collective land in separate programs,
    with the per-chip program bit-identical to the pool path's
    single-chip ``channelize`` (tests/test_sharded.py pins this)."""

    def gather(blk):
        out = jax.lax.all_gather(blk, BANK_AXIS, axis=3, tiled=True)
        if despike_nfpc >= 2:
            out = despike(out, despike_nfpc)
        return out

    return shard_map(
        gather,
        mesh=mesh,
        in_specs=partition_rule("filterbank_sharded"),
        out_specs=partition_rule("filterbank_stitched"),
        check_vma=False,  # all_gather output is bank-invariant
    )(x)


def shard_voltages(
    voltages: np.ndarray, mesh: Mesh
) -> jax.Array:
    """Place a host ``(nband, nbank, ...)`` voltage array onto the mesh with
    one block per chip (the host→device feed for tests and the dry run)."""
    return jax.device_put(voltages, voltage_sharding(mesh))


def put_local_shards(
    blocks: Dict, mesh: Mesh, global_shape, role: Union[str, P] = "voltages"
) -> jax.Array:
    """``jax.device_put`` with shardings, multi-host-shaped: assemble the
    global sharded array for ``role`` from one host block per LOCALLY
    OWNED ``(band, bank)`` player — the sharded plane's replacement for
    the pool path's per-worker H2D scatter.

    ``blocks`` maps ``(band, bank)`` to that player's host block with the
    leading ``(1, 1, ...)`` block axes already present.  Each block goes
    straight onto its chip and the global array is built from the
    single-device shards, so the host never materializes the whole scan
    and no ``device_put`` targets a non-addressable device (the
    multi-process contract of :func:`blit.parallel.scan._feed_window`,
    now partition-rule-driven)."""
    shards = [
        jax.device_put(blk, mesh.devices[b, k])
        for (b, k), blk in sorted(blocks.items())
    ]
    return jax.make_array_from_single_device_arrays(
        tuple(global_shape), sharding_for(mesh, role), shards
    )


class ShardedAccumulator:
    """A windowed fold accumulator that CARRIES its partition rule
    (ISSUE 9 tentpole): the value pytree, the mesh, and the
    :data:`PARTITION_RULES` entry that shards it travel together, so
    every fold dispatch and the final readback agree on placement by
    construction.

    Contract (the ``correlate_stream`` fold discipline, generalized):

    - :meth:`init` installs the first window's value (already sharded by
      the producing program — its out_specs must match this rule).
    - :meth:`fold` applies a caller-jitted fold whose FIRST argument is
      the current value, **donated** (``donate_argnums=0`` on the
      caller's jit): HBM is reused in place across the whole stream and
      the accumulator never exists twice.  The fold's out_specs must
      preserve the rule — :meth:`fold` asserts the returned sharding
      still matches, so a drifted spec fails loudly at the first window
      instead of silently regathering every fold.
    - :attr:`value` holds the live pytree; ``spec``/``sharding`` expose
      the rule for finish programs (the correlator's closing band psum).
    """

    def __init__(self, mesh: Mesh, rule: Union[str, P]):
        self.mesh = mesh
        self.rule = rule
        self.spec = partition_rule(rule)
        self.value = None

    @property
    def sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec)

    def init(self, value):
        self.value = value
        self._check(value)
        return value

    def fold(self, fn, *args, **kw):
        """``value = fn(value, *args, **kw)`` — ``fn`` must donate its
        first argument (a donated token can no longer be waited on, so
        callers must lag-sync BEFORE the next fold, the
        :class:`blit.outplane.FoldInFlight` rule)."""
        if self.value is None:
            raise RuntimeError("ShardedAccumulator.fold before init")
        self.value = fn(self.value, *args, **kw)
        self._check(self.value)
        return self.value

    def _check(self, value) -> None:
        want = self.sharding
        for leaf in jax.tree_util.tree_leaves(value):
            got = getattr(leaf, "sharding", None)
            if got is not None and not got.is_equivalent_to(want, leaf.ndim):
                raise ValueError(
                    f"accumulator sharding drifted from rule {self.rule!r}: "
                    f"{got} != {want}"
                )
