"""File-fed antenna-array products: per-antenna GUPPI RAW recordings →
sharded planar voltages for the collective products (VERDICT r3 item 4).

BASELINE configs 4-5 prescribe beamforming and FX correlation over the
mesh; :mod:`blit.parallel.beamform` / :mod:`blit.parallel.correlator`
implement the collectives, and this module is the missing data plane: it
maps an antenna array's RAW recordings (one recording per antenna — the
per-element capture layout of BL's array backends; the GBT reference has
no array data, its single-dish recordings are per *bank*,
src/gbt.jl:28-42) onto ``antenna_sharding`` / ``correlator_sharding``
with per-process file locality, the same way blit/parallel/scan.py feeds
the (band, bank) filterbank mesh.

Voltages arrive planar — ``(re, im)`` float32 pairs dequantized from the
RAW int8 complex samples — because this TPU backend has no complex-dtype
HLOs (DESIGN.md §1).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from blit.io.guppi import open_raw
from blit.parallel.scan import _gapless, _gather_int64, _kept_samples

Planar = Tuple["object", "object"]

_ERR = 1 << 60  # rides the pod-wide agreement; see scan._SAMPS_ERR


def _resolve_plane_dtype(dtype):
    """Device residency dtype for the planar loaders: f32 or bf16 (bf16
    is lossless for 8-bit RAW voltages and halves HBM/ICI traffic in the
    collectives — DESIGN.md §9 r5 addendum)."""
    import jax.numpy as jnp

    d = jnp.dtype(dtype)
    if d not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(f"dtype must be float32 or bfloat16, got {dtype}")
    return d


def _open_antennas(raw_paths: Sequence, needed: Sequence[int]):
    """Open the antenna recordings in ``needed`` (indices into
    ``raw_paths``) and agree (samples, nchan, npol) pod-wide with
    symmetric errors, like the scan loader's player agreement.

    Every process reports a sample count (or the ERR marker) for every
    antenna it was asked to open; the cross-process MIN both finds the
    common span and propagates any opener's failure to every peer before
    the collectives run.  Antennas nobody opened stay at INT64_MAX // 2
    and are caught by the caller's coverage check.
    """
    nant = len(raw_paths)
    raws, errs = {}, {}
    for a in needed:
        try:
            r = open_raw(raw_paths[a])
            if r.nblocks == 0:
                raise ValueError(f"empty RAW file: {r.path}")
            raws[a] = r
        except Exception as e:  # noqa: BLE001 — reported pod-wide below
            errs[a] = e

    geo = (0, 0)
    if raws:
        h = raws[sorted(raws)[0]].header(0)
        geo = (h["OBSNCHAN"], 2 if h["NPOL"] > 2 else h["NPOL"])
    samps = np.full(nant, (1 << 62) - 1, np.int64)
    for a, r in raws.items():
        samps[a] = _kept_samples(r)
    for a in errs:
        samps[a] = _ERR
    gathered = _gather_int64(np.concatenate([samps, geo]))
    samps = gathered[:, :-2].min(axis=0)
    failed = [int(a) for a in np.argwhere(samps == _ERR).ravel()]
    if failed:
        mine = "; ".join(
            f"antenna {a}: {type(e).__name__}: {e}"
            for a, e in sorted(errs.items())
        )
        raise ValueError(
            f"antennas {failed} failed to open on their owning process"
            + (f" (this process: {mine})" if mine else "")
        ) from next(iter(errs.values()), None)
    geos = gathered[:, -2:]
    geos = geos[(geos != 0).any(axis=1)]
    if len(geos) and not (geos == geos[0]).all():
        raise ValueError(
            f"processes disagree on (nchan, npol): {[tuple(g) for g in geos]}"
        )
    nchan, npol = (int(geos[0][0]), int(geos[0][1])) if len(geos) else (0, 0)
    return raws, int(samps.min()), nchan, npol


def _planar_block(raw, start: int, ntime: int) -> Tuple[np.ndarray, np.ndarray]:
    """Samples ``[start, start+ntime)`` of one recording as planar float32
    ``(nchan, ntime, npol)`` re/im planes (RAW int8 (re, im) dequantized)."""
    v = _gapless(raw, ntime, skip=start)  # (nchan, ntime, npol, 2) int8
    if v.shape[1] < ntime:
        raise ValueError(
            f"{raw.path}: {v.shape[1]} samples from offset {start}, "
            f"need {ntime}"
        )
    v = v[:, :ntime]
    # astype yields fresh C-contiguous planes; int8 → f32 is exact.
    return v[..., 0].astype(np.float32), v[..., 1].astype(np.float32)


def load_antennas_mesh(
    raw_paths: Sequence,
    *,
    mesh,
    axis: str = "bank",
    max_samples: Optional[int] = None,
    dtype="float32",
    layout: str = "antenna",
) -> Tuple[Dict, Planar]:
    """Load per-antenna RAW recordings onto the beamform layout:
    ``(nant, nchan, ntime, npol)`` planar voltages with the antenna axis
    sharded over ``axis`` (:func:`blit.parallel.beamform.antenna_sharding`).

    Each process opens ONLY the antennas whose chips it owns (the
    per-element twin of the scan loader's player locality); the common
    sample span is agreed pod-wide.  Returns ``(header, (vr, vi))`` where
    ``header`` is the first local antenna's RAW header plus the agreed
    ``ntime``.

    ``raw_paths``: one RAW source per antenna (path / ``.NNNN.raw`` stem /
    path list), length divisible by the ``axis`` mesh size.

    ``dtype``: device residency of the planes — ``"float32"`` (default)
    or ``"bfloat16"``.  RAW voltages are 8-bit integers, exactly
    representable in bf16, so bf16 residency is LOSSLESS for the data
    plane and halves both HBM reads and ICI psum bytes downstream
    (:func:`blit.parallel.beamform.beamform` runs its whole contraction
    in bf16 for bf16 inputs — measured +26% end-to-end, DESIGN.md §9 r5
    addendum).

    ``layout``: ``"antenna"`` (above) or ``"chan"`` — packed chan-major
    ``(nchan, nant, npol, ntime)`` planes for ``beamform(layout="chan")``
    and its fused detect kernel (measured 2.1x; the pack happens in the
    host copy this loader performs anyway, so it is free here, unlike a
    device-side transpose).
    """
    import jax

    from blit.parallel.beamform import antenna_sharding

    dev_dtype = _resolve_plane_dtype(dtype)
    if layout not in ("antenna", "chan"):
        raise ValueError(f"bad layout {layout!r}")

    nant = len(raw_paths)
    ax_size = mesh.shape[axis]
    if nant % ax_size:
        raise ValueError(
            f"nant={nant} must divide over the {ax_size}-way {axis!r} axis"
        )
    per = nant // ax_size
    if layout == "chan":
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P(None, axis))
    else:
        sharding = antenna_sharding(mesh, axis)

    # The antenna blocks this process must place: one per addressable
    # device, covering the antenna slice that device owns — the device's
    # mesh coordinate along `axis` (both layouts shard ONLY the antenna
    # dim in equal blocks, so the block index IS that coordinate).
    ax_i = list(mesh.axis_names).index(axis)

    def ant_lo(d) -> int:
        pos = np.argwhere(mesh.devices == d)[0]
        return int(pos[ax_i]) * per

    local_ants = sorted({
        a
        for d in sharding.addressable_devices
        for a in range(ant_lo(d), ant_lo(d) + per)
    })
    raws, min_samps, nchan, npol = _open_antennas(raw_paths, local_ants)
    ntime = min_samps if max_samples is None else min(min_samps, max_samples)
    if ntime <= 0:
        raise ValueError(f"no common samples across {nant} antennas")

    shards_r, shards_i = [], []
    for d in sharding.addressable_devices:
        lo = ant_lo(d)
        if layout == "chan":
            br = np.empty((nchan, per, npol, ntime), np.float32)
            bi = np.empty_like(br)
            for j, a in enumerate(range(lo, lo + per)):
                pr, pi = _planar_block(raws[a], 0, ntime)  # (c, t, p)
                br[:, j] = np.transpose(pr, (0, 2, 1))
                bi[:, j] = np.transpose(pi, (0, 2, 1))
        else:
            br = np.empty((per, nchan, ntime, npol), np.float32)
            bi = np.empty_like(br)
            for j, a in enumerate(range(lo, lo + per)):
                br[j], bi[j] = _planar_block(raws[a], 0, ntime)
        # int8-origin values are exact in bf16: the cast loses nothing.
        shards_r.append(jax.device_put(br.astype(dev_dtype, copy=False), d))
        shards_i.append(jax.device_put(bi.astype(dev_dtype, copy=False), d))
    global_shape = (
        (nchan, nant, npol, ntime)
        if layout == "chan"
        else (nant, nchan, ntime, npol)
    )
    vr = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_r
    )
    vi = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_i
    )
    hdr = dict(raws[local_ants[0]].header(0))
    hdr["_ntime"] = ntime
    hdr["_nant"] = nant
    return hdr, (vr, vi)




def load_correlator_mesh(
    raw_paths: Sequence,
    *,
    mesh,
    nfft: int,
    ntap: int = 4,
    max_samples: Optional[int] = None,
    dtype="float32",
) -> Tuple[Dict, Planar]:
    """Load per-antenna RAW recordings onto the FX-correlator layout:
    ``(nant, nchan, ntime, npol)`` planar voltages with frequency sharded
    over ``bank`` and time over ``band``
    (:func:`blit.parallel.correlator.correlator_sharding`).

    Antennas are replicated across the mesh in this layout, so every
    process reads every antenna's recording — but only its band rows'
    TIME WINDOW of it (the band axis is the file-split that preserves
    locality here; a per-chip channel subset still comes from the same
    bytes because RAW blocks interleave all channels).  Each band row's
    segment is trimmed to whole ``nfft`` blocks with at least ``ntap``
    of them, matching ``correlate``'s segment semantics.

    ``dtype``: ``"float32"`` (default) or ``"bfloat16"`` residency — see
    :func:`load_antennas_mesh`; ``correlate`` runs its bf16-staged path
    for bf16 planes (measured +25% at nant=64, DESIGN.md §9 r5).
    """
    import jax

    from blit.parallel.correlator import correlator_sharding

    dev_dtype = _resolve_plane_dtype(dtype)

    nant = len(raw_paths)
    nband = mesh.shape["band"]
    nbank = mesh.shape["bank"]
    sharding = correlator_sharding(mesh)

    # Every local device needs every antenna: open them all, agree span.
    raws, min_samps, nchan, npol = _open_antennas(
        raw_paths, list(range(nant))
    )
    if nchan % nbank:
        raise ValueError(f"nchan={nchan} must divide over {nbank} banks")
    total = min_samps if max_samples is None else min(min_samps, max_samples)
    seg = (total // nband) // nfft * nfft
    if seg // nfft < ntap:
        raise ValueError(
            f"correlator needs >= {ntap} nfft-blocks per band segment; "
            f"have {seg // nfft} (total {total} samples over {nband} bands)"
        )
    ntime = seg * nband
    cper = nchan // nbank

    # Read each (antenna, band-row) time window ONCE, slice per bank.
    # Devices are grouped by band row so a row's decoded blocks are freed
    # as soon as that row's local devices are fed (device_put has copied
    # them) — host residency is ONE band row of all antennas, not every
    # owned row at once (ADVICE r4: the flat cache held nant * nchan * seg
    # * npol * 8 bytes per owned row simultaneously).
    shards_r, shards_i = [], []
    dev_map = sharding.addressable_devices_indices_map(
        (nant, nchan, ntime, npol)
    )
    by_band: Dict[int, list] = {}
    for d, idx in dev_map.items():
        b = (idx[2].start or 0) // seg  # band row from the time slice
        by_band.setdefault(b, []).append((d, idx))
    for b in sorted(by_band):
        blocks = [_planar_block(raws[a], b * seg, seg) for a in range(nant)]
        for d, idx in by_band[b]:
            k = (idx[1].start or 0) // cper
            br = np.stack([blocks[a][0][k * cper:(k + 1) * cper]
                           for a in range(nant)])
            bi = np.stack([blocks[a][1][k * cper:(k + 1) * cper]
                           for a in range(nant)])
            shards_r.append(jax.device_put(br.astype(dev_dtype, copy=False), d))
            shards_i.append(jax.device_put(bi.astype(dev_dtype, copy=False), d))
        del blocks
    global_shape = (nant, nchan, ntime, npol)
    vr = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_r
    )
    vi = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_i
    )
    hdr = dict(raws[0].header(0))
    hdr["_ntime"] = ntime
    hdr["_nant"] = nant
    return hdr, (vr, vi)
