"""File-fed antenna-array products: per-antenna GUPPI RAW recordings →
sharded planar voltages for the collective products (VERDICT r3 item 4).

BASELINE configs 4-5 prescribe beamforming and FX correlation over the
mesh; :mod:`blit.parallel.beamform` / :mod:`blit.parallel.correlator`
implement the collectives, and this module is the missing data plane: it
maps an antenna array's RAW recordings (one recording per antenna — the
per-element capture layout of BL's array backends; the GBT reference has
no array data, its single-dish recordings are per *bank*,
src/gbt.jl:28-42) onto ``antenna_sharding`` / ``correlator_sharding``
with per-process file locality, the same way blit/parallel/scan.py feeds
the (band, bank) filterbank mesh.

Two access shapes:

- One-shot loaders (:func:`load_antennas_mesh` /
  :func:`load_correlator_mesh`): the whole requested span as one sharded
  array, from any ``start_sample`` — right for recordings that fit.
- Windowed streams (:class:`AntennaStream` / :class:`CorrelatorStream`):
  a bounded-window, double-buffered iterator over the same recordings —
  a producer thread fills a ``prefetch_depth`` rotation of stable host
  buffers (the :class:`blit.pipeline.BufferRotation` core the single-chip
  reducer streams through) while the previous window's sharded
  ``device_put`` + collective dispatch are in flight, so host reads,
  host→device transfer and device compute overlap and host residency is
  ``prefetch_depth`` windows regardless of recording length (the slab
  access of the reference, src/gbtworkerfunctions.jl:171-189, applied to
  the collective data plane).  :class:`CorrelatorStream` windows overlap
  by the F-engine's ``(ntap-1)*nfft`` PFB tail — carried between
  rotation buffers by the same memcpy the reducer uses across chunks —
  so windowed spectra are bit-identical to a one-shot F-engine pass.

Voltages arrive planar — ``(re, im)`` float32 pairs dequantized from the
RAW int8 complex samples — because this TPU backend has no complex-dtype
HLOs (DESIGN.md §1).
"""

from __future__ import annotations

import logging
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from blit import faults, observability
from blit.io.guppi import open_raw
from blit.observability import Timeline
from blit.parallel.scan import _gapless, _gather_int64, _kept_samples

log = logging.getLogger("blit.antenna")


def _traced_fill(fill, name: str):
    """Wrap a BufferRotation fill callback so the producer thread's whole
    run records as one span, parented on the driver span that started the
    feed (the fill runs on the rotation's thread, where the driver's
    thread-local trace context would otherwise be invisible)."""
    ctx = observability.tracer().context()

    def run(rot):
        tr = observability.tracer()
        with tr.activate(ctx), tr.span(name):
            fill(rot)

    return run

Planar = Tuple["object", "object"]

_ERR = 1 << 60  # rides the pod-wide agreement; see scan._SAMPS_ERR


def _resolve_plane_dtype(dtype):
    """Device residency dtype for the planar loaders: f32 or bf16 (bf16
    is lossless for 8-bit RAW voltages and halves HBM/ICI traffic in the
    collectives — DESIGN.md §9 r5 addendum)."""
    import jax.numpy as jnp

    d = jnp.dtype(dtype)
    if d not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(f"dtype must be float32 or bfloat16, got {dtype}")
    return d


def _open_antennas(raw_paths: Sequence, needed: Sequence[int]):
    """Open the antenna recordings in ``needed`` (indices into
    ``raw_paths``) and agree (samples, nchan, npol) pod-wide with
    symmetric errors, like the scan loader's player agreement.

    Every process reports a sample count (or the ERR marker) for every
    antenna it was asked to open; the cross-process MIN both finds the
    common span and propagates any opener's failure to every peer before
    the collectives run.  Antennas nobody opened stay at INT64_MAX // 2
    and are caught by the caller's coverage check.
    """
    nant = len(raw_paths)
    raws, errs = {}, {}
    for a in needed:
        try:
            r = open_raw(raw_paths[a])
            if r.nblocks == 0:
                raise ValueError(f"empty RAW file: {r.path}")
            raws[a] = r
        except Exception as e:  # noqa: BLE001 — reported pod-wide below
            errs[a] = e

    geo = (0, 0)
    if raws:
        h = raws[sorted(raws)[0]].header(0)
        geo = (h["OBSNCHAN"], 2 if h["NPOL"] > 2 else h["NPOL"])
    samps = np.full(nant, (1 << 62) - 1, np.int64)
    for a, r in raws.items():
        samps[a] = _kept_samples(r)
    for a in errs:
        samps[a] = _ERR
    gathered = _gather_int64(np.concatenate([samps, geo]))
    samps = gathered[:, :-2].min(axis=0)
    failed = [int(a) for a in np.argwhere(samps == _ERR).ravel()]
    if failed:
        mine = "; ".join(
            f"antenna {a}: {type(e).__name__}: {e}"
            for a, e in sorted(errs.items())
        )
        raise ValueError(
            f"antennas {failed} failed to open on their owning process"
            + (f" (this process: {mine})" if mine else "")
        ) from next(iter(errs.values()), None)
    geos = gathered[:, -2:]
    geos = geos[(geos != 0).any(axis=1)]
    if len(geos) and not (geos == geos[0]).all():
        raise ValueError(
            f"processes disagree on (nchan, npol): {[tuple(g) for g in geos]}"
        )
    nchan, npol = (int(geos[0][0]), int(geos[0][1])) if len(geos) else (0, 0)
    return raws, int(samps.min()), nchan, npol


def _planar_block(raw, start: int, ntime: int) -> Tuple[np.ndarray, np.ndarray]:
    """Samples ``[start, start+ntime)`` of one recording as planar float32
    ``(nchan, ntime, npol)`` re/im planes (RAW int8 (re, im) dequantized)."""
    v = _gapless(raw, ntime, skip=start)  # (nchan, ntime, npol, 2) int8
    if v.shape[1] < ntime:
        raise ValueError(
            f"{raw.path}: {v.shape[1]} samples from offset {start}, "
            f"need {ntime}"
        )
    v = v[:, :ntime]
    # astype yields fresh C-contiguous planes; int8 → f32 is exact.
    return v[..., 0].astype(np.float32), v[..., 1].astype(np.float32)


def _span_from(min_samps: int, start_sample: int,
               max_samples: Optional[int]) -> int:
    """Usable samples from ``start_sample`` given the agreed common span
    (every loader/stream's offset arithmetic, in one place)."""
    if start_sample < 0:
        raise ValueError(f"start_sample must be >= 0, got {start_sample}")
    avail = min_samps - start_sample
    if max_samples is not None:
        avail = min(avail, max_samples)
    return avail


def _antenna_shard_plan(mesh, axis: str, layout: str, nant: int):
    """The beamform-layout placement plan shared by the one-shot loader
    and :class:`AntennaStream`: ``(sharding, per, [(device, lo)])`` where
    each addressable device owns antennas ``[lo, lo + per)`` (both
    layouts shard ONLY the antenna dim in equal blocks, so a device's
    block index IS its mesh coordinate along ``axis``)."""
    from blit.parallel.beamform import antenna_sharding

    if layout not in ("antenna", "chan"):
        raise ValueError(f"bad layout {layout!r}")
    ax_size = mesh.shape[axis]
    if nant % ax_size:
        raise ValueError(
            f"nant={nant} must divide over the {ax_size}-way {axis!r} axis"
        )
    per = nant // ax_size
    if layout == "chan":
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P(None, axis))
    else:
        sharding = antenna_sharding(mesh, axis)
    ax_i = list(mesh.axis_names).index(axis)

    def ant_lo(d) -> int:
        pos = np.argwhere(mesh.devices == d)[0]
        return int(pos[ax_i]) * per

    plan = [(d, ant_lo(d)) for d in sharding.addressable_devices]
    return sharding, per, plan


def load_antennas_mesh(
    raw_paths: Sequence,
    *,
    mesh,
    axis: str = "bank",
    start_sample: int = 0,
    max_samples: Optional[int] = None,
    dtype="float32",
    layout: str = "antenna",
) -> Tuple[Dict, Planar]:
    """Load per-antenna RAW recordings onto the beamform layout:
    ``(nant, nchan, ntime, npol)`` planar voltages with the antenna axis
    sharded over ``axis`` (:func:`blit.parallel.beamform.antenna_sharding`).

    Each process opens ONLY the antennas whose chips it owns (the
    per-element twin of the scan loader's player locality); the common
    sample span is agreed pod-wide.  Returns ``(header, (vr, vi))`` where
    ``header`` is the first local antenna's RAW header plus the agreed
    ``ntime``.

    ``raw_paths``: one RAW source per antenna (path / ``.NNNN.raw`` stem /
    path list), length divisible by the ``axis`` mesh size.

    ``start_sample``: gap-free sample offset to start from — an arbitrary
    re-entry point into the recordings (the loaders used to be pinned at
    sample 0; VERDICT r5 missing #2).  ``max_samples`` then caps the span
    from there.

    ``dtype``: device residency of the planes — ``"float32"`` (default)
    or ``"bfloat16"``.  RAW voltages are 8-bit integers, exactly
    representable in bf16, so bf16 residency is LOSSLESS for the data
    plane and halves both HBM reads and ICI psum bytes downstream
    (:func:`blit.parallel.beamform.beamform` runs its whole contraction
    in bf16 for bf16 inputs — measured +26% end-to-end, DESIGN.md §9 r5
    addendum).

    ``layout``: ``"antenna"`` (above) or ``"chan"`` — packed chan-major
    ``(nchan, nant, npol, ntime)`` planes for ``beamform(layout="chan")``
    and its fused detect kernel (measured 2.1x; the pack happens in the
    host copy this loader performs anyway, so it is free here, unlike a
    device-side transpose).
    """
    import jax

    dev_dtype = _resolve_plane_dtype(dtype)
    nant = len(raw_paths)
    sharding, per, plan = _antenna_shard_plan(mesh, axis, layout, nant)

    local_ants = sorted({a for _d, lo in plan for a in range(lo, lo + per)})
    raws, min_samps, nchan, npol = _open_antennas(raw_paths, local_ants)
    ntime = _span_from(min_samps, start_sample, max_samples)
    if ntime <= 0:
        raise ValueError(
            f"no common samples across {nant} antennas from offset "
            f"{start_sample} (common span {min_samps})"
        )

    shards_r, shards_i = [], []
    for d, lo in plan:
        if layout == "chan":
            br = np.empty((nchan, per, npol, ntime), np.float32)
            bi = np.empty_like(br)
            for j, a in enumerate(range(lo, lo + per)):
                pr, pi = _planar_block(raws[a], start_sample, ntime)  # (c,t,p)
                br[:, j] = np.transpose(pr, (0, 2, 1))
                bi[:, j] = np.transpose(pi, (0, 2, 1))
        else:
            br = np.empty((per, nchan, ntime, npol), np.float32)
            bi = np.empty_like(br)
            for j, a in enumerate(range(lo, lo + per)):
                br[j], bi[j] = _planar_block(raws[a], start_sample, ntime)
        # int8-origin values are exact in bf16: the cast loses nothing.
        shards_r.append(jax.device_put(br.astype(dev_dtype, copy=False), d))
        shards_i.append(jax.device_put(bi.astype(dev_dtype, copy=False), d))
    global_shape = (
        (nchan, nant, npol, ntime)
        if layout == "chan"
        else (nant, nchan, ntime, npol)
    )
    vr = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_r
    )
    vi = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_i
    )
    hdr = dict(raws[local_ants[0]].header(0))
    hdr["_ntime"] = ntime
    hdr["_nant"] = nant
    return hdr, (vr, vi)


def load_correlator_mesh(
    raw_paths: Sequence,
    *,
    mesh,
    nfft: int,
    ntap: int = 4,
    start_sample: int = 0,
    max_samples: Optional[int] = None,
    dtype="float32",
) -> Tuple[Dict, Planar]:
    """Load per-antenna RAW recordings onto the FX-correlator layout:
    ``(nant, nchan, ntime, npol)`` planar voltages with frequency sharded
    over ``bank`` and time over ``band``
    (:func:`blit.parallel.correlator.correlator_sharding`).

    Antennas are replicated across the mesh in this layout, so every
    process reads every antenna's recording — but only its band rows'
    TIME WINDOW of it (the band axis is the file-split that preserves
    locality here; a per-chip channel subset still comes from the same
    bytes because RAW blocks interleave all channels).  Each band row's
    segment is trimmed to whole ``nfft`` blocks with at least ``ntap``
    of them, matching ``correlate``'s segment semantics.

    ``start_sample`` re-enters the recordings at an arbitrary gap-free
    offset (band segmentation then applies to the remaining span);
    ``max_samples`` caps the span from there.

    ``dtype``: ``"float32"`` (default) or ``"bfloat16"`` residency — see
    :func:`load_antennas_mesh`; ``correlate`` runs its bf16-staged path
    for bf16 planes (measured +25% at nant=64, DESIGN.md §9 r5).
    """
    import jax

    from blit.parallel.correlator import correlator_sharding

    dev_dtype = _resolve_plane_dtype(dtype)

    nant = len(raw_paths)
    nband = mesh.shape["band"]
    nbank = mesh.shape["bank"]
    sharding = correlator_sharding(mesh)

    # Every local device needs every antenna: open them all, agree span.
    raws, min_samps, nchan, npol = _open_antennas(
        raw_paths, list(range(nant))
    )
    if nchan % nbank:
        raise ValueError(f"nchan={nchan} must divide over {nbank} banks")
    total = _span_from(min_samps, start_sample, max_samples)
    seg = (total // nband) // nfft * nfft if total > 0 else 0
    if seg // nfft < ntap:
        raise ValueError(
            f"correlator needs >= {ntap} nfft-blocks per band segment; "
            f"have {seg // nfft} (total {total} samples over {nband} bands "
            f"from offset {start_sample})"
        )
    ntime = seg * nband
    cper = nchan // nbank

    # Read each (antenna, band-row) time window ONCE, slice per bank.
    # Devices are grouped by band row so a row's decoded blocks are freed
    # as soon as that row's local devices are fed (device_put has copied
    # them) — host residency is ONE band row of all antennas, not every
    # owned row at once (ADVICE r4: the flat cache held nant * nchan * seg
    # * npol * 8 bytes per owned row simultaneously).
    shards_r, shards_i = [], []
    dev_map = sharding.addressable_devices_indices_map(
        (nant, nchan, ntime, npol)
    )
    by_band: Dict[int, list] = {}
    for d, idx in dev_map.items():
        b = (idx[2].start or 0) // seg  # band row from the time slice
        by_band.setdefault(b, []).append((d, idx))
    for b in sorted(by_band):
        blocks = [
            _planar_block(raws[a], start_sample + b * seg, seg)
            for a in range(nant)
        ]
        for d, idx in by_band[b]:
            k = (idx[1].start or 0) // cper
            br = np.stack([blocks[a][0][k * cper:(k + 1) * cper]
                           for a in range(nant)])
            bi = np.stack([blocks[a][1][k * cper:(k + 1) * cper]
                           for a in range(nant)])
            shards_r.append(jax.device_put(br.astype(dev_dtype, copy=False), d))
            shards_i.append(jax.device_put(bi.astype(dev_dtype, copy=False), d))
        del blocks
    global_shape = (nant, nchan, ntime, npol)
    vr = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_r
    )
    vi = jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards_i
    )
    hdr = dict(raws[0].header(0))
    hdr["_ntime"] = ntime
    hdr["_nant"] = nant
    return hdr, (vr, vi)


# -- windowed streaming feeds ---------------------------------------------


class Window:
    """One window of a collective stream: sharded planar ``(vr, vi)``
    global arrays fed from a rotation slot's host buffers.

    The consumer MUST call :meth:`release` once nothing still reads the
    window — in practice, after the device compute that consumed it has
    synchronized: the streaming drivers hand ``release`` to the output
    plane's readback thread as the ``on_consumed`` hook
    (:meth:`blit.outplane.OutputRotation.put`) or release via the shared
    :class:`blit.outplane.FoldInFlight` lag bookkeeping, so the call may
    arrive from a thread other than the iterator's (the rotation's slot
    accounting is lock-guarded for exactly this).  ``arrays`` may
    alias the slot's host buffers until then (CPU backends transfer
    zero-copy when alignment allows), so a released window's arrays must
    not be read again; an unreleased window back-pressures the producer
    exactly like an unreleased :class:`blit.pipeline.RawReducer` chunk.
    """

    __slots__ = ("index", "start", "ntime", "frames", "arrays", "masked",
                 "_rot", "_slot")

    def __init__(self, index: int, start: int, ntime: int,
                 frames: Optional[int], arrays: Planar, rot, slot: int,
                 masked: Tuple[int, ...] = ()):
        self.index = index    # window ordinal in the stream
        self.start = start    # sample (AntennaStream) / frame (Correlator-
        #                       Stream, per band segment) offset
        self.ntime = ntime    # global time extent of ``arrays``
        self.frames = frames  # F-engine frames this window contributes
        #                       (CorrelatorStream only)
        self.arrays = arrays
        self.masked = masked  # antennas zero-weighted in this window
        #                       (degraded continuation; see stream docs)
        self._rot = rot
        self._slot = slot

    def release(self) -> None:
        """Hand the host slot back to the producer (idempotent)."""
        if self._rot is not None:
            rot, self._rot = self._rot, None
            rot.release(self._slot)


def record_mask(masked: set, ident, reason: str, *, header: Dict,
                timeline: Timeline, kind: str = "antenna") -> bool:
    """The one zero-weight mask bookkeeping rule (ISSUE 2 tentpole,
    shared): add ``ident`` to ``masked``, mirror the sorted set into the
    product header (``_masked_<kind>s``), bump the ``<kind>.masked``
    timeline counter and the process-wide ``mask.<kind>`` fault counter,
    and log the degradation — so a degraded run SAYS so everywhere a
    healthy one reports.  Used by the windowed antenna/correlator feeds
    (``kind="antenna"``) and the streaming ingest plane's watermark
    masking (``kind="chunk"``, blit/stream — a missing chunk zero-fills
    exactly like a zero-weighted antenna plane: it contributes nothing
    to any linear product downstream).  Returns True when ``ident`` was
    newly masked."""
    if ident in masked:
        return False
    masked.add(ident)
    header[f"_masked_{kind}s"] = sorted(masked)
    timeline.count(f"{kind}.masked")
    faults.incr(f"mask.{kind}")
    log.warning(
        "%s %s %s; masking it (zero weight) and continuing degraded",
        kind, ident, reason,
    )
    return True


class _DegradedContinuation:
    """Shared degraded-antenna state for the windowed streams (ISSUE 2
    tentpole): with ``on_antenna_error="mask"`` a HARD mid-stream antenna
    failure (truncated recording, retries exhausted, wedged mount
    surfacing as an error) zero-weights that antenna from the failing
    window onward instead of aborting the scan.  Zeroed planes contribute
    exactly nothing to the linear beam sums and baseline cross-products,
    so the collectives need no math changes; the flag rides every
    subsequent :class:`Window` (``masked``), the stream's
    ``masked_antennas`` set, the product header
    (``_masked_antennas``) and the ``antenna.masked`` timeline counter,
    so a degraded run SAYS so in its report.

    Masking is per-process: on multi-process pods each process masks the
    antennas whose files it reads; processes that never read the failed
    recording keep their (already-agreed) span untouched."""

    def _init_degraded(self, on_antenna_error: str,
                       stall_timeout_s: Optional[float]) -> None:
        if on_antenna_error not in ("raise", "mask"):
            raise ValueError(
                f"on_antenna_error must be 'raise' or 'mask', "
                f"got {on_antenna_error!r}"
            )
        self.on_antenna_error = on_antenna_error
        self.stall_timeout_s = stall_timeout_s
        self.masked_antennas: set = set()

    def _mask(self, a: int, err: BaseException) -> None:
        record_mask(
            self.masked_antennas, a,
            f"hard-failed mid-stream ({type(err).__name__}: {err})",
            header=self.header, timeline=self.timeline, kind="antenna",
        )


class AntennaStream(_DegradedContinuation):
    """Windowed, double-buffered feed of per-antenna RAW recordings onto
    the beamform layout — the streaming twin of :func:`load_antennas_mesh`
    (module docstring: the ``RawReducer`` rotation applied to the
    collective data plane).

    Iterating yields :class:`Window`\\ s covering gap-free samples
    ``[start_sample + i*window_samples, ...)`` in order; every sample of
    the agreed span from ``start_sample`` lands in exactly one window
    (the final window is smaller when the span is ragged).  Stage
    timings land in ``timeline``: ``ingest`` (RAW file bytes read),
    ``pack`` (dequant/pack into the planar host buffers), ``transfer``
    (sharded ``device_put``, planar bytes moved).

    Fault tolerance (ISSUE 2): transient read errors already retry inside
    :meth:`blit.io.guppi.GuppiRaw.read_block_into` (invisible here beyond
    the ``retry.io`` counter); ``on_antenna_error="mask"`` turns HARD
    per-antenna failures into degraded continuation
    (:class:`_DegradedContinuation`) instead of a stream abort;
    ``stall_timeout_s`` arms the rotation's producer-progress watchdog so
    a wedged read bounds the hang.
    """

    def __init__(
        self,
        raw_paths: Sequence,
        *,
        mesh,
        axis: str = "bank",
        window_samples: int,
        start_sample: int = 0,
        max_samples: Optional[int] = None,
        dtype="float32",
        layout: str = "antenna",
        prefetch_depth: int = 2,
        timeline: Optional[Timeline] = None,
        on_antenna_error: str = "raise",
        stall_timeout_s: Optional[float] = None,
    ):
        if window_samples <= 0:
            raise ValueError(f"window_samples must be > 0, got {window_samples}")
        self._init_degraded(on_antenna_error, stall_timeout_s)
        self.mesh = mesh
        self.axis = axis
        self.layout = layout
        self.window_samples = window_samples
        self.start_sample = start_sample
        self.prefetch_depth = max(2, prefetch_depth)
        self.timeline = timeline if timeline is not None else Timeline()
        self.dev_dtype = _resolve_plane_dtype(dtype)
        self.nant = len(raw_paths)
        self.sharding, self.per, self.plan = _antenna_shard_plan(
            mesh, axis, layout, self.nant
        )
        local_ants = sorted({
            a for _d, lo in self.plan for a in range(lo, lo + self.per)
        })
        self._local_ants = local_ants
        self._raws, min_samps, self.nchan, self.npol = _open_antennas(
            raw_paths, local_ants
        )
        self.total_samples = _span_from(min_samps, start_sample, max_samples)
        if self.total_samples <= 0:
            raise ValueError(
                f"no common samples across {self.nant} antennas from offset "
                f"{start_sample} (common span {min_samps})"
            )
        # The window plan, identical on every process (derived from the
        # pod-agreed span): (sample offset within the span, samples).
        self.spans: List[Tuple[int, int]] = [
            (w0, min(window_samples, self.total_samples - w0))
            for w0 in range(0, self.total_samples, window_samples)
        ]
        self.header = dict(self._raws[local_ants[0]].header(0))
        self.header["_ntime"] = self.total_samples
        self.header["_nant"] = self.nant
        # Rotation slot storage: per slot, one (br, bi) pair per local
        # device, allocated lazily at the full window shape (ragged final
        # windows fill a prefix and transfer a view).
        self._store: List[Optional[Dict]] = [None] * self.prefetch_depth

    @property
    def nwindows(self) -> int:
        return len(self.spans)

    def _alloc(self, slot: int) -> Dict:
        if self._store[slot] is None:
            W = self.window_samples
            shape = (
                (self.nchan, self.per, self.npol, W)
                if self.layout == "chan"
                else (self.per, self.nchan, W, self.npol)
            )
            self._store[slot] = {
                d: (np.empty(shape, self.dev_dtype),
                    np.empty(shape, self.dev_dtype))
                for d, _lo in self.plan
            }
        return self._store[slot]

    def _zero_antenna(self, br, bi, j: int, wt: int) -> None:
        """Zero-weight one local antenna's planes for this window (the
        masked-antenna contribution to every linear collective is then
        exactly zero)."""
        if self.layout == "chan":
            br[:, j, :, :wt] = 0
            bi[:, j, :, :wt] = 0
        else:
            br[j, :, :wt] = 0
            bi[j, :, :wt] = 0

    def _fill(self, rot) -> None:
        """Producer thread: read + dequant each window into its slot's
        planar buffers (one antenna-window of int8 scratch at a time).
        Hard per-antenna failures mask-and-continue under
        ``on_antenna_error="mask"`` (class docstring)."""
        tl = self.timeline
        scratch = np.empty(
            (self.nchan, self.window_samples, self.npol, 2), np.int8
        )
        for w, (w0, wt) in enumerate(self.spans):
            slot = rot.acquire()
            if slot is None:
                return  # consumer abandoned the stream
            store = self._alloc(slot)
            raw_bytes = self.nchan * wt * self.npol * 2
            for d, lo in self.plan:
                br, bi = store[d]
                for j, a in enumerate(range(lo, lo + self.per)):
                    if a in self.masked_antennas:
                        self._zero_antenna(br, bi, j, wt)
                        continue
                    try:
                        faults.fire(
                            "antenna.produce", key=self._raws[a].path
                        )
                        with tl.stage("ingest", nbytes=raw_bytes):
                            v = _gapless(
                                self._raws[a], wt,
                                skip=self.start_sample + w0, out=scratch,
                            )
                        if v.shape[1] < wt:
                            raise ValueError(
                                f"{self._raws[a].path}: {v.shape[1]} "
                                f"samples from offset "
                                f"{self.start_sample + w0}, need {wt}"
                            )
                        with tl.stage(
                            "pack",
                            nbytes=2 * self.nchan * wt * self.npol
                            * self.dev_dtype.itemsize,
                        ):
                            if self.layout == "chan":
                                br[:, j, :, :wt] = np.transpose(
                                    v[..., 0], (0, 2, 1))
                                bi[:, j, :, :wt] = np.transpose(
                                    v[..., 1], (0, 2, 1))
                            else:
                                br[j, :, :wt] = v[..., 0]
                                bi[j, :, :wt] = v[..., 1]
                    except Exception as e:  # noqa: BLE001 — classified
                        if self.on_antenna_error != "mask":
                            raise
                        self._mask(a, e)
                        self._zero_antenna(br, bi, j, wt)
            rot.emit(slot, (w, w0, wt, tuple(sorted(self.masked_antennas))))

    def __iter__(self) -> Iterator[Window]:
        import jax

        from blit.pipeline import BufferRotation

        tl = self.timeline
        rot = BufferRotation(
            self.prefetch_depth, _traced_fill(self._fill, "antenna.produce"),
            name="blit-antenna-feed",
            stall_timeout_s=self.stall_timeout_s,
        )
        try:
            for slot, (w, w0, wt, masked) in rot.slots():
                store = self._store[slot]
                if self.layout == "chan":
                    global_shape = (self.nchan, self.nant, self.npol, wt)
                else:
                    global_shape = (self.nant, self.nchan, wt, self.npol)
                nbytes = 0
                with tl.stage("transfer"):
                    shards_r, shards_i = [], []
                    for d, _lo in self.plan:
                        br, bi = store[d]
                        if self.layout == "chan":
                            br, bi = br[..., :wt], bi[..., :wt]
                        else:
                            br, bi = br[:, :, :wt], bi[:, :, :wt]
                        shards_r.append(jax.device_put(br, d))
                        shards_i.append(jax.device_put(bi, d))
                        nbytes += br.nbytes + bi.nbytes
                    vr = jax.make_array_from_single_device_arrays(
                        global_shape, self.sharding, shards_r
                    )
                    vi = jax.make_array_from_single_device_arrays(
                        global_shape, self.sharding, shards_i
                    )
                tl.stages["transfer"].bytes += nbytes
                # The consumer releases (Window docstring): device_put may
                # be zero-copy (CPU) or still in flight (TPU DMA), so the
                # slot is only safe to refill once the compute that read
                # this window has synchronized.
                yield Window(
                    w, self.start_sample + w0, wt, None, (vr, vi), rot,
                    slot, masked=masked,
                )
        finally:
            rot.close()


class CorrelatorStream(_DegradedContinuation):
    """Windowed, double-buffered feed onto the FX-correlator layout — the
    streaming twin of :func:`load_correlator_mesh`.

    The agreed span from ``start_sample`` splits into ``nband`` time
    segments exactly as the one-shot loader's (band axis = disjoint time
    segments, :func:`blit.parallel.correlator.correlator_sharding`); each
    segment's F-engine frames then stream in windows of ``window_frames``.
    Window ``w`` carries frames ``[w*window_frames, ...)`` of EVERY band
    segment: its arrays are ``(nant, nchan, nband*wsamps, npol)`` with
    ``wsamps = (frames + ntap - 1) * nfft``, directly consumable by the
    per-window correlator step.  Consecutive windows overlap by the
    ``(ntap-1)*nfft``-sample PFB tail, memcpy'd between rotation buffers
    (the ``RawReducer`` state-carry; every other byte is read from disk
    exactly once), so the windowed spectra are bit-identical to a
    one-shot F-engine pass over each whole segment —
    :func:`blit.parallel.correlator.correlate_stream` accumulates their
    visibilities across windows on-device.
    """

    def __init__(
        self,
        raw_paths: Sequence,
        *,
        mesh,
        nfft: int,
        ntap: int = 4,
        window_frames: int,
        start_sample: int = 0,
        max_samples: Optional[int] = None,
        dtype="float32",
        prefetch_depth: int = 2,
        timeline: Optional[Timeline] = None,
        on_antenna_error: str = "raise",
        stall_timeout_s: Optional[float] = None,
    ):
        if window_frames <= 0:
            raise ValueError(f"window_frames must be > 0, got {window_frames}")
        self._init_degraded(on_antenna_error, stall_timeout_s)
        self.mesh = mesh
        self.nfft, self.ntap = nfft, ntap
        self.window_frames = window_frames
        self.start_sample = start_sample
        self.prefetch_depth = max(2, prefetch_depth)
        self.timeline = timeline if timeline is not None else Timeline()
        self.dev_dtype = _resolve_plane_dtype(dtype)
        self.nant = len(raw_paths)
        self.nband = mesh.shape["band"]
        self.nbank = mesh.shape["bank"]

        from blit.parallel.correlator import correlator_sharding

        self.sharding = correlator_sharding(mesh)
        self._raws, min_samps, self.nchan, self.npol = _open_antennas(
            raw_paths, list(range(self.nant))
        )
        if self.nchan % self.nbank:
            raise ValueError(
                f"nchan={self.nchan} must divide over {self.nbank} banks"
            )
        self.cper = self.nchan // self.nbank
        total = _span_from(min_samps, start_sample, max_samples)
        self.seg = (total // self.nband) // nfft * nfft if total > 0 else 0
        if self.seg // nfft < ntap:
            raise ValueError(
                f"correlator needs >= {ntap} nfft-blocks per band segment; "
                f"have {self.seg // nfft} (total {total} samples over "
                f"{self.nband} bands from offset {start_sample})"
            )
        self.total_frames = self.seg // nfft - ntap + 1
        # The window plan (identical on every process): frame spans per
        # band segment.
        self.spans: List[Tuple[int, int]] = [
            (f0, min(window_frames, self.total_frames - f0))
            for f0 in range(0, self.total_frames, window_frames)
        ]
        self.header = dict(self._raws[0].header(0))
        self.header["_ntime"] = self.seg * self.nband
        self.header["_nant"] = self.nant
        # Local band rows and their devices (multi-process pods own a
        # subset of rows; every process reads every antenna, but only its
        # rows' time windows — the one-shot loader's locality rule).
        dev_map = self.sharding.addressable_devices_indices_map(
            (self.nant, self.nchan, self.seg * self.nband, self.npol)
        )
        self._by_band: Dict[int, list] = {}
        for d, idx in dev_map.items():
            b = (idx[2].start or 0) // self.seg
            k = (idx[1].start or 0) // self.cper
            self._by_band.setdefault(b, []).append((d, k))
        # Slot storage: per slot, one (br, bi) planar pair per local band
        # row, at the full window sample extent.
        self._store: List[Optional[Dict]] = [None] * self.prefetch_depth
        self._wsamps_max = (window_frames + ntap - 1) * nfft

    @property
    def nwindows(self) -> int:
        return len(self.spans)

    def _alloc(self, slot: int) -> Dict:
        if self._store[slot] is None:
            shape = (self.nant, self.nchan, self._wsamps_max, self.npol)
            self._store[slot] = {
                b: (np.empty(shape, self.dev_dtype),
                    np.empty(shape, self.dev_dtype))
                for b in sorted(self._by_band)
            }
        return self._store[slot]

    def _fill(self, rot) -> None:
        """Producer: each window's fresh samples read + dequantized into
        its slot, the PFB tail memcpy'd from the previous slot's buffers
        (which the consumer may still be reading — a slot is only
        REFILLED after release, exactly the reducer's rotation rule)."""
        tl = self.timeline
        nfft, ntap = self.nfft, self.ntap
        ov = (ntap - 1) * nfft
        scratch = np.empty(
            (self.nchan, self._wsamps_max, self.npol, 2), np.int8
        )
        prev: Optional[Dict] = None
        prev_used = 0
        for w, (f0, fw) in enumerate(self.spans):
            slot = rot.acquire()
            if slot is None:
                return
            store = self._alloc(slot)
            if store is prev:
                # The tail memcpy below reads the PREVIOUS slot; in-order
                # release over >= 2 slots can never hand the producer the
                # tail source itself (slots rotate FIFO), so this is a
                # consumer releasing out of order — fail loud, don't
                # self-copy.
                raise RuntimeError(
                    "correlator feed: window released out of order "
                    "(producer re-acquired its PFB-tail source slot)"
                )
            used = (fw + ntap - 1) * nfft
            fresh0 = 0 if w == 0 else ov  # tail comes from prev buffers
            fresh = used - fresh0
            for b in sorted(self._by_band):
                br, bi = store[b]
                if fresh0:
                    with tl.stage(
                        "state",
                        nbytes=2 * self.nant * self.nchan * ov * self.npol
                        * self.dev_dtype.itemsize,
                    ):
                        pbr, pbi = prev[b]
                        br[:, :, :ov] = pbr[:, :, prev_used - ov:prev_used]
                        bi[:, :, :ov] = pbi[:, :, prev_used - ov:prev_used]
                row_base = self.start_sample + b * self.seg
                raw_bytes = self.nchan * fresh * self.npol * 2
                for a in range(self.nant):
                    if a in self.masked_antennas:
                        # Whole window extent, PFB tail included — a
                        # masked antenna's stale tail must not leak.
                        br[a, :, :used] = 0
                        bi[a, :, :used] = 0
                        continue
                    try:
                        faults.fire(
                            "antenna.produce", key=self._raws[a].path
                        )
                        with tl.stage("ingest", nbytes=raw_bytes):
                            v = _gapless(
                                self._raws[a], fresh,
                                skip=row_base + f0 * nfft + fresh0,
                                out=scratch,
                            )
                        if v.shape[1] < fresh:
                            raise ValueError(
                                f"{self._raws[a].path}: {v.shape[1]} "
                                f"samples from offset "
                                f"{row_base + f0 * nfft + fresh0}, "
                                f"need {fresh}"
                            )
                        with tl.stage(
                            "pack",
                            nbytes=2 * self.nchan * fresh * self.npol
                            * self.dev_dtype.itemsize,
                        ):
                            br[a, :, fresh0:used] = v[..., 0]
                            bi[a, :, fresh0:used] = v[..., 1]
                    except Exception as e:  # noqa: BLE001 — classified
                        if self.on_antenna_error != "mask":
                            raise
                        self._mask(a, e)
                        # The window is masked WHOLE for this antenna,
                        # across every band row of the current slot (some
                        # rows were already packed with its pre-failure
                        # bytes this window).
                        for bb in sorted(self._by_band):
                            bbr, bbi = store[bb]
                            bbr[a, :, :used] = 0
                            bbi[a, :, :used] = 0
            rot.emit(slot, (w, f0, fw, used,
                            tuple(sorted(self.masked_antennas))))
            prev, prev_used = store, used

    def __iter__(self) -> Iterator[Window]:
        import jax

        from blit.pipeline import BufferRotation

        tl = self.timeline
        rot = BufferRotation(
            self.prefetch_depth,
            _traced_fill(self._fill, "correlator.produce"),
            name="blit-correlator-feed",
            stall_timeout_s=self.stall_timeout_s,
        )
        try:
            for slot, (w, f0, fw, used, masked) in rot.slots():
                store = self._store[slot]
                global_shape = (
                    self.nant, self.nchan, self.nband * used, self.npol
                )
                nbytes = 0
                with tl.stage("transfer"):
                    shards = {}
                    for b in sorted(self._by_band):
                        br, bi = store[b]
                        for d, k in self._by_band[b]:
                            cr = br[:, k * self.cper:(k + 1) * self.cper,
                                    :used]
                            ci = bi[:, k * self.cper:(k + 1) * self.cper,
                                    :used]
                            shards[d] = (jax.device_put(cr, d),
                                         jax.device_put(ci, d))
                            nbytes += cr.nbytes + ci.nbytes
                    vr = jax.make_array_from_single_device_arrays(
                        global_shape, self.sharding,
                        [s[0] for s in shards.values()],
                    )
                    vi = jax.make_array_from_single_device_arrays(
                        global_shape, self.sharding,
                        [s[1] for s in shards.values()],
                    )
                tl.stages["transfer"].bytes += nbytes
                # Consumer releases once its compute synchronized (Window
                # docstring) — the PFB-tail memcpy additionally reads the
                # previous slot, which the rotation's refill-after-release
                # rule already covers.
                yield Window(
                    w, f0, self.nband * used, fw, (vr, vi), rot, slot,
                    masked=masked,
                )
        finally:
            rot.close()
