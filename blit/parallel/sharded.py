"""The sharded reduction plane (ISSUE 9): one scan as ONE SPMD program,
threaded end to end through the ingest and output planes.

``reduce_scan_mesh_to_files`` (blit/parallel/scan.py) already reduces a
scan as a single sharded computation, but its window loop is serial:
synchronous per-window ``_gapless`` re-reads, a blocking readback, an
inline write.  This module is the same SPMD math with every host leg on
its own thread — the ``RawReducer._pump`` architecture lifted onto the
``(band, bank)`` mesh:

- **feed**: a :class:`blit.pipeline.BufferRotation` whose slots are
  per-local-player pinned host slabs (:mod:`blit.hostmem` pool), filled
  by a producer thread while the mesh computes earlier windows; the
  global sharded voltage array is assembled with ``jax.device_put`` +
  shardings (:func:`blit.parallel.mesh.put_local_shards` — the
  partition-rule-driven replacement for the pool path's per-worker H2D
  scatter);
- **compute**: the per-chip channelize and the cross-bank stitch run as
  two dispatches (``band_reduce(stitch=False)`` +
  :func:`blit.parallel.mesh.stitch_despike`) so the all_gather can be
  timed honestly on probe windows (``mesh.gather_s``) and its ICI bytes
  accounted per window — the per-chip program is bit-identical to the
  pool path's single-chip ``channelize`` (the byte-identity oracle,
  tests/test_sharded.py);
- **readback**: only ADDRESSABLE shards cross D2H — each owned band
  row's bank-0 shard goes through an
  :class:`blit.outplane.OutputRotation` readback thread; processes that
  own no band row sync their window with a fetch-free put (they still
  participate in every collective);
- **write**: per-band products stream write-behind through
  :class:`blit.outplane.AsyncSink` into the SAME writers (and the same
  pod-wide-agreed resume machinery) as the sync loop
  (:func:`blit.parallel.scan._open_band_writers`).

The pool path (:func:`blit.parallel.scan.reduce_scan_pool_to_files`)
stays as the fallback and the correctness oracle: products here are
byte-identical to it — ``.fil``, ``.h5`` and, via
:func:`search_scan_sharded_to_files`, per-player ``.hits`` (each chip
searches its own frequency slice with the identical ``dedoppler_hits``
program the pool-path :class:`blit.search.DedopplerReducer` runs).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from blit import faults, observability
from blit.monitor import published
from blit.observability import Timeline, profile_trace
from blit.ops.channelize import pfb_coeffs, usable_frames
from blit.parallel import mesh as M
from blit.parallel.scan import (
    _despike_nfpc,
    _gapless,
    _gather_int64,
    _open_band_writers,
    _open_players,
    _resolve_grid,
    _resolve_out_paths,
    _scan_headers,
)

log = logging.getLogger("blit.sharded")


class _ShardWindow:
    """One window of the sharded feed: the assembled global voltage
    array plus its frame coordinates.  ``release`` hands the slot back
    to the producer — call it only once the dispatch that consumed
    ``volt`` has synchronized (the ``on_consumed`` discipline)."""

    __slots__ = ("volt", "index", "f0", "frames", "ntime", "_rot", "_slot")

    def __init__(self, volt, index, f0, frames, ntime, rot, slot):
        self.volt = volt
        self.index = index
        self.f0 = f0
        self.frames = frames
        self.ntime = ntime
        self._rot = rot
        self._slot = slot

    def release(self) -> None:
        if self._rot is not None:
            rot, self._rot = self._rot, None
            rot.release(self._slot)


class _ShardFeed:
    """The pipelined per-shard window feed: a producer thread reads each
    LOCAL player's gap-free span for window ``w+1`` into pinned staging
    slabs while the mesh computes window ``w`` — the
    :class:`blit.pipeline.BufferRotation` ingest discipline applied to
    the whole-scan grid.  Stage accounting: ``ingest`` (RAW bytes read,
    producer thread), ``transfer`` (device_put of every local shard)."""

    def __init__(self, raws, local, mesh, nchan, npol, *, nfft, ntap,
                 wf, total, f0_start, timeline,
                 prefetch_depth=2, extra_slots=0, stall_timeout_s=None):
        self.raws, self.local, self.mesh = raws, local, mesh
        self.nchan, self.npol = nchan, npol
        self.nfft, self.ntap = nfft, ntap
        self.tl = timeline
        self.spans: List[Tuple[int, int]] = []
        f0 = f0_start
        while f0 < total:
            n = min(wf, total - f0)
            self.spans.append((f0, n))
            f0 += n
        self.max_ntime = (wf + ntap - 1) * nfft
        self.nslots = max(2, prefetch_depth) + max(0, extra_slots)
        self.stall_timeout_s = stall_timeout_s
        self._store: List[Optional[Dict]] = [None] * self.nslots

    @property
    def nwindows(self) -> int:
        return len(self.spans)

    def _alloc(self, slot: int) -> Dict:
        if self._store[slot] is None:
            from blit import hostmem

            shape = (self.nchan, self.max_ntime, self.npol, 2)
            pool = hostmem.slab_pool()
            self._store[slot] = {
                bk: pool.take(shape, np.int8) for bk in self.local
            }
        return self._store[slot]

    def _fill(self, rot) -> None:
        nfft, ntap = self.nfft, self.ntap
        for w, (f0, n) in enumerate(self.spans):
            slot = rot.acquire()
            if slot is None:
                return  # consumer abandoned the stream
            store = self._alloc(slot)
            ntime = (n + ntap - 1) * nfft
            for bk in self.local:
                r = self.raws[bk]
                with self.tl.stage(
                    "ingest", nbytes=self.nchan * ntime * self.npol * 2
                ):
                    v = _gapless(r, ntime, skip=f0 * nfft,
                                 out=store[bk][:, :ntime])
                if (v.shape[0] != self.nchan or v.shape[1] < ntime
                        or v.shape[2:] != (self.npol, 2)):
                    raise ValueError(
                        f"{r.path}: shape {v.shape} incompatible with "
                        f"(nchan={self.nchan}, ntime>={ntime}, "
                        f"npol={self.npol}, 2)"
                    )
            rot.emit(slot, (w, f0, n, ntime))

    def windows(self):
        """Yield :class:`_ShardWindow` in stream order (the consumer MUST
        release every window once its dispatch synchronized)."""
        import jax  # noqa: F401 — device_put inside put_local_shards

        from blit.pipeline import BufferRotation

        nband, nbank = self.mesh.devices.shape
        rot = BufferRotation(
            self.nslots, self._fill, name="blit-mesh-feed",
            stall_timeout_s=self.stall_timeout_s,
        )
        try:
            for slot, (w, f0, n, ntime) in rot.slots():
                store = self._store[slot]
                gshape = (nband, nbank, self.nchan, ntime, self.npol, 2)
                nbytes = 0
                with self.tl.stage("transfer"):
                    blocks = {}
                    for bk in self.local:
                        blk = store[bk][:, :ntime][None, None]
                        if not blk.flags["C_CONTIGUOUS"]:
                            # Only the final ragged window pays a copy —
                            # full windows fill the slab exactly.
                            blk = np.ascontiguousarray(blk)
                        blocks[bk] = blk
                        nbytes += blk.nbytes
                    volt = M.put_local_shards(blocks, self.mesh, gshape)
                self.tl.stages["transfer"].bytes += nbytes
                yield _ShardWindow(volt, w, f0, n, ntime, rot, slot)
        finally:
            rot.close()

    def retire(self) -> None:
        """Return the staging slabs to the process pool — call only
        after a TERMINAL sync (stream drained, sinks closed), never on an
        error path where an un-synced dispatch might still read one."""
        from blit import hostmem

        pool = hostmem.slab_pool()
        for store in self._store:
            if store:
                for slab in store.values():
                    pool.give(slab)
        self._store = [None] * self.nslots


def _mesh_probe_windows() -> int:
    from blit.config import mesh_defaults

    return mesh_defaults()["probe_windows"]


@published
def reduce_scan_sharded_to_files(
    raw_paths,
    scan: Optional[str] = None,
    *,
    inventories=None,
    out_dir: Optional[str] = None,
    out_paths: Optional[Sequence[str]] = None,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fqav_by: int = 1,
    fft_method: str = "auto",
    window: str = "hamming",
    despike: bool = True,
    max_frames: Optional[int] = None,
    window_frames: Optional[int] = None,
    compression: Optional[str] = None,
    resume: bool = False,
    mesh=None,
    dtype: str = "float32",
    prefetch_depth: Optional[int] = None,
    out_depth: Optional[int] = None,
    probe_windows: Optional[int] = None,
    timeline=None,
    trace_logdir: Optional[str] = None,
    heartbeat=None,
) -> Dict[int, Tuple[str, Dict]]:
    """Reduce one scan across the mesh with the fully-threaded sharded
    plane (module docstring) and stream each stitched band to its
    product.  Call shapes, resume semantics (pod-wide agreed restart)
    and products are those of
    :func:`blit.parallel.scan.reduce_scan_mesh_to_files` — byte-identical
    to it AND to the pool oracle
    (:func:`blit.parallel.scan.reduce_scan_pool_to_files`) at matching
    ``window_frames``.

    New knobs: ``prefetch_depth``/``out_depth`` size the feed rotation
    and the readback/write-behind planes (``None`` = the ingest-plane
    defaults — the CLI resolves them from this rig's tuning profile,
    exactly as ``blit reduce`` does); ``probe_windows`` (default
    ``BLIT_MESH_PROBE`` / SiteConfig ``mesh_probe_windows``) is how many
    leading windows time the stitch collective honestly — those windows
    sync the per-chip compute first, so ``mesh.gather_s`` measures the
    all_gather dispatch alone; steady-state windows stay fully
    overlapped and only account ICI bytes.

    ``heartbeat`` (ISSUE 12) is an optional per-window liveness callback
    ``heartbeat(window_index)``, invoked between windows on the consumer
    thread — the :class:`blit.recover.ScanSupervisor` passes its lease
    refresh here, so a peer that stops making window progress (dead OR
    wedged in a collective) stops beating and the supervisor can detect
    it from outside the SPMD program.  The ``mesh.window`` fault point
    fires at the same cadence (``kill``/``hang`` chaos drills).
    """
    import jax.numpy as jnp

    from blit.outplane import (
        AsyncSink,
        OutputRotation,
        readback_extra_slots,
    )

    band_ids, raw_paths = _resolve_grid(raw_paths, scan, inventories)
    mesh, local, raws, nchan, npol, min_samps = _open_players(raw_paths, mesh)
    nband, nbank = mesh.devices.shape

    total = usable_frames(min_samps, nfft, ntap, nint)
    if max_frames is not None:
        total = min(total, (max_frames // nint) * nint)
    if total <= 0:
        raise ValueError(
            f"scan too short: {min_samps} samples for nfft={nfft}"
        )
    if window_frames is None:
        from blit.config import default_window_frames

        window_frames = default_window_frames(nfft)
    wf = max((window_frames // nint) * nint, nint)
    prefetch = max(2, prefetch_depth or 2)
    depth = max(2, out_depth or prefetch)
    if probe_windows is None:
        probe_windows = _mesh_probe_windows()

    out_paths = _resolve_out_paths(
        band_ids, nband, out_dir, out_paths, compression
    )
    h0, bases, per_bank = _scan_headers(
        raws, local, nfft=nfft, nint=nint, stokes=stokes, fqav_by=fqav_by,
    )
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft, window))
    despike_nfpc = _despike_nfpc(despike, nfft, fqav_by)

    mine, headers, writers, f0_start = _open_band_writers(
        mesh, raws, out_paths, h0=h0, bases=bases,
        per_bank=per_bank, stokes=stokes, nfft=nfft, ntap=ntap, nint=nint,
        window=window, fqav_by=fqav_by, dtype=dtype,
        despike_nfpc=despike_nfpc, compression=compression, resume=resume,
        wf=wf, total=total,
    )

    tl = timeline if timeline is not None else Timeline()
    feed = _ShardFeed(
        raws, local, mesh, nchan, npol, nfft=nfft, ntap=ntap, wf=wf,
        total=total, f0_start=f0_start, timeline=tl,
        prefetch_depth=prefetch,
        extra_slots=readback_extra_slots(depth, prefetch),
    )
    def route(slab) -> None:
        b = slab.payload
        sinks[b].append(slab.data[0], release=slab.release)

    rot = None
    sinks = {}
    nsamps = {}
    try:
        # Construct the readback/write-behind planes INSIDE the guarded
        # region: a failed constructor (e.g. thread creation under
        # resource pressure) must still abort every band's writer — the
        # except below aborts built sinks AND bare not-yet-wrapped
        # writers, so no .partial products or stale cursors leak.
        rot = OutputRotation(depth=depth, timeline=tl, reuse=True,
                             name="blit-mesh-readback")
        for b in mine:
            sinks[b] = AsyncSink(writers[b], depth=depth, timeline=tl)
        with profile_trace(trace_logdir), observability.span(
            "mesh.scan", nfft=nfft, nband=nband, nbank=nbank,
            sharded=True,
        ), tl.stage("stream"):
            for win in feed.windows():
                faults.fire("mesh.window", key=f"w{win.index}")
                if heartbeat is not None:
                    heartbeat(win.index)
                with observability.span("mesh.window", i=win.index), \
                        tl.stage("dispatch", byte_free=True):
                    part = M.band_reduce(
                        win.volt, coeffs, mesh=mesh, nfft=nfft, ntap=ntap,
                        nint=nint, stokes=stokes, fft_method=fft_method,
                        stitch=False, despike_nfpc=0, fqav_by=fqav_by,
                        dtype=dtype,
                    )
                gather_s = None
                if win.index < probe_windows and nbank > 1:
                    # Honest collective probe: sync the per-chip compute
                    # so the timed dispatch below is the all_gather
                    # program alone.  Serializes ONLY these windows.
                    with tl.stage("mesh.probe", byte_free=True):
                        part.block_until_ready()
                        if win.index == 0:
                            # Warm-up: the stream's first stitch call
                            # pays trace+XLA compile — execute it
                            # untimed so every mesh.gather_s sample is
                            # the collective, not the compiler (the
                            # bench leg's own warm-up idiom).
                            M.stitch_despike(
                                part, mesh=mesh,
                                despike_nfpc=despike_nfpc,
                            ).block_until_ready()
                        t0 = time.perf_counter()
                        out = M.stitch_despike(
                            part, mesh=mesh, despike_nfpc=despike_nfpc
                        )
                        out.block_until_ready()
                        gather_s = time.perf_counter() - t0
                else:
                    with tl.stage("dispatch", byte_free=True):
                        out = M.stitch_despike(
                            part, mesh=mesh, despike_nfpc=despike_nfpc
                        )
                if nbank > 1:
                    shard_bytes = part.nbytes // (nband * nbank)
                    M.record_ici(
                        tl, "gather",
                        M.gather_ici_bytes(shard_bytes, nbank), gather_s,
                    )
                # Release the feed slot only when EVERY addressable
                # shard of the window's stitched output is ready: the
                # GLOBAL sync proves every local device consumed its
                # staged voltage block (async H2D transfers included) —
                # syncing one band's shard would not cover devices in
                # OTHER band rows, and the producer would overwrite a
                # pinned slab a transfer still reads.  fetch=False:
                # ordering/back-pressure only, no bytes move; processes
                # owning no band row ride the same put.
                fed = (len(local) * nchan * win.ntime * npol * 2)
                for slab in rot.put(out, nbytes=fed, fetch=False,
                                    on_consumed=win.release):
                    route(slab)
                # Readback: ADDRESSABLE shards only — one per owned band
                # row (the stitched band is replicated across the row).
                by_dev = {s.device: s.data for s in out.addressable_shards}
                for b in mine:
                    for slab in rot.put(by_dev[mesh.devices[b, 0]],
                                        payload=b):
                        route(slab)
            # Drain + close run INSIDE the stream stage — its __exit__
            # already covers them (unlike RawReducer._pump, whose stage
            # closes before the drain and must add the tail manually).
            for slab in rot.drain():
                route(slab)
            for b in list(sinks):
                sinks[b].close()
                nsamps[b] = sinks.pop(b).nsamps
    except BaseException:
        for s in sinks.values():
            s.abort()  # the writers' own crash contracts (resume point)
        for b in mine:
            if b not in sinks and b not in nsamps:
                writers[b].abort()  # never wrapped in a sink
        raise
    finally:
        if rot is not None:
            rot.close()
    tl.overlap_efficiency()
    feed.retire()
    for b in mine:
        headers[b]["nsamps"] = nsamps[b]
    return {band_ids[b]: (out_paths[b], headers[b]) for b in mine}


def _mesh_dedoppler_fn():
    """Build (once) the jitted mesh-wide dedoppler step: every chip runs
    the IDENTICAL ``dedoppler_hits`` program the pool path runs on its
    own frequency slice — zero-padded band edges per chip, per-band
    top-k per chip — with no collective at all: hits stay
    ``(band, bank)``-sharded and each process reads back only its own
    players' packed tables."""
    import jax
    from jax.sharding import PartitionSpec as P

    from blit.compat import shard_map
    from blit.ops.pallas_dedoppler import dedoppler_hits

    @functools.partial(
        jax.jit,
        static_argnames=("mesh", "top_k", "nbands", "max_drift_bins",
                         "kernel", "interpret"),
    )
    def step(spectra, thr, *, mesh, top_k, nbands, max_drift_bins,
             kernel, interpret):
        def body(x, t):
            return dedoppler_hits(
                x[0], t, top_k=top_k, nbands=nbands,
                max_drift_bins=max_drift_bins, kernel=kernel,
                interpret=interpret,
            )[None, None]

        return shard_map(
            body, mesh=mesh,
            in_specs=(P(M.BAND_AXIS, None, M.BANK_AXIS), P()),
            out_specs=M.partition_rule("packed_hits"),
            check_vma=False,  # per-chip extraction, no collectives
        )(spectra, thr)

    return step


_MESH_DEDOPPLER = None


def _mesh_dedoppler():
    global _MESH_DEDOPPLER
    if _MESH_DEDOPPLER is None:
        _MESH_DEDOPPLER = _mesh_dedoppler_fn()
    return _MESH_DEDOPPLER


@published
def search_scan_sharded_to_files(
    raw_paths,
    scan: Optional[str] = None,
    *,
    inventories=None,
    out_dir: Optional[str] = None,
    out_paths=None,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    window: str = "hamming",
    fft_method: str = "auto",
    dtype: str = "float32",
    window_spectra: Optional[int] = None,
    top_k: Optional[int] = None,
    snr_threshold: Optional[float] = None,
    max_drift_bins: Optional[int] = None,
    kernel: str = "auto",
    interpret: bool = False,
    max_frames: Optional[int] = None,
    window_frames: Optional[int] = None,
    resume: bool = False,
    mesh=None,
    prefetch_depth: Optional[int] = None,
    out_depth: Optional[int] = None,
    timeline=None,
    trace_logdir: Optional[str] = None,
    heartbeat=None,
) -> Dict[Tuple[int, int], Tuple[str, Dict]]:
    """Drift-search one scan across the mesh: every chip channelizes AND
    searches its own ``(band, bank)`` frequency slice in one SPMD window
    loop, writing per-player ``.hits`` products BYTE-IDENTICAL to the
    pool path's per-player :meth:`blit.search.DedopplerReducer.
    search_to_file` runs at matching dispatch shapes
    (``chunk_frames == window_frames``; tests/test_sharded.py).

    The spectra never stitch and the packed hit tables never gather —
    frequency stays the sharded axis end to end, each process reads back
    only its ADDRESSABLE players' ``(nbands, top_k, 4)`` tables (a few
    hundred bytes per window per chip crossing D2H instead of the whole
    filterbank), and the owning process writes that player's ``.hits``.

    ``window_frames`` is rounded to a whole number of search windows
    (``window_spectra * nint`` frames each) and the scan span truncated
    to full windows — the pool path's deterministic trailing-partial
    drop, reproduced exactly.  Returns ``{(band_id, bank):
    (path, header)}`` for the players THIS process wrote.

    ``resume=True`` (ISSUE 12) makes the sharded search crash-resumable,
    the :class:`~blit.search.dedoppler.SearchCursor` twin of the reduce
    plane's pod-wide resume: each local player's ``.hits`` carries a
    cursor sidecar claiming windows only after their lines are fsync'd,
    the restart window is the pod-wide-agreed MINIMUM across every
    player (window-aligned — the SPMD loop must restart identically on
    every process), each file truncates to that window's recorded byte
    claim (``SearchCursor.window_claims``), and the finished products
    are byte-identical to an uninterrupted run.  ``heartbeat`` is the
    per-window liveness callback of the reduce plane (the supervisor's
    lease refresh); the ``mesh.window`` fault point fires per window.
    """
    import os

    import jax  # noqa: F401
    import jax.numpy as jnp

    from blit.io.hits import HitsWriter, ResumableHitsWriter, WindowHits
    from blit.outplane import OutputRotation, readback_extra_slots
    from blit.pipeline import ReductionCursor
    from blit.search.dedoppler import DedopplerReducer, SearchCursor
    from blit.search.hits import hits_from_packed

    band_ids, raw_paths = _resolve_grid(raw_paths, scan, inventories)
    mesh, local, raws, nchan, npol, min_samps = _open_players(raw_paths, mesh)
    nband, nbank = mesh.devices.shape

    # Knob resolution + per-player headers ride the pool path's OWN
    # reducer (byte-identity demands identical header lines and physical
    # hit mapping).  The probe reducer is never streamed — it only
    # resolves knobs and builds headers.
    sred = DedopplerReducer(
        nfft=nfft, ntap=ntap, nint=nint, window=window,
        fft_method=fft_method, dtype=dtype, window_spectra=window_spectra,
        top_k=top_k, snr_threshold=snr_threshold,
        max_drift_bins=max_drift_bins, kernel=kernel, interpret=interpret,
        prefetch_depth=prefetch_depth, out_depth=out_depth,
    )
    T = sred.window_spectra
    unit = T * nint  # frames per search window

    total = usable_frames(min_samps, nfft, ntap, nint)
    if max_frames is not None:
        total = min(total, (max_frames // nint) * nint)
    nwin_total = total // unit
    if nwin_total <= 0:
        raise ValueError(
            f"scan too short for one search window: {total} frames, "
            f"need {unit} (window_spectra={T} x nint={nint})"
        )
    total = nwin_total * unit  # deterministic trailing-partial drop
    if window_frames is None:
        from blit.config import default_window_frames

        window_frames = default_window_frames(nfft)
    # Whole search windows per scan window, >= 1.
    wf = max((window_frames // unit) * unit, unit)
    prefetch = max(2, prefetch_depth or sred.prefetch_depth)
    depth = max(2, out_depth or sred.out_depth)

    if out_paths is None:
        if out_dir is None:
            raise ValueError("pass out_dir= or out_paths=")
        out_paths = [
            [os.path.join(
                out_dir, f"band{band_ids[b]}bank{k}.hits"
            ) for k in range(nbank)]
            for b in range(nband)
        ]
    if (len(out_paths) != nband
            or any(len(row) != nbank for row in out_paths)):
        raise ValueError("out_paths must be a rectangular nband x nbank "
                         "grid (one .hits per player)")

    hdrs = {bk: sred.header_for(raws[bk]) for bk in local}
    nbands = sred._nbands(nchan * nfft)
    thr = np.float32(sred.snr_threshold)
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft, window))
    jfn = _mesh_dedoppler()

    # Pod-wide-agreed resume point (ISSUE 12): each local player's cursor
    # names the windows it durably claimed; the restart window is the
    # MINIMUM across the whole pod, rounded DOWN to whole SCAN windows so
    # the resumed dispatch shapes match the uninterrupted run's (dispatch
    # shape is part of the byte-identity contract).  Ledger-less cursors
    # (pre-window_claims sidecars) cannot truncate to an arbitrary
    # earlier window, so they count as zero — restart fresh, never splice.
    start_window = 0
    cursors: Dict[Tuple[int, int], SearchCursor] = {}
    if resume:
        swin = wf // unit  # search windows per scan window
        local_done = []
        for bk in local:
            b, k = bk
            path = out_paths[b][k]
            paths_bk = getattr(raws[bk], "paths", None) or raws[bk].path
            cur = SearchCursor.load(path)
            ok = (
                cur is not None
                and cur.matches(sred, paths_bk)
                and cur.window_claims is not None
                and os.path.exists(path)
                and os.path.getsize(path) >= cur.byte_offset
            )
            if ok:
                # Content verification of the claim (ISSUE 13): a flip
                # INSIDE the claimed lines or a tampered sidecar fails
                # closed to a fresh start — the byte-length probe above
                # cannot see either, and the resumed writer would bake
                # the corruption into a fresh manifest.
                from blit import integrity

                ok = integrity.verify_claim(path, cur.windows_done,
                                            fmt="hits") is not False
            if not ok:
                size, mtime_ns = ReductionCursor.stat_raw(paths_bk)
                cur = SearchCursor(
                    paths_bk, nfft, ntap, nint, window=window, dtype=dtype,
                    window_spectra=T, top_k=sred.top_k,
                    snr_threshold=float(sred.snr_threshold),
                    max_drift_bins=(
                        -1 if sred.max_drift_bins is None
                        else int(sred.max_drift_bins)
                    ),
                    raw_size=size, raw_mtime_ns=mtime_ns,
                    window_claims=[],
                )
            cursors[bk] = cur
            local_done.append(cur.windows_done if ok else 0)
        local_min = min(local_done) if local_done else 1 << 61
        agreed = int(_gather_int64(
            np.asarray([local_min], np.int64)
        ).min())
        start_window = min((agreed // swin) * swin, nwin_total)

    tl = timeline if timeline is not None else Timeline()
    feed = _ShardFeed(
        raws, local, mesh, nchan, npol, nfft=nfft, ntap=ntap, wf=wf,
        total=total, f0_start=start_window * unit, timeline=tl,
        prefetch_depth=prefetch,
        extra_slots=readback_extra_slots(depth, prefetch),
    )
    rot = OutputRotation(depth=depth, timeline=tl, reuse=False,
                         name="blit-mesh-search-readback")
    writers = {}
    nwindows = {bk: start_window for bk in local}

    def route(slab) -> None:
        widx, bk = slab.payload
        hits = hits_from_packed(slab.data[0, 0], widx, hdrs[bk])
        tl.observe("search.hits_per_window", len(hits))
        writers[bk].append(WindowHits(widx, hits))
        nwindows[bk] += 1
        slab.release()

    try:
        for bk in local:
            b, k = bk
            if resume:
                writers[bk] = ResumableHitsWriter(
                    out_paths[b][k], hdrs[bk], start_window, cursors[bk])
            else:
                writers[bk] = HitsWriter(out_paths[b][k], hdrs[bk])
        with profile_trace(trace_logdir), observability.span(
            "mesh.search", nfft=nfft, nband=nband, nbank=nbank,
        ), tl.stage("stream"):
            for win in feed.windows():
                faults.fire("mesh.window", key=f"w{win.index}")
                if heartbeat is not None:
                    heartbeat(win.index)
                with observability.span("mesh.window", i=win.index), \
                        tl.stage("dispatch", byte_free=True):
                    part = M.band_reduce(
                        win.volt, coeffs, mesh=mesh, nfft=nfft, ntap=ntap,
                        nint=nint, stokes="I", fft_method=fft_method,
                        stitch=False, despike_nfpc=0, dtype=dtype,
                    )
                # Release the feed slot only when EVERY local chip's
                # channelize is done: the GLOBAL `part` sync proves the
                # staged voltage slab was fully consumed (async H2D
                # included) — syncing one player's packed table would
                # not cover the other local chips.  The later jfn
                # dispatches read `part` (device-resident), never the
                # slab, so releasing here is safe.
                for slab in rot.put(part, fetch=False,
                                    on_consumed=win.release):
                    route(slab)
                rows = win.frames // nint
                for j in range(rows // T):
                    widx = win.f0 // unit + j
                    with tl.stage("dispatch", byte_free=True):
                        packed = jfn(
                            part[:, j * T:(j + 1) * T, 0, :], thr,
                            mesh=mesh, top_k=sred.top_k, nbands=nbands,
                            max_drift_bins=sred.max_drift_bins,
                            kernel=sred.kernel, interpret=sred.interpret,
                        )
                    by_dev = {
                        s.device: s.data
                        for s in packed.addressable_shards
                    }
                    for bk in local:
                        for slab in rot.put(
                            by_dev[mesh.devices[bk]],
                            payload=(widx, bk),
                        ):
                            route(slab)
            for slab in rot.drain():
                route(slab)
        for bk in list(writers):
            w = writers.pop(bk)
            w.close()
    except BaseException:
        for w in writers.values():
            w.abort()
        raise
    finally:
        rot.close()
    feed.retire()
    out = {}
    for bk in local:
        b, k = bk
        hdr = dict(hdrs[bk])
        hdr["search_windows"] = nwindows[bk]
        out[(band_ids[b], k)] = (out_paths[b][k], hdr)
    return out
