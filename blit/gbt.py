"""Main-process orchestration API — the rebuild of module ``GBT``
(src/gbt.jl).

Call pattern parity (SURVEY.md §3): every function fans one call per worker
(or per (worker, file) pair) through the pool and gathers results ordered
like its inputs; reductions happen worker-side before results cross any
wire.  ``load_scan`` makes the reference's commented-out scan loader
(src/gbt.jl:90-114) first-class: per-band bank stitching + DC despike.

The TPU data plane (mesh stitching via all_gather, beamforming via psum)
lives in ``blit.parallel``; this module is the host-side control plane.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from blit import workers as wf
from blit.config import DEFAULT, SiteConfig, datahosts  # noqa: F401 (re-export)
from blit.inventory import (  # noqa: F401 (re-exports)
    InventoryRecord,
    raw_sequences,
    scan_grid,
    to_dataframe,
)
from blit.ops.despike import despike as _despike
from blit.ops.fqav import fqav_range
from blit.parallel.pool import (  # noqa: F401 (re-export)
    WorkerError,
    WorkerPool,
    current_pool,
    setup_workers,
)


def load_scan_mesh(*args, **kw):
    """Mesh-backed whole-scan reduction (RAW files -> sharded channelize ->
    ICI band stitch); see :func:`blit.parallel.scan.load_scan_mesh`.  Lazy
    wrapper so the host-only API keeps importing without JAX device state."""
    from blit.parallel.scan import load_scan_mesh as _impl

    return _impl(*args, **kw)


def reduce_scan_mesh_to_files(*args, **kw):
    """Windowed mesh reduction streaming each stitched band to a ``.fil``
    product; see :func:`blit.parallel.scan.reduce_scan_mesh_to_files`.
    Lazy wrapper, as :func:`load_scan_mesh`."""
    from blit.parallel.scan import reduce_scan_mesh_to_files as _impl

    return _impl(*args, **kw)


def reduce_scan_sharded_to_files(*args, **kw):
    """The sharded reduction plane (ISSUE 9): one scan as one SPMD
    program, threaded end to end through the ingest/output planes; see
    :func:`blit.parallel.sharded.reduce_scan_sharded_to_files`.  Lazy
    wrapper, as :func:`load_scan_mesh`."""
    from blit.parallel.sharded import reduce_scan_sharded_to_files as _impl

    return _impl(*args, **kw)


def reduce_scan_pool_to_files(*args, **kw):
    """The pool-path whole-scan fallback and byte-identity oracle (one
    ``RawReducer`` per player + main-process ``vcat`` stitch); see
    :func:`blit.parallel.scan.reduce_scan_pool_to_files`."""
    from blit.parallel.scan import reduce_scan_pool_to_files as _impl

    return _impl(*args, **kw)


def search_scan_sharded_to_files(*args, **kw):
    """Sharded whole-scan drift search: each chip searches its own
    frequency slice, per-player ``.hits`` products byte-identical to the
    pool path's; see
    :func:`blit.parallel.sharded.search_scan_sharded_to_files`."""
    from blit.parallel.sharded import search_scan_sharded_to_files as _impl

    return _impl(*args, **kw)

log = logging.getLogger("blit.gbt")

Idxs = Tuple
_ALL = (slice(None), slice(None), slice(None))


def _pool(pool: Optional[WorkerPool]) -> WorkerPool:
    p = pool or current_pool()
    if p is None:
        raise RuntimeError("no worker pool: call setup_workers() first")
    return p


def get_inventories(
    file_re=None,
    *,
    pool: Optional[WorkerPool] = None,
    on_error: str = "raise",
    **kw,
) -> List[Union[List[InventoryRecord], WorkerError]]:
    """Fan the inventory crawl out to every worker; returns one (possibly
    empty) record list per worker, ordered like the pool's hosts
    (reference: ``GBT.getinventories``, src/gbt.jl:48-58)."""
    p = _pool(pool)

    def kwargs_for(w):
        d = dict(kw)
        d["worker"] = w.wid
        d["host"] = w.host
        if file_re is not None:
            d["file_re"] = file_re
        return d

    return p.broadcast(wf.get_inventory, kwargs_for, on_error=on_error)


def get_headers(
    worker_ids: Sequence[int],
    fnames: Sequence[str],
    *,
    pool: Optional[WorkerPool] = None,
    on_error: str = "raise",
) -> List[Dict]:
    """One header per (worker, fname) pair (reference: ``GBT.getheaders``,
    src/gbt.jl:60-67, including its size assertion)."""
    if len(worker_ids) != len(fnames):
        raise ValueError("worker_ids and fnames must have the same size")
    p = _pool(pool)
    return p.run_on(worker_ids, wf.get_header, [(f,) for f in fnames], on_error=on_error)


def get_data(
    worker_ids: Sequence[int],
    fnames: Sequence[str],
    idxs: Idxs = _ALL,
    fqav_by: int = 1,
    fqav_func: Optional[Callable] = None,
    *,
    pool: Optional[WorkerPool] = None,
    on_error: str = "raise",
) -> List[np.ndarray]:
    """One data slab per (worker, fname) pair, frequency-averaged
    worker-side (reference: ``GBT.getdata``, src/gbt.jl:69-79)."""
    if len(worker_ids) != len(fnames):
        raise ValueError("worker_ids and fnames must have the same size")
    p = _pool(pool)
    return p.run_on(
        worker_ids,
        wf.get_data,
        [(f, idxs) for f in fnames],
        kwargs={"fqav_by": fqav_by, "fqav_func": fqav_func},
        on_error=on_error,
    )


def get_kurtosis(
    worker_ids: Sequence[int],
    fnames: Sequence[str],
    idxs: Idxs = _ALL,
    device: bool = False,
    *,
    pool: Optional[WorkerPool] = None,
    on_error: str = "raise",
) -> List[np.ndarray]:
    """Per-file excess-kurtosis maps, shape (nchan, nifs) each (reference:
    ``GBT.getkurtosis``, src/gbt.jl:81-88).  ``device=True`` runs each
    worker's moment reduction on its accelerator under jit
    (:func:`blit.workers.get_kurtosis`)."""
    if len(worker_ids) != len(fnames):
        raise ValueError("worker_ids and fnames must have the same size")
    p = _pool(pool)
    return p.run_on(
        worker_ids,
        wf.get_kurtosis,
        [(f, idxs) for f in fnames],
        kwargs={"device": device},
        on_error=on_error,
    )


def load_scan(
    inventories: Sequence[Sequence[InventoryRecord]],
    session: str,
    scan: str,
    suffix: str = "0002.h5",
    idxs: Idxs = _ALL,
    fqav_by: int = 1,
    fqav_func: Optional[Callable] = None,
    do_despike: bool = True,
    *,
    pool: Optional[WorkerPool] = None,
) -> Dict[int, Tuple[Dict, np.ndarray]]:
    """Load one (session, scan) across all bands: fetch every bank's file,
    stitch the 8 banks of each band into one contiguous band array along the
    channel axis (bank-ascending), and repair the per-coarse-channel DC
    spikes.

    The first-class rebuild of the reference's commented-out ``loadscan``
    (src/gbt.jl:90-114) — same stitch (``reduce(vcat, banks)``) and despike
    semantics, without the main-process-only limitation: this host-side path
    serves small/interactive reads, while ``blit.parallel.stitch`` runs the
    same product as an ``all_gather`` over the TPU mesh.

    Returns ``{band: (stitched_header, stitched_array)}``; bands with missing
    banks are stitched from what exists (ragged results are first-class) with
    a warning.
    """
    from blit.inventory import _is_worker_error

    recs = [
        r
        for inv in inventories
        if not _is_worker_error(inv)
        for r in inv
        if r.session == session and r.scan == scan and r.file.endswith(suffix)
    ]
    if not recs:
        return {}
    out: Dict[int, Tuple[Dict, np.ndarray]] = {}
    bands = sorted({r.band for r in recs})
    for band in bands:
        # One record per bank: duplicates (two workers inventorying the
        # same file on a shared filesystem, or two files claiming one
        # player) must not stitch the bank twice into a double-width
        # band.  First record per bank wins, like raw_sequences' dedup.
        by_bank: Dict[int, InventoryRecord] = {}
        for r in sorted((r for r in recs if r.band == band),
                        key=lambda r: r.bank):
            if r.bank in by_bank:
                if r.file != by_bank[r.bank].file:
                    log.warning(
                        "band %d bank %d: multiple files (%s kept, %s "
                        "dropped)", band, r.bank, by_bank[r.bank].file,
                        r.file,
                    )
                continue
            by_bank[r.bank] = r
        bankrecs = list(by_bank.values())
        if len(bankrecs) < 8:
            log.warning(
                "band %d: only banks %s present for %s/%s",
                band,
                [r.bank for r in bankrecs],
                session,
                scan,
            )
        wids = [r.worker for r in bankrecs]
        files = [r.file for r in bankrecs]
        datas = get_data(
            wids, files, idxs, fqav_by=fqav_by, fqav_func=fqav_func, pool=pool
        )
        hdrs = get_headers(wids, files, pool=pool)
        stitched = np.concatenate(datas, axis=-1)
        hdr = dict(hdrs[0])
        fch1, foff, _ = fqav_range(
            hdr["fch1"], hdr["foff"], hdr["nchans"], fqav_by
        )
        hdr.update(
            fch1=fch1,
            foff=foff,
            nchans=stitched.shape[-1],
            nsamps=stitched.shape[0],
            data_size=stitched.nbytes,
        )
        if do_despike:
            nfpc = max(int(hdr.get("nfpc", 0)) // max(fqav_by, 1), 0)
            if nfpc >= 2 and stitched.shape[-1] % nfpc == 0:
                stitched = _despike(stitched, nfpc)
            else:
                log.warning("band %d: skipping despike (nfpc=%s)", band, nfpc)
        out[band] = (hdr, stitched)
    return out


def reduce_raw(
    worker_ids: Sequence[int],
    raw_paths: Sequence[Union[str, Sequence[str]]],
    out_paths: Optional[Sequence[str]] = None,
    *,
    pool: Optional[WorkerPool] = None,
    on_error: str = "raise",
    **reducer_kw,
) -> List:
    """Fan GUPPI RAW → filterbank reduction out over the workers that own
    the files, one (worker, raw source) pair at a time — the distributed
    rawspec replacement (capability extension over the reference, which
    only reads already-reduced products; BASELINE.json configs 1-2).

    Each entry of ``raw_paths`` may be a single file path, a ``.NNNN.raw``
    sequence stem, or a path list (one scan's multi-file recording —
    :func:`blit.inventory.raw_sequences` groups an inventory into exactly
    these units).  ``reducer_kw`` passes through to
    :func:`blit.workers.reduce_raw` (``product=`` preset or
    ``nfft``/``nint``/``stokes``).
    """
    if len(worker_ids) != len(raw_paths):
        raise ValueError("worker_ids and raw_paths must have the same size")
    if out_paths is not None and len(out_paths) != len(raw_paths):
        raise ValueError("out_paths must match raw_paths")
    p = _pool(pool)
    args = [
        (rp,) if out_paths is None else (rp, op)
        for rp, op in zip(raw_paths, out_paths or raw_paths)
    ]
    return p.run_on(worker_ids, wf.reduce_raw, args, kwargs=reducer_kw,
                    on_error=on_error)
