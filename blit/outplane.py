"""Asynchronous output plane: overlapped device→host readback and
write-behind product sinks.

The ingest side of the framework has been pipelined since PR 1 (the
:class:`blit.pipeline.BufferRotation` prefetch core), but the OUTPUT side
stayed serialized: every streaming driver synced a chunk's product with
``np.asarray(jax.block_until_ready(out))`` on the consumer thread and then
wrote it to disk before dispatching the next chunk — device compute,
device→host readback and FBH5/SIGPROC appends ran one-at-a-time.  On rigs
whose device→host link is slow relative to compute (the dev tunnel reads
back at ~18 MB/s where the kernels run at 19 GB/s — BENCH_r05's 350 s
"stream" stage) the whole end-to-end rate collapses to the sum of the
three legs.  The paper's premise is per-node reduction *so only small
products cross the slow link*; the framework must therefore hide that
link behind compute the same way the ingest rotation hides file reads.

This module is the result-side mirror of ``BufferRotation``:

- :class:`OutputRotation` keeps up to ``depth`` device outputs in flight,
  reads them back on a dedicated thread (``block_until_ready`` +
  host fetch) into a bounded ring of reusable host slabs, and hands
  completed :class:`OutputSlab` handles back to the consumer in stream
  order.  Back-pressure is two-sided: :meth:`OutputRotation.put` blocks
  while ``depth`` outputs are pending (bounding device HBM), and the
  readback thread blocks when every ring slab is held downstream
  (bounding host RSS at ``depth + 1`` slabs).
- :class:`AsyncSink` is a bounded-queue write-behind writer: product
  appends run on a background thread against any slab writer
  (``FBH5Writer`` / ``FilWriter`` / the resumable twins), with
  :meth:`AsyncSink.flush` barriers for resume checkpoints, writer-thread
  failures re-raised cleanly on the consumer side, and ``sink.write`` /
  ``sink.flush`` fault-injection points (blit/faults.py).
- :class:`FoldInFlight` is the shared lag-``depth`` bookkeeping for the
  on-device fold drivers (``correlate_stream``, ``beamform_accumulate``):
  a window slot frees once the fold that consumed it has synchronized,
  and :meth:`FoldInFlight.drain` releases the tail *without* a second
  sync when the caller's terminal sync already proved completion.

Both threaded stages reuse ``BufferRotation``'s liveness discipline: a
producer-progress stall watchdog (back-pressure waits count as progress),
and a bounded close-join that abandons a wedged daemon thread with a
warning instead of converting teardown into the hang it detected.

Stage accounting (:class:`blit.observability.Timeline`): the readback
thread times ``device`` (the lag-synchronized wait on a dispatch; carries
the input bytes when the caller supplies them, else byte-free) and
``readback`` (host fetch, product bytes); the sink thread times ``write``
(bytes appended).  ``Timeline.overlap_efficiency`` turns those plus the
driver's wall stage into the overlap gauge operators read when diagnosing
a slow link (docs/WORKFLOWS.md).

Outputs are byte-identical to the synchronous path: the readback thread
processes dispatches strictly in put order, ring slabs receive exact
copies of the fetched products, and the sink appends in queue order —
no float operation moves, only the waiting does.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Callable, Iterator, List, Optional

import numpy as np

from blit import faults, observability
from blit.observability import Timeline

log = logging.getLogger("blit.outplane")

_EOF = object()

# The output plane's per-chunk histograms, in the order the bench /
# ingest-bench / tune stage_quantiles blocks report them.  One constant —
# adding or renaming a hist here updates every report surface at once.
INGEST_HISTS = ("out.chunk_latency_s", "out.readback_lag_s", "out.write_s")


def readback_extra_slots(out_depth: int, prefetch_depth: int) -> int:
    """Chunk-rotation widening required by the readback plane: a
    readback deeper than the producer's prefetch pins more un-synced
    chunk buffers than ``prefetch_depth`` provides, so the rotation must
    grow by the difference plus one read-ahead slot — otherwise the
    producer starves (and the all-slots-held starvation heuristic stops
    being a true bug signal).  Shared by every plane that pairs a chunk
    :class:`~blit.pipeline.BufferRotation` with an
    :class:`OutputRotation` (reduce and search) so the invariant cannot
    drift between them."""
    return 1 + max(0, max(2, out_depth) - max(2, prefetch_depth))


class OutputSlab:
    """A completed readback handed to the consumer: ``data`` is the host
    product (an exact copy in a ring slab when the rotation reuses slabs,
    else the fetched array itself).  The consumer MUST :meth:`release`
    every slab once nothing still reads ``data`` — in ring mode the slab
    storage is recycled for a later chunk after that (idempotent)."""

    __slots__ = ("data", "payload", "_release")

    def __init__(self, data: np.ndarray, payload, release) -> None:
        self.data = data
        self.payload = payload
        self._release = release

    def release(self) -> None:
        if self._release is not None:
            rel, self._release = self._release, None
            rel()


class OutputRotation:
    """The prefetch rotation of the result side: a dedicated readback
    thread turns in-flight device outputs into host slabs while the
    caller keeps dispatching (class docstring; the
    :class:`blit.pipeline.BufferRotation` contract mirrored).

    Contract:

    - :meth:`put` hands an async-dispatched device array to the readback
      thread and returns any slabs completed so far (stream order).  It
      blocks while ``depth`` outputs are already pending — that wait is
      the device-memory bound AND where compute/readback overlap happens
      (the caller's *next* dispatch is already queued device-side).
    - ``on_consumed`` fires on the readback thread right after the
      output synchronizes — the moment the dispatch's *inputs* are free
      (release an ingest chunk / feed window there).
    - :meth:`drain` ends the stream: yields the remaining slabs in
      order, then returns.  Readback-thread exceptions re-raise in the
      consumer from :meth:`put`/:meth:`drain`.
    - ``reuse=True`` decouples emitted slabs from jax-owned memory:
      fetches that alias the device buffer (CPU backends) copy into a
      bounded recycling ring (``depth + 1`` resident); fetches that
      already allocated fresh host memory (TPU/GPU D2H) are emitted
      as-is, with no second copy.  ``reuse=False`` emits the fetched
      arrays directly (callers that hand slabs to arbitrary consumers —
      the public ``RawReducer.stream`` — must not recycle under them).
    """

    def __init__(self, depth: int = 1, *, timeline: Optional[Timeline] = None,
                 reuse: bool = False, name: str = "blit-readback",
                 stall_timeout_s: Optional[float] = None):
        self.depth = max(1, depth)
        self.reuse = reuse
        self.stall_timeout_s = stall_timeout_s
        self._tl = timeline if timeline is not None else Timeline()
        self._in: "queue.Queue" = queue.Queue()
        self._cv = threading.Condition()
        self._pending = 0        # put but not yet emitted (readback bound)
        self._done: deque = deque()  # completed slabs, stream order
        self._exc: Optional[BaseException] = None
        self._eof = False
        self._stop = threading.Event()
        self._free: List[np.ndarray] = []  # released ring slabs (reuse)
        self._nslabs = 0
        self._wd = observability.StallWatchdog(
            stall_timeout_s, name,
            what="a wedged device fetch would otherwise hang the stream",
        )
        # Captured at construction (the consumer's thread): the readback
        # thread's lifetime span parents onto whatever driver span built
        # the rotation, keeping the output plane causally linked in a
        # trace (ISSUE 5 tentpole #1).
        self._span_ctx = observability.tracer().context()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    # -- readback thread ---------------------------------------------------
    def _run(self) -> None:
        tr = observability.tracer()
        with tr.activate(self._span_ctx), tr.span("outplane.readback"):
            self._run_inner()

    def _run_inner(self) -> None:
        import jax

        try:
            while True:
                try:
                    item = self._in.get(timeout=0.2)
                except queue.Empty:
                    if self._stop.is_set():
                        return
                    continue
                if item is _EOF:
                    with self._cv:
                        self._eof = True
                        self._cv.notify_all()
                    return
                out, nbytes, payload, on_consumed, fetch, t_enq = item
                t_got = time.perf_counter()
                # Queue-side lag distribution (ISSUE 5 tentpole #2): how
                # long dispatches wait before the readback thread reaches
                # them — the leading indicator of a saturating D2H link.
                self._tl.observe("out.readback_lag_s", t_got - t_enq)
                self._wd.beat()
                # The wait on the dispatch IS the device stage: overlapped
                # with the consumer thread's next dispatch and the ingest
                # producer's next read.
                if nbytes is None:
                    with self._tl.stage("device", byte_free=True):
                        jax.block_until_ready(out)
                else:
                    with self._tl.stage("device", nbytes=nbytes):
                        jax.block_until_ready(out)
                if on_consumed is not None:
                    # Output ready ⇒ inputs consumed: ingest slots refill.
                    on_consumed()
                self._wd.beat()
                if not fetch:
                    # Sync-only put (the sharded plane's non-writer pod
                    # processes, ISSUE 9): the dispatch had to be waited
                    # out — it pins feed slots and orders the stream —
                    # but nothing reads its bytes host-side, so no
                    # device→host fetch happens and no slab is emitted.
                    del out, item
                    with self._cv:
                        self._pending -= 1
                        self._cv.notify_all()
                    continue
                recycled = False
                with self._tl.stage("readback"):
                    host = np.asarray(out)
                    if self.reuse and (host.base is not None
                                       or not host.flags.owndata):
                        # The fetch was a zero-copy VIEW aliasing the jax
                        # buffer (CPU backends): copy into a ring slab so
                        # the buffer frees now and the slab recycles.  On
                        # backends where the fetch itself allocated fresh
                        # host memory (TPU/GPU D2H), that array IS the
                        # slab — a second product-sized memcpy on this
                        # (critical, slow-link) thread would buy nothing,
                        # and the ring could never avoid the allocation
                        # np.asarray already made.
                        slab = self._take_slab(host.shape, host.dtype)
                        if slab is None:
                            return  # closed while waiting for a slab
                        np.copyto(slab, host)
                        host = slab
                        recycled = True
                self._tl.stages["readback"].bytes += host.nbytes
                # Drop the device reference NOW — HBM frees as soon as the
                # host copy exists, not when the product hits disk.
                del out, item
                self._wd.beat()
                release = (
                    (lambda s=host: self._release_slab(s))
                    if recycled else None
                )
                # Per-chunk service latency (sync wait + host fetch) —
                # the distribution behind the aggregate device/readback
                # stage seconds.
                self._tl.observe("out.chunk_latency_s",
                                 time.perf_counter() - t_got)
                with self._cv:
                    self._pending -= 1
                    self._done.append(OutputSlab(host, payload, release))
                    self._cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — forwarded to the consumer
            with self._cv:
                self._exc = e
                self._cv.notify_all()

    def _take_slab(self, shape, dtype) -> Optional[np.ndarray]:
        """A free ring slab matching ``(shape, dtype)`` — allocating up to
        ``depth + 1`` resident slabs, retiring a mismatched free slab when
        at the limit (the final flush chunk is smaller than steady state),
        else waiting for the consumer to release one.  That wait is
        back-pressure from the sink, not a readback stall — the beat keeps
        ticking.  Returns None if closed while waiting."""
        alloc_shape = None
        evicted = None
        with self._cv:
            while True:
                for i, s in enumerate(self._free):
                    if s.shape == shape and s.dtype == dtype:
                        return self._free.pop(i)
                if self._nslabs <= self.depth:
                    self._nslabs += 1
                    alloc_shape = shape
                    break
                if self._free:  # at the limit, none match: replace one
                    evicted = self._free.pop()
                    alloc_shape = shape
                    break
                if self._stop.is_set():
                    return None
                self._wd.beat()
                self._cv.wait(timeout=0.2)
        # Aligned, pool-recycled staging (blit/hostmem.py): a previous
        # stream's already-faulted slab when one matches.
        from blit import hostmem

        pool = hostmem.slab_pool()
        if evicted is not None:
            # The replaced steady-state slab retires to the staging pool
            # (the close() rule) — not to the GC.
            pool.give(evicted)
        return pool.take(alloc_shape, dtype)

    def _release_slab(self, slab: np.ndarray) -> None:
        with self._cv:
            if not self._stop.is_set():
                self._free.append(slab)
                self._cv.notify_all()
                return
        # Released after close() swept the ring (e.g. the AsyncSink
        # draining its write-behind tail): retire straight to the staging
        # pool — appending to a closed rotation's _free just feeds the GC
        # and makes the next stream re-pay allocation + first-touch
        # faults for its tail slabs.
        from blit import hostmem

        hostmem.slab_pool().give(slab)

    # -- consumer side -----------------------------------------------------
    def _poll(self) -> float:
        return self._wd.poll_s(0.2)

    def _check(self) -> None:
        """Raise under ``self._cv``: forwarded readback error or stall.
        The error re-raises on EVERY call — a consumer that swallowed one
        raise must not see the rotation as healthy afterwards."""
        if self._exc is not None:
            raise self._exc
        if self._pending > 0:
            self._wd.check("readback stalled",
                           active=self._thread.is_alive())

    def put(self, out, *, nbytes: Optional[int] = None, payload=None,
            on_consumed: Optional[Callable[[], None]] = None,
            fetch: bool = True) -> List[OutputSlab]:
        """Enqueue an async-dispatched device array for readback; return
        the slabs completed so far (possibly empty), blocking while
        ``depth`` outputs are pending.  ``nbytes`` (the dispatch's input
        bytes) lands on the ``device`` stage; omitted ⇒ byte-free.
        ``fetch=False`` syncs the dispatch (and fires ``on_consumed``)
        without a device→host fetch — no slab is ever emitted for it."""
        with self._cv:
            self._check()
            self._pending += 1
        self._in.put((out, nbytes, payload, on_consumed, fetch,
                      time.perf_counter()))
        ready: List[OutputSlab] = []
        with self._cv:
            while True:
                while self._done:
                    ready.append(self._done.popleft())
                self._check()
                if self._pending < self.depth:
                    return ready
                self._cv.wait(timeout=self._poll())

    def drain(self) -> Iterator[OutputSlab]:
        """End the stream: yield every remaining slab in order."""
        self._in.put(_EOF)
        while True:
            batch: List[OutputSlab] = []
            finished = False
            with self._cv:
                while True:
                    while self._done:
                        batch.append(self._done.popleft())
                    self._check()
                    if self._eof:
                        finished = True
                        break
                    if batch:
                        break
                    self._cv.wait(timeout=self._poll())
            # Yield OUTSIDE the lock: consumers release slabs (and the
            # sink thread releases ring slabs) re-entering _cv.
            for slab in batch:
                yield slab
            if finished:
                return

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Stop the readback thread and join it (idempotent).  Bounded:
        a thread wedged inside a device wait is abandoned with a warning
        (the BufferRotation close rule) rather than hanging teardown."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=join_timeout_s)
        if self._thread.is_alive():
            log.warning(
                "%s: readback thread did not exit within %.1fs of close; "
                "abandoning the daemon thread", self._thread.name,
                join_timeout_s,
            )
            return
        # Joined cleanly: retire the free ring slabs to the process
        # staging pool (blit/hostmem.py) so the next stream's readback
        # ring reuses already-faulted host memory.  Slabs still held by
        # consumers stay theirs; _release_slab retires them to the pool
        # too once they come back (the sink's write-behind tail).
        from blit import hostmem

        pool = hostmem.slab_pool()
        with self._cv:
            free, self._free = self._free, []
        for s in free:
            pool.give(s)


class _FlushBarrier:
    __slots__ = ("event",)

    def __init__(self) -> None:
        self.event = threading.Event()


_SINK_STOP = object()


class AsyncSink:
    """Bounded-queue write-behind product writer.

    Wraps any slab writer with the ``append(slab)`` / ``close()`` /
    ``abort()`` contract (``FBH5Writer``, ``FilWriter``,
    ``ResumableFBH5Writer``, ``ResumableFilWriter``): :meth:`append`
    enqueues and returns — the disk write happens on a background thread
    while the caller dispatches the next chunk.  The queue is bounded at
    ``depth`` slabs, so a slow disk back-pressures the whole plane
    instead of buffering the product in RAM.

    Durability semantics are the WRAPPED writer's, unchanged: the
    resumable writers fsync data before their cursor claims it *inside*
    ``append``, which now runs on the sink thread — a crash still leaves
    the cursor at-or-behind the durable bytes, so ``resume_target_ok``
    and the skip-frames replay behave exactly as on the synchronous path
    (the cursor may simply sit a few queued-but-unwritten slabs earlier).
    :meth:`flush` is the resume-checkpoint barrier: when it returns,
    every prior append has been applied and the writer's own flush hook
    (when it has one) has run.

    Failure contract: a writer-thread exception is held and re-raised on
    the CONSUMER side at the next :meth:`append`/:meth:`flush`/
    :meth:`close`; queued slabs after the failure are skipped but still
    released (the readback ring must not leak), the thread keeps
    draining to its stop sentinel so teardown always joins — no orphaned
    daemon — and :meth:`abort` leaves the wrapped writer's crash
    artifacts exactly as the synchronous path would (``.partial``
    dropped; resumable file + cursor kept).  ``sink.write`` and
    ``sink.flush`` are fault-injection points (blit/faults.py), keyed by
    the writer's path.
    """

    def __init__(self, writer, *, depth: int = 2,
                 timeline: Optional[Timeline] = None,
                 name: str = "blit-sink", key=None,
                 stall_timeout_s: Optional[float] = None):
        self._writer = writer
        self._tl = timeline if timeline is not None else Timeline()
        self._key = key if key is not None else getattr(writer, "path", None)
        self.stall_timeout_s = stall_timeout_s
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._exc: Optional[BaseException] = None
        self._stopped = False
        self._stop_ev = threading.Event()
        self._wd = observability.StallWatchdog(
            stall_timeout_s, name,
            what="a wedged disk append would otherwise hang the plane",
        )
        self._span_ctx = observability.tracer().context()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    # -- writer thread -----------------------------------------------------
    def _run(self) -> None:
        tr = observability.tracer()
        with tr.activate(self._span_ctx), tr.span(
            "outplane.sink", path=str(self._key or "")
        ):
            self._run_inner()

    def _run_inner(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                # Polling get: teardown must never need to squeeze a stop
                # sentinel into a FULL queue behind a wedged writer.
                if self._stop_ev.is_set():
                    return
                continue
            if item is _SINK_STOP:
                return
            self._wd.beat()
            if isinstance(item, _FlushBarrier):
                if self._exc is None:
                    try:
                        faults.fire("sink.flush", key=self._key)
                        fl = getattr(self._writer, "flush", None)
                        if fl is not None:
                            with self._tl.stage("flush", byte_free=True):
                                fl()
                    except BaseException as e:  # noqa: BLE001 — consumer re-raises
                        self._exc = e
                # FIFO ⇒ every append before the barrier was applied (or
                # the failure is recorded); wake the waiter either way.
                item.event.set()
                continue
            slab, release = item
            if self._exc is None:
                try:
                    faults.fire("sink.write", key=self._key)
                    t0 = time.perf_counter()
                    with self._tl.stage("write", nbytes=slab.nbytes):
                        self._writer.append(slab)
                    # Per-append latency distribution (ISSUE 8 satellite:
                    # the bench tables report write p50/p99, not just the
                    # stage mean — a bursty disk hides behind an average).
                    self._tl.observe("out.write_s",
                                     time.perf_counter() - t0)
                except BaseException as e:  # noqa: BLE001 — consumer re-raises
                    self._exc = e
            # Release even after a failure: later slabs are skipped, but
            # the readback ring they live in must keep rotating so the
            # consumer reaches its next append() and sees the error.
            if release is not None:
                release()
            self._wd.beat()

    # -- consumer side -----------------------------------------------------
    def _check(self) -> None:
        # Re-raise on EVERY call: close() after a swallowed append error
        # must refuse to finalize, not rename a truncated product.
        if self._exc is not None:
            raise self._exc

    def _put(self, item) -> None:
        poll = self._wd.poll_s(0.2)
        while True:
            try:
                self._q.put(item, timeout=poll)
                return
            except queue.Full:
                self._check()
                self._wd.check("writer stalled",
                               active=self._thread.is_alive())

    def append(self, slab: np.ndarray,
               release: Optional[Callable[[], None]] = None) -> None:
        """Enqueue a product slab (write-behind).  ``release`` fires on
        the sink thread once the write (or post-failure skip) is done —
        hand the slab's :meth:`OutputSlab.release` here so ring slabs
        recycle only after their bytes are on disk."""
        self._check()
        self._put((slab, release))

    def flush(self) -> None:
        """Barrier: every append enqueued before this call has been
        applied by the wrapped writer when it returns (re-raising a
        writer-thread failure instead).  The resume-checkpoint hook —
        crash semantics stay those of the wrapped writer."""
        self._check()
        barrier = _FlushBarrier()
        self._put(barrier)
        poll = self._wd.poll_s(0.5)
        while not barrier.event.wait(timeout=poll):
            self._wd.check("writer stalled inside flush barrier",
                           active=self._thread.is_alive())
            if not self._thread.is_alive():
                break  # died without recording? _check below decides
        self._check()

    def _join(self, join_timeout_s: float) -> bool:
        if not self._stopped:
            self._stopped = True
            self._stop_ev.set()
            try:
                # Prompt exit when the queue has room; the stop event
                # alone suffices otherwise (never block teardown).
                self._q.put_nowait(_SINK_STOP)
            except queue.Full:
                pass
        self._thread.join(timeout=join_timeout_s)
        if self._thread.is_alive():
            log.warning(
                "%s: writer thread did not exit within %.1fs; abandoning "
                "the daemon thread (writer left un-finalized)",
                self._thread.name, join_timeout_s,
            )
            return False
        return True

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Flush, stop the thread, then finalize the wrapped writer on
        the calling thread (rename-into-place / sidecar removal happen
        exactly as on the synchronous path).  Re-raises a writer-thread
        failure BEFORE finalizing — a failed product must not be
        renamed complete."""
        self.flush()
        joined = self._join(join_timeout_s)
        self._check()
        if joined:
            self._writer.close()

    def abort(self, join_timeout_s: float = 10.0) -> None:
        """Teardown on the error path: stop the thread (queued slabs are
        dropped — exactly what a synchronous crash at this point would
        not have written) and ``abort()`` the wrapped writer.  Never
        raises; the caller is already propagating the real error."""
        joined = self._join(join_timeout_s)
        if joined:
            try:
                self._writer.abort()
            except Exception:  # noqa: BLE001 — teardown must not mask the cause
                log.exception("async sink: writer abort failed")

    @property
    def nsamps(self) -> int:
        return self._writer.nsamps


class FoldInFlight:
    """Lag-``depth`` bookkeeping for on-device fold drivers: each admitted
    window carries the device token whose readiness implies the window's
    arrays were consumed (the fold output).  :meth:`make_room` — called
    BEFORE dispatching the next fold — synchronizes and releases the
    oldest windows down to ``depth`` in flight; the order matters because
    the next fold *donates* the previous accumulator
    (``correlate_stream``), so its token must be synced before dispatch
    deletes it.  :meth:`drain` releases the tail; ``synced=True`` skips
    the redundant wait when the caller's terminal sync (the finish-psum
    fetch) already proved every fold complete — the correlator's old tail
    path synced the accumulator twice for exactly this reason."""

    def __init__(self, timeline: Optional[Timeline] = None, depth: int = 1):
        self._tl = timeline if timeline is not None else Timeline()
        self.depth = max(1, depth)
        self._pending: deque = deque()

    def make_room(self) -> None:
        import jax

        while len(self._pending) >= self.depth:
            win, token = self._pending.popleft()
            with self._tl.stage("device", byte_free=True):
                jax.block_until_ready(token)
            win.release()

    def admit(self, win, token) -> None:
        self._pending.append((win, token))

    def drain(self, synced: bool = False) -> None:
        import jax

        while self._pending:
            win, token = self._pending.popleft()
            if not synced:
                with self._tl.stage("device", byte_free=True):
                    jax.block_until_ready(token)
            win.release()
