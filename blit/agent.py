"""Per-host worker agent: ``python -m blit.agent``.

The remote half of the ``backend="remote"`` worker pool
(blit/parallel/remote.py) — the rebuild of the Julia worker process that
``Distributed.addprocs`` starts over ssh and loads ``WorkerFunctions`` into
(reference: src/gbt.jl:28-42).

Protocol (stdin/stdout, logs on stderr):
    banner   := b"BLITAGENT1\\n"            (emitted once at startup; the
                                            client discards any ssh/rc noise
                                            preceding it before framing)
    request  := u64-le length | pickle((fn_path, args, kwargs))
    response := u64-le length | pickle(("ok", result) | ("err", type, msg, tb))

Two enforcement layers keep the wire from invoking arbitrary code:
``fn_path`` must resolve inside the ``blit`` package, AND deserialization
uses a restricted unpickler whose ``find_class`` only admits blit / numpy /
stdlib-safe globals — a plain ``pickle.loads`` would execute attacker
``__reduce__`` payloads before any allow-list ran.  One request is serviced
at a time, matching the reference's one-``@spawnat``-at-a-time-per-worker
usage.
"""

from __future__ import annotations

import importlib
import io
import pickle
import struct
import sys
import traceback

MAGIC = b"BLITAGENT1\n"
_LEN = struct.Struct("<Q")

# Module prefixes whose globals may cross the wire (requests AND responses:
# arguments are regexes/slices/arrays, results are arrays/records/dicts).
_SAFE_MODULE_PREFIXES = ("blit", "numpy", "re")
_SAFE_BUILTINS = frozenset(
    {"slice", "complex", "range", "frozenset", "set", "bytearray"}
)


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        top = module.split(".", 1)[0]
        if top in _SAFE_MODULE_PREFIXES:
            return super().find_class(module, name)
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"agent wire refuses global {module}.{name}"
        )


def resolve(fn_path: str):
    """Import and return a callable from a ``blit.``-prefixed dotted path."""
    if not fn_path.startswith("blit."):
        raise PermissionError(f"agent refuses non-blit callable {fn_path!r}")
    mod_path, _, name = fn_path.rpartition(".")
    fn = getattr(importlib.import_module(mod_path), name)
    if not callable(fn):
        raise TypeError(f"{fn_path} is not callable")
    return fn


def read_msg(stream) -> object:
    head = stream.read(_LEN.size)
    if len(head) < _LEN.size:
        raise EOFError
    (n,) = _LEN.unpack(head)
    body = stream.read(n)
    if len(body) < n:
        raise EOFError
    return _RestrictedUnpickler(io.BytesIO(body)).load()


def write_msg(stream, obj) -> None:
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    stream.write(_LEN.pack(len(body)))
    stream.write(body)
    stream.flush()


def serve(stdin=None, stdout=None) -> None:
    """Blocking request loop; returns on EOF (pool shutdown / ssh drop)."""
    stdin = stdin or sys.stdin.buffer
    stdout = stdout or sys.stdout.buffer
    while True:
        try:
            fn_path, args, kwargs = read_msg(stdin)
        except EOFError:
            return
        try:
            result = resolve(fn_path)(*args, **kwargs)
            write_msg(stdout, ("ok", result))
        except BaseException as e:  # noqa: BLE001 — everything crosses the wire
            write_msg(
                stdout,
                ("err", type(e).__name__, str(e), traceback.format_exc()),
            )


def main() -> None:
    # Anything the worker functions print must not corrupt the framing:
    # repoint sys.stdout at stderr and keep the real fd for the protocol.
    proto_out = sys.stdout.buffer
    sys.stdout = io.TextIOWrapper(sys.stderr.buffer, line_buffering=True)
    # Handshake: lets the client skip any ssh/rc banner noise ahead of us.
    proto_out.write(MAGIC)
    proto_out.flush()
    serve(sys.stdin.buffer, proto_out)


if __name__ == "__main__":
    main()
