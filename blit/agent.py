"""Per-host worker agent: ``python -m blit.agent``.

The remote half of the ``backend="remote"`` worker pool
(blit/parallel/remote.py) — the rebuild of the Julia worker process that
``Distributed.addprocs`` starts over ssh and loads ``WorkerFunctions`` into
(reference: src/gbt.jl:28-42).

Protocol (stdin/stdout, logs on stderr):
    banner   := b"BLITAGENT1\\n"            (emitted once at startup; the
                                            client discards any ssh/rc noise
                                            preceding it before framing)
    request  := u64-le length | pickle((fn_path, args, kwargs))
    response := u64-le length | pickle(("ok", result) | ("err", type, msg, tb))

Three enforcement layers keep the wire from invoking arbitrary code or
exhausting memory: ``fn_path`` must resolve inside the ``blit`` package; the
length header is capped (:data:`MAX_MSG_BYTES`) before any allocation; and
deserialization uses a restricted unpickler whose ``find_class`` admits only
an exact (module, name) allow-list of value constructors / reconstructors /
pure reducers — module-prefix trust would let pickle REDUCE invoke any
callable in an admitted namespace with attacker-chosen arguments.  One
request is serviced at a time, matching the reference's
one-``@spawnat``-at-a-time-per-worker usage.
"""

from __future__ import annotations

import importlib
import io
import os
import pickle
import struct
import sys
import traceback

MAGIC = b"BLITAGENT1\n"
_LEN = struct.Struct("<Q")

# Upper bound on one framed message, enforced BEFORE the body buffer is
# allocated — an untrusted length header must not be able to force multi-GB
# allocations.  Full data slabs legitimately cross the wire (reference
# semantics: whole arrays travel main-ward, src/gbt.jl:78), so the default is
# generous; deployments can tighten or widen it via the env var.
MAX_MSG_BYTES = int(
    os.environ.get("BLIT_AGENT_MAX_MSG_BYTES", str(8 << 30))
)

# Exact globals that may cross the wire, (module, qualname) pairs — NOT
# module prefixes: pickle REDUCE can call any admitted callable with
# attacker-chosen arguments, so each entry must be safe to invoke blind
# (value constructors / reconstructors / pure reducers only).  Requests carry
# regexes/slices/arrays/records; responses carry arrays/records/dicts.
_SAFE_GLOBALS = frozenset({
    # numpy array/scalar reconstruction — numpy 2.x paths...
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy._core.numeric", "_frombuffer"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    # ...and their numpy 1.x spellings (a remote host may run 1.x).
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    # Pure elementwise/axis reducers users pass as ``fqav_func``.
    ("numpy", "sum"), ("numpy", "mean"), ("numpy", "median"),
    ("numpy", "max"), ("numpy", "min"), ("numpy", "amax"),
    ("numpy", "amin"), ("numpy", "nansum"), ("numpy", "nanmean"),
    ("numpy", "std"), ("numpy", "var"),
    ("numpy", "nanmedian"), ("numpy", "nanmax"), ("numpy", "nanmin"),
    # blit record types that legitimately cross the wire.
    ("blit.inventory", "InventoryRecord"),
    ("blit.naming", "GuppiName"),
    ("blit.config", "SiteConfig"),
})

# Requests additionally carry compiled regex patterns (inventory filters) —
# ``re._compile`` is a pure pattern constructor, acceptable on the *request*
# side where the caller already controls what the agent executes.  Responses
# must not admit it: a compromised peer's reply could hand the client a
# pathological pattern (ReDoS on next use), and no legitimate response needs
# to construct one — results are arrays/records/dicts.
_SAFE_GLOBALS_REQUEST = _SAFE_GLOBALS | {("re", "_compile")}
_SAFE_GLOBALS_RESPONSE = _SAFE_GLOBALS

# A peer claiming a frame beyond this is not merely oversized, it is hostile
# or corrupt (the u64 header can claim up to 16 EiB); draining it would pin
# the reader in a discard loop, so the stream is torn down instead.
_DRAIN_CAP_MULTIPLE = 4
_SAFE_BUILTINS = frozenset(
    {"slice", "complex", "range", "frozenset", "set", "bytearray"}
)


class _RestrictedUnpickler(pickle.Unpickler):
    def __init__(self, file, safe_globals=_SAFE_GLOBALS_REQUEST):
        super().__init__(file)
        self._safe_globals = safe_globals

    def find_class(self, module: str, name: str):
        if (module, name) in self._safe_globals:
            return super().find_class(module, name)
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"agent wire refuses global {module}.{name}"
        )


def ping() -> str:
    """Liveness probe: the cheapest possible round trip through the full
    request path (framing, unpickle, resolve, reply).  The client's health
    check (:meth:`blit.parallel.remote.RemoteWorker._ensure`) calls this as
    an ordinary ``blit.agent.ping`` request — a wedged-but-alive agent that
    cannot answer it within the ping deadline is killed and respawned
    (SURVEY.md §5 "health-checked worker pool")."""
    return "pong"


def resolve(fn_path: str):
    """Import and return a callable from a ``blit.``-prefixed dotted path."""
    if not fn_path.startswith("blit."):
        raise PermissionError(f"agent refuses non-blit callable {fn_path!r}")
    mod_path, _, name = fn_path.rpartition(".")
    fn = getattr(importlib.import_module(mod_path), name)
    if not callable(fn):
        raise TypeError(f"{fn_path} is not callable")
    return fn


def read_msg(
    stream,
    max_bytes: int = 0,
    safe_globals=_SAFE_GLOBALS_REQUEST,
    drain_oversized: bool = True,
) -> object:
    """Read one framed message.  The length header is untrusted: it is
    validated against ``max_bytes`` (default :data:`MAX_MSG_BYTES`) before
    any buffer is allocated.

    On a modestly oversized header the body is consumed in bounded chunks
    and discarded before :class:`pickle.UnpicklingError` is raised, so the
    stream stays framed and the peer can keep servicing requests.  A claim
    beyond ``_DRAIN_CAP_MULTIPLE`` times the limit is treated as a dead or
    hostile stream — :class:`EOFError` tears the connection down rather than
    letting a 2^64-byte claim pin the reader in a discard loop.

    ``safe_globals`` picks the unpickling allow-list for the direction:
    requests admit compiled regexes (:data:`_SAFE_GLOBALS_REQUEST`, the
    default), responses do not (:data:`_SAFE_GLOBALS_RESPONSE`).

    ``drain_oversized=False`` skips the keep-the-stream-framed drain and
    refuses an oversized frame immediately — for callers who tear the
    connection down on refusal anyway (the client's response path), where
    draining a multi-GiB body through an ssh pipe would be pure waste.
    """
    head = stream.read(_LEN.size)
    if len(head) < _LEN.size:
        raise EOFError
    (n,) = _LEN.unpack(head)
    limit = max_bytes or MAX_MSG_BYTES
    if n > limit:
        if not drain_oversized:
            raise pickle.UnpicklingError(
                f"agent wire message of {n} bytes exceeds the {limit}-byte "
                "limit (stream not drained; tear down the connection)"
            )
        if n > _DRAIN_CAP_MULTIPLE * limit:
            raise EOFError(
                f"agent wire claims a {n}-byte frame (> {_DRAIN_CAP_MULTIPLE}x "
                f"the {limit}-byte limit); tearing down the stream"
            )
        remaining = n
        while remaining > 0:
            chunk = stream.read(min(remaining, 1 << 20))
            if not chunk:
                break  # peer hung up mid-body; refusal below still applies
            remaining -= len(chunk)
        raise pickle.UnpicklingError(
            f"agent wire message of {n} bytes exceeds the "
            f"{limit}-byte limit (BLIT_AGENT_MAX_MSG_BYTES)"
        )
    body = stream.read(n)
    if len(body) < n:
        raise EOFError
    # The frame is fully consumed: any decode failure past this point
    # (truncated pickle → EOFError, UnicodeDecodeError, struct.error, an
    # allow-listed global missing in this numpy version → AttributeError...)
    # leaves the stream correctly framed, so it is reported as a refusal the
    # peer can recover from — never confused with stream-level EOF.
    try:
        return _RestrictedUnpickler(io.BytesIO(body), safe_globals).load()
    except pickle.UnpicklingError:
        raise
    except Exception as e:
        raise pickle.UnpicklingError(
            f"agent wire body failed to decode: {type(e).__name__}: {e}"
        ) from e


def write_msg(stream, obj) -> None:
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    stream.write(_LEN.pack(len(body)))
    stream.write(body)
    stream.flush()


def serve(stdin=None, stdout=None) -> None:
    """Blocking request loop; returns on EOF (pool shutdown / ssh drop)."""
    stdin = stdin or sys.stdin.buffer
    stdout = stdout or sys.stdout.buffer
    while True:
        try:
            msg = read_msg(stdin)
        except (EOFError, OSError):
            # Stream-level trouble (EOF, hostile length claim, dropped
            # pipe/pty): the connection is gone or unframed — end the loop
            # rather than spin err frames into a dead stream.
            return
        except pickle.UnpicklingError as e:
            # A refused or malformed request must not kill the worker:
            # read_msg consumed the framed body (and translates every
            # decode failure to UnpicklingError), so the stream is still
            # framed — report the refusal and keep serving.
            write_msg(stdout, ("err", "UnpicklingError", str(e), ""))
            continue
        try:
            fn_path, args, kwargs = msg
        except (TypeError, ValueError) as e:
            # Decoded fine but not the (fn_path, args, kwargs) shape.
            write_msg(stdout, ("err", type(e).__name__, str(e), ""))
            continue
        # Reserved wire kwarg (never reaches the worker fn): the driver's
        # trace context — this request's span parents onto the pool
        # dispatch span that shipped it (ISSUE 5 tentpole #1).
        tctx = kwargs.pop("_blit_trace", None) if isinstance(kwargs, dict) else None
        try:
            from blit.observability import tracer

            tr = tracer()
            with tr.activate(tctx), tr.span(
                f"agent.{fn_path.rpartition('.')[2]}", fn=fn_path
            ):
                result = resolve(fn_path)(*args, **kwargs)
            write_msg(stdout, ("ok", result))
        except BaseException as e:  # noqa: BLE001 — everything crosses the wire
            write_msg(
                stdout,
                ("err", type(e).__name__, str(e), traceback.format_exc()),
            )


def main() -> None:
    # Anything the worker functions print must not corrupt the framing:
    # repoint sys.stdout at stderr and keep the real fd for the protocol.
    proto_out = sys.stdout.buffer
    sys.stdout = io.TextIOWrapper(sys.stderr.buffer, line_buffering=True)
    # Worker-startup logging (ISSUE 5 satellite): the pool stamps each
    # agent's environment with its worker id, and BLIT_LOG_JSON flips the
    # stderr records to machine-parseable JSON lines so a fleet's logs
    # aggregate without re-parsing the human format.
    try:
        from blit.observability import configure_logging

        configure_logging(
            worker=int(os.environ.get("BLIT_WORKER_ID", "0") or 0),
            json_lines=bool(os.environ.get("BLIT_LOG_JSON")),
        )
    except Exception:  # noqa: BLE001 — logging must not block serving
        pass
    # Handshake: lets the client skip any ssh/rc banner noise ahead of us.
    proto_out.write(MAGIC)
    proto_out.flush()
    serve(sys.stdin.buffer, proto_out)


if __name__ == "__main__":
    main()
