"""Synthetic BL@GBT data generators (test fixtures + benchmark inputs).

The reference ships no fixtures at all (SURVEY.md §4); these generators are
the foundation of blit's far larger test surface: round-trip tests for every
codec, fake observation trees for the inventory crawl, and deterministic
voltage streams with injected tones for end-to-end pipeline validation.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from blit.config import COARSE_MHZ, nfpc_from_foff
from blit.io import write_fbh5, write_fil, write_raw


def signal_ready(outdir: str, tag) -> str:
    """Atomically drop a readiness marker ``<outdir>/.ready<tag>`` — the
    multi-process test harness's bring-up barrier (tests/
    test_multiprocess.py): a pod child writes it the moment its
    distributed runtime is up, so the parent can time the WORK phase
    separately from coordinator/collective bring-up (which legitimately
    runs long on loaded CI machines; ISSUE 8 satellite — the barrier
    replaced a blanket flaky-rerun)."""
    path = os.path.join(outdir, f".ready{tag}")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(os.getpid()))
    os.replace(tmp, path)
    return path


def make_fil_header(
    nchans: int = 64,
    nifs: int = 1,
    fch1: float = 8437.5,
    foff: Optional[float] = None,
    tsamp: float = 1.0e-3,
    tstart: float = 59897.0,
    source_name: str = "SYNTH",
) -> Dict:
    """A plausible GBT filterbank header; ``foff`` defaults to one coarse
    channel per fine channel bank slice (nfpc computes cleanly)."""
    if foff is None:
        foff = -COARSE_MHZ / max(nchans // 64, 1)
    return {
        "telescope_id": 6,  # GBT
        "machine_id": 0,
        "data_type": 1,
        "source_name": source_name,
        "barycentric": 0,
        "pulsarcentric": 0,
        "az_start": 0.0,
        "za_start": 0.0,
        "src_raj": 120000.0,
        "src_dej": 450000.0,
        "tstart": tstart,
        "tsamp": tsamp,
        "fch1": fch1,
        "foff": foff,
        "nchans": nchans,
        "nifs": nifs,
    }


def make_spectra(
    nsamps: int = 16,
    nifs: int = 1,
    nchans: int = 64,
    seed: int = 0,
    dtype=np.float32,
) -> np.ndarray:
    """Deterministic positive 'power' spectra shaped (nsamps, nifs, nchans)."""
    rng = np.random.default_rng(seed)
    base = rng.chisquare(4, size=(nsamps, nifs, nchans))
    ramp = 1.0 + np.arange(nchans) / nchans
    return (base * ramp).astype(dtype)


def synth_fil(path: str, nsamps=16, nifs=1, nchans=64, seed=0, **hdrkw) -> Tuple[Dict, np.ndarray]:
    hdr = make_fil_header(nchans=nchans, nifs=nifs, **hdrkw)
    data = make_spectra(nsamps, nifs, nchans, seed)
    write_fil(path, hdr, data)
    return hdr, data


def synth_fbh5(
    path: str, nsamps=16, nifs=1, nchans=64, seed=0, compression=None, **hdrkw
) -> Tuple[Dict, np.ndarray]:
    hdr = make_fil_header(nchans=nchans, nifs=nifs, **hdrkw)
    hdr["nfpc"] = nfpc_from_foff(hdr["foff"])
    data = make_spectra(nsamps, nifs, nchans, seed)
    write_fbh5(path, hdr, data, compression=compression)
    return hdr, data


def make_raw_header(
    obsnchan: int = 64,
    npol: int = 2,
    obsfreq: float = 8437.5,
    obsbw: float = 187.5,
    tbin: Optional[float] = None,
    overlap: int = 0,
    src_name: str = "SYNTH",
    stt_imjd: int = 59897,
    stt_smjd: int = 21221,
) -> Dict:
    if tbin is None:
        tbin = abs(obsnchan / (obsbw * 1e6))  # critically sampled
    return {
        "SRC_NAME": src_name,
        "TELESCOP": "GBT",
        "OBSFREQ": obsfreq,
        "OBSBW": obsbw,
        "OBSNCHAN": obsnchan,
        "NPOL": 4 if npol == 2 else npol,
        "NBITS": 8,
        "TBIN": tbin,
        "OVERLAP": overlap,
        "STT_IMJD": stt_imjd,
        "STT_SMJD": stt_smjd,
        "PKTIDX": 0,
        "CHAN_BW": obsbw / obsnchan,
    }


def tone_drift_for(nfft: int, nspectra: int, drift_bins: float) -> float:
    """The ``tone_drift`` (cycles/sample²) that drifts a tone by
    ``drift_bins`` FINE channels (bin width ``1/nfft`` cycles/sample)
    over ``nspectra`` consecutive nfft-point spectra — the known-ḟ
    injection for drift-search recovery tests (ISSUE 6 satellite):
    inject with this, search with ``window_spectra=nspectra``, and the
    top hit's ``drift_bins`` must match within one drift step."""
    return drift_bins / (nfft * nspectra * nfft)


def make_voltages(
    obsnchan: int,
    ntime: int,
    npol: int = 2,
    seed: int = 0,
    tone_chan: Optional[int] = None,
    tone_freq: float = 0.25,
    tone_amp: float = 20.0,
    noise_rms: float = 8.0,
    tone_drift: float = 0.0,
) -> np.ndarray:
    """Quantized complex voltages (obsnchan, ntime, npol, 2) int8: Gaussian
    noise plus an optional complex tone in one coarse channel (a
    'technosignature' for end-to-end detection tests).  ``tone_drift``
    chirps the tone linearly — instantaneous frequency
    ``tone_freq + tone_drift·t`` cycles/sample (phase integrates the
    chirp: ``2π(f₀·t + ½·ḟ·t²)``); :func:`tone_drift_for` maps a target
    fine-bin drift to this unit."""
    rng = np.random.default_rng(seed)
    v = rng.normal(0.0, noise_rms, size=(obsnchan, ntime, npol, 2))
    if tone_chan is not None:
        t = np.arange(ntime, dtype=np.float64)
        ph = 2 * np.pi * (tone_freq * t + 0.5 * tone_drift * t * t)
        v[tone_chan, :, :, 0] += tone_amp * np.cos(ph)[:, None]
        v[tone_chan, :, :, 1] += tone_amp * np.sin(ph)[:, None]
    return np.clip(np.round(v), -128, 127).astype(np.int8)


def synth_raw(
    path: str,
    nblocks: int = 2,
    obsnchan: int = 64,
    ntime_per_block: int = 1024,
    npol: int = 2,
    overlap: int = 0,
    directio: bool = False,
    seed: int = 0,
    tone_chan: Optional[int] = None,
    tone_drift: float = 0.0,
    tone_freq: float = 0.25,
    tone_amp: float = 20.0,
    **hdrkw,
) -> Tuple[Dict, List[np.ndarray]]:
    """Write a synthetic GUPPI RAW file.  With ``overlap`` > 0, consecutive
    blocks share their trailing/leading ``overlap`` samples, as on disk at
    GBT.  ``tone_drift`` chirps the injected tone (a drifting
    technosignature — :func:`tone_drift_for`)."""
    hdr = make_raw_header(obsnchan=obsnchan, npol=npol, overlap=overlap, **hdrkw)
    step = ntime_per_block - overlap
    total = step * (nblocks - 1) + ntime_per_block
    stream = make_voltages(obsnchan, total, npol, seed=seed,
                           tone_chan=tone_chan, tone_drift=tone_drift,
                           tone_freq=tone_freq, tone_amp=tone_amp)
    blocks = [stream[:, i * step : i * step + ntime_per_block] for i in range(nblocks)]
    write_raw(path, hdr, blocks, directio=directio)
    return hdr, blocks


def synth_raw_sequence(
    stem: str,
    nfiles: int = 2,
    blocks_per_file: int = 2,
    obsnchan: int = 64,
    ntime_per_block: int = 1024,
    npol: int = 2,
    overlap: int = 0,
    seed: int = 0,
    tone_chan: Optional[int] = None,
    tone_drift: float = 0.0,
    **hdrkw,
) -> Tuple[List[str], np.ndarray]:
    """Write a multi-file ``.NNNN.raw`` scan sequence carrying ONE contiguous
    voltage stream (the on-disk GBT recording layout: the block stream —
    including the OVERLAP convention — continues across file boundaries).

    Returns ``(paths, stream)`` where ``stream`` is the full gap-free
    voltage stream the sequence encodes.
    """
    nblocks = nfiles * blocks_per_file
    hdr = make_raw_header(obsnchan=obsnchan, npol=npol, overlap=overlap, **hdrkw)
    step = ntime_per_block - overlap
    total = step * (nblocks - 1) + ntime_per_block
    stream = make_voltages(obsnchan, total, npol, seed=seed,
                           tone_chan=tone_chan, tone_drift=tone_drift)
    blocks = [
        stream[:, i * step : i * step + ntime_per_block] for i in range(nblocks)
    ]
    paths = []
    for f in range(nfiles):
        p = f"{stem}.{f:04d}.raw"
        fhdr = dict(hdr)
        # PKTIDX continues across files (write_raw advances it per block).
        fhdr["PKTIDX"] = f * blocks_per_file * step
        write_raw(p, fhdr, blocks[f * blocks_per_file : (f + 1) * blocks_per_file])
        paths.append(p)
    return paths, stream


def build_observation_tree(
    root: str,
    session: str = "AGBT22B_999_01",
    scans: Tuple[str, ...] = ("0011",),
    players: Tuple[Tuple[int, int], ...] = ((0, 0), (0, 1)),
    nsamps: int = 16,
    nchans: int = 64,
    kind: str = "fbh5",
    nfiles: int = 1,
    raw_ntime: int = 1024,
) -> List[str]:
    """A fake BL@GBT data tree: ``<root>/<session>/GUPPI/BLPbb/<guppi name>``
    with real, readable product files.  Returns created paths.

    ``kind="raw"`` writes per-player ``.NNNN.raw`` sequences (``nfiles``
    members, ``raw_ntime`` samples per block) whose bank frequencies tile
    contiguously across each band (bank k owns the k-th 187.5/8 MHz slice,
    descending GBT sign) — so a tree feeds
    :func:`blit.inventory.scan_grid` / ``load_scan_mesh`` directly."""
    paths = []
    for band, bank in players:
        player = f"BLP{band}{bank}"
        host = f"blc{band}{bank}"
        d = os.path.join(root, session, "GUPPI", player)
        os.makedirs(d, exist_ok=True)
        for scan in scans:
            base = f"{host}_guppi_59897_21221_HD_84406_{scan}"
            if kind == "fbh5":
                p = os.path.join(d, base + ".rawspec.0002.h5")
                synth_fbh5(p, nsamps=nsamps, nchans=nchans, seed=band * 8 + bank)
            elif kind == "fil":
                p = os.path.join(d, base + ".rawspec.0002.fil")
                synth_fil(p, nsamps=nsamps, nchans=nchans, seed=band * 8 + bank)
            elif kind == "raw":
                bank_bw = -187.5 / 8
                ps, _ = synth_raw_sequence(
                    os.path.join(d, base),
                    nfiles=nfiles,
                    blocks_per_file=2,
                    obsnchan=nchans,
                    ntime_per_block=raw_ntime,
                    seed=band * 8 + bank,
                    tone_chan=bank % nchans,
                    obsbw=bank_bw,
                    obsfreq=8000.0 + band * 500.0 + (bank + 0.5) * bank_bw,
                )
                paths.extend(ps)
                continue
            else:
                raise ValueError(f"unknown kind {kind!r}")
            paths.append(p)
    return paths


def sync_compare_verdict(async_path: str, sync_path: str,
                         async_wall_s: float, sync_wall_s: float) -> Dict:
    """The ISSUE 8 async-vs-sync acceptance, defined ONCE for every
    surface that publishes it (``bench.py`` product leg, ``blit
    ingest-bench --sync-compare``): the async (device-narrowed when
    nbits<32) and sync products of the same recording must be the same
    file, and the speedup is the sync/async wall ratio.  Constant-memory
    compare — product files can be large."""
    import filecmp

    return {
        "async_speedup": round(sync_wall_s / max(async_wall_s, 1e-9), 3),
        "products_identical": filecmp.cmp(async_path, sync_path,
                                          shallow=False),
    }
