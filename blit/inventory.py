"""On-node inventory crawl of GUPPI-convention directory trees.

Reference: ``WorkerFunctions.getinventory`` + ``InventoryTuple``
(src/gbtworkerfunctions.jl:63-129).  The crawl walks
``<root>/<session>/<extra>/<player>/**``: top-level session directories
(symlinks to directories included) filtered by ``session_re``, player
directories filtered by ``player_re``, then a recursive walk per player with
files filtered by ``file_re``; each hit is parsed with
:func:`blit.naming.parse_guppi_name`, warning and skipping on mismatch.
"""

from __future__ import annotations

import json
import logging
import os
import socket
from typing import Iterable, List, NamedTuple, Optional, Pattern, Tuple, Union

from blit import naming
from blit.config import DEFAULT, SiteConfig, _compile

log = logging.getLogger("blit.inventory")


class InventoryRecord(NamedTuple):
    """One data-product file found on one host.

    Field names, order, and types match the reference ``InventoryTuple``
    (src/gbtworkerfunctions.jl:63-66; README.md:77-89) so downstream tabular
    workflows (pandas ``DataFrame(records)``, groupby on (session, scan))
    carry over unchanged.
    """

    imjd: int
    smjd: int
    session: str
    scan: str
    src_name: str
    band: int
    bank: int
    host: str
    file: str
    worker: int


def _listdirs(path: str) -> List[str]:
    """Names of subdirectories of `path`, *including* symlinks that resolve to
    directories (reference includes session symlinks: src/gbtworkerfunctions.jl:81-83).
    Sorted for determinism (Julia's walkdir sorts by name)."""
    try:
        with os.scandir(path) as it:
            names = [e.name for e in it if e.is_dir(follow_symlinks=True)]
    except OSError:
        return []
    return sorted(names)


def get_inventory(
    file_re: Union[str, Pattern, None] = None,
    *,
    root: Optional[str] = None,
    session_re: Union[str, Pattern, None] = None,
    extra: Optional[str] = None,
    player_re: Union[str, Pattern, None] = None,
    worker: int = 0,
    host: Optional[str] = None,
    config: SiteConfig = DEFAULT,
) -> List[InventoryRecord]:
    """Crawl this host's data tree and return its inventory.

    Matches reference behavior (src/gbtworkerfunctions.jl:68-129):

    - returns ``[]`` early if ``root`` is not a directory;
    - session symlinks are followed;
    - files whose *basename* matches ``file_re`` are parsed against the full
      path; parse failures log a warning and are skipped (per-file
      warn-and-skip is the reference's only "fault tolerance" — SURVEY.md §5);
    - ``host``/``worker`` are stamped into every record.
    """
    file_re = _compile(file_re) if file_re is not None else config.file_re
    session_re = _compile(session_re) if session_re is not None else config.session_re
    player_re = _compile(player_re) if player_re is not None else config.player_re
    root = root if root is not None else config.root
    extra = extra if extra is not None else config.extra
    host = host or socket.gethostname()

    records: List[InventoryRecord] = []
    if not os.path.isdir(root):
        return records

    sessions = [s for s in _listdirs(root) if session_re.search(s)]
    for session in sessions:
        playerdir = os.path.join(root, session, extra)
        players = [p for p in _listdirs(playerdir) if player_re.search(p)]
        for player in players:
            top = os.path.join(playerdir, player)
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames.sort()
                for base in sorted(filenames):
                    if not file_re.search(base):
                        continue
                    path = os.path.join(dirpath, base)
                    parsed = naming.parse_guppi_name(path)
                    if parsed is None:
                        log.warning("%s:%s did not match guppiname regex", host, path)
                        continue
                    if parsed.band is None or parsed.bank is None:
                        log.warning("%s:%s did not match player regex", host, path)
                        continue
                    records.append(
                        InventoryRecord(
                            imjd=parsed.imjd,
                            smjd=parsed.smjd,
                            session=session,
                            scan=parsed.scan,
                            src_name=parsed.src,
                            band=parsed.band,
                            bank=parsed.bank,
                            host=host,
                            file=path,
                            worker=worker,
                        )
                    )
    return records


def raw_sequences(
    records: Iterable[InventoryRecord],
) -> List[Tuple[InventoryRecord, List[str]]]:
    """Group RAW-file inventory records into ``.NNNN.raw`` scan sequences.

    A GBT scan is recorded as ``<stem>.0000.raw, <stem>.0001.raw, ...``
    (the NNNN field of the reference's filename grammar,
    src/gbtworkerfunctions.jl:35-47; README.md:25-27) — one logical unit
    the reducer must consume as a single gap-free stream
    (blit/io/guppi.GuppiScan).  Returns ``(first_record, sorted_paths)``
    per sequence, stem-sorted; records whose ``file`` is not a ``.NNNN.raw``
    member are ignored.

    Duplicate members (two workers inventorying the same file on a shared
    filesystem) are deduped by exact path — file identity IS the path
    string here, matching the reference's one-root-path-per-site
    convention (src/gbt.jl:48).  Two spellings of one file (differing
    mount prefixes) are NOT conflated: in a pod the same string can name
    DIFFERENT files on different hosts, so normalizing (realpath) from
    the main process would be wrong more often than right; such aliases
    surface as scan_grid's explicit "multiple RAW sequences" error.
    """
    from blit.io.guppi import SEQ_RE

    groups: dict = {}
    for r in records:
        m = SEQ_RE.match(r.file)
        if m is None:
            continue
        # Dedupe by (stem, seq): on a shared filesystem two workers can
        # both inventory the same member, and a duplicated path must not
        # double the "sequence" (GuppiScan would read the recording
        # twice as if it were longer).  First reporter wins.
        members = groups.setdefault(m.group("stem"), {})
        members.setdefault(int(m.group("seq")), r)
    out = []
    for stem in sorted(groups):
        members = sorted(groups[stem].items())
        out.append((members[0][1], [r.file for _, r in members]))
    return out


def scan_grid(
    inventories: Iterable,
    session: str,
    scan: str,
) -> Tuple[List[int], List[int], List[List[List[str]]]]:
    """Resolve one (session, scan)'s RAW recordings into the rectangular
    ``raw_paths[band][bank]`` grid :func:`blit.parallel.scan.load_scan_mesh`
    consumes — the bridge from the inventory workflow (the reference's
    DataFrame groupby on (session, scan), README.md:95-157) to the TPU mesh
    data plane.

    ``inventories`` is per-worker record lists as :func:`blit.gbt.
    get_inventories` returns them (``WorkerError`` entries skipped, like the
    host-side ``load_scan``).  RAW records are grouped into ``.NNNN.raw``
    sequences (:func:`raw_sequences`); each (band, bank) player must have
    exactly one sequence for the scan.  The grid is rectangular over the
    sorted band and bank ids found — a band missing a bank other bands have
    is an error (the mesh needs one recording per chip), matching
    ``load_scan_mesh``'s rectangularity requirement.

    Returns ``(band_ids, bank_ids, grid)`` where ``grid[i][j]`` is the
    sorted path list of band ``band_ids[i]``, bank ``bank_ids[j]``.
    """
    recs = [
        r
        for inv in inventories
        if not _is_worker_error(inv)
        for r in inv
        if r.session == session and r.scan == scan
    ]
    cells: dict = {}
    for rec, paths in raw_sequences(recs):
        key = (rec.band, rec.bank)
        if key in cells:
            raise ValueError(
                f"band {rec.band} bank {rec.bank} has multiple RAW sequences "
                f"for {session}/{scan}: {cells[key][0]} and {paths[0]}"
            )
        cells[key] = paths
    if not cells:
        raise ValueError(f"no RAW sequences for {session}/{scan} in inventories")
    band_ids = sorted({b for b, _ in cells})
    bank_ids = sorted({k for _, k in cells})
    missing = [
        (b, k) for b in band_ids for k in bank_ids if (b, k) not in cells
    ]
    if missing:
        raise ValueError(
            f"{session}/{scan}: players {missing} have no RAW sequence — "
            f"the (band, bank) grid must be rectangular"
        )
    grid = [[cells[(b, k)] for k in bank_ids] for b in band_ids]
    return band_ids, bank_ids, grid


def to_dataframe(inventories: Iterable[Iterable[InventoryRecord]]):
    """Flatten per-worker inventories into one pandas DataFrame — the L4
    analysis-layer workflow from the reference README
    (``DataFrame(Iterators.flatten(invs))``, README.md:95-157).  Captured
    ``WorkerError`` entries (live or restored by :func:`load_inventories`)
    are skipped, like every other consumer of the ragged shape."""
    import pandas as pd

    flat = [
        rec
        for inv in inventories
        if not _is_worker_error(inv)
        for rec in inv
    ]
    return pd.DataFrame(flat, columns=InventoryRecord._fields)


def save_inventories(path: str, inventories) -> int:
    """Persist per-worker inventories as JSON-lines (the reference's
    "state" is a saved pid vector + inventory DataFrame, README.md:62-64,
    100-101 — this is the durable half).  Each line is one record plus its
    worker-list index, so :func:`load_inventories` restores the ragged
    per-worker shape exactly — including ``WorkerError`` entries from a
    captured fan-out (``get_inventories(on_error="capture")``), which
    round-trip as error markers rather than crashing the save.  Returns
    the record count."""
    n = 0
    with open(path, "w") as f:
        for w, inv in enumerate(inventories):
            if _is_worker_error(inv):
                # getattr fallbacks: _is_worker_error also admits bare
                # Exception entries, which lack WorkerError's fields.
                err = getattr(inv, "error", inv)
                f.write(json.dumps({
                    "_w": w,
                    "_error": f"{type(err).__name__}: {err}",
                    "_host": getattr(inv, "host", ""),
                    "_worker": getattr(inv, "worker", w),
                }) + "\n")
                continue
            wrote_any = False
            for rec in inv:
                row = rec._asdict()
                row["_w"] = w
                f.write(json.dumps(row) + "\n")
                n += 1
                wrote_any = True
            if not wrote_any:
                f.write(json.dumps({"_w": w, "_empty": True}) + "\n")
    return n


def _is_worker_error(entry) -> bool:
    """True for a captured per-worker failure entry (lazy import: the pool
    is jax-free but keeping inventory importable standalone matters)."""
    from blit.parallel.pool import WorkerError

    return isinstance(entry, (WorkerError, Exception))


def load_inventories(path: str) -> List:
    """Restore what :func:`save_inventories` wrote (ragged shape included).
    Captured failures come back as ``WorkerError`` entries carrying the
    saved message, so downstream consumers (``scan_grid``, ``load_scan``)
    skip them exactly as they would the live objects."""
    from blit.parallel.pool import WorkerError

    out: List = []
    with open(path) as f:
        for line in f:
            row = json.loads(line)
            w = row.pop("_w")
            while len(out) <= w:
                out.append([])
            if "_error" in row:
                out[w] = WorkerError(
                    worker=row.get("_worker", w),
                    host=row.get("_host", ""),
                    error=RuntimeError(row["_error"]),
                )
                continue
            if row.pop("_empty", False):
                continue
            out[w].append(InventoryRecord(**row))
    return out
