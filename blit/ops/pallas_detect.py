"""Pallas TPU kernel fusing Stokes-I detection with the DFT untwist.

The matmul DFT's two per-level untwist transposes plus the detect pass
move ~3 full planes of traffic after the last matmul stage (DESIGN.md §9:
2×21 ms + 41 ms at the production shape).  Detection is elementwise, so it
can read the spectra in TWISTED (digit-permuted) order — the layout
`dft(order="twisted")` emits for free — and this kernel writes each
detected tile straight into its natural-order position: the twisted axes
``(k1, k2, klast)`` map to natural order by axis REVERSAL
(blit/ops/dft.untwist), so an output block over reversed axes is still a
rectangular BlockSpec slice, with the f1 axis (128 for the hi-res product)
as the output lane dimension.  One pass replaces untwist+untwist+detect.

The pure-XLA twisted experiment lost 20% because XLA lowered the reversed
multi-axis power transpose badly (DESIGN.md §9 item 5); here the transpose
happens tile-wise in VMEM with lane-aligned writes — measured on the chip
before being wired as a default.

Stokes I only; ≤ 3 DFT factors (axis reversal == middle-preserving only
up to three digit axes); other products keep the unfused path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# Middle-axis tile: VMEM per instance ≈ npol·2·f1·tile_mid·flast·esize in
# + flast·tile_mid·f1·4 out.  At the hi-res shape (f1=128, flast=64,
# tile_mid=16, bf16): ~1 MB in + 0.5 MB out.
_DEF_TILE_MID = 16

# Per-instance VMEM budget (matches pallas_pfb's stance: leave headroom
# for double buffering on a ~16 MB part).
_VMEM_BUDGET = 6 << 20


def _fit_tile(factors, npol: int, esize: int, tile_mid: int) -> int:
    """Largest mid-axis tile (a divisor of mid, <= tile_mid) whose blocks
    fit the VMEM budget; 0 if none does even at tile_mid=1 — f1/flast are
    never tiled, so huge factor sizes must take the XLA path."""
    f1, flast = factors[0], factors[-1]
    n = 1
    for f in factors:
        n *= f
    mid = n // (f1 * flast)
    while mid % tile_mid:
        tile_mid //= 2
    while tile_mid >= 1:
        per = f1 * tile_mid * flast
        if per * (npol * 2 * esize) + per * 4 <= _VMEM_BUDGET:
            return tile_mid
        tile_mid //= 2
    return 0


def fits(factors, npol: int = 2, esize: int = 2,
         tile_mid: int = _DEF_TILE_MID) -> bool:
    """VMEM-fit gate for :func:`detect_untwist_i` — the check
    ``channelize`` runs before allowing ``detect_kernel="pallas"``."""
    return len(factors) <= 3 and _fit_tile(factors, npol, esize, tile_mid) > 0


def _detect_kernel(sr_ref, si_ref, o_ref):
    # sr/si: (1, npol, 1, f1, tile_mid, flast); o: (1, 1, flast, tile_mid, f1)
    sr = sr_ref[0, :, 0].astype(jnp.float32)
    si = si_ref[0, :, 0].astype(jnp.float32)
    p = (sr * sr + si * si).sum(axis=0)  # Stokes I over pols: (f1, mid, last)
    o_ref[0, 0] = jnp.transpose(p, (2, 1, 0))


def detect_untwist_i(
    sr: jax.Array,
    si: jax.Array,
    factors: Tuple[int, ...],
    *,
    tile_mid: int = _DEF_TILE_MID,
    interpret: bool = False,
) -> jax.Array:
    """Twisted planar spectra → natural-order Stokes-I power, one pass.

    Args:
      sr, si: ``(nchan, npol, nframes, n)`` spectra in the twisted layout
        of ``dft(order="twisted")`` (n = prod(factors)).
      factors: the DFT factorization that produced the twisted layout
        (at most 3 factors — axis reversal handles one middle axis).

    Returns float32 ``(nchan, nframes, n)`` natural-order total power.
    """
    from jax.experimental import pallas as pl

    nchan, npol, nframes, n = sr.shape
    if len(factors) > 3:
        raise ValueError("detect_untwist_i supports at most 3 DFT factors")
    if len(factors) == 1:
        p = sr.astype(jnp.float32) ** 2 + si.astype(jnp.float32) ** 2
        return p.sum(axis=1)
    f1, flast = factors[0], factors[-1]
    mid = n // (f1 * flast)
    sr6 = sr.reshape(nchan, npol, nframes, f1, mid, flast)
    si6 = si.reshape(nchan, npol, nframes, f1, mid, flast)
    tile_mid = _fit_tile(factors, npol, sr.dtype.itemsize, tile_mid)
    if tile_mid == 0:
        raise ValueError(
            f"detect_untwist_i: factor sizes {factors} exceed the VMEM "
            "budget (f1/flast are untiled) — use the XLA detect path"
        )

    in_spec = pl.BlockSpec((1, npol, 1, f1, tile_mid, flast),
                           lambda c, f, j: (c, 0, f, 0, j, 0))
    out_spec = pl.BlockSpec((1, 1, flast, tile_mid, f1),
                            lambda c, f, j: (c, f, 0, j, 0))
    out = pl.pallas_call(
        _detect_kernel,
        grid=(nchan, nframes, mid // tile_mid),
        in_specs=[in_spec, in_spec],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct(
            (nchan, nframes, flast, mid, f1), jnp.float32
        ),
        interpret=interpret,
    )(sr6, si6)
    # (flast, mid, f1) row-major IS the natural order: natural index
    # k = k1 + f1*(mid digits) + f1*mid*klast (axis reversal, dft.untwist).
    return out.reshape(nchan, nframes, n)
