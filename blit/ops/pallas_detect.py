"""Pallas TPU kernels fusing Stokes-I detection with the DFT tail.

Two kernels, one idea — detection is elementwise, so it can consume the
DFT's internal layouts directly and write each detected tile straight
into its natural-order position, instead of paying materialized untwist
transposes plus a separate detect pass:

- :func:`detect_untwist_i` consumes TWISTED (digit-permuted) spectra —
  the layout ``dft(order="twisted")`` emits for free — and untwists while
  detecting: the twisted axes ``(k1, k2, klast)`` map to natural order by
  axis REVERSAL (blit/ops/dft.untwist), so an output block over reversed
  axes is still a rectangular BlockSpec slice.  One pass replaces
  untwist+untwist+detect.  (The pure-XLA twisted experiment lost 20%
  because XLA lowered the reversed multi-axis power transpose badly,
  DESIGN.md §9 item 5; here the transpose happens tile-wise in VMEM.)

- :func:`tail2_detect_i` goes further: it fuses the final TWO
  Cooley-Tukey levels themselves (pallas_dft.dft_tail2's batched MXU
  dots), the inner untwist, Stokes-I detection across both
  polarizations, AND the channelizer's final product transpose into one
  pass — stage-1 spectra in, f32 natural-order power out, written
  directly in the filterbank product layout ``(frame, chan, fine)``.
  The bf16 tail spectra never exist in HBM and the product needs no
  further transpose.

:func:`detect_untwist_i` is Stokes I only; :func:`tail2_detect` covers
every ``detect_stokes_planar`` product (the polarization pair is
block-resident, so cross products cost only extra output planes).  Both
need ≤ 3 DFT factors (axis reversal == middle-preserving only up to
three digit axes); ineligible shapes keep the unfused path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# Middle-axis tile: VMEM per instance ≈ npol·2·f1·tile_mid·flast·esize in
# + flast·tile_mid·f1·4 out.  At the hi-res shape (f1=128, flast=64,
# tile_mid=16, bf16): ~1 MB in + 0.5 MB out.
_DEF_TILE_MID = 16

# Per-instance VMEM budget (matches pallas_pfb's stance: leave headroom
# for double buffering on a ~16 MB part).
_VMEM_BUDGET = 6 << 20


def _fit_tile(factors, npol: int, esize: int, tile_mid: int) -> int:
    """Largest mid-axis tile (a divisor of mid, <= tile_mid) whose blocks
    fit the VMEM budget; 0 if none does even at tile_mid=1 — f1/flast are
    never tiled, so huge factor sizes must take the XLA path."""
    f1, flast = factors[0], factors[-1]
    n = 1
    for f in factors:
        n *= f
    mid = n // (f1 * flast)
    while mid % tile_mid:
        tile_mid //= 2
    while tile_mid >= 1:
        per = f1 * tile_mid * flast
        if per * (npol * 2 * esize) + per * 4 <= _VMEM_BUDGET:
            return tile_mid
        tile_mid //= 2
    return 0


def fits(factors, npol: int = 2, esize: int = 2,
         tile_mid: int = _DEF_TILE_MID) -> bool:
    """VMEM-fit gate for :func:`detect_untwist_i` — the check
    ``channelize`` runs before allowing ``detect_kernel="pallas"``."""
    return len(factors) <= 3 and _fit_tile(factors, npol, esize, tile_mid) > 0


def _detect_kernel(sr_ref, si_ref, o_ref):
    # sr/si: (1, npol, 1, f1, tile_mid, flast); o: (1, 1, flast, tile_mid, f1)
    sr = sr_ref[0, :, 0].astype(jnp.float32)
    si = si_ref[0, :, 0].astype(jnp.float32)
    p = (sr * sr + si * si).sum(axis=0)  # Stokes I over pols: (f1, mid, last)
    o_ref[0, 0] = jnp.transpose(p, (2, 1, 0))


def detect_untwist_i(
    sr: jax.Array,
    si: jax.Array,
    factors: Tuple[int, ...],
    *,
    tile_mid: int = _DEF_TILE_MID,
    interpret: bool = False,
) -> jax.Array:
    """Twisted planar spectra → natural-order Stokes-I power, one pass.

    Args:
      sr, si: ``(nchan, npol, nframes, n)`` spectra in the twisted layout
        of ``dft(order="twisted")`` (n = prod(factors)).
      factors: the DFT factorization that produced the twisted layout
        (at most 3 factors — axis reversal handles one middle axis).

    Returns float32 ``(nchan, nframes, n)`` natural-order total power.
    """
    from jax.experimental import pallas as pl

    nchan, npol, nframes, n = sr.shape
    if len(factors) > 3:
        raise ValueError("detect_untwist_i supports at most 3 DFT factors")
    if len(factors) == 1:
        p = sr.astype(jnp.float32) ** 2 + si.astype(jnp.float32) ** 2
        return p.sum(axis=1)
    f1, flast = factors[0], factors[-1]
    mid = n // (f1 * flast)
    sr6 = sr.reshape(nchan, npol, nframes, f1, mid, flast)
    si6 = si.reshape(nchan, npol, nframes, f1, mid, flast)
    tile_mid = _fit_tile(factors, npol, sr.dtype.itemsize, tile_mid)
    if tile_mid == 0:
        raise ValueError(
            f"detect_untwist_i: factor sizes {factors} exceed the VMEM "
            "budget (f1/flast are untiled) — use the XLA detect path"
        )

    in_spec = pl.BlockSpec((1, npol, 1, f1, tile_mid, flast),
                           lambda c, f, j: (c, 0, f, 0, j, 0))
    out_spec = pl.BlockSpec((1, 1, flast, tile_mid, f1),
                            lambda c, f, j: (c, f, 0, j, 0))
    out = pl.pallas_call(
        _detect_kernel,
        grid=(nchan, nframes, mid // tile_mid),
        in_specs=[in_spec, in_spec],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct(
            (nchan, nframes, flast, mid, f1), jnp.float32
        ),
        interpret=interpret,
    )(sr6, si6)
    # (flast, mid, f1) row-major IS the natural order: natural index
    # k = k1 + f1*(mid digits) + f1*mid*klast (axis reversal, dft.untwist).
    return out.reshape(nchan, nframes, n)


# nif (product-plane count) per detection product — mirrors
# blit.ops.channelize.detect_stokes_planar's table.
_STOKES_NIF = {"I": 1, "XX": 1, "YY": 1, "XXYY": 2, "full": 4, "IQUV": 4}


def _td_fit_tile(f1: int, f2: int, f3: int, npol: int, esize: int,
                 tile_f1: int, nif: int = 1) -> int:
    """Largest f1-axis tile (a divisor of f1, <= tile_f1) whose blocks fit
    the VMEM budget; 0 when even tile_f1=1 does not (huge f2·f3 panels take
    the unfused path).  Per instance: the planar input pair over
    ``npol*tile`` batch panels, ~6 live f32 scratch panels of the same
    extent, the f32 output tile (``nif`` product planes), and the constant
    DFT/twiddle matrices."""
    consts = (f2 * f2 + f3 * f3 + f2 * f3) * 8
    while tile_f1 >= 1:
        # The tile sits in the output block's sublane dim: mosaic accepts
        # it only 8-divisible or covering the whole f1 axis.
        legal = f1 % tile_f1 == 0 and (tile_f1 % 8 == 0 or tile_f1 == f1)
        if legal:
            per = npol * tile_f1 * f2 * f3
            need = (consts + per * (2 * esize + 6 * 4)
                    + nif * f2 * f3 * tile_f1 * 4)
            if need <= _VMEM_BUDGET:
                return tile_f1
        tile_f1 //= 2
    return 0


def tail2_detect_fits(factors, npol: int = 2, esize: int = 2,
                      tile_f1: int = 16, stokes: str = "I") -> bool:
    """VMEM-fit gate for :func:`tail2_detect` — the check ``channelize``
    runs before resolving the combined pallas tail+detect path."""
    if len(factors) != 3 or stokes not in _STOKES_NIF:
        return False
    if npol == 1 and stokes not in ("I", "XX"):
        return False
    f1, f2, f3 = factors
    return _td_fit_tile(f1, f2, f3, npol, esize, tile_f1,
                        _STOKES_NIF[stokes]) > 0


def _td_kernel(npol, tile, stokes, xr_ref, xi_ref, w2r_ref, w2i_ref,
               w3r_ref, w3i_ref, tr_ref, ti_ref, o_ref):
    """DFT levels 2+3 + inner untwist + Stokes detect, one VMEM pass.

    Blocks: x (1, npol, 1, tile_f1, f2, f3) planar stage-1 row panels;
    o (1, nif, 1, f3, tile_f1, f2) — natural order up to ONE final lane
    swap (f1 ⇄ f2) that the caller leaves to XLA.  Mosaic requires the
    last two block dims be (8, 128)-divisible or full: f1 is tiled, so it
    cannot sit in the lane dim, and lane-slice stores into a resident
    full-f1 block need 128-aligned offsets — keeping f2 (=128 at the
    production shape) as the lane axis satisfies both, and the leftover
    swap is in XLA's fastest transpose class rather than the slow fused
    detect pass (DESIGN.md §9).  The DFT body is
    pallas_dft._tail2_kernel's (batched dots and transposes only —
    mosaic rejects reshapes that collapse transposed vector axes); the
    epilogue forms the detection product planes
    (detect_stokes_planar's table) from the per-pol spectra.
    """
    # bf16 mode runs the dots at the MXU's full (4x) rate.  Accuracy: the
    # bf16-STORED spectra lose nothing (their products are exact in the
    # f32 accumulator), but the f32 DFT matrices and the post-twiddle
    # intermediates ARE rounded to bf16 first — the same operand rounding
    # XLA's precision=None einsums apply, i.e. default-precision grade,
    # not bit-identical to all-f32 dots.  The twiddle multiply stays f32
    # on the VPU.
    dot_dtype = xr_ref.dtype if xr_ref.dtype == jnp.bfloat16 else jnp.float32
    xr4 = xr_ref[0, :, 0].astype(dot_dtype)  # (npol, tile, f2, f3)
    xi4 = xi_ref[0, :, 0].astype(dot_dtype)
    _, _, f2, f3 = xr4.shape
    b = npol * tile
    xr = xr4.reshape(b, f2, f3)  # leading-axis collapse only: mosaic-safe
    xi = xi4.reshape(b, f2, f3)
    w2r = w2r_ref[...].astype(dot_dtype)
    w2i = w2i_ref[...].astype(dot_dtype)

    def stage2(w, a):
        # (b, f2l, f3) × (f2k, f2l) → dot layout (b, f3, f2k)
        return jax.lax.dot_general(
            a, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    rr = stage2(w2r, xr)
    ii = stage2(w2i, xi)
    ri = stage2(w2r, xi)
    ir = stage2(w2i, xr)
    sr = (rr - ii).transpose(0, 2, 1)  # (b, f2k, f3)
    si = (ri + ir).transpose(0, 2, 1)
    tr = tr_ref[...][None]
    ti = ti_ref[...][None]
    ur = (sr * tr - si * ti).astype(dot_dtype)
    ui = (sr * ti + si * tr).astype(dot_dtype)
    w3r = w3r_ref[...].astype(dot_dtype)
    w3i = w3i_ref[...].astype(dot_dtype)

    def stage3(a, w):
        # (b, f2, f3j) × (f3j, f3k) → (b, f2, f3k)
        return jax.lax.dot_general(
            a, w, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    ar = stage3(ur, w3r)
    bi = stage3(ui, w3i)
    br = stage3(ui, w3r)
    ai = stage3(ur, w3i)
    # (npol, tile, f2, f3) — leading-axis reshape: mosaic-safe.
    vr = (ar - bi).reshape(npol, tile, f2, f3)
    vi = (br + ai).reshape(npol, tile, f2, f3)
    if npol == 1:
        planes = [vr[0] * vr[0] + vi[0] * vi[0]]  # "I"/"XX"
    else:
        pxr, pyr = vr[0], vr[1]
        pxi, pyi = vi[0], vi[1]
        xx = pxr * pxr + pxi * pxi
        yy = pyr * pyr + pyi * pyi
        if stokes == "I":
            planes = [xx + yy]
        elif stokes == "XX":
            planes = [xx]
        elif stokes == "YY":
            planes = [yy]
        elif stokes == "XXYY":
            planes = [xx, yy]
        else:
            # X·conj(Y) cross products (detect_stokes_planar).
            xy_re = pxr * pyr + pxi * pyi
            xy_im = pxi * pyr - pxr * pyi
            if stokes == "full":
                planes = [xx, yy, xy_re, xy_im]
            else:  # IQUV
                planes = [xx + yy, xx - yy, 2 * xy_re, -2 * xy_im]
    # Natural order within a coarse channel is (k3, k2, k1); the block
    # keeps f2 in the lane dim — (f3, tile_f1, f2) — and the caller's
    # final XLA swap moves k1 innermost.
    for i, p in enumerate(planes):
        o_ref[0, i, 0] = jnp.transpose(p, (2, 0, 1))


def tail2_detect(
    ur: jax.Array,
    ui: jax.Array,
    f2: int,
    f3: int,
    *,
    stokes: str = "I",
    tile_f1: int = 16,
    interpret: bool = False,
) -> jax.Array:
    """Fused DFT tail (levels 2+3 + inner untwist) + Stokes detection.

    Consumes the stage-1 outputs of blit/ops/pallas_pfb.pfb_dft1 and
    returns the detected product planes in the channelizer's layout — the
    bf16 tail spectra never hit HBM, and of the unfused path's three
    post-stage-1 passes (untwist, detect, product transpose) only one
    cheap XLA lane swap remains (the reference's detect runs in rawspec
    off-chip; here it is the epilogue of the last DFT pass).  All of
    detect_stokes_planar's products are supported — the polarization pair
    is already resident in the block, so cross products (full/IQUV) cost
    only the extra output planes.

    Args:
      ur, ui: ``(nchan, npol, nframes, f1, m)`` planar stage-1 spectra
        with ``m = f2·f3`` (f32 or bf16).
      f2, f3: the remaining Cooley-Tukey factors.
      stokes: detection product (see ``detect_stokes_planar``).

    Returns f32 ``(nframes, nif, nchan, f1·m)`` natural-order product
    planes — frame-major, ready to reshape to ``(time, nif, chan)``.
    """
    from jax.experimental import pallas as pl

    from blit.ops.dft import dft_matrices, twiddles

    nchan, npol, nframes, f1, m = ur.shape
    if m != f2 * f3:
        raise ValueError(f"tail2_detect: last axis {m} != {f2}*{f3}")
    if stokes not in _STOKES_NIF:
        raise ValueError(f"unknown stokes {stokes!r}")
    if npol == 1 and stokes not in ("I", "XX"):
        raise ValueError(f"stokes={stokes!r} needs 2 pols, got 1")
    nif = _STOKES_NIF[stokes]
    tile = _td_fit_tile(f1, f2, f3, npol, ur.dtype.itemsize, tile_f1, nif)
    if tile == 0:
        raise ValueError(
            f"tail2_detect: ({f2}, {f3}) panels exceed the VMEM budget — "
            "use the unfused tail (channelize tail_kernel='xla')"
        )
    ur6 = ur.reshape(nchan, npol, nframes, f1, f2, f3)
    ui6 = ui.reshape(nchan, npol, nframes, f1, f2, f3)
    w2r, w2i = (jnp.asarray(a) for a in dft_matrices(f2, "float32"))
    w3r, w3i = (jnp.asarray(a) for a in dft_matrices(f3, "float32"))
    t2r, t2i = (jnp.asarray(a) for a in twiddles(f2, f3, "float32"))
    kern = functools.partial(_td_kernel, npol, tile, stokes)
    x_spec = pl.BlockSpec((1, npol, 1, tile, f2, f3),
                          lambda c, t, j: (c, 0, t, j, 0, 0))
    # f2 stays the lane dim (128-divisible or full); the tiled f1 sits in
    # the sublane dim where an 8-divisible tile is legal.
    o_spec = pl.BlockSpec((1, nif, 1, f3, tile, f2),
                          lambda c, t, j: (t, 0, c, 0, j, 0))
    w_spec2 = pl.BlockSpec((f2, f2), lambda c, t, j: (0, 0))
    w_spec3 = pl.BlockSpec((f3, f3), lambda c, t, j: (0, 0))
    t_spec = pl.BlockSpec((f2, f3), lambda c, t, j: (0, 0))
    out = pl.pallas_call(
        kern,
        grid=(nchan, nframes, f1 // tile),
        in_specs=[x_spec, x_spec, w_spec2, w_spec2, w_spec3, w_spec3,
                  t_spec, t_spec],
        out_specs=o_spec,
        out_shape=jax.ShapeDtypeStruct(
            (nframes, nif, nchan, f3, f1, f2), jnp.float32
        ),
        interpret=interpret,
    )(ur6, ui6, w2r, w2i, w3r, w3i, t2r, t2i)
    # One XLA lane swap finishes natural order — (f3, f2, f1) row-major is
    # the per-channel natural index k = k1 + f1·k2 + f1·f2·k3.  (A pallas
    # per-tile transpose of the same swap was measured SLOWER: 20.2 vs
    # 11.9 ms at the production shape — mosaic's lane⇄sublane relayout
    # loses to XLA's transpose lowering here, so the swap stays in XLA.)
    return jnp.swapaxes(out, -1, -2).reshape(nframes, nif, nchan, f1 * m)


# Backwards-compatible alias for the Stokes-I-only round-3 entry point.
def tail2_detect_i(ur, ui, f2, f3, *, tile_f1: int = 16,
                   interpret: bool = False) -> jax.Array:
    """Stokes-I :func:`tail2_detect` returning ``(nframes, nchan, n)``."""
    out = tail2_detect(ur, ui, f2, f3, stokes="I", tile_f1=tile_f1,
                       interpret=interpret)
    return out[:, 0]
