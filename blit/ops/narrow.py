"""Compression-aware readback narrowing (ISSUE 8 tentpole c).

Some products' on-disk form is narrower than the float32 the reduction
computes: SIGPROC ``.fil`` files carry ``nbits=8/16`` quantized samples
(the survey archive's dominant format — 4x/2x smaller), and the search
plane's ``.hits`` tables are packed int32 (blit/ops/pallas_dedoppler
already narrows those on device).  On rigs whose device→host link is the
bottleneck (DESIGN.md §8: the dev tunnel reads back at ~18 MB/s against
19 GB/s kernels) shipping float32 across the link only to quantize on
the host wastes exactly the bytes the link can't afford.

This module is ONE quantization rule with two bit-identical
implementations:

- :func:`narrow_host` — NumPy, the synchronous path (and the writer-side
  rule for host-resident slabs).
- :func:`narrow_device` — jax.numpy, applied to the reduction output
  *before* D2H, so the async output plane reads back 1/4 (nbits=8) or
  1/2 (nbits=16) of the bytes.

Bit-identity holds because every step is an IEEE-exact f32 op on both
sides: ``y = clip(rint(x * scale + offset), 0, 2^nbits - 1)`` — one f32
multiply, one f32 add (both correctly rounded on CPU/TPU), ``rint``
round-half-to-even (NumPy's and XLA's shared rule), and a clip to the
integer range before an exact small-int cast.  ``tests/test_narrow.py``
pins host == device bitwise and async == sync product byte-identity;
that is what lets the narrowed readback stay the DEFAULT for nbits<32
products rather than an opt-in.  (Narrowings that do NOT commute with
the writer — e.g. reading back bf16 spectra for an f32 product — change
product bytes and stay opt-in; see DESIGN.md §8 "tuning the tunnel".)
"""

from __future__ import annotations

import numpy as np

NARROW_DTYPES = {8: np.uint8, 16: np.uint16, 32: np.float32}


def check_quant(nbits: int) -> None:
    if nbits not in NARROW_DTYPES:
        raise ValueError(
            f"nbits={nbits} unsupported (SIGPROC quantized products are "
            f"8/16/32)"
        )


def narrow_host(slab: np.ndarray, nbits: int, scale: float = 1.0,
                offset: float = 0.0) -> np.ndarray:
    """Quantize a float32 slab to the product's ``nbits`` integer form
    (identity for nbits=32).  The synchronous-path twin of
    :func:`narrow_device`."""
    check_quant(nbits)
    if nbits == 32:
        return np.asarray(slab, np.float32)
    lo, hi = np.float32(0.0), np.float32(2.0 ** nbits - 1)
    y = np.rint(
        np.asarray(slab, np.float32) * np.float32(scale) + np.float32(offset)
    )
    return np.clip(y, lo, hi).astype(NARROW_DTYPES[nbits])


def narrow_device(out, nbits: int, scale: float = 1.0,
                  offset: float = 0.0):
    """The on-device twin: same formula in jax.numpy over the (possibly
    still in-flight) reduction output, so only the narrowed bytes cross
    the D2H link.  Bitwise-identical to :func:`narrow_host` (module
    docstring)."""
    import jax.numpy as jnp

    check_quant(nbits)
    if nbits == 32:
        return out
    y = jnp.rint(
        out.astype(jnp.float32) * jnp.float32(scale) + jnp.float32(offset)
    )
    y = jnp.clip(y, jnp.float32(0.0), jnp.float32(2.0 ** nbits - 1))
    return y.astype(NARROW_DTYPES[nbits])
