"""Sample statistics over the time axis.

Reference: ``WorkerFunctions.getkurtosis`` (src/gbtworkerfunctions.jl:197-202)
uses ``StatsBase.kurtosis`` = *excess* kurtosis with biased (divide-by-n)
central moments (README.md:216-217).
"""

from __future__ import annotations


def kurtosis(data, axis: int = 0):
    """Excess kurtosis ``m4/m2**2 - 3`` with biased central moments, reduced
    over ``axis`` (default: the time axis of a ``(time, pol, chan)`` array).

    Works on NumPy and JAX arrays.  For the canonical 3-D layout the result
    has shape ``(pol, chan)``; :func:`blit.workers.get_kurtosis` transposes to
    ``(chan, pol)`` for reference indexing parity (src/gbtworkerfunctions.jl:201).
    """
    mu = data.mean(axis=axis, keepdims=True)
    d = data - mu
    d2 = d * d
    m2 = d2.mean(axis=axis)
    m4 = (d2 * d2).mean(axis=axis)
    return m4 / (m2 * m2) - 3.0
