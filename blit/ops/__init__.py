"""blit.ops — compute kernels (NumPy host path + JAX/Pallas TPU path)."""

from blit.ops.fqav import fqav, fqav_range
from blit.ops.stats import kurtosis

__all__ = ["fqav", "fqav_range", "kurtosis"]
