"""blit.ops — compute kernels (NumPy host path + JAX/Pallas TPU path)."""

from blit.ops.fqav import fqav, fqav_range
from blit.ops.stats import kurtosis

__all__ = ["fqav", "fqav_range", "kurtosis"]


def __getattr__(name):
    # Lazy: these pull in JAX; keep `import blit.ops` light.
    if name in (
        "channelize",
        "dft",
        "despike",
        "pallas_pfb",
        "pallas_dft",
        "pallas_detect",
        "pallas_xengine",
        "pallas_beamform",
    ):
        import importlib

        return importlib.import_module(f"blit.ops.{name}")
    raise AttributeError(f"module 'blit.ops' has no attribute {name!r}")
