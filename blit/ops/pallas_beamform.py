"""VMEM-resident fused beamform+detect (Pallas, packed layout).

The einsum beamform path materializes the (nbeam, nchan, ntime, npol)
beam-voltage planes in HBM (written by the contraction, read back by
detection) — at the bench shape that is 2x 268 MB of pure intermediate
traffic for a 33 MB detected product.  This kernel keeps the beams in
VMEM: per (chan, time-tile) grid step it holds the channel's weights and
one voltage tile, forms the four real products as dot_generals, squares,
and integrates — voltages are read once, only integrated power is
written.

Measured (tools/ab_pallas_beamform.py, interleaved, bench shape nant=64
nbeam=64 nchan=64 ntime=8192 nint=8, f32-equivalent input GB/s,
steady-state rounds):

    einsum bf16 planes      ~76         this kernel bf16  ~160  (2.1x)
    einsum f32 planes       ~59         this kernel f32   ~125  (2.1x)
    tile=2048: 146 (worse than 1024); first call on the rig pays a
    one-off ~19 ms allocation artifact, steady-state thereafter.
    Max rel err vs the einsum path: 4.9e-3 (same bf16 MXU multiplies,
    different reduce orders).

Mosaic shapes this kernel's two non-obvious moves:

- time integration contracts the LANE axis, and lane-axis reshapes are
  rejected — so integration is a matmul against a static 0/1
  block-diagonal S (tile, tile/nint) on the MXU (FLOPs are free next to
  the saved HBM pass);
- the output block's last dim must be 128-divisible, so the tile is
  ``nint * 128`` (tile/nint = one 128-lane block per grid step).

Layouts are PACKED, chan-major (the `beamform(layout="chan")` opt-in,
mirroring the correlator's `vis_layout="packed"`): voltages
``(nchan, nant, npol, ntime)``, weights ``(nchan, nbeam, nant)``, output
``(nchan, nbeam, npol, ntime // nint)``.

Fusing detection under a psum is only valid when the antenna axis is
WHOLE on each chip (power of the sum != sum of powers): the caller gates
on mesh axis size 1 and falls back to einsums + psum + detect otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from blit.ops.dft import Planar

_VMEM_LIMIT = 16 << 20
_SCOPED_FACTOR = 1.7  # measured headroom convention (pallas_xengine)


def pick_tile(
    nant: int,
    nbeam: int,
    npol: int,
    ntime: int,
    nint: int,
    itemsize: int = 4,
) -> Optional[int]:
    """The time tile for :func:`fused_beamform_detect`, or None when the
    kernel does not apply (→ einsum path).  tile = nint*128 satisfies the
    output-lane rule by construction; eligibility needs it to divide
    ``ntime`` and fit the VMEM model."""
    if nint < 1:
        return None
    tile = nint * 128
    if ntime % tile or nbeam % 8:
        return None
    in_bytes = 2 * nant * npol * tile * itemsize  # both voltage planes
    w_bytes = 2 * nbeam * nant * itemsize
    s_bytes = tile * (tile // nint) * 4
    # f32 intermediates (4 products + 2 combines + power) live in VMEM
    # scratch; budget the 4 persistent-ish ones.
    mid_bytes = 4 * nbeam * npol * tile * 4
    out_bytes = nbeam * npol * (tile // nint) * 4
    scoped = (
        (in_bytes + out_bytes) * 2 + w_bytes + s_bytes + mid_bytes
    ) * _SCOPED_FACTOR
    return tile if scoped <= _VMEM_LIMIT else None


def _kernel(vr_ref, vi_ref, wr_ref, wi_ref, s_ref, out_ref):
    vr = vr_ref[0]  # (nant, npol, tile)
    vi = vi_ref[0]
    wr = wr_ref[0]  # (nbeam, nant)
    wi = wi_ref[0]
    dn = (((1,), (0,)), ((), ()))  # W (b,a) x V (a,p,t) -> (b,p,t)
    kw = dict(preferred_element_type=jnp.float32)
    rr = jax.lax.dot_general(wr, vr, dn, **kw)
    ii = jax.lax.dot_general(wi, vi, dn, **kw)
    ri = jax.lax.dot_general(wr, vi, dn, **kw)
    ir = jax.lax.dot_general(wi, vr, dn, **kw)
    br = rr - ii
    bi = ri + ir
    power = br * br + bi * bi  # (nbeam, npol, tile) f32
    out_ref[0] = jax.lax.dot_general(
        power, s_ref[...], (((2,), (0,)), ((), ())), **kw
    )


@functools.partial(jax.jit, static_argnames=("nint", "tile", "interpret"))
def fused_beamform_detect(
    vr: jax.Array,
    vi: jax.Array,
    wr: jax.Array,
    wi: jax.Array,
    *,
    nint: int,
    tile: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Packed-layout fused beamform + detect + integrate.

    ``v``: (nchan, nant, npol, ntime) planar pair; ``w``: (nchan, nbeam,
    nant) planar pair → integrated power (nchan, nbeam, npol,
    ntime//nint) float32.
    """
    nchan, nant, npol, ntime = vr.shape
    nbeam = wr.shape[1]
    if tile is None:
        tile = pick_tile(nant, nbeam, npol, ntime, nint,
                         itemsize=vr.dtype.itemsize)
        if tile is None:
            raise ValueError(
                "shape not eligible for the fused kernel (ntime must "
                "divide into nint*128 tiles inside VMEM); use the einsum "
                "path"
            )
    # Explicit tiles are validated for the SILENT failure modes: an
    # undivided ntime leaves output tail blocks unwritten (garbage), a
    # tile not divisible by nint splits integration windows.  Lane/
    # sublane rules (128 | tile/nint, 8 | nbeam on TPU) are left to
    # Mosaic, whose native refusal is loud — and interpret-mode tests
    # legitimately run smaller tiles.
    if nint < 1 or tile % nint or ntime % tile:
        raise ValueError(
            f"tile={tile} invalid for nint={nint}, ntime={ntime}: "
            "need nint | tile and tile | ntime"
        )
    nto = tile // nint
    spec_v = pl.BlockSpec((1, nant, npol, tile), lambda c, t: (c, 0, 0, t))
    spec_w = pl.BlockSpec((1, nbeam, nant), lambda c, t: (c, 0, 0))
    spec_s = pl.BlockSpec((tile, nto), lambda c, t: (0, 0))
    spec_o = pl.BlockSpec((1, nbeam, npol, nto), lambda c, t: (c, 0, 0, t))
    # S stays f32: the power operand is f32 and 0/1 entries are exact.
    S = np.zeros((tile, nto), np.float32)
    for j in range(nto):
        S[j * nint:(j + 1) * nint, j] = 1.0
    return pl.pallas_call(
        _kernel,
        grid=(nchan, ntime // tile),
        in_specs=[spec_v, spec_v, spec_w, spec_w, spec_s],
        out_specs=spec_o,
        out_shape=jax.ShapeDtypeStruct(
            (nchan, nbeam, npol, ntime // nint), jnp.float32
        ),
        interpret=interpret,
    )(vr, vi, wr, wi, jnp.asarray(S))


def pack_voltages(vr, vi) -> Planar:
    """API-layout (nant, nchan, ntime, npol) planes → packed
    (nchan, nant, npol, ntime) (one transpose pass; prefer loading
    packed directly via ``load_antennas_mesh(layout="chan")``)."""
    return (
        jnp.transpose(vr, (1, 0, 3, 2)),
        jnp.transpose(vi, (1, 0, 3, 2)),
    )


def pack_weights(wr, wi) -> Planar:
    """(nbeam, nant, nchan) weight planes → packed (nchan, nbeam, nant)
    (tiny: one pass over ~MBs)."""
    return (
        jnp.transpose(wr, (2, 0, 1)),
        jnp.transpose(wi, (2, 0, 1)),
    )
