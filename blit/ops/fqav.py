"""Frequency averaging (``fqav``) with reference semantics.

Reference: ``WorkerFunctions.fqav`` (src/gbtworkerfunctions.jl:16-33).

Array-layout note (important for parity): the reference indexes filterbank
arrays ``(channel, pol, time)`` in column-major Julia, so *channel is the
fastest-varying axis*.  blit's canonical layout is the natural C-order read of
the same files: ``(time, pol, channel)`` with channel again fastest-varying —
identical memory semantics, transposed indexing.  ``fqav`` therefore reduces
groups of ``n`` along the *last* axis here, where the reference reduces along
its first axis (``reshape(A, (n, :, ...)); reduce dims=1``).
"""

from __future__ import annotations

from typing import Callable, Tuple

import numpy as np


def fqav(a, n: int, f: Callable = None):
    """Reduce every ``n`` consecutive elements of the channel (last) axis of
    ``a`` to a single value using reduction ``f`` (default: sum).

    - ``n <= 1`` returns ``a`` unchanged (src/gbtworkerfunctions.jl:17).
    - ``n`` must divide the channel count: the reference's ``reshape`` throws
      otherwise (README.md:186-191); we raise ``ValueError``.
    - ``f`` is any reduction accepting ``(array, axis=...)`` — e.g. ``np.sum``,
      ``np.mean``, ``np.max``, ``jnp.sum``.  Works on NumPy and JAX arrays
      alike (only ``reshape`` + the supplied reduction are used).
    """
    if n <= 1:
        return a
    nchan = a.shape[-1]
    if nchan % n != 0:
        raise ValueError(f"fqav: n={n} does not divide channel count {nchan}")
    if f is None:
        f = _default_sum
    grouped = a.reshape(a.shape[:-1] + (nchan // n, n))
    return f(grouped, axis=-1)


def _default_sum(a, axis):
    if (
        isinstance(a, np.ndarray)
        and a.dtype in (np.float32, np.float64)
        and axis in (-1, a.ndim - 1)
        # The dot accumulates sequentially/FMA (error ~O(n)) where np.sum
        # is pairwise (~O(log n)); at production fqav sizes that is noise,
        # but huge averaging groups keep the better-conditioned reduce
        # (ADVICE r3).
        and a.shape[-1] <= 1024
    ):
        # One BLAS pass instead of numpy's small-last-axis reduce loop —
        # measured 6.0 vs 2.4 GB/s at the config-1 shape (the group axis is
        # contiguous, so x @ 1 is the same sum with a fast inner kernel).
        return a @ np.ones(a.shape[-1], a.dtype)
    return a.sum(axis=axis)


def fqav_range(fch1: float, foff: float, nchans: int, n: int) -> Tuple[float, float, int]:
    """Frequency-*axis* averaging: the ``(fch1, foff, nchans)`` triple of the
    channel axis after ``fqav`` by ``n``.

    Reference: ``fqav(r::AbstractRange, n)`` (src/gbtworkerfunctions.jl:27-33):
    new first frequency ``fch1 + (n-1)*foff/2`` (the mean of the first group),
    step ``n*foff``, length ``nchans ÷ n``.  Always the mean, regardless of the
    array reduction used (README.md:222-226).
    """
    if n <= 1:
        return (fch1, foff, nchans)
    return (fch1 + (n - 1) * foff / 2, n * foff, nchans // n)
