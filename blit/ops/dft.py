"""Planar (real/imag) DFT on the MXU: FFT as matmuls.

The TPU-native FFT path.  Two facts drive this design (probed on hardware,
see bench.py):

1. This TPU backend implements **no complex-dtype ops** (no FFT HLO, no
   complex matmul, not even complex device_put) — the compute path must be
   real-valued end to end.
2. The MXU wants big batched matmuls.  A DFT *is* a matmul (``y = W x``), and
   the four-step factorization N = N1·N2 turns an arbitrarily large FFT into
   two batched ≤4K-point DFT matmuls plus one elementwise twiddle — for the
   1M-point hi-res product that is two 1024×1024 matrices applied to large
   batches: peak MXU shape (SURVEY.md §7 "hard parts", pallas_guide.md MXU
   notes).

"Planar" complex convention used across blit's TPU path: a complex array is
a ``(re, im)`` pair of equal-shape real arrays.  4 real matmuls implement one
complex matmul; XLA fuses the adds.

All matrices/twiddles are precomputed NumPy constants — they are jit-time
constants, transferred to HBM once and reused every step.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

# Largest DFT applied as a single matmul; larger sizes four-step-decompose.
# 4096² f32 matrices are 64 MB each — HBM-comfortable, VMEM-tileable.
DIRECT_DFT_MAX = 4096

Planar = Tuple[jax.Array, jax.Array]

# A planar entry point's input: one complex array (CPU/GPU convenience) or a
# planar (re, im) pair (the TPU-native form).
ComplexOrPlanar = Union[jax.Array, Tuple[jax.Array, jax.Array]]


def as_planar(x) -> Tuple[jax.Array, jax.Array, bool]:
    """Normalize a complex array or a planar pair to ``(re, im,
    was_complex)``.

    The shared input-dispatch for every planar entry point (beamform,
    correlator, …): planar ``(re, im)`` pairs — the TPU-native form — pass
    through; complex arrays split (CPU/GPU convenience; the dispatch is
    trace-time static since it keys on python type / dtype); real arrays get
    a zero imaginary plane.
    """
    if isinstance(x, (tuple, list)):
        xr, xi = x
        return jnp.asarray(xr), jnp.asarray(xi), False
    x = jnp.asarray(x)
    if jnp.iscomplexobj(x):
        return jnp.real(x), jnp.imag(x), True
    return x, jnp.zeros_like(x), False


@functools.lru_cache(maxsize=32)
def dft_matrices(n: int, dtype: str = "float32") -> Tuple[np.ndarray, np.ndarray]:
    """(Wr, Wi): real and imaginary parts of the n-point DFT matrix
    ``W[k, j] = exp(-2πi k j / n)`` (symmetric, so it applies to either
    side of a matmul without transposition)."""
    k = np.arange(n).reshape(n, 1).astype(np.float64)
    j = np.arange(n).reshape(1, n).astype(np.float64)
    ang = -2.0 * np.pi * ((k * j) % n) / n
    return np.cos(ang).astype(dtype), np.sin(ang).astype(dtype)


@functools.lru_cache(maxsize=32)
def twiddles(n1: int, n2: int, dtype: str = "float32") -> Tuple[np.ndarray, np.ndarray]:
    """(Tr, Ti): four-step twiddle factors ``exp(-2πi k1 j2 / (n1 n2))``
    shaped (n1, n2) — k1 indexes stage-1 output rows, j2 stage-2 columns."""
    n = n1 * n2
    k1 = np.arange(n1).reshape(n1, 1).astype(np.float64)
    j2 = np.arange(n2).reshape(1, n2).astype(np.float64)
    ang = -2.0 * np.pi * ((k1 * j2) % n) / n
    return np.cos(ang).astype(dtype), np.sin(ang).astype(dtype)


def default_factors(n: int) -> Tuple[int, ...]:
    """Factorization policy for the multi-level decomposition.

    The DFT-matmul cost is ``N · Σ factors`` complex MACs, so small factors
    win FLOPs — but the MXU is a 128×128 systolic array, so factors below
    128 waste it.  Policy: peel factors of 128 while the remainder stays
    >= 128, yielding e.g. 2^20 → (128, 128, 64) (sum 320, 6.4× fewer FLOPs
    than the square 1024×1024 split).  Non-power-of-two sizes fall back to
    as-square-as-possible two-factor splits.
    """
    if n <= DIRECT_DFT_MAX:
        return (n,)
    if n & (n - 1) == 0:
        factors = []
        while n > DIRECT_DFT_MAX:
            f = min(128, n)
            factors.append(f)
            n //= f
        factors.append(n)
        return tuple(factors)
    n1 = int(math.isqrt(n))
    while n % n1:
        n1 -= 1
    if n1 == 1 or max(n1, n // n1) > DIRECT_DFT_MAX:
        raise NotImplementedError(
            f"dft: no supported factorization for n={n}"
        )
    return (n1, n // n1)


def _cmatmul_last(
    xr: jax.Array, xi: jax.Array, wr: jax.Array, wi: jax.Array, precision
) -> Planar:
    """Complex DFT along the LAST axis via 4 real matmuls:
    ``y[..., k] = Σ_j x[..., j]·W[k, j]`` — with symmetric W this is
    ``x @ W``."""
    rr = jnp.matmul(xr, wr, precision=precision)
    ri = jnp.matmul(xr, wi, precision=precision)
    ir = jnp.matmul(xi, wr, precision=precision)
    ii = jnp.matmul(xi, wi, precision=precision)
    return rr - ii, ri + ir


# Largest DFT matrix held whole in VMEM by the pallas kernels (n x n f32
# twice = 8 MB at 1024; above that the jnp path tiles through XLA instead).
_PALLAS_MAX_N = 1024


def untwist(x: jax.Array, factors: Tuple[int, ...]) -> jax.Array:
    """Restore natural frequency order after ``dft(..., order="twisted")``.

    The twisted-flat layout enumerates the per-level digit axes
    ``(k1, k2, ..., klast)`` row-major, while the true frequency index is
    ``k = k1 + f1*k2 + f1*f2*k3 + ...`` — so the untwist is one reshape /
    reverse-axes transpose / reshape, a single materialized pass.
    """
    if len(factors) == 1:
        return x
    batch = x.shape[:-1]
    nb = len(batch)
    y = x.reshape(batch + tuple(factors))
    perm = tuple(range(nb)) + tuple(reversed(range(nb, nb + len(factors))))
    return jnp.transpose(y, perm).reshape(batch + (int(np.prod(factors)),))


def dft(
    xr: jax.Array,
    xi: jax.Array,
    *,
    precision=None,
    dtype: str = "float32",
    factors: Optional[Tuple[int, ...]] = None,
    use_pallas: bool = False,
    order: str = "natural",
) -> Planar:
    """Planar DFT along the last axis.

    Sizes <= DIRECT_DFT_MAX use one matmul; larger sizes recurse on the
    Cooley-Tukey split n = n1 · rest — an n1-point DFT matmul down the
    columns, a twiddle multiply, and a recursive DFT along the rows.  With
    :func:`default_factors` the 1M-point case runs as three matmul stages
    (128, 128, 64).  Matches ``np.fft.fft`` (golden-tested).

    ``precision``: a ``jax.lax.Precision`` for the matmuls — ``HIGHEST``
    forces full-f32 MXU passes; None uses the backend default (bf16-grade
    multiplies on TPU, exact on CPU).
    ``factors``: override the factorization (each factor <= DIRECT_DFT_MAX,
    product == n); None → :func:`default_factors`.
    ``use_pallas``: run the stages as fused pallas kernels
    (blit/ops/pallas_dft.py) — one VMEM-resident pass per stage.  Measured
    on a v5e (160× 1M-point, batched): XLA einsum path 95 ms/call, pallas
    108 ms/call — XLA's own fusion already wins at these shapes, so the
    default is the XLA path; the kernels remain available (and correct on
    hardware, sum-checked) as the tuning surface for future tile-size work.
    ``order``: ``"natural"`` emits true frequency order; ``"twisted"``
    skips the per-level untwist transposes — the two materialized
    HBM passes of the multi-level path — and emits the digit-permuted
    layout that :func:`untwist` restores.  Order-oblivious consumers
    (elementwise power detection) read the twisted spectra directly and
    untwist once on their smaller output (the channelize fast path).
    """
    n = xr.shape[-1]
    if factors is None:
        factors = default_factors(n)
    if int(np.prod(factors)) != n:
        raise ValueError(f"dft: factors {factors} do not multiply to {n}")
    if order not in ("natural", "twisted"):
        raise ValueError(f"order must be 'natural' or 'twisted', got {order!r}")
    if use_pallas and dtype != "float32":
        # The kernels hardcode f32 tiles/accumulators (pallas_dft.py).
        raise ValueError("use_pallas supports dtype='float32' only")
    # Off-TPU, the kernels run in pallas interpreter mode (slow, correct) so
    # the flag is safe on every backend.
    interpret = jax.default_backend() not in ("tpu", "axon")
    return _dft_rec(xr, xi, factors, precision, dtype, use_pallas, interpret,
                    order == "twisted")


def _dft_rec(
    xr: jax.Array, xi: jax.Array, factors: Tuple[int, ...], precision, dtype,
    use_pallas: bool = False, interpret: bool = False, twisted: bool = False,
) -> Planar:
    n = xr.shape[-1]
    if len(factors) == 1:
        if n > DIRECT_DFT_MAX:
            raise NotImplementedError(f"dft: single factor {n} too large")
        wr, wi = dft_matrices(n, dtype)
        if use_pallas and n <= _PALLAS_MAX_N:
            from blit.ops.pallas_dft import dft_last

            return dft_last(xr, xi, jnp.asarray(wr), jnp.asarray(wi),
                            interpret=interpret)
        return _cmatmul_last(xr, xi, jnp.asarray(wr), jnp.asarray(wi), precision)
    n1 = factors[0]
    n2 = n // n1
    batch = xr.shape[:-1]
    # x[j] with j = n2*j1 + j2 → rows j1, cols j2.
    xr_ = xr.reshape(batch + (n1, n2))
    xi_ = xi.reshape(batch + (n1, n2))
    # Stage 1: n1-point DFTs down the columns, then the twiddle
    # W_n^{k1·j2}: y[..., k1, j2] = tw · Σ_j1 W1[k1, j1] x[..., j1, j2].
    w1r, w1i = (jnp.asarray(a) for a in dft_matrices(n1, dtype))
    tr, ti = (jnp.asarray(a) for a in twiddles(n1, n2, dtype))
    if use_pallas and n1 <= _PALLAS_MAX_N:
        from blit.ops.pallas_dft import dft_stage

        ur, ui = dft_stage(xr_, xi_, w1r, w1i, tr, ti, interpret=interpret)
    else:
        ar = jnp.einsum("kj,...jm->...km", w1r, xr_, precision=precision)
        ai = jnp.einsum("kj,...jm->...km", w1i, xr_, precision=precision)
        br = jnp.einsum("kj,...jm->...km", w1r, xi_, precision=precision)
        bi = jnp.einsum("kj,...jm->...km", w1i, xi_, precision=precision)
        sr, si = ar - bi, ai + br
        ur = sr * tr - si * ti
        ui = sr * ti + si * tr
    # Recurse: n2-point DFTs along the rows (last axis).
    vr, vi = _dft_rec(ur, ui, factors[1:], precision, dtype, use_pallas,
                      interpret, twisted)
    if twisted:
        # Keep the (k1, <twisted n2>) layout: flatten row-major; the digit
        # axes accumulate as (k1 of every level..., last k) — exactly what
        # :func:`untwist` reverses.  No transpose pass at any level.
        vr = vr.reshape(batch + (n,))
        vi = vi.reshape(batch + (n,))
        return vr, vi
    # Output index k = k1 + n1*k2: transpose (k1, k2) → (k2, k1) then flatten.
    vr = jnp.swapaxes(vr, -1, -2).reshape(batch + (n,))
    vi = jnp.swapaxes(vi, -1, -2).reshape(batch + (n,))
    return vr, vi


def dft_tail(
    ur: jax.Array,
    ui: jax.Array,
    factors: Tuple[int, ...],
    *,
    precision=None,
    dtype: str = "float32",
    order: str = "natural",
) -> Planar:
    """Finish a DFT whose first stage (n1-point matmul + twiddle) was
    computed externally — e.g. by the fused dequant+PFB+stage-1 pallas
    kernel (blit/ops/pallas_pfb.pfb_dft1): run the remaining ``factors[1:]``
    along the last axis and assemble natural frequency order.

    ``ur, ui``: ``(..., n1, m)`` stage-1 outputs (twiddle already applied).
    Returns ``(..., n1*m)`` spectra — natural order, or the digit-permuted
    layout of :func:`untwist` with ``order="twisted"`` (for order-oblivious
    consumers like the fused detect kernel; keeps the twisted-flat layout
    contract in this module).
    """
    n1, m = ur.shape[-2], ur.shape[-1]
    if factors[0] != n1 or int(np.prod(factors[1:])) != m:
        raise ValueError(f"dft_tail: factors {factors} mismatch ({n1}, {m})")
    if order not in ("natural", "twisted"):
        raise ValueError(f"order must be 'natural' or 'twisted', got {order!r}")
    batch = ur.shape[:-2]
    if order == "twisted":
        vr, vi = _dft_rec(ur, ui, factors[1:], precision, dtype, twisted=True)
        return (vr.reshape(batch + (n1 * m,)),
                vi.reshape(batch + (n1 * m,)))
    vr, vi = _dft_rec(ur, ui, factors[1:], precision, dtype)
    vr = jnp.swapaxes(vr, -1, -2).reshape(batch + (n1 * m,))
    vi = jnp.swapaxes(vi, -1, -2).reshape(batch + (n1 * m,))
    return vr, vi


def dft_np(xr: np.ndarray, xi: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """NumPy golden reference (tests)."""
    z = np.fft.fft(xr + 1j * xi)
    return z.real, z.imag
