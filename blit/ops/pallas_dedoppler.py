"""Taylor-tree dedoppler: drift-rate transform + on-device hit extraction.

The mission downstream of every BL filterbank is a drift-rate search
(turboSETI-style: Enriquez & Price 2019): for each candidate drift rate,
sum the power along the corresponding sloped path through the
(time, frequency) waterfall and look for outliers.  Brute force costs
O(T·D·F) sums for T spectra, D drifts, F channels; the Taylor tree
(Taylor 1974 — the same log₂-stage shift-and-add that powers incoherent
dedispersion) shares partial path sums between neighbouring drifts and
does all D = T drifts in O(T·log₂T·F).

Layout / path convention (pinned — the golden tests and the ``.hits``
product shape both depend on it):

- input is ``(T, F)`` float32 power with T a power of two, time-major;
- output row ``d`` is the sum over the tree's drift-``d`` path ANCHORED
  AT t=0: ``out[d, f] = Σ_t x[t, f + shift(d, t)]`` with
  ``shift(d, t)`` given by :func:`tree_path_shift` (the classic tree
  recursion: each half inherits drift ``d>>1``; the second half starts
  offset by ``(d+1)>>1``).  Positive drift moves toward increasing
  channel index; negative drifts come from running the tree over the
  frequency-flipped array (:func:`drift_spectra`).
- paths running off the band edge read zeros (the frequency axis is
  zero-padded by T on the high side; wrap-around contamination from the
  rolls provably never reaches the first F columns because every path's
  total shift is < T).

Three execution paths, byte-identical where they overlap:

- the PURE-LAX reference (``kernel="reference"``) — rolls + adds only,
  runs everywhere (the tier-1 CPU path);
- the Pallas TPU kernel (``kernel="pallas"``) — the same stage body on
  VMEM-resident frequency tiles (halo = T columns of real neighbour
  data), grid over tiles; ``interpret=True`` runs it on CPU for tests.
  Both paths perform the identical per-element add sequence (one add
  per stage), so results agree BITWISE, not just approximately.
- ``kernel="auto"`` resolves to pallas on TPU backends when
  :func:`fits` passes, else reference.

:func:`dedoppler_hits` is the full on-device search step: tree (both
drift signs) → per-drift-row SNR normalization → drift-range mask →
device-side threshold + per-band top-k → one packed int32 array (the
single-fetch output shape the async output plane wants).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Per-instance VMEM budget for the tiled kernel (pallas_detect's stance:
# leave headroom for double buffering on a ~16 MB part).
_VMEM_BUDGET = 6 << 20

# Default frequency-tile width for the pallas path (lane-aligned).
_DEF_TILE = 512

# The stage loops are python-unrolled (T rows per stage, log2 T stages);
# beyond this the trace/compile cost stops being worth it and callers
# should split the window.
MAX_WINDOW = 1024

# Encoded hit-table columns (:func:`dedoppler_hits` packed output):
# [snr_bits(f32), power_bits(f32), drift_bins(i32), chan(i32)].
HIT_PACK_COLS = 4


def tree_path_shift(d: int, t: int, T: int) -> int:
    """The frequency shift the tree's drift-``d`` path applies at time
    ``t`` over a window of ``T`` spectra — the EXACT path the transform
    sums, host-side (the brute-force golden reference builds on this).

    Recursion mirrors the tree: the first half-window inherits internal
    drift ``d>>1``; the second half starts ``(d+1)>>1`` bins up and
    inherits the same internal drift (``(d+1)>>1 + d>>1 == d``)."""
    if T == 1:
        return 0
    half = T // 2
    if t < half:
        return tree_path_shift(d >> 1, t, half)
    return ((d + 1) >> 1) + tree_path_shift(d >> 1, t - half, half)


def _check_window(T: int) -> None:
    if T < 2 or T & (T - 1):
        raise ValueError(f"window_spectra must be a power of two >= 2, got {T}")
    if T > MAX_WINDOW:
        raise ValueError(
            f"window_spectra {T} > {MAX_WINDOW}: the unrolled tree stages "
            "stop being compile-affordable — search shorter windows"
        )


def _tree_stages(buf: jax.Array, T: int) -> jax.Array:
    """The shared tree body: ``(T, Fp)`` padded power → ``(T, Fp)`` drift
    sums (drifts 0..T-1, module-docstring convention).  Rolls + adds
    only — mosaic-safe inside the pallas kernel, XLA-friendly as the
    reference — and ONE add per element per stage, so every execution
    path produces bitwise-identical sums."""
    # (nblocks, L, Fp) block view; stage L -> 2L merges block pairs.
    buf = buf[:, None, :]  # (T, 1, Fp)
    L = 1
    while L < T:
        top = buf[0::2]  # (nb2, L, Fp)
        bot = buf[1::2]
        rows = []
        for d in range(2 * L):
            s = (d + 1) >> 1
            r2 = bot[:, d >> 1]
            if s:
                r2 = jnp.roll(r2, -s, axis=-1)
            rows.append(top[:, d >> 1] + r2)
        buf = jnp.stack(rows, axis=1)  # (nb2, 2L, Fp)
        L *= 2
    return buf[0]


def fits(T: int, tile: int = _DEF_TILE) -> bool:
    """VMEM-fit gate for the tiled pallas kernel: the (T, tile+T) f32
    block plus one stage's worth of live scratch must fit the budget."""
    if T < 2 or T & (T - 1) or T > MAX_WINDOW:
        return False
    per = T * (tile + T) * 4
    # input block + output block + ~2 live stage buffers.
    return 4 * per <= _VMEM_BUDGET


def _tree_kernel(T, x_ref, o_ref):
    # x: (1, T, tile+T) power tile with T halo columns; o: (1, T, tile).
    out = _tree_stages(x_ref[0], T)
    o_ref[0] = out[:, : o_ref.shape[2]]


def taylor_tree(
    power: jax.Array,
    *,
    kernel: str = "auto",
    interpret: bool = False,
    tile: int = _DEF_TILE,
) -> jax.Array:
    """Drift-rate transform of one window: ``(T, F)`` float32 power →
    ``(T, F)`` path sums for drifts 0..T-1 (module docstring).

    ``kernel``: "reference" (pure lax), "pallas" (tiled TPU kernel;
    ``interpret=True`` for CPU tests), or "auto".
    """
    T, F = power.shape
    _check_window(T)
    power = power.astype(jnp.float32)
    if kernel == "auto":
        # interpret=True is a request to EXERCISE the pallas kernel (CPU
        # smoke tests) — auto must not silently resolve it away to the
        # reference path.
        want_pallas = interpret or jax.default_backend() == "tpu"
        kernel = "pallas" if want_pallas and fits(T, tile) else "reference"
    if kernel == "reference":
        xp = jnp.pad(power, ((0, 0), (0, T)))
        return _tree_stages(xp, T)[:, :F]
    if kernel != "pallas":
        raise ValueError(f"unknown dedoppler kernel {kernel!r}")
    if not fits(T, tile):
        raise ValueError(
            f"taylor_tree: (T={T}, tile={tile}) exceeds the VMEM budget — "
            "use kernel='reference' or a smaller tile"
        )
    from jax.experimental import pallas as pl

    ntiles = -(-F // tile)
    # Pad so every tile has a full `tile` body plus T halo columns of
    # real neighbour data (zeros past the band edge).
    xp = jnp.pad(power, ((0, 0), (0, ntiles * tile + T - F)))
    tiles = jnp.stack(
        [
            jax.lax.slice(xp, (0, i * tile), (T, i * tile + tile + T))
            for i in range(ntiles)
        ]
    )  # (ntiles, T, tile+T)
    out = pl.pallas_call(
        functools.partial(_tree_kernel, T),
        grid=(ntiles,),
        in_specs=[pl.BlockSpec((1, T, tile + T), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, T, tile), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((ntiles, T, tile), jnp.float32),
        interpret=interpret,
    )(tiles)
    return out.transpose(1, 0, 2).reshape(T, ntiles * tile)[:, :F]


def drift_spectra(
    power: jax.Array,
    *,
    kernel: str = "auto",
    interpret: bool = False,
    tile: int = _DEF_TILE,
) -> jax.Array:
    """Both-sign drift transform: ``(T, F)`` → ``(2T-1, F)`` with row
    ``i`` holding drift ``i - (T-1)`` bins per window (negative = toward
    decreasing channel index).  Row ``T-1`` (drift 0) is shared between
    the two tree passes and appears once."""
    T = power.shape[0]
    kw = dict(kernel=kernel, interpret=interpret, tile=tile)
    pos = taylor_tree(power, **kw)  # drifts 0..T-1
    neg = taylor_tree(power[:, ::-1], **kw)[:, ::-1]  # drifts 0..-(T-1)
    # neg reversed rows: drifts -(T-1)..-1 (drop its drift-0 duplicate).
    return jnp.concatenate([neg[:0:-1], pos], axis=0)


def drift_rates(T: int) -> np.ndarray:
    """The drift values (bins per window) of :func:`drift_spectra` rows."""
    return np.arange(-(T - 1), T)


def snr_normalize(dd: jax.Array) -> jax.Array:
    """Per-drift-row SNR: ``(dd - mean_f) / std_f`` over the frequency
    axis.  Row-wise because each drift sums a different number of
    in-band bins near the edges; deterministic (single fused pass)."""
    mu = jnp.mean(dd, axis=1, keepdims=True)
    sd = jnp.std(dd, axis=1, keepdims=True)
    return (dd - mu) / jnp.maximum(sd, 1e-30)


@functools.partial(
    jax.jit,
    static_argnames=(
        "top_k", "nbands", "max_drift_bins", "kernel", "interpret", "tile",
    ),
)
def dedoppler_hits(
    power: jax.Array,
    snr_threshold: jax.Array,
    *,
    top_k: int = 8,
    nbands: int = 1,
    max_drift_bins: Optional[int] = None,
    kernel: str = "auto",
    interpret: bool = False,
    tile: int = _DEF_TILE,
) -> jax.Array:
    """The on-device search step: one window of power → packed top hits.

    ``power`` is ``(T, F)`` float32; ``snr_threshold`` a scalar (dynamic,
    so re-tuning it never recompiles).  The frequency axis is split into
    ``nbands`` equal bands (``F % nbands == 0``) and the strongest
    ``top_k`` (drift, channel) cells are extracted PER BAND — the
    waterfall never leaves the device, only ``nbands·top_k`` packed
    records do.

    Jitted at module level with the knobs static (the channelize
    convention): compilations cache PROCESS-wide, so the service layer's
    fresh-reducer-per-request pattern reuses one compiled program, and
    the dynamic ``snr_threshold`` retunes without recompiling.

    Returns int32 ``(nbands, top_k, 4)``: ``[snr_bits, power_bits,
    drift_bins, chan]`` sorted by descending SNR within each band.
    Entries below the threshold are sentineled on device (snr bits set
    to -inf) so the host-side decode just drops non-finite rows —
    device-side thresholding without a data-dependent output shape.
    """
    T, F = power.shape
    if F % nbands:
        raise ValueError(f"nbands={nbands} does not divide F={F}")
    dd = drift_spectra(power, kernel=kernel, interpret=interpret, tile=tile)
    snr = snr_normalize(dd)  # (D, F), D = 2T-1
    D = 2 * T - 1
    if max_drift_bins is not None:
        keep = np.abs(drift_rates(T)) <= max_drift_bins
        snr = jnp.where(jnp.asarray(keep)[:, None], snr, -jnp.inf)
    Fb = F // nbands
    # (D, nbands, Fb) -> (nbands, D*Fb): top_k over every (drift, chan)
    # cell of each band.
    flat_snr = snr.reshape(D, nbands, Fb).transpose(1, 0, 2).reshape(
        nbands, D * Fb
    )
    flat_pow = dd.reshape(D, nbands, Fb).transpose(1, 0, 2).reshape(
        nbands, D * Fb
    )
    vals, idx = jax.lax.top_k(flat_snr, top_k)  # (nbands, k)
    pwr = jnp.take_along_axis(flat_pow, idx, axis=1)
    drift = idx // Fb - (T - 1)
    chan = idx % Fb + jnp.arange(nbands, dtype=idx.dtype)[:, None] * Fb
    # Device-side threshold: sub-threshold entries become -inf sentinels
    # the host decode discards.
    vals = jnp.where(vals >= snr_threshold, vals, -jnp.inf)
    return jnp.stack(
        [
            jax.lax.bitcast_convert_type(vals, jnp.int32),
            jax.lax.bitcast_convert_type(pwr, jnp.int32),
            drift.astype(jnp.int32),
            chan.astype(jnp.int32),
        ],
        axis=-1,
    )


def brute_force_dedoppler(power: np.ndarray) -> np.ndarray:
    """O(T·D·F) host reference summing the EXACT tree paths
    (:func:`tree_path_shift`) in float64 — the golden oracle for the
    transform (zero outside the band, like the padded tree)."""
    T, F = power.shape
    out = np.zeros((T, F), np.float64)
    x = power.astype(np.float64)
    for d in range(T):
        for t in range(T):
            s = tree_path_shift(d, t, T)
            if s < F:
                out[d, : F - s] += x[t, s:]
    return out


def unpack_hits(
    packed: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Decode a fetched :func:`dedoppler_hits` array → parallel arrays
    ``(snr, power, drift_bins, chan, band)`` with the -inf sentinels
    (device-side threshold rejects) already dropped, order preserved
    (band-major, SNR-descending within a band — deterministic)."""
    packed = np.asarray(packed)
    nbands, k, _ = packed.shape
    flat = packed.reshape(nbands * k, HIT_PACK_COLS)
    snr = flat[:, 0].view(np.float32)
    ok = np.isfinite(snr)
    band = np.repeat(np.arange(nbands, dtype=np.int32), k)[ok]
    return (
        snr[ok],
        flat[:, 1].view(np.float32)[ok],
        flat[:, 2][ok],
        flat[:, 3][ok],
        band,
    )
