"""Pallas TPU kernels for the planar DFT stages.

Why: profiling the matmul DFT (blit/ops/dft.py) on chip shows the stages are
HBM-bound, not MXU-bound — the XLA lowering of one complex matmul
materializes four real product arrays (rr, ri, ir, ii) plus the two
combines, and the twiddle multiply is another full pass.  This kernel does
one DFT stage as a single VMEM-resident pass: the four MXU dots, the
re/im combines, and the twiddle epilogue happen per tile, writing exactly
two output arrays.  (pallas_guide.md: MXU via jnp.dot with
preferred_element_type; grid/BlockSpec tiling.)

Layout: a stage applies the n×n DFT matrix down axis -2 of a batch of
(n, m) panels — ``out[b, k, j] = Σ_l W[k, l] · x[b, l, j]`` — which is both
the column stage of the Cooley-Tukey recursion and (after the cheap
transpose XLA already performs) its row stage.  The twiddle (n, m) epilogue
covers the inter-stage factors.

Opt-in via :func:`blit.ops.dft.dft`'s ``use_pallas=True`` (float32 only).
Benchmarked on a v5e at 160× 1M-point: XLA einsum path 95 ms/call vs 108
ms/call here — XLA's fusion currently wins at these shapes, so the XLA path
is the default and these kernels are the tuning surface for future tile
work.  CPU tests run them in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_DEF_TILE_M = 512


def _pick_tile(extent: int, target: int) -> int:
    """Largest divisor of ``extent`` that is <= ``target`` — keeps tiles
    VMEM-bounded for any extent instead of falling back to whole rows
    (preferring lane-aligned multiples of 128 when one divides)."""
    if extent <= target:
        return extent
    best = 1
    for t in range(target, 0, -1):
        if extent % t == 0:
            if t % 128 == 0:
                return t
            if best == 1:
                best = t
    return best


def _stage_kernel_tw(xr_ref, xi_ref, wr_ref, wi_ref, tr_ref, ti_ref,
                     or_ref, oi_ref):
    wr = wr_ref[...]
    wi = wi_ref[...]
    xr = xr_ref[0]
    xi = xi_ref[0]
    rr = jnp.dot(wr, xr, preferred_element_type=jnp.float32)
    ii = jnp.dot(wi, xi, preferred_element_type=jnp.float32)
    ri = jnp.dot(wr, xi, preferred_element_type=jnp.float32)
    ir = jnp.dot(wi, xr, preferred_element_type=jnp.float32)
    sr = rr - ii
    si = ri + ir
    tr = tr_ref[...]
    ti = ti_ref[...]
    or_ref[0] = sr * tr - si * ti
    oi_ref[0] = sr * ti + si * tr


def _stage_kernel(xr_ref, xi_ref, wr_ref, wi_ref, or_ref, oi_ref):
    wr = wr_ref[...]
    wi = wi_ref[...]
    xr = xr_ref[0]
    xi = xi_ref[0]
    rr = jnp.dot(wr, xr, preferred_element_type=jnp.float32)
    ii = jnp.dot(wi, xi, preferred_element_type=jnp.float32)
    ri = jnp.dot(wr, xi, preferred_element_type=jnp.float32)
    ir = jnp.dot(wi, xr, preferred_element_type=jnp.float32)
    or_ref[0] = rr - ii
    oi_ref[0] = ri + ir


def dft_stage(
    xr: jax.Array,
    xi: jax.Array,
    wr: jax.Array,
    wi: jax.Array,
    tr: Optional[jax.Array] = None,
    ti: Optional[jax.Array] = None,
    *,
    tile_m: int = _DEF_TILE_M,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One fused planar DFT stage.

    Args:
      xr, xi: (..., n, m) panels (leading dims are batch).
      wr, wi: (n, n) DFT matrix parts (symmetric).
      tr, ti: optional (n, m) twiddle parts applied after the transform.
      tile_m: panel-column tile per kernel instance (lane-dim multiple of
        128; n×tile_m f32 tiles must fit VMEM several times over).

    Returns (or_, oi_) with ``o[b, k, j] = tw[k, j] · Σ_l W[k, l] x[b, l, j]``.
    """
    from jax.experimental import pallas as pl

    n, m = xr.shape[-2], xr.shape[-1]
    batch = xr.shape[:-2]
    b = 1
    for d in batch:
        b *= d
    xr3 = xr.reshape(b, n, m)
    xi3 = xi.reshape(b, n, m)
    tile_m = _pick_tile(m, tile_m)
    grid = (b, m // tile_m)

    x_spec = pl.BlockSpec((1, n, tile_m), lambda i, j: (i, 0, j))
    w_spec = pl.BlockSpec((n, n), lambda i, j: (0, 0))
    t_spec = pl.BlockSpec((n, tile_m), lambda i, j: (0, j))
    out_shape = [
        jax.ShapeDtypeStruct((b, n, m), jnp.float32),
        jax.ShapeDtypeStruct((b, n, m), jnp.float32),
    ]
    if tr is not None:
        fn = pl.pallas_call(
            _stage_kernel_tw,
            grid=grid,
            in_specs=[x_spec, x_spec, w_spec, w_spec, t_spec, t_spec],
            out_specs=[x_spec, x_spec],
            out_shape=out_shape,
            interpret=interpret,
        )
        or_, oi_ = fn(xr3, xi3, wr, wi, tr, ti)
    else:
        fn = pl.pallas_call(
            _stage_kernel,
            grid=grid,
            in_specs=[x_spec, x_spec, w_spec, w_spec],
            out_specs=[x_spec, x_spec],
            out_shape=out_shape,
            interpret=interpret,
        )
        or_, oi_ = fn(xr3, xi3, wr, wi)
    return or_.reshape(batch + (n, m)), oi_.reshape(batch + (n, m))


def stage_reference(xr, xi, wr, wi, tr=None, ti=None):
    """jnp reference implementation of :func:`dft_stage` (tests)."""
    rr = jnp.einsum("kl,...lm->...km", wr, xr)
    ii = jnp.einsum("kl,...lm->...km", wi, xi)
    ri = jnp.einsum("kl,...lm->...km", wr, xi)
    ir = jnp.einsum("kl,...lm->...km", wi, xr)
    sr, si = rr - ii, ri + ir
    if tr is None:
        return sr, si
    return sr * tr - si * ti, sr * ti + si * tr


def _last_kernel(xr_ref, xi_ref, wr_ref, wi_ref, or_ref, oi_ref):
    wr = wr_ref[...]
    wi = wi_ref[...]
    xr = xr_ref[...]
    xi = xi_ref[...]
    rr = jnp.dot(xr, wr, preferred_element_type=jnp.float32)
    ii = jnp.dot(xi, wi, preferred_element_type=jnp.float32)
    ri = jnp.dot(xi, wr, preferred_element_type=jnp.float32)
    ir = jnp.dot(xr, wi, preferred_element_type=jnp.float32)
    or_ref[...] = rr - ii
    oi_ref[...] = ri + ir


def dft_last(
    xr: jax.Array,
    xi: jax.Array,
    wr: jax.Array,
    wi: jax.Array,
    *,
    tile_r: int = 256,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused planar DFT along the LAST axis (the recursion's base case):
    ``o[..., k] = Σ_j x[..., j] · W[j, k]`` as one pallas pass (4 MXU dots +
    combines per tile)."""
    from jax.experimental import pallas as pl

    n = xr.shape[-1]
    batch = xr.shape[:-1]
    r = 1
    for d in batch:
        r *= d
    xr2 = xr.reshape(r, n)
    xi2 = xi.reshape(r, n)
    tile_r = _pick_tile(r, tile_r)
    grid = (r // tile_r,)
    x_spec = pl.BlockSpec((tile_r, n), lambda i: (i, 0))
    w_spec = pl.BlockSpec((n, n), lambda i: (0, 0))
    out_shape = [
        jax.ShapeDtypeStruct((r, n), jnp.float32),
        jax.ShapeDtypeStruct((r, n), jnp.float32),
    ]
    or_, oi_ = pl.pallas_call(
        _last_kernel,
        grid=grid,
        in_specs=[x_spec, x_spec, w_spec, w_spec],
        out_specs=[x_spec, x_spec],
        out_shape=out_shape,
        interpret=interpret,
    )(xr2, xi2, wr, wi)
    return or_.reshape(batch + (n,)), oi_.reshape(batch + (n,))
