"""Pallas TPU kernels for the planar DFT stages.

Why: profiling the matmul DFT (blit/ops/dft.py) on chip shows the stages are
HBM-bound, not MXU-bound — the XLA lowering of one complex matmul
materializes four real product arrays (rr, ri, ir, ii) plus the two
combines, and the twiddle multiply is another full pass.  This kernel does
one DFT stage as a single VMEM-resident pass: the four MXU dots, the
re/im combines, and the twiddle epilogue happen per tile, writing exactly
two output arrays.  (pallas_guide.md: MXU via jnp.dot with
preferred_element_type; grid/BlockSpec tiling.)

Layout: a stage applies the n×n DFT matrix down axis -2 of a batch of
(n, m) panels — ``out[b, k, j] = Σ_l W[k, l] · x[b, l, j]`` — which is both
the column stage of the Cooley-Tukey recursion and (after the cheap
transpose XLA already performs) its row stage.  The twiddle (n, m) epilogue
covers the inter-stage factors.

Opt-in via :func:`blit.ops.dft.dft`'s ``use_pallas=True`` (float32 only).
Benchmarked on a v5e at 160× 1M-point: XLA einsum path 95 ms/call vs 108
ms/call here — XLA's fusion currently wins at these shapes, so the XLA path
is the default and these kernels are the tuning surface for future tile
work.  CPU tests run them in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_DEF_TILE_M = 512


def _pick_tile(extent: int, target: int) -> int:
    """Largest divisor of ``extent`` that is <= ``target`` — keeps tiles
    VMEM-bounded for any extent instead of falling back to whole rows
    (preferring lane-aligned multiples of 128 when one divides)."""
    if extent <= target:
        return extent
    best = 1
    for t in range(target, 0, -1):
        if extent % t == 0:
            if t % 128 == 0:
                return t
            if best == 1:
                best = t
    return best


def _stage_kernel_tw(xr_ref, xi_ref, wr_ref, wi_ref, tr_ref, ti_ref,
                     or_ref, oi_ref):
    wr = wr_ref[...]
    wi = wi_ref[...]
    xr = xr_ref[0]
    xi = xi_ref[0]
    rr = jnp.dot(wr, xr, preferred_element_type=jnp.float32)
    ii = jnp.dot(wi, xi, preferred_element_type=jnp.float32)
    ri = jnp.dot(wr, xi, preferred_element_type=jnp.float32)
    ir = jnp.dot(wi, xr, preferred_element_type=jnp.float32)
    sr = rr - ii
    si = ri + ir
    tr = tr_ref[...]
    ti = ti_ref[...]
    or_ref[0] = sr * tr - si * ti
    oi_ref[0] = sr * ti + si * tr


def _stage_kernel(xr_ref, xi_ref, wr_ref, wi_ref, or_ref, oi_ref):
    wr = wr_ref[...]
    wi = wi_ref[...]
    xr = xr_ref[0]
    xi = xi_ref[0]
    rr = jnp.dot(wr, xr, preferred_element_type=jnp.float32)
    ii = jnp.dot(wi, xi, preferred_element_type=jnp.float32)
    ri = jnp.dot(wr, xi, preferred_element_type=jnp.float32)
    ir = jnp.dot(wi, xr, preferred_element_type=jnp.float32)
    or_ref[0] = rr - ii
    oi_ref[0] = ri + ir


def dft_stage(
    xr: jax.Array,
    xi: jax.Array,
    wr: jax.Array,
    wi: jax.Array,
    tr: Optional[jax.Array] = None,
    ti: Optional[jax.Array] = None,
    *,
    tile_m: int = _DEF_TILE_M,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One fused planar DFT stage.

    Args:
      xr, xi: (..., n, m) panels (leading dims are batch).
      wr, wi: (n, n) DFT matrix parts (symmetric).
      tr, ti: optional (n, m) twiddle parts applied after the transform.
      tile_m: panel-column tile per kernel instance (lane-dim multiple of
        128; n×tile_m f32 tiles must fit VMEM several times over).

    Returns (or_, oi_) with ``o[b, k, j] = tw[k, j] · Σ_l W[k, l] x[b, l, j]``.
    """
    from jax.experimental import pallas as pl

    n, m = xr.shape[-2], xr.shape[-1]
    batch = xr.shape[:-2]
    b = 1
    for d in batch:
        b *= d
    xr3 = xr.reshape(b, n, m)
    xi3 = xi.reshape(b, n, m)
    tile_m = _pick_tile(m, tile_m)
    grid = (b, m // tile_m)

    x_spec = pl.BlockSpec((1, n, tile_m), lambda i, j: (i, 0, j))
    w_spec = pl.BlockSpec((n, n), lambda i, j: (0, 0))
    t_spec = pl.BlockSpec((n, tile_m), lambda i, j: (0, j))
    out_shape = [
        jax.ShapeDtypeStruct((b, n, m), jnp.float32),
        jax.ShapeDtypeStruct((b, n, m), jnp.float32),
    ]
    if tr is not None:
        fn = pl.pallas_call(
            _stage_kernel_tw,
            grid=grid,
            in_specs=[x_spec, x_spec, w_spec, w_spec, t_spec, t_spec],
            out_specs=[x_spec, x_spec],
            out_shape=out_shape,
            interpret=interpret,
        )
        or_, oi_ = fn(xr3, xi3, wr, wi, tr, ti)
    else:
        fn = pl.pallas_call(
            _stage_kernel,
            grid=grid,
            in_specs=[x_spec, x_spec, w_spec, w_spec],
            out_specs=[x_spec, x_spec],
            out_shape=out_shape,
            interpret=interpret,
        )
        or_, oi_ = fn(xr3, xi3, wr, wi)
    return or_.reshape(batch + (n, m)), oi_.reshape(batch + (n, m))


def stage_reference(xr, xi, wr, wi, tr=None, ti=None):
    """jnp reference implementation of :func:`dft_stage` (tests)."""
    rr = jnp.einsum("kl,...lm->...km", wr, xr)
    ii = jnp.einsum("kl,...lm->...km", wi, xi)
    ri = jnp.einsum("kl,...lm->...km", wr, xi)
    ir = jnp.einsum("kl,...lm->...km", wi, xr)
    sr, si = rr - ii, ri + ir
    if tr is None:
        return sr, si
    return sr * tr - si * ti, sr * ti + si * tr


def _tail2_kernel(out_dtype,
                  xr_ref, xi_ref, w2r_ref, w2i_ref, w3r_ref, w3i_ref,
                  tr_ref, ti_ref, or_ref, oi_ref):
    """Two DFT levels + the inner untwist in one VMEM pass.

    Blocks: x (tile_b, f2, f3) planar pair — one stage-1 output row panel
    per batch element; out (tile_b, f3, f2) natural-m order.
    """
    # No in-kernel reshapes: mosaic rejects collapses of transposed vector
    # axes — everything rides batched dot_generals and transposes.
    # bf16 mode: dots at the MXU's full rate, with the matrices and
    # post-twiddle intermediates rounded to bf16 operands — XLA
    # default-precision grade, not bit-identical to all-f32 dots (see
    # pallas_detect._td_kernel).
    dot_dtype = xr_ref.dtype if xr_ref.dtype == jnp.bfloat16 else jnp.float32
    xr = xr_ref[...].astype(dot_dtype)  # (tile_b, f2, f3)
    xi = xi_ref[...].astype(dot_dtype)
    w2r = w2r_ref[...].astype(dot_dtype)
    w2i = w2i_ref[...].astype(dot_dtype)

    def stage2(w, a):
        # (b, f2l, f3) × (f2k, f2l) → dot layout (b, f3, f2k)
        return jax.lax.dot_general(
            a, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    rr = stage2(w2r, xr)
    ii = stage2(w2i, xi)
    ri = stage2(w2r, xi)
    ir = stage2(w2i, xr)
    # Combine in the dot layout, transpose only the two results.
    sr = (rr - ii).transpose(0, 2, 1)  # (b, f2k, f3)
    si = (ri + ir).transpose(0, 2, 1)
    # Level-2 twiddle exp(-2πi k2 j3 / (f2 f3)): (f2, f3), broadcast over b.
    tr = tr_ref[...][None]
    ti = ti_ref[...][None]
    ur = (sr * tr - si * ti).astype(dot_dtype)
    ui = (sr * ti + si * tr).astype(dot_dtype)
    # Stage 3 contracts the f3 (last) axis against the symmetric W3.
    w3r = w3r_ref[...].astype(dot_dtype)
    w3i = w3i_ref[...].astype(dot_dtype)

    def stage3(a, w):
        # (b, f2, f3j) × (f3j, f3k) → (b, f2, f3k)
        return jax.lax.dot_general(
            a, w, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    ar = stage3(ur, w3r)
    bi = stage3(ui, w3i)
    br = stage3(ui, w3r)
    ai = stage3(ur, w3i)
    vr = ar - bi
    vi = br + ai
    # Inner untwist: natural m-index = k2 + f2*k3 → layout (k3, k2).
    or_ref[...] = jnp.transpose(vr, (0, 2, 1)).astype(out_dtype)
    oi_ref[...] = jnp.transpose(vi, (0, 2, 1)).astype(out_dtype)


# Per-instance VMEM budget for dft_tail2 (conservative: in+out blocks plus
# ~6 f32 scratch panels per tile element, plus the constant matrices).
_TAIL2_VMEM_BUDGET = 6 << 20


def _tail2_tile(b: int, f2: int, f3: int, esize: int, tile_b: int) -> int:
    """Largest tile_b (divisor of b, <= tile_b) fitting the VMEM budget;
    0 when even tile_b=1 is too large (huge f2·f3 panels)."""
    consts = (f2 * f2 + f3 * f3 + f2 * f3) * 8
    while tile_b >= 1:
        if b % tile_b == 0:
            per = tile_b * f2 * f3
            if consts + per * (4 * esize + 6 * 4) <= _TAIL2_VMEM_BUDGET:
                return tile_b
        tile_b //= 2
    return 0


def tail2_fits(b: int, f2: int, f3: int, dtype: str = "float32",
               tile_b: int = 16) -> bool:
    """VMEM-fit gate for :func:`dft_tail2` — checked by ``channelize``
    before 'auto' prefers the fused tail."""
    esize = 2 if dtype == "bfloat16" else 4
    return _tail2_tile(b, f2, f3, esize, tile_b) > 0


def dft_tail2(
    xr: jax.Array,
    xi: jax.Array,
    f2: int,
    f3: int,
    *,
    dtype: str = "float32",
    tile_b: int = 16,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused final two Cooley-Tukey levels + inner untwist.

    For a 3-factor DFT (f1, f2, f3), consumes the stage-1 outputs
    ``(..., m)`` with ``m = f2·f3`` (the per-``k1`` row panels of
    blit/ops/pallas_pfb.pfb_dft1, batch = everything else) and returns the
    natural-order sub-spectra ``(..., m)`` — replacing two einsum stages,
    a twiddle pass, and one materialized transpose with a single pallas
    pass (three large MXU matmuls per tile).  The caller's remaining work
    is the level-0 untwist only.
    """
    from jax.experimental import pallas as pl

    from blit.ops.dft import dft_matrices, twiddles

    m = xr.shape[-1]
    if m != f2 * f3:
        raise ValueError(f"dft_tail2: last axis {m} != {f2}*{f3}")
    batch = xr.shape[:-1]
    b = 1
    for d in batch:
        b *= d
    esize = 2 if dtype == "bfloat16" else 4
    tile_b = _tail2_tile(b, f2, f3, esize, tile_b)
    if tile_b == 0:
        raise ValueError(
            f"dft_tail2: ({f2}, {f3}) panels exceed the VMEM budget — use "
            "the XLA tail (channelize tail_kernel='xla'; 'auto' gates on "
            "tail2_fits)"
        )
    xr3 = xr.reshape(b, f2, f3)
    xi3 = xi.reshape(b, f2, f3)
    w2r, w2i = (jnp.asarray(a) for a in dft_matrices(f2, "float32"))
    w3r, w3i = (jnp.asarray(a) for a in dft_matrices(f3, "float32"))
    t2r, t2i = (jnp.asarray(a) for a in twiddles(f2, f3, "float32"))
    out_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    kern = functools.partial(_tail2_kernel, out_dtype)
    x_spec = pl.BlockSpec((tile_b, f2, f3), lambda i: (i, 0, 0))
    o_spec = pl.BlockSpec((tile_b, f3, f2), lambda i: (i, 0, 0))
    w_spec2 = pl.BlockSpec((f2, f2), lambda i: (0, 0))
    w_spec3 = pl.BlockSpec((f3, f3), lambda i: (0, 0))
    t_spec = pl.BlockSpec((f2, f3), lambda i: (0, 0))
    vr, vi = pl.pallas_call(
        kern,
        grid=(b // tile_b,),
        in_specs=[x_spec, x_spec, w_spec2, w_spec2, w_spec3, w_spec3,
                  t_spec, t_spec],
        out_specs=[o_spec, o_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, f3, f2), out_dtype),
            jax.ShapeDtypeStruct((b, f3, f2), out_dtype),
        ],
        interpret=interpret,
    )(xr3, xi3, w2r, w2i, w3r, w3i, t2r, t2i)
    return vr.reshape(batch + (m,)), vi.reshape(batch + (m,))


def _last_kernel(xr_ref, xi_ref, wr_ref, wi_ref, or_ref, oi_ref):
    wr = wr_ref[...]
    wi = wi_ref[...]
    xr = xr_ref[...]
    xi = xi_ref[...]
    rr = jnp.dot(xr, wr, preferred_element_type=jnp.float32)
    ii = jnp.dot(xi, wi, preferred_element_type=jnp.float32)
    ri = jnp.dot(xi, wr, preferred_element_type=jnp.float32)
    ir = jnp.dot(xr, wi, preferred_element_type=jnp.float32)
    or_ref[...] = rr - ii
    oi_ref[...] = ri + ir


def dft_last(
    xr: jax.Array,
    xi: jax.Array,
    wr: jax.Array,
    wi: jax.Array,
    *,
    tile_r: int = 256,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused planar DFT along the LAST axis (the recursion's base case):
    ``o[..., k] = Σ_j x[..., j] · W[j, k]`` as one pallas pass (4 MXU dots +
    combines per tile)."""
    from jax.experimental import pallas as pl

    n = xr.shape[-1]
    batch = xr.shape[:-1]
    r = 1
    for d in batch:
        r *= d
    xr2 = xr.reshape(r, n)
    xi2 = xi.reshape(r, n)
    tile_r = _pick_tile(r, tile_r)
    grid = (r // tile_r,)
    x_spec = pl.BlockSpec((tile_r, n), lambda i: (i, 0))
    w_spec = pl.BlockSpec((n, n), lambda i: (0, 0))
    out_shape = [
        jax.ShapeDtypeStruct((r, n), jnp.float32),
        jax.ShapeDtypeStruct((r, n), jnp.float32),
    ]
    or_, oi_ = pl.pallas_call(
        _last_kernel,
        grid=grid,
        in_specs=[x_spec, x_spec, w_spec, w_spec],
        out_specs=[x_spec, x_spec],
        out_shape=out_shape,
        interpret=interpret,
    )(xr2, xi2, wr, wi)
    return or_.reshape(batch + (n,)), oi_.reshape(batch + (n,))
