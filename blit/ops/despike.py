"""DC-spike repair.

Each coarse channel's center fine channel carries the FFT DC artifact; the
repair copies the neighboring fine channel over it.  Reference (the only
in-repo evidence, in the commented-out ``loadscan``): spike index
``nfpc÷2 + 1`` (1-based), repaired as
``d[spike:nfpc:end,:,:] .= d[spike-1:nfpc:end,:,:]`` (src/gbt.jl:101-111).
In blit's 0-based ``(time, pol, chan)`` layout the spike sits at ``nfpc//2``
within each coarse channel on the last axis.
"""

from __future__ import annotations

import numpy as np


def despike(data, nfpc: int):
    """Return ``data`` with every coarse channel's DC fine channel replaced
    by its lower neighbor, along the last (channel) axis.

    Works on NumPy (copies) and JAX arrays (functional ``.at`` update).
    ``nfpc`` must divide the channel count and be >= 2.
    """
    nchan = data.shape[-1]
    if nfpc < 2 or nchan % nfpc:
        raise ValueError(f"despike: nfpc={nfpc} invalid for {nchan} channels")
    spike = nfpc // 2
    src = slice(spike - 1, None, nfpc)
    dst = slice(spike, None, nfpc)
    if isinstance(data, np.ndarray):
        out = data.copy()
        out[..., dst] = data[..., src]
        return out
    return data.at[..., dst].set(data[..., src])
