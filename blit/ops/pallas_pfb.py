"""Pallas TPU kernel fusing dequantization + the polyphase FIR frontend.

Why: the corrected roofline (DESIGN.md §9, tools/roofline.py) shows
dequant+PFB is the channelizer's dominant stage — 90 ms at 64 GB/s (8% of
the HBM roof) vs 25-29 ms at ~230 GB/s for each DFT matmul stage — because
XLA materializes the dequantized gross planes and re-reads them once per
tap, with the (chan, time, pol) → (chan, pol, time) transpose riding
along.  This kernel does the whole stage in ONE pass: the int8 voltages
enter VMEM exactly once (packed — each (npol=2, re/im) sample group is one
int32 lane element, so the awkward size-2 minor axes never meet the lane
dimension), bytes are sign-extended in-register, the ``ntap`` sign-folded
window taps accumulate in f32, and the planar frame tensors stream out in
the compute dtype.  HBM traffic drops from ~(2·gross·esize·ntap reads +
2·plane writes) to (gross int8 read + 2·plane writes).

Opt-in from :func:`blit.ops.channelize.channelize` via
``pfb_kernel="pallas"``; CPU tests run in interpreter mode (golden vs the
jnp path).  npol=2, NBITS=8 only — the GBT recording format
(SURVEY.md §0); other shapes fall back to the jnp path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# Fine-channel tile per kernel instance (upper bound; shrunk until the
# VMEM budget below holds).  Swept on the chip at the production shape
# (48ch × 8fr bf16): 2048 ≈ 86-90 ms, 4096 ≈ 89, 8192 ≈ 92-95,
# 16384/32768 ≈ 94-95 — smaller tiles pipeline HBM↔VMEM better.
_DEF_TILE_J = 4096

# Per-instance VMEM budget (v5e has ~16 MB; leave room for double
# buffering and the compiler's own scratch).
_VMEM_BUDGET = 6 << 20


def _tile_bytes(tile_j: int, nblk: int, nframes: int, ntap: int,
                esize: int) -> int:
    """VMEM resident bytes for one kernel instance at fine-tile ``tile_j``:
    packed int32 input + 4 decoded f32 gross planes + 4 output frame
    planes (re/im × 2 pols) + the coeff tile."""
    return tile_j * (
        nblk * 4 + 4 * nblk * 4 + 2 * 2 * nframes * esize + ntap * 4
    )


def pick_tile(nfft: int, nblk: int, nframes: int, ntap: int,
              esize: int, target: int = _DEF_TILE_J) -> int:
    """Largest usable divisor of ``nfft`` <= target whose instance fits
    the VMEM budget; 0 if none — the caller falls back to the XLA path.
    Usable = lane-aligned (multiple of 128) or the whole axis: sub-lane
    tiles would technically fit VMEM but serialize the vector unit, which
    is worse than not running the kernel at all."""
    for t in range(min(target, nfft), 0, -1):
        if nfft % t or (t % 128 and t != nfft):
            continue
        if _tile_bytes(t, nblk, nframes, ntap, esize) <= _VMEM_BUDGET:
            return t
    return 0


def fits(nfft: int, nblk: int, ntap: int, dtype: str = "float32") -> bool:
    """True when :func:`pfb_dequant` can run these shapes inside the VMEM
    budget — the gate ``channelize(pfb_kernel="auto")`` uses before
    preferring the kernel (e.g. the '0002' preset's 2048-frame chunks
    exceed any fine tile and must take the XLA path)."""
    esize = 2 if dtype == "bfloat16" else 4
    return pick_tile(nfft, nblk, nblk - ntap + 1, ntap, esize) > 0


def _kernel(nframes: int, ntap: int, out_dtype, v_ref, w_ref, or_ref, oi_ref):
    x = v_ref[0]  # (nblk, tile_j) int32 — packed (p0r, p0i, p1r, p1i) bytes
    w = w_ref[...]  # (ntap, tile_j) f32 (sign-folded window)

    def byte(i: int) -> jax.Array:
        # Little-endian byte i of each int32, sign-extended from int8.
        return ((((x >> (8 * i)) & 0xFF) ^ 0x80) - 0x80).astype(jnp.float32)

    def pfb(p: jax.Array) -> jax.Array:
        # p: (nblk, tile_j) f32 → (nframes, tile_j): windowed tap sums.
        acc = w[0] * p[0:nframes]
        for k in range(1, ntap):
            acc = acc + w[k] * p[k : k + nframes]
        return acc.astype(out_dtype)

    or_ref[0, 0] = pfb(byte(0))
    oi_ref[0, 0] = pfb(byte(1))
    or_ref[0, 1] = pfb(byte(2))
    oi_ref[0, 1] = pfb(byte(3))


def _fused1_kernel(nframes: int, ntap: int, n1: int, out_dtype,
                   v_ref, w_ref, w1r_ref, w1i_ref, tr_ref, ti_ref,
                   or_ref, oi_ref):
    """dequant + PFB + DFT stage 1 (+twiddle), one VMEM pass.

    Blocks (per grid instance, fine columns ``j2``-tiled):
      v:   (1, nblk, n1, tile_m) int32  packed voltages
      w:   (ntap, n1, tile_m)    f32    sign-folded window
      w1:  (n1, n1)              f32    stage-1 DFT matrix (re, im)
      tw:  (n1, tile_m)          f32    stage-1 twiddle (re, im)
      out: (1, npol, nframes, n1, tile_m) out_dtype (re, im)
    """
    x = v_ref[0]  # (nblk, n1, tile_m) int32
    w = w_ref[...]
    w1r = w1r_ref[...]
    w1i = w1i_ref[...]
    tr = tr_ref[...]
    ti = ti_ref[...]

    def byte(i: int) -> jax.Array:
        return ((((x >> (8 * i)) & 0xFF) ^ 0x80) - 0x80).astype(jnp.float32)

    # bf16 mode runs the MXU at full rate: f32-input dots cost 4x on a
    # v5e, and bf16-grade multiplies are exactly what the XLA path's
    # precision=None einsums do anyway (channelize docstring).  The tap
    # accumulation and twiddle stay f32 on the VPU either way.
    dot_dtype = (
        jnp.bfloat16 if out_dtype == jnp.bfloat16 else jnp.float32
    )
    w1r = w1r.astype(dot_dtype)
    w1i = w1i.astype(dot_dtype)

    planes = (byte(0), byte(1), byte(2), byte(3))  # p0r p0i p1r p1i
    for p in range(2):
        re_g, im_g = planes[2 * p], planes[2 * p + 1]
        for f in range(nframes):
            fr = w[0] * re_g[f]
            fi = w[0] * im_g[f]
            for k in range(1, ntap):
                fr = fr + w[k] * re_g[f + k]
                fi = fi + w[k] * im_g[f + k]
            fr = fr.astype(dot_dtype)
            fi = fi.astype(dot_dtype)
            # Stage-1 complex DFT down the n1 axis + twiddle.
            rr = jnp.dot(w1r, fr, preferred_element_type=jnp.float32)
            ii = jnp.dot(w1i, fi, preferred_element_type=jnp.float32)
            ri = jnp.dot(w1r, fi, preferred_element_type=jnp.float32)
            ir = jnp.dot(w1i, fr, preferred_element_type=jnp.float32)
            sr = rr - ii
            si = ri + ir
            or_ref[0, p, f] = (sr * tr - si * ti).astype(out_dtype)
            oi_ref[0, p, f] = (sr * ti + si * tr).astype(out_dtype)


def fused1_fits(nfft: int, nblk: int, ntap: int, n1: int,
                dtype: str = "float32") -> bool:
    """VMEM-fit gate for :func:`pfb_dft1` (see :func:`_fused1_tile`)."""
    return _fused1_tile(nfft, nblk, ntap, n1, dtype) > 0


def _fused1_tile(nfft: int, nblk: int, ntap: int, n1: int,
                 dtype: str, target: int = 512) -> int:
    esize = 2 if dtype == "bfloat16" else 4
    m = nfft // n1
    nframes = nblk - ntap + 1
    for t in range(min(target, m), 0, -1):
        if m % t or (t % 128 and t != m):
            continue
        bts = t * (
            nblk * n1 * 4          # packed input
            + ntap * n1 * 4        # window
            + 2 * n1 * 4           # twiddles
            + 2 * 2 * nframes * n1 * esize  # outputs (2 planes x 2 pols)
        ) + 2 * n1 * n1 * 4        # DFT matrices
        if bts <= _VMEM_BUDGET:
            return t
    return 0


def pfb_dft1(
    voltages: jax.Array,
    coeffs: jax.Array,
    w1r: jax.Array,
    w1i: jax.Array,
    tr: jax.Array,
    ti: jax.Array,
    *,
    dtype: str = "float32",
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused dequant + PFB + first Cooley-Tukey DFT stage.

    One HBM pass replaces three: the PFB frame planes never materialize —
    int8 in, stage-1 spectra (twiddled, ready for the remaining factors of
    :func:`blit.ops.dft._dft_rec`) out.

    Args:
      voltages: int8 ``(nchan, ntime, 2, 2)``.
      coeffs: ``(ntap, nfft)`` f32 sign-folded window.
      w1r, w1i: ``(n1, n1)`` stage-1 DFT matrix parts.
      tr, ti: ``(n1, nfft//n1)`` stage-1 twiddle parts.

    Returns ``(ur, ui)`` shaped ``(nchan, npol, nframes, n1, nfft//n1)``.
    """
    from jax.experimental import pallas as pl

    nchan, ntime, npol, ncomp = voltages.shape
    if npol != 2 or ncomp != 2:
        raise ValueError("pfb_dft1: npol=2 complex int8 input required")
    ntap, nfft = coeffs.shape
    n1 = w1r.shape[0]
    m = nfft // n1
    if ntime % nfft:
        raise ValueError(f"ntime={ntime} not a multiple of nfft={nfft}")
    nblk = ntime // nfft
    nframes = nblk - ntap + 1
    tile_m = _fused1_tile(nfft, nblk, ntap, n1, dtype)
    if tile_m == 0:
        raise ValueError(
            "pfb_dft1: no column tile fits VMEM at these shapes — use the "
            "unfused path"
        )

    packed = jax.lax.bitcast_convert_type(
        voltages.reshape(nchan, nblk, n1, m, npol * ncomp), jnp.int32
    )  # (nchan, nblk, n1, m)
    wv = coeffs.reshape(ntap, n1, m)
    out_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    kern = functools.partial(_fused1_kernel, nframes, ntap, n1, out_dtype)
    out_shape = [
        jax.ShapeDtypeStruct((nchan, npol, nframes, n1, m), out_dtype),
        jax.ShapeDtypeStruct((nchan, npol, nframes, n1, m), out_dtype),
    ]
    ur, ui = pl.pallas_call(
        kern,
        grid=(nchan, m // tile_m),
        in_specs=[
            pl.BlockSpec((1, nblk, n1, tile_m), lambda c, j: (c, 0, 0, j)),
            pl.BlockSpec((ntap, n1, tile_m), lambda c, j: (0, 0, j)),
            pl.BlockSpec((n1, n1), lambda c, j: (0, 0)),
            pl.BlockSpec((n1, n1), lambda c, j: (0, 0)),
            pl.BlockSpec((n1, tile_m), lambda c, j: (0, j)),
            pl.BlockSpec((n1, tile_m), lambda c, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, npol, nframes, n1, tile_m),
                         lambda c, j: (c, 0, 0, 0, j)),
            pl.BlockSpec((1, npol, nframes, n1, tile_m),
                         lambda c, j: (c, 0, 0, 0, j)),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(packed, wv, w1r, w1i, tr, ti)
    return ur, ui


def pfb_dequant(
    voltages: jax.Array,
    coeffs: jax.Array,
    *,
    dtype: str = "float32",
    tile_j: int = _DEF_TILE_J,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused int8 dequant + polyphase FIR, one HBM pass.

    Args:
      voltages: int8 ``(nchan, ntime, npol=2, 2)`` with ``ntime`` a
        multiple of ``coeffs.shape[1]`` (GuppiRaw block layout).
      coeffs: ``(ntap, nfft)`` float32 window (fftshift sign already
        folded by the caller, as in :func:`channelize`).

    Returns planar ``(fr, fi)`` shaped ``(nchan, npol, nframes, nfft)`` in
    ``dtype`` — exactly ``pfb_frontend(moveaxis(dequantize(v)))``.
    """
    from jax.experimental import pallas as pl

    nchan, ntime, npol, ncomp = voltages.shape
    if npol != 2 or ncomp != 2:
        raise ValueError("pfb_dequant: npol=2 complex int8 input required")
    ntap, nfft = coeffs.shape
    if ntime % nfft:
        raise ValueError(f"ntime={ntime} not a multiple of nfft={nfft}")
    nblk = ntime // nfft
    nframes = nblk - ntap + 1
    if nframes < 1:
        raise ValueError(f"need >= {ntap} blocks of {nfft}, got {nblk}")
    esize = 2 if dtype == "bfloat16" else 4
    tile_j = pick_tile(nfft, nblk, nframes, ntap, esize, tile_j)
    if tile_j == 0:
        raise ValueError(
            f"pfb_dequant: no fine-channel tile of nfft={nfft} fits VMEM at "
            f"{nblk} blocks ({nframes} frames) — use the XLA path "
            f"(channelize pfb_kernel='xla'; 'auto' gates on pallas_pfb.fits)"
        )

    # Pack each sample's 4 int8 components into one int32 lane element —
    # a pure bitcast of the contiguous buffer (no data movement).
    packed = jax.lax.bitcast_convert_type(
        voltages.reshape(nchan, nblk, nfft, npol * ncomp), jnp.int32
    )  # (nchan, nblk, nfft)
    grid = (nchan, nfft // tile_j)
    out_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    kern = functools.partial(_kernel, nframes, ntap, out_dtype)
    out_shape = [
        jax.ShapeDtypeStruct((nchan, npol, nframes, nfft), out_dtype),
        jax.ShapeDtypeStruct((nchan, npol, nframes, nfft), out_dtype),
    ]
    fr, fi = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, nblk, tile_j), lambda c, j: (c, 0, j)),
            pl.BlockSpec((ntap, tile_j), lambda c, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, npol, nframes, tile_j), lambda c, j: (c, 0, 0, j)),
            pl.BlockSpec((1, npol, nframes, tile_j), lambda c, j: (c, 0, 0, j)),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(packed, coeffs)
    return fr, fi
